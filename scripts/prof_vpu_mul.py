"""Micro-benchmark: VPU elementwise multiply throughput, int32 vs f32,
inside a pallas kernel (dependent chain so nothing folds away).

Motivation: if the VPU emulates 32-bit integer multiply in multiple
passes while f32 is single-pass, a 9-bit-limb f32 field representation
(29 limbs, products+sums < 2^24 => exact) could beat the 13-bit int32
schoolbook even with ~2.1x the MAC count.
"""

import os
import sys
import time
from functools import lru_cache

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

ROWS, BLK, GRID = 160, 512, 20  # wide rows: ILP hides per-op latency
K = 400  # chain length inside the kernel


def make_kernel(dtype):
    def kernel(a_ref, b_ref, o_ref):
        a = a_ref[:]
        b = b_ref[:]

        def body(i, v):
            # dependent multiply-add chain over a WIDE value: 160x512
            # per step issues plenty of independent lanes/sublanes, so
            # this is throughput- not latency-bound; mask keeps ints small
            v = v * b
            if dtype == jnp.int32:
                v = v & 0x1FFF
            else:
                v = v - jnp.floor(v / 8192.0) * 8192.0
            return v + a

        o_ref[:] = jax.lax.fori_loop(0, K, body, a)

    return kernel


@lru_cache(maxsize=4)
def build(dtype):
    spec = pl.BlockSpec((ROWS, BLK), lambda i: (0, i))
    return pl.pallas_call(
        make_kernel(dtype),
        grid=(GRID,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((ROWS, BLK * GRID), dtype),
    )


def slope(fn, args, k=12):
    """Median-of-3 slope between 1 and k back-to-back dispatches."""
    np.asarray(fn(*args))
    ests = []
    for _ in range(3):
        t0 = time.perf_counter(); np.asarray(fn(*args)); t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn(*args)
        np.asarray(out)
        tk = time.perf_counter() - t0
        ests.append((tk - t1) / (k - 1) * 1000)
    return sorted(ests)[1]


rng = np.random.default_rng(0)
for dtype, name in ((jnp.int32, "int32"), (jnp.float32, "f32")):
    a = rng.integers(1, 500, size=(ROWS, BLK * GRID))
    b = rng.integers(1, 3, size=(ROWS, BLK * GRID))
    da = jnp.asarray(a, dtype=dtype)
    db = jnp.asarray(b, dtype=dtype)
    fn = build(dtype)
    ms = slope(fn, (da, db))
    nmul = ROWS * BLK * GRID * K
    print(f"{name}: {ms:8.2f} ms for {nmul/1e6:.0f}M mul(+mask+add) "
          f"-> {nmul/ms/1e6:.1f} Gmul/s")
