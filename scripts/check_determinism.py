#!/usr/bin/env python
"""check_determinism — static nondeterminism analyzer for the
consensus-critical call graph.

Everything the replay/crash/parallel machinery leans on assumes that
re-executing a block yields bit-identical state: one wall-clock read,
unseeded RNG draw, or set-iteration order escaping into an app hash,
event stream, stored row, or wire frame is a chain-splitting bug.
This gate parses the consensus-critical modules (no imports, pure AST
— the static half; tools/detcheck.py is the runtime twin) and enforces
the determinism discipline rules (DT-1..DT-6, README "Correctness
tooling"):

  DT-CLOCK  wall-clock reads (time.time/time_ns, datetime.now/utcnow,
            now_ns) whose value reaches hashed/serialized/stored state
            or is returned into the consensus call graph
  DT-RAND   unseeded entropy (module-level random.*, os.urandom,
            secrets.*, uuid1/uuid4, SystemRandom, argless Random()) in
            a deterministic path — seeded random.Random(seed)
            instances are the sanctioned idiom
  DT-ITER   set/frozenset iteration whose ORDER escapes into
            accumulated, hashed, stored, or wire output (set order is
            hash-randomized across processes), plus any builtin
            hash() call — bytes/str hashing is PYTHONHASHSEED-seeded,
            so hash-keyed partitioning diverges per process
  DT-ENV    os.environ/getenv, platform.*, hostname/pid reads inside
            state transitions
  DT-FLOAT  float arithmetic feeding hashed/serialized/stored state,
            or truncated via int() into consensus-affecting integers
  DT-ID     id() / default object repr escaping into output (process-
            address-dependent)

Sanctioned escape hatches the analyzer recognizes: sorted(S) /
V.sort() launder iteration-order taint; accumulating INTO a set stays
order-free; random.Random(seed) is a seeded source.

Findings are suppressed ONLY via scripts/determinism_allowlist.json
(shared discipline with the concurrency gate — scripts/allowlist_util:
every entry justified, stale entries surfaced). Wired into the test
suite as a tier-1 gate (tests/test_check_determinism.py) and runnable
standalone:

    python scripts/check_determinism.py [--json] [paths...]
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import allowlist_util  # noqa: E402

load_allowlist = allowlist_util.load_allowlist

# the consensus-critical call graph: modules whose output is hashed,
# serialized, stored, or gossiped. Directory scans restrict to these;
# explicit file arguments (fixture corpora) are scanned wholesale.
CRITICAL_SUFFIXES = (
    "state/execution.py",
    "state/parallel.py",
    "state/lanepool.py",
    "state/state.py",
    "state/store.py",
    "state/txindex.py",
    "state/validation.py",
    "consensus/state.py",
    "consensus/replay.py",
    "consensus/handel.py",
    "types/basic.py",
    "types/block.py",
    "types/serde.py",
    "types/part_set.py",
    "types/evidence.py",
    "types/event_bus.py",
    "types/genesis.py",
    "types/validator_set.py",
    "types/vote_set.py",
    "abci/example/kvstore.py",
    "abci/example/counter.py",
    "abci/example/sharded_kvstore.py",
    "mempool/mempool.py",
    "mempool/preverify.py",
    "statesync/restore.py",
    "statesync/chunker.py",
)

# wall-clock sources: attr name -> required receiver names (None entry
# = bare-call form allowed, e.g. the repo's own now_ns())
_CLOCK_CALLS = {
    "time": ("time", "_time"),
    "time_ns": ("time", "_time"),
    "now": ("datetime", "date"),
    "utcnow": ("datetime",),
    "today": ("datetime", "date"),
    "now_ns": None,
}

# unseeded-entropy sources (receiver-qualified module calls)
_RAND_MODULES = ("random", "_random", "secrets")
_RAND_EXEMPT_ATTRS = {"Random"}  # Random(seed) is the seeded idiom
_RAND_DIRECT = {"urandom": ("os",), "uuid1": ("uuid",),
                "uuid4": ("uuid",), "SystemRandom": (None,)}

_ENV_ATTRS = {"environ", "getenv", "getpid", "gethostname", "getuser"}
_ENV_RECEIVERS = ("os", "platform", "socket", "getpass")

# sink shapes: where a nondeterministic value becomes consensus-visible
_SERIALIZE_NAMES = {"pack", "packb", "to_bytes"}
_HASH_NAMES = {"sha256", "sha512", "sha1", "blake2b", "md5",
               "hash_from_byte_slices", "tx_hash", "simple_hash"}
_HASHISH_RECV_RE = re.compile(r"(hash|dig|hasher|sha\d*|md)$")
_DB_RECV_RE = re.compile(r"(db|batch|store|wal|backing)$", re.IGNORECASE)
_DB_WRITE_ATTRS = {"set", "set_sync", "put"}
_SEND_NAMES = {"send", "try_send", "broadcast", "sendall"}
_CTOR_SINKS = {"TxResult", "KVPair", "ValidatorUpdate", "Vote",
               "Proposal", "Snapshot", "Header", "Commit", "BlockID",
               "make_block"}


def _last_attr(expr) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _recv_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return _last_attr(fn.value)
    return None


class Finding:
    def __init__(self, rule: str, key: str, path: str, line: int,
                 message: str):
        self.rule = rule
        self.key = key
        self.path = path
        self.line = line
        self.message = message
        self.suppressed_by: Optional[str] = None

    def as_dict(self) -> dict:
        return {"rule": self.rule, "key": self.key, "path": self.path,
                "line": self.line, "message": self.message,
                "suppressed": self.suppressed_by is not None}


def _collect_imports(tree) -> Dict[str, Dict]:
    """Per-file import aliasing so the usual idioms cannot bypass the
    source tables: `import random as rnd` (module alias) and
    `from time import time` / `from os import urandom` (bare names)."""
    mod: Dict[str, str] = {}
    frm: Dict[str, Tuple[str, str]] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                root = a.name.split(".")[0]
                mod[a.asname or root] = root
        elif isinstance(n, ast.ImportFrom):
            m = (n.module or "").split(".")[-1]
            for a in n.names:
                frm[a.asname or a.name] = (m, a.name)
    return {"mod": mod, "from": frm}


def _source_of_call(call: ast.Call,
                    imports: Optional[Dict] = None
                    ) -> Optional[Tuple[str, str]]:
    """(rule, label) when `call` is itself a nondeterminism source."""
    attr = _last_attr(call.func)
    if attr is None:
        return None
    recv = _recv_name(call)
    if imports:
        if recv is not None:
            # import random as rnd → rnd.random() reads as random.*
            recv = imports["mod"].get(recv, recv)
        elif isinstance(call.func, ast.Name):
            # from time import time → time() reads as time.time()
            hit = imports["from"].get(call.func.id)
            if hit is not None:
                recv, attr = hit

    if attr == "now_ns":  # the repo's own accessor, however imported
        return "DT-CLOCK", "now_ns()"
    want = _CLOCK_CALLS.get(attr)
    if want is not None and recv in want:
        return "DT-CLOCK", f"{recv}.{attr}()"

    if recv in _RAND_MODULES and attr not in _RAND_EXEMPT_ATTRS:
        return "DT-RAND", f"{recv}.{attr}()"
    if attr in _RAND_DIRECT:
        wanted = _RAND_DIRECT[attr]
        if recv in wanted or (None in wanted):
            return "DT-RAND", f"{recv or ''}.{attr}()".lstrip(".")
    if attr == "Random" and not call.args and not call.keywords:
        return "DT-RAND", "unseeded Random()"

    if attr in _ENV_ATTRS and recv in _ENV_RECEIVERS:
        return "DT-ENV", f"{recv}.{attr}"
    if recv == "environ":  # os.environ.get(...) / .setdefault(...)
        return "DT-ENV", f"os.environ.{attr}"
    if recv == "platform":
        return "DT-ENV", f"platform.{attr}()"

    if isinstance(call.func, ast.Name):
        if call.func.id == "id":
            return "DT-ID", "id()"
        if call.func.id == "hash":
            return "DT-ITER", "builtin hash() (PYTHONHASHSEED-seeded)"
    return None


def _sink_label(call: ast.Call) -> Optional[str]:
    """A short label when `call` is a consensus-visible output sink."""
    attr = _last_attr(call.func)
    if attr is None:
        return None
    recv = _recv_name(call)
    if attr in _SERIALIZE_NAMES:
        return f"serialize .{attr}()"
    if attr in _HASH_NAMES:
        return f"hash {attr}()"
    if attr == "update" and recv and _HASHISH_RECV_RE.search(recv):
        return f"hash {recv}.update()"
    if attr in _DB_WRITE_ATTRS and recv and _DB_RECV_RE.search(recv):
        return f"store {recv}.{attr}()"
    if attr in _SEND_NAMES:
        return f"wire .{attr}()"
    if attr in _CTOR_SINKS and isinstance(call.func, (ast.Name,
                                                      ast.Attribute)):
        return f"{attr}(...)"
    if attr.startswith("Response") and attr[8:9].isupper():
        return f"{attr}(...)"
    return None


class _FuncDet(ast.NodeVisitor):
    """Per-function walker: taint through locals (clock/rand/float/id),
    set-typedness, iteration-order taint, sink detection."""

    def __init__(self, owner: str, relpath: str, set_fields: Set[str],
                 float_fields: Set[str], sink: List[Finding],
                 imports: Optional[Dict] = None):
        self.owner = owner
        self.relpath = relpath
        self.set_fields = set_fields
        self.float_fields = float_fields
        self.sink = sink
        self.imports = imports
        # name -> (rule, label): value-taint (clock/rand/float/id)
        self.tainted: Dict[str, Tuple[str, str]] = {}
        # names known to hold set/frozenset values (order-free to KEEP,
        # dangerous to ITERATE)
        self.setvars: Set[str] = set()
        # name -> label: sequences whose ORDER came from set iteration
        self.ordervars: Dict[str, str] = {}
        self._emitted: Set[str] = set()
        # stack of "iterating a set right now" labels
        self._set_loop: List[str] = []

    # -- emit ----------------------------------------------------------

    def _emit(self, rule: str, detail: str, line: int, message: str):
        key = f"{rule}:{self.relpath}:{self.owner}:{detail}"
        if key in self._emitted:
            return
        self._emitted.add(key)
        self.sink.append(Finding(rule, key, self.relpath, line, message))

    # -- expression classification ------------------------------------

    def _is_set_expr(self, expr) -> bool:
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return True
        if isinstance(expr, ast.Name):
            return expr.id in self.setvars
        if isinstance(expr, ast.Call):
            name = _last_attr(expr.func)
            # bare-name constructors only: `db.set(k, v)` is a store,
            # not a set() construction
            if name in ("set", "frozenset") \
                    and isinstance(expr.func, ast.Name):
                return True
            # set-producing methods: union/intersection/difference of a
            # set variable
            if name in ("union", "intersection", "difference", "copy"):
                recv = _recv_name(expr)
                return recv in self.setvars
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return expr.attr in self.set_fields
        if isinstance(expr, ast.BinOp) \
                and isinstance(expr.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
            return (self._is_set_expr(expr.left)
                    or self._is_set_expr(expr.right))
        if isinstance(expr, ast.BoolOp):
            return any(self._is_set_expr(v) for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return (self._is_set_expr(expr.body)
                    or self._is_set_expr(expr.orelse))
        return False

    def _float_op(self, expr) -> bool:
        """BinOp that is float arithmetic: true division, a float
        constant operand, or an operand that is a known-float field."""
        if not isinstance(expr, ast.BinOp):
            return False
        if isinstance(expr.op, ast.Div):
            return True
        for side in (expr.left, expr.right):
            if isinstance(side, ast.Constant) and isinstance(side.value,
                                                             float):
                return True
            if isinstance(side, ast.Attribute) \
                    and isinstance(side.value, ast.Name) \
                    and side.value.id == "self" \
                    and side.attr in self.float_fields:
                return True
            if isinstance(side, ast.Name) \
                    and self.tainted.get(side.id, ("",))[0] == "DT-FLOAT":
                return True
            if self._float_op(side):
                return True
        return False

    def _taint_of(self, expr) -> Optional[Tuple[str, str]]:
        """Value-taint of an expression: a source call, a tainted name,
        float arithmetic, or propagation through calls/ops. sorted()
        launders ITERATION-order taint only — never value taint."""
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name):
                t = self.tainted.get(sub.id)
                if t is not None:
                    return t
            elif isinstance(sub, ast.Call):
                src = _source_of_call(sub, self.imports)
                if src is not None and src[0] != "DT-ITER":
                    # builtin hash() is flagged directly, not tainted
                    return src
        if self._float_op(expr):
            return "DT-FLOAT", "float arithmetic"
        return None

    def _order_taint_of(self, expr) -> Optional[str]:
        """Iteration-order taint of an expression: a sequence built by
        iterating a set, unless laundered through sorted()."""
        if isinstance(expr, ast.Name):
            return self.ordervars.get(expr.id)
        if isinstance(expr, ast.Call):
            name = _last_attr(expr.func)
            if name == "sorted":
                return None  # laundered
            if name in ("list", "tuple") and expr.args:
                if self._is_set_expr(expr.args[0]):
                    return f"{name}(<set>)"
                return self._order_taint_of(expr.args[0])
            if name == "join" and expr.args \
                    and self._is_set_expr(expr.args[0]):
                return "join(<set>)"
            for a in expr.args:
                t = self._order_taint_of(a)
                if t is not None:
                    return t
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp)):
            for gen in expr.generators:
                if self._is_set_expr(gen.iter):
                    return "comprehension over set"
                t = self._order_taint_of(gen.iter)
                if t is not None:
                    return t
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            return (self._order_taint_of(expr.left)
                    or self._order_taint_of(expr.right))
        return None

    # -- visitors ------------------------------------------------------

    def visit_Assign(self, node: ast.Assign):
        self.generic_visit(node)
        taint = self._taint_of(node.value)
        is_set = self._is_set_expr(node.value)
        order = None if is_set else self._order_taint_of(node.value)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                if taint is not None:
                    # sticky across branches: the walker is flow-
                    # insensitive, so an untainted reassignment in one
                    # branch must not hide a tainted one in another
                    self.tainted[tgt.id] = taint
                if is_set:
                    self.setvars.add(tgt.id)
                    self.ordervars.pop(tgt.id, None)
                elif order is not None:
                    self.ordervars[tgt.id] = order
                    self.setvars.discard(tgt.id)
                else:
                    self.setvars.discard(tgt.id)
                    self.ordervars.pop(tgt.id, None)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.generic_visit(node)
        if isinstance(node.target, ast.Name):
            taint = self._taint_of(node.value)
            if taint is not None:
                self.tainted[node.target.id] = taint
            order = self._order_taint_of(node.value)
            if order is not None:
                self.ordervars[node.target.id] = order

    def visit_For(self, node: ast.For):
        # the iterable expression itself can contain source calls
        # (`for tx in random.sample(...)`) — run the normal call
        # checks over it before entering the body
        self.visit(node.iter)
        entered = False
        if self._is_set_expr(node.iter):
            self._set_loop.append(
                f"iterating {ast.unparse(node.iter)[:40]}"
                if hasattr(ast, "unparse") else "iterating a set")
            entered = True
        else:
            ot = self._order_taint_of(node.iter)
            if ot is not None:
                self._set_loop.append(ot)
                entered = True
        for stmt in node.body:
            self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)
        if entered:
            self._set_loop.pop()

    def visit_Return(self, node: ast.Return):
        self.generic_visit(node)
        if node.value is None:
            return
        taint = self._taint_of(node.value)
        if taint is not None and taint[0] in ("DT-CLOCK",):
            self._emit(
                taint[0], "return", node.lineno,
                f"{self.owner} returns a value derived from {taint[1]} "
                f"into the consensus call graph")
        order = self._order_taint_of(node.value)
        if order is not None:
            self._emit(
                "DT-ITER", "return", node.lineno,
                f"{self.owner} returns a sequence whose order came from "
                f"set iteration ({order}) — set order is hash-randomized "
                f"across processes")

    def visit_Yield(self, node: ast.Yield):
        self.generic_visit(node)
        if self._set_loop:
            self._emit(
                "DT-ITER", "yield", node.lineno,
                f"{self.owner} yields while {self._set_loop[-1]} — the "
                f"emitted order is hash-randomized across processes")

    def visit_YieldFrom(self, node: ast.YieldFrom):
        self.generic_visit(node)
        if self._is_set_expr(node.value) \
                or self._order_taint_of(node.value) is not None:
            self._emit(
                "DT-ITER", "yield-from", node.lineno,
                f"{self.owner} yields from a set (or set-ordered "
                f"sequence) — the emitted order is hash-randomized "
                f"across processes")

    def visit_Subscript(self, node: ast.Subscript):
        self.generic_visit(node)
        # os.environ["X"] — the call-free env read
        base = node.value
        if _last_attr(base) == "environ":
            self._emit(
                "DT-ENV", "os.environ[]", node.lineno,
                f"{self.owner} reads os.environ in a consensus-critical "
                f"path")

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        attr = _last_attr(node.func)
        recv = _recv_name(node)

        # direct-flag sources that need no sink: entropy, env, id, hash
        src = _source_of_call(node, self.imports)
        if src is not None and src[0] in ("DT-RAND", "DT-ENV", "DT-ID",
                                          "DT-ITER"):
            self._emit(
                src[0], src[1], node.lineno,
                f"{self.owner} calls {src[1]} in a consensus-critical "
                f"path")

        # int() truncation of float arithmetic: the classic rounding
        # chain-splitter (validator powers, batch sizes)
        if isinstance(node.func, ast.Name) and node.func.id == "int" \
                and node.args and self._float_op(node.args[0]):
            self._emit(
                "DT-FLOAT", "int-truncation", node.lineno,
                f"{self.owner} truncates float arithmetic via int() — "
                f"rounding must be integer-exact in consensus paths")

        # .sort() launders order taint in place
        if attr == "sort" and recv is not None:
            self.ordervars.pop(recv, None)

        # accumulating under a set-ordered loop: the accumulator's
        # order is now hash-randomized (accumulating into a SET is fine)
        if self._set_loop and attr in ("append", "extend", "appendleft",
                                       "insert") and recv is not None:
            if recv not in self.setvars:
                self.ordervars[recv] = self._set_loop[-1]

        label = _sink_label(node)
        if label is None:
            return
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            taint = self._taint_of(arg)
            if taint is not None:
                self._emit(
                    taint[0], f"{taint[1]}->{label}", node.lineno,
                    f"{self.owner} feeds a value derived from "
                    f"{taint[1]} into {label}")
            order = self._order_taint_of(arg)
            if order is not None:
                self._emit(
                    "DT-ITER", f"order->{label}", node.lineno,
                    f"{self.owner} feeds a set-iteration-ordered "
                    f"sequence ({order}) into {label}")
        if self._set_loop:
            self._emit(
                "DT-ITER", f"loop->{label}", node.lineno,
                f"{self.owner} calls {label} while {self._set_loop[-1]} "
                f"— per-iteration output lands in hash-randomized order")

    # nested defs/lambdas: analyze separately via the class walker; do
    # not leak this scope's taint into them
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        pass


def _class_set_and_float_fields(cls: ast.ClassDef) -> Tuple[Set[str],
                                                            Set[str]]:
    """Fields assigned set()/frozenset() anywhere in the class, and
    fields that are float-valued (assigned a float constant, or
    assigned from an __init__ parameter whose default is a float)."""
    set_fields: Set[str] = set()
    float_fields: Set[str] = set()
    float_params: Set[str] = set()
    for sub in cls.body:
        if isinstance(sub, ast.FunctionDef) and sub.name == "__init__":
            args = sub.args
            defaults = args.defaults
            pos = args.args[len(args.args) - len(defaults):]
            for a, d in zip(pos, defaults):
                if isinstance(d, ast.Constant) and isinstance(d.value,
                                                              float):
                    float_params.add(a.arg)
    for sub in ast.walk(cls):
        if not isinstance(sub, ast.Assign):
            continue
        for tgt in sub.targets:
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                v = sub.value
                if isinstance(v, ast.Call) \
                        and _last_attr(v.func) in ("set", "frozenset"):
                    set_fields.add(tgt.attr)
                elif isinstance(v, (ast.Set, ast.SetComp)):
                    set_fields.add(tgt.attr)
                elif isinstance(v, ast.Constant) \
                        and isinstance(v.value, float):
                    float_fields.add(tgt.attr)
                elif isinstance(v, ast.Name) and v.id in float_params:
                    float_fields.add(tgt.attr)
    return set_fields, float_fields


def analyze_file(path: str, relpath: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    findings: List[Finding] = []
    imports = _collect_imports(tree)

    def direct_inner_defs(fn):
        """Function defs DIRECTLY inside fn — never descending into
        them, so a def nested two levels down is analyzed exactly once
        (by its own parent's recursion), not once per ancestor."""
        out, stack = [], list(fn.body)
        while stack:
            n = stack.pop(0)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(n)
                continue
            stack.extend(ast.iter_child_nodes(n))
        return out

    def walk_func(fn, owner: str, set_fields=frozenset(),
                  float_fields=frozenset()):
        w = _FuncDet(owner, relpath, set(set_fields), set(float_fields),
                     findings, imports)
        for stmt in fn.body:
            w.visit(stmt)
        # nested functions get their own (taint-isolated) walk
        for inner in direct_inner_defs(fn):
            walk_func(inner, f"{owner}.{inner.name}",
                      set_fields, float_fields)

    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            set_fields, float_fields = _class_set_and_float_fields(node)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    walk_func(sub, f"{node.name}.{sub.name}",
                              set_fields, float_fields)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_func(node, node.name)
    return findings


def collect_files(paths: List[str], root: str) -> List[Tuple[str, str]]:
    """Explicit .py files are taken as-is (fixture corpora); directory
    scans restrict to the consensus-critical module list."""
    out: List[Tuple[str, str]] = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            out.append((ap, os.path.relpath(ap, root)))
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    fp = os.path.join(dirpath, fn)
                    rel = os.path.relpath(fp, root)
                    norm = rel.replace(os.sep, "/")
                    # inside the production tree only the consensus-
                    # critical modules are in scope; anything else
                    # (fixture corpora) scans wholesale
                    if "tendermint_tpu/" in norm + "/" or \
                            norm.startswith("tendermint_tpu"):
                        if any(norm.endswith(sfx)
                               for sfx in CRITICAL_SUFFIXES):
                            out.append((fp, rel))
                    else:
                        out.append((fp, rel))
    return out


DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "determinism_allowlist.json")


def run_check(paths: List[str], root: str,
              allowlist: Dict[str, str]) -> Tuple[List[Finding], dict]:
    files = collect_files(paths, root)
    findings: List[Finding] = []
    errors: List[str] = []
    for path, rel in files:
        try:
            findings.extend(analyze_file(path, rel))
        except SyntaxError as e:
            errors.append(f"{rel}: {e}")
    stale = allowlist_util.apply_allowlist(findings, allowlist)
    summary = allowlist_util.summarize(
        findings, len(files),
        {"stale_allowlist": stale, "parse_errors": errors})
    by_class, by_class_unsup = allowlist_util.counts_by_class(findings)
    summary["by_class"] = by_class
    summary["by_class_unsuppressed"] = by_class_unsup
    return findings, summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: tendermint_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings (baseline mode)")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    ap.add_argument("--all", action="store_true",
                    help="show suppressed findings too")
    args = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = args.paths or [os.path.join(root, "tendermint_tpu")]
    t0 = time.time()
    try:
        allowlist = load_allowlist(args.allowlist)
    except ValueError as e:
        print(f"check_determinism: FAIL: {e}", file=sys.stderr)
        return 2
    findings, summary = run_check(paths, root, allowlist)
    elapsed = time.time() - t0

    if args.json:
        print(json.dumps(
            {"findings": [f.as_dict() for f in findings],
             "summary": summary, "elapsed_s": round(elapsed, 3)},
            indent=1))
    else:
        shown = [f for f in findings
                 if args.all or f.suppressed_by is None]
        shown.sort(key=lambda f: (f.rule, f.path, f.line))
        for f in shown:
            tag = " [allowlisted]" if f.suppressed_by else ""
            print(f"{f.rule}{tag} {f.path}:{f.line}\n  {f.message}\n"
                  f"  key: {f.key}")
        for s in summary["stale_allowlist"]:
            print(f"WARNING: stale allowlist entry (no matching finding):"
                  f" {s}")
        for e in summary["parse_errors"]:
            print(f"WARNING: parse error: {e}")
        verdict = ("OK" if summary["unsuppressed"] == 0
                   and not summary["parse_errors"] else "FAIL")
        print(f"check_determinism: {verdict} — {summary['files']} files, "
              f"{summary['findings']} findings "
              f"({summary['suppressed']} allowlisted, "
              f"{summary['unsuppressed']} unsuppressed) "
              f"in {elapsed:.2f}s")
    # an unparseable critical file means zero rules were checked on it
    # — that is a gate failure, not a warning
    return 0 if (summary["unsuppressed"] == 0
                 and not summary["parse_errors"]) else 1


if __name__ == "__main__":
    sys.exit(main())
