"""Smoke test: does pallas/Mosaic lower and run through axon with the op
mix the straus kernel needs (concat, roll, int32 mul, fori_loop, dynamic
row read)?"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def kernel(wsel_ref, a_ref, b_ref, out_ref):
    a = a_ref[:]
    b = b_ref[:]
    blk = a.shape[1]

    def conv_row(i):
        prod = a[i : i + 1] * b  # (20, blk)
        padded = jnp.concatenate([prod, jnp.zeros((19, blk), jnp.int32)], axis=0)
        return pltpu.roll(padded, i, 0)

    def body(w, acc):
        row = wsel_ref[pl.ds(w, 1), :]  # dynamic row read (1, blk)
        c = conv_row(0)
        for i in range(1, 20):
            c = c + conv_row(i)
        # fold 39 -> 20 like _reduce_conv
        r = c >> 13
        m = c & 8191
        full = jnp.concatenate([m, jnp.zeros((1, blk), jnp.int32)], axis=0) + \
               jnp.concatenate([jnp.zeros((1, blk), jnp.int32), r], axis=0)
        v = full[:20] + 608 * full[20:]
        return acc + v * row

    out_ref[:] = jax.lax.fori_loop(0, 4, body, jnp.zeros_like(a))


B = 512
rng = np.random.default_rng(0)
a = jnp.asarray(rng.integers(0, 8191, (20, B), np.int32))
b = jnp.asarray(rng.integers(0, 8191, (20, B), np.int32))
wsel = jnp.asarray(rng.integers(0, 3, (8, B), np.int32))

fn = pl.pallas_call(
    kernel,
    out_shape=jax.ShapeDtypeStruct((20, B), jnp.int32),
)
out = fn(wsel, a, b)
print("pallas OK:", np.asarray(out).sum() % 100000)
