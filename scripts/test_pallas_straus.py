"""Correctness + perf check: pallas straus vs XLA curve.straus_mul_sub."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto.jaxed25519 import curve, field, pack, pallas_kernels, ref

B = int(sys.argv[1]) if len(sys.argv) > 1 else 1024

rng = np.random.default_rng(42)
s_ints = [int(rng.integers(0, 2**63)) * int(rng.integers(0, 2**63)) % ref.L for _ in range(B)]
k_ints = [int(rng.integers(0, 2**63)) * int(rng.integers(0, 2**63)) % ref.L for _ in range(B)]
a_ints = [int(rng.integers(0, 2**63)) % ref.L for _ in range(B)]

s_limbs = jnp.asarray(
    np.stack([pack.int_to_limbs(v) for v in s_ints], axis=1).astype(np.int32))
k_limbs = jnp.asarray(
    np.stack([pack.int_to_limbs(v) for v in k_ints], axis=1).astype(np.int32))
a_limbs = jnp.asarray(
    np.stack([pack.int_to_limbs(v) for v in a_ints], axis=1).astype(np.int32))

# arbitrary valid curve points: [a]B, negated
pts = jax.jit(curve.fixed_base_mul)(a_limbs)
neg_a = jax.jit(curve.negate)(pts)

xla_fn = jax.jit(curve.straus_mul_sub)
pal_fn = jax.jit(lambda s, k, na: pallas_kernels.straus_mul_sub(s, k, na))

t0 = time.perf_counter()
ref_out = xla_fn(s_limbs, k_limbs, neg_a)
ref_np = [np.asarray(c) for c in ref_out]
print(f"xla compile+run: {time.perf_counter()-t0:.1f}s")

t0 = time.perf_counter()
pal_out = pal_fn(s_limbs, k_limbs, neg_a)
pal_np = [np.asarray(c) for c in pal_out]
print(f"pallas compile+run: {time.perf_counter()-t0:.1f}s")

for name, r, p in zip("XYZT", ref_np, pal_np):
    if not np.array_equal(r, p):
        bad = np.argwhere(r != p)
        print(f"MISMATCH {name}: {bad.shape[0]} cells, first {bad[:5]}")
        sys.exit(1)
print("EXACT MATCH")


def timeit(name, fn, *args, n=5):
    np.asarray(fn(*args)[0]).ravel()[0]
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(fn(*args)[0]).ravel()[0]
        ts.append(time.perf_counter() - t0)
    print(f"{name:28s} {min(ts)*1000:9.2f} ms (wall incl. sync)")


def device_ms(name, fn, *args, k=8):
    def run(k):
        out = None
        for _ in range(k):
            out = fn(*args)
        np.asarray(out[0]).ravel()[0]
    run(1)
    ts1, tsk = [], []
    for _ in range(3):
        t0 = time.perf_counter(); run(1); ts1.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); run(k); tsk.append(time.perf_counter() - t0)
    dev = (min(tsk) - min(ts1)) / (k - 1) * 1000
    print(f"{name:28s} {dev:9.2f} ms (device, slope)")


timeit("xla straus", xla_fn, s_limbs, k_limbs, neg_a)
timeit("pallas straus", pal_fn, s_limbs, k_limbs, neg_a)
device_ms("xla straus", xla_fn, s_limbs, k_limbs, neg_a)
device_ms("pallas straus", pal_fn, s_limbs, k_limbs, neg_a)
