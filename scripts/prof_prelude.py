"""Break the XLA prelude of the packed verify pipeline into stages and
slope-time each on the real chip: (a) byte unpack + SHA block build,
(b) SHA-512 compression, (c) scalar reduce + window extraction.
"""

import os
import secrets
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import keys
from tendermint_tpu.crypto.jaxed25519 import pack, scalar, sha512
from tendermint_tpu.crypto.jaxed25519 import verify as V
from tendermint_tpu.crypto.jaxed25519.curve import _windows_msb_first

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10000

sks = [keys.PrivKeyEd25519.generate() for _ in range(128)]
msgs, sigs, pks = [], [], []
for i in range(N):
    sk = sks[i % len(sks)]
    m = secrets.token_bytes(110)
    msgs.append(m)
    sigs.append(sk.sign(m))
    pks.append(sk.pub_key().bytes())
sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(N, 64)
pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(N, 32)
buf, nb, mrows, bpad = V.pack_buffer(msgs, sig_arr, pk_arr, 1)
dbuf = jax.device_put(buf)


def unpack_stage(buf):
    bdim = buf.shape[-1]
    mlen = buf[0]
    sig_bytes = V._bytes_from_rows(buf[1:17], 64)
    pk_bytes = V._bytes_from_rows(buf[17:25], 32)
    msg_bytes = V._bytes_from_rows(buf[25:], mrows * 4)
    region_len = nb * 128 - 64
    if mrows * 4 < region_len:
        msg_bytes = jnp.concatenate(
            [msg_bytes, jnp.zeros((region_len - mrows * 4, bdim), jnp.int32)], axis=0)
    j = jnp.arange(region_len, dtype=jnp.int32)[:, None]
    inb = (mlen + 64 + 17 + 127) // 128
    region = jnp.where(j < mlen[None, :], msg_bytes, 0)
    region = region + jnp.where(j == mlen[None, :], 0x80, 0)
    bitlen = (mlen + 64) * 8
    base = inb * 128 - 72
    for t in range(8):
        v = (bitlen >> (8 * (7 - t))) & 0xFF
        region = region + jnp.where(j == (base + t)[None, :], v[None, :], 0)
    full = jnp.concatenate([sig_bytes[:32], pk_bytes, region], axis=0)
    f4 = full.astype(jnp.uint32).reshape(nb * 32, 4, bdim)
    words32 = (f4[:, 0] << 24) | (f4[:, 1] << 16) | (f4[:, 2] << 8) | f4[:, 3]
    words = words32.reshape(nb, 16, 2, bdim)
    r_y = V._limbs_from_bytes(sig_bytes[:32])
    s_limbs = V._limbs_from_bytes(sig_bytes[32:64])
    a_y = V._limbs_from_bytes(pk_bytes)
    return words, inb, r_y, s_limbs, a_y


def sha_stage(words, inb):
    return sha512.sha512_batch(words, inb)


def reduce_windows_stage(digest, s_limbs):
    k = scalar.reduce_512(sha512.digest_to_scalar_limbs(digest))
    bdim = k.shape[-1]
    return _windows_msb_first(s_limbs, bdim), _windows_msb_first(k, bdim)


u_j = jax.jit(unpack_stage)
s_j = jax.jit(sha_stage)
r_j = jax.jit(reduce_windows_stage)


def slope(fn, args, k=8):
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    ests = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(k):
            out = fn(*args)
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        tk = time.perf_counter() - t0
        ests.append((tk - t1) / (k - 1) * 1000)
    return sorted(ests)[1]


u_ms = slope(u_j, (dbuf,))
words, inb, r_y, s_limbs, a_y = [jnp.asarray(x) for x in u_j(dbuf)]
sh_ms = slope(s_j, (words, inb))
digest = jnp.asarray(s_j(words, inb))
rw_ms = slope(r_j, (digest, s_limbs))
print(f"N={N}: unpack+blocks {u_ms:.1f} ms, sha512 {sh_ms:.1f} ms, "
      f"reduce+windows {rw_ms:.1f} ms")
