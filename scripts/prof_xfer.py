"""Measure h2d / d2h bandwidth and dispatch latency through the axon tunnel."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

f = jax.jit(lambda x: x * 2 + 1)
g_scalar = jax.jit(lambda x: (x * 2 + 1).sum())

for size in (1 << 10, 1 << 17, 1 << 20, 1 << 23):
    host = np.ones(size // 4, dtype=np.int32)
    # h2d
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        d = jnp.asarray(host)
        d.block_until_ready()
        ts.append(time.perf_counter() - t0)
    h2d = min(ts)
    # d2h of a FRESH computation result (no host cache)
    ts = []
    for _ in range(3):
        out = f(d)
        t0 = time.perf_counter()
        np.asarray(out)
        ts.append(time.perf_counter() - t0)
    d2h = min(ts)
    # dispatch+sync with scalar output only
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(g_scalar(d))
        ts.append(time.perf_counter() - t0)
    disp = min(ts)
    mb = size / 1e6
    print(f"{mb:8.3f} MB  h2d {h2d*1000:8.2f} ms ({mb/h2d:7.1f} MB/s)   "
          f"d2h {d2h*1000:8.2f} ms ({mb/d2h:7.1f} MB/s)   scalar-rt {disp*1000:7.2f} ms")
