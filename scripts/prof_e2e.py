"""End-to-end verify_batch timing on TPU + transfer variant experiments."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10240

from tendermint_tpu.crypto import keys
from tendermint_tpu.crypto.jaxed25519.verify import verify_batch

import secrets

sks = [keys.PrivKeyEd25519.generate() for _ in range(200)]
msgs, sigs, pks, want = [], [], [], []
for i in range(N):
    sk = sks[i % len(sks)]
    msg = secrets.token_bytes(110)
    sig = sk.sign(msg)
    if i % 100 == 37:
        sig = bytes([sig[0] ^ 1]) + sig[1:]
        want.append(False)
    else:
        want.append(True)
    msgs.append(msg)
    sigs.append(sig)
    pks.append(sk.pub_key().bytes())

t0 = time.perf_counter()
got = verify_batch(msgs, sigs, pks)
print(f"first call (compile): {time.perf_counter()-t0:.1f}s")
assert got == want, "mask mismatch"

ts = []
for _ in range(5):
    t0 = time.perf_counter()
    verify_batch(msgs, sigs, pks)
    ts.append((time.perf_counter() - t0) * 1000)
print(f"verify_batch e2e: min {min(ts):.1f} ms  all {[round(t) for t in ts]}")

# breakdown: host packing time
import jax
import jax.numpy as jnp
from tendermint_tpu.crypto.jaxed25519 import pack, verify as V

sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(N, 64)
pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(N, 32)

t0 = time.perf_counter()
s_ok = pack.lt_const_le_batch(sig_arr[:, 32:], V._ref_L())
buf, nb, mrows, bpad = V.pack_buffer(msgs, sig_arr, pk_arr, 1)
host_ms = (time.perf_counter() - t0) * 1000
print(f"host packing: {host_ms:.1f} ms; buf {buf.nbytes/1e6:.2f} MB")

fn = V._jitted_packed(nb, mrows, bpad, 1)

# h2d only
ts = []
for _ in range(3):
    t0 = time.perf_counter()
    d = jnp.asarray(buf)
    d.block_until_ready()
    ts.append((time.perf_counter() - t0) * 1000)
print(f"h2d jnp.asarray: {min(ts):.1f} ms")

# device_put async?
t0 = time.perf_counter()
d2 = jax.device_put(buf)
t_submit = (time.perf_counter() - t0) * 1000
d2.block_until_ready()
t_total = (time.perf_counter() - t0) * 1000
print(f"device_put: submit {t_submit:.1f} ms, ready {t_total:.1f} ms")

# dispatch on resident data + fetch mask
ts = []
for _ in range(3):
    t0 = time.perf_counter()
    mask = fn(d)
    np.asarray(mask)
    ts.append((time.perf_counter() - t0) * 1000)
print(f"dispatch+compute+fetch (data resident): {min(ts):.1f} ms")

# slope device time of the verify kernel itself
def run_k(k):
    out = None
    for _ in range(k):
        out = fn(d)
    np.asarray(out)

run_k(1)
t0 = time.perf_counter(); run_k(1); t1 = time.perf_counter() - t0
t0 = time.perf_counter(); run_k(8); t8 = time.perf_counter() - t0
print(f"verify kernel device time (slope): {(t8-t1)/7*1000:.1f} ms")
