"""Separate per-call dispatch overhead from device compute on axon.

Times k back-to-back dispatches of the same jitted fn (sync once at the
end): slope over k = true per-execution cost; intercept = one-time
dispatch/sync overhead.
"""

import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto.jaxed25519 import curve, field

B = int(sys.argv[1]) if len(sys.argv) > 1 else 10240

rng = np.random.default_rng(0)
a = jnp.asarray(rng.integers(0, 8191, size=(20, B), dtype=np.int32))
b = jnp.asarray(rng.integers(0, 8191, size=(20, B), dtype=np.int32))


@partial(jax.jit, static_argnums=2)
def mul_chain(a, b, n):
    def body(i, v):
        return field.mul(v, b)
    return jax.lax.fori_loop(0, n, body, a)


def run_k(fn, k, *args):
    out = None
    for _ in range(k):
        out = fn(*args)
    return np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0]


def meas(name, fn, *args, ks=(1, 2, 4, 8)):
    run_k(fn, 1, *args)  # compile
    for k in ks:
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            run_k(fn, k, *args)
            ts.append(time.perf_counter() - t0)
        print(f"{name} k={k}: {min(ts)*1000:9.3f} ms")


meas("mul_chain(100)", mul_chain, a, b, 100)
meas("mul_chain(1000)", mul_chain, a, b, 1000)
