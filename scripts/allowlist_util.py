"""allowlist_util — the shared suppression-list discipline for the
static gates (check_concurrency, check_determinism).

Both checkers suppress findings ONLY through a JSON allowlist whose
every entry carries a non-empty justification (an entry is a reviewed
design decision, not a mute button), and both surface stale entries
(no matching finding) so the lists cannot rot. That loading/matching
logic lives here once so the two gates cannot drift on the rules.

Allowlist format::

    {"entries": [{"key": "<finding key>", "justification": "why"}]}
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Set, Tuple


def load_allowlist(path: str) -> Dict[str, str]:
    """{key: justification}; raises ValueError on entries with a
    missing/empty justification — suppression must be explained.
    An empty/missing path means no suppressions."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    entries = doc.get("entries", [])
    out: Dict[str, str] = {}
    for i, e in enumerate(entries):
        key = e.get("key", "")
        just = (e.get("justification") or "").strip()
        if not key:
            raise ValueError(f"allowlist entry {i} has no key")
        if not just:
            raise ValueError(
                f"allowlist entry {key!r} has no justification — "
                f"every suppression must say why")
        out[key] = just
    return out


def apply_allowlist(findings, allowlist: Dict[str, str]) -> List[str]:
    """Mark each finding whose .key is allowlisted (sets .suppressed_by
    to the justification) and return the STALE allowlist keys — entries
    that matched nothing and should be pruned."""
    matched: Set[str] = set()
    for f in findings:
        if f.key in allowlist:
            f.suppressed_by = allowlist[f.key]
            matched.add(f.key)
    return sorted(set(allowlist) - matched)


def summarize(findings, files: int, extra: dict = None) -> dict:
    """The common summary block both checkers report/test against."""
    out = {
        "files": files,
        "findings": len(findings),
        "suppressed": sum(1 for f in findings if f.suppressed_by),
        "unsuppressed": sum(1 for f in findings if not f.suppressed_by),
    }
    if extra:
        out.update(extra)
    return out


def counts_by_class(findings) -> Tuple[Dict[str, int], Dict[str, int]]:
    """({rule: total}, {rule: unsuppressed}) — the detlint metric view."""
    total: Dict[str, int] = {}
    unsup: Dict[str, int] = {}
    for f in findings:
        total[f.rule] = total.get(f.rule, 0) + 1
        if not f.suppressed_by:
            unsup[f.rule] = unsup.get(f.rule, 0) + 1
    return total, unsup
