#!/usr/bin/env python
"""check_concurrency — static lock-discipline analyzer for the threaded
stack.

Parses every Python file under tendermint_tpu/ (no imports, pure AST)
and enforces the concurrency discipline rules (CD-1..CD-7, README
"Correctness tooling") the runtime half (libs/lockdep.py) checks in
live executions:

  CC-GUARD   a field written under a class's lock in some methods is
             read/written bare (or under a different lock) in others
  CC-ORDER   lock-order cycles in the acquisition graph built from
             nested `with` scopes and cross-class calls made while a
             lock is held (plus nested re-entry of a non-reentrant
             Lock, which deadlocks unconditionally)
  CC-BLOCK   blocking calls — sleeps, joins, waits, socket/HTTP I/O,
             subprocess, pairing/XLA dispatch — made while holding a
             lock (the exact shape of the PR-7 absorb_certificate bug)
  CC-THREAD  threading.Thread creations with no termination path: not
             joined anywhere, and the owning class has no
             stop()/shutdown()/close() that joins or signals
  CC-TORN    the PR-10 tearing idiom: data derived from a
             get_round_state() shallow copy flowing into a wire send
             (send/try_send/broadcast) without checking the snapshot's
             `snapshot_consistent` stamp

Findings are suppressed ONLY via scripts/concurrency_allowlist.json;
every entry must carry a non-empty justification string. Keys are
line-number-free so they survive drift. Wired into the test suite as a
tier-1 gate (tests/test_check_concurrency.py, mirroring check_metrics)
and runnable standalone:

    python scripts/check_concurrency.py [--json] [paths...]
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Set, Tuple

# attribute names that read as locks even without a visible
# threading.Lock() assignment (duck-typed / injected locks)
_LOCKISH_RE = re.compile(r"(^|_)(lock|rlock|wlock|mtx|mu)$|_lock$|^mtx$")

# methods named *_locked are the repo's caller-holds-the-lock
# convention: their bodies are analyzed as if every class lock is held
_ASSUME_HELD_SUFFIX = "_locked"

# stop-path method names for CC-THREAD (on_stop: the BaseService hook)
_STOP_NAMES = ("stop", "shutdown", "close", "stop_all", "join", "on_stop")

# wire-send call names for CC-TORN
_SEND_NAMES = {"send", "try_send", "broadcast", "_broadcast"}

# queue-ish receiver names for blocking get/put
_QUEUEISH_RE = re.compile(r"(queue|_q$|^q$)", re.IGNORECASE)
_THREADISH_RE = re.compile(r"(thread|^t\d?$|proc|worker)", re.IGNORECASE)

# method calls that mutate a container field in place — these count as
# WRITES for guard inference (self._cache[k] = v never rebinds _cache)
_MUTATOR_METHODS = {
    "append", "add", "pop", "popleft", "popitem", "update", "setdefault",
    "extend", "remove", "discard", "clear", "insert", "appendleft",
    "set_index", "or_update",
}


def _last_attr(expr) -> Optional[str]:
    """Trailing identifier of a Name/Attribute chain."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _dotted(expr) -> Optional[str]:
    """Render a Name/Attribute chain like self.mempool._lock; None for
    anything more complex (calls, subscripts)."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def _is_threading_lock_call(call: ast.Call) -> Optional[str]:
    """'Lock'/'RLock' if the call is threading.Lock()/RLock() or
    lockdep.leaf_lock() (the lockdep-exempt leaf constructor — still a
    plain Lock for discipline purposes), else None."""
    fn = call.func
    name = _last_attr(fn)
    if name == "leaf_lock":
        return "Lock"
    if name in ("Lock", "RLock"):
        if isinstance(fn, ast.Attribute):
            base = _dotted(fn.value)
            if base not in (None, "threading", "_threading"):
                return None
        return name
    return None


def _is_thread_create(call: ast.Call) -> bool:
    fn = call.func
    if _last_attr(fn) != "Thread":
        return False
    if isinstance(fn, ast.Attribute):
        return _dotted(fn.value) in ("threading", None)
    return True


BLOCKING_PATTERNS: Tuple[Tuple[str, object], ...] = ()


def _classify_blocking(call: ast.Call) -> Optional[str]:
    """A short label when `call` matches the blocking-call allowlist
    (things that may stall the holder for unbounded/IO-scale time)."""
    fn = call.func
    attr = _last_attr(fn)
    if attr is None:
        return None
    recv = fn.value if isinstance(fn, ast.Attribute) else None
    recv_name = _last_attr(recv) if recv is not None else None

    if attr == "sleep" and recv_name in ("time", "_time"):
        return "time.sleep"
    if attr == "wait":
        return ".wait()"
    if attr == "join":
        # str.join is ubiquitous: require a threadish receiver
        if recv_name and _THREADISH_RE.search(recv_name):
            return ".join()"
        return None
    if attr == "result" and recv is not None:
        return "future.result()"
    if attr in ("recv", "recvfrom", "accept", "sendall",
                "create_connection"):
        return f"socket .{attr}()"
    if attr == "connect" and recv_name and "sock" in recv_name.lower():
        return "socket .connect()"
    if attr in ("run", "check_output", "check_call", "call", "Popen") \
            and recv_name == "subprocess":
        return f"subprocess.{attr}"
    if attr == "urlopen":
        return "urlopen"
    if attr == "block_until_ready":
        return "jax block_until_ready"
    if attr in ("fast_aggregate_verify", "aggregate_verify", "pairing",
                "multi_pairing", "pairing_check"):
        return f"BLS {attr}"
    if attr in ("batch_verify", "verify_commit"):
        return f"batched verify {attr}"
    if attr in ("get", "put") and recv_name \
            and _QUEUEISH_RE.search(recv_name):
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return None
        return f"queue .{attr}()"
    return None


class MethodFacts:
    def __init__(self, name: str):
        self.name = name
        # (field, is_write, frozenset(held lock names), lineno)
        self.accesses: List[Tuple[str, bool, frozenset, int]] = []
        # (outer lock, inner lock, lineno) for directly nested withs
        self.nested: List[Tuple[str, str, int]] = []
        # lock names this method acquires directly (any depth)
        self.acquires: Set[str] = set()
        # (held frozenset, receiver kind 'self'|'other', method, lineno)
        self.calls_under_lock: List[Tuple[frozenset, str, str, int]] = []
        # (held frozenset, blocking label, lineno)
        self.blocking: List[Tuple[frozenset, str, int]] = []
        # (lineno, stored name 'self.X'|'X'|None)
        self.thread_creates: List[Tuple[int, Optional[str]]] = []
        self.joins: Set[str] = set()          # names .join() was called on
        self.signals = False                   # .set() / flag = False seen
        self.grs_vars: Set[str] = set()        # names bound to get_round_state()
        self.torn_sends: List[Tuple[str, int]] = []
        self.mentions_gate = False             # snapshot_consistent referenced


class ClassFacts:
    def __init__(self, name: str, path: str, lineno: int):
        self.name = name
        self.path = path
        self.lineno = lineno
        self.lock_fields: Dict[str, str] = {}  # attr -> Lock|RLock
        self.methods: Dict[str, MethodFacts] = {}
        self.bases: List[str] = []


class _FuncWalker(ast.NodeVisitor):
    """Statement walker for one function body, tracking the stack of
    held locks through `with` scopes."""

    def __init__(self, facts: MethodFacts, cls: Optional[ClassFacts],
                 assume_held: frozenset):
        self.f = facts
        self.cls = cls
        self.held: List[str] = list(assume_held)
        self.assumed = frozenset(assume_held)

    # -- lock recognition ---------------------------------------------

    def _lock_name(self, expr) -> Optional[str]:
        """Canonical held-lock name for a with-context expr, or None if
        it isn't a lock. self.X locks use the bare field name; other
        paths keep their dotted spelling."""
        d = _dotted(expr)
        if d is None:
            return None
        if d.startswith("self."):
            attr = d.split(".", 1)[1]
            if "." not in attr:
                if self.cls is not None and attr in self.cls.lock_fields:
                    return attr
                if _LOCKISH_RE.search(attr):
                    return attr
                return None
            # deeper path (self.obj._lock): lockish tail only
            tail = attr.rsplit(".", 1)[-1]
            return d if _LOCKISH_RE.search(tail) else None
        tail = d.rsplit(".", 1)[-1]
        return d if _LOCKISH_RE.search(tail) else None

    # -- visitors ------------------------------------------------------

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            ln = self._lock_name(item.context_expr)
            if ln is not None:
                self.f.acquires.add(ln)
                for h in self.held:
                    self.f.nested.append((h, ln, node.lineno))
                acquired.append(ln)
                self.held.append(ln)
            # the context expr itself may contain calls/accesses
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.f.accesses.append(
                (node.attr, is_write, frozenset(self.held), node.lineno))
        if node.attr == "snapshot_consistent":
            self.f.mentions_gate = True
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        # self.X[k] = v / del self.X[k]: a WRITE to the container field
        if isinstance(node.ctx, (ast.Store, ast.Del)) \
                and isinstance(node.value, ast.Attribute) \
                and isinstance(node.value.value, ast.Name) \
                and node.value.value.id == "self":
            self.f.accesses.append(
                (node.value.attr, True, frozenset(self.held), node.lineno))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        if node.id == "snapshot_consistent":
            self.f.mentions_gate = True

    def visit_Constant(self, node: ast.Constant):
        if node.value == "snapshot_consistent":
            self.f.mentions_gate = True

    def visit_Assign(self, node: ast.Assign):
        # x = <...>.get_round_state(), plus transitive taint: anything
        # computed FROM a snapshot variable (the PR-10 bug built the
        # wire bytes first, then broadcast the local)
        tainted = (isinstance(node.value, ast.Call)
                   and _last_attr(node.value.func) == "get_round_state")
        if not tainted and self.f.grs_vars:
            tainted = any(isinstance(sub, ast.Name)
                          and sub.id in self.f.grs_vars
                          for sub in ast.walk(node.value))
        if tainted:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.f.grs_vars.add(tgt.id)
        # thread creation storage + stop-flag signals
        if isinstance(node.value, ast.Call) \
                and _is_thread_create(node.value):
            stored = None
            for tgt in node.targets:
                d = _dotted(tgt)
                if d is not None:
                    stored = d
            self.f.thread_creates.append((node.lineno, stored))
            node.value._cc_recorded = True
        elif isinstance(node.value, ast.Constant) \
                and node.value.value is False:
            for tgt in node.targets:
                if _dotted(tgt) and _dotted(tgt).startswith("self."):
                    self.f.signals = True
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        attr = _last_attr(node.func)
        recv = node.func.value if isinstance(node.func, ast.Attribute) \
            else None

        if _is_thread_create(node) and not getattr(node, "_cc_recorded",
                                                   False):
            # bare Thread(...) not caught via visit_Assign (passed
            # straight to .start(), appended to a list, ...)
            self.f.thread_creates.append((node.lineno, None))

        if attr == "join" and recv is not None:
            d = _dotted(recv)
            if d is not None:
                self.f.joins.add(d)
        if attr in ("set", "clear") and recv is not None:
            # Event.set() / Event.clear(): both idioms signal loop exit
            self.f.signals = True
        if attr in ("stop", "shutdown", "close") and recv is not None:
            self.f.signals = True

        # in-place container mutation through a method: a WRITE to the
        # receiver field for guard inference
        if attr in _MUTATOR_METHODS and isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self":
            self.f.accesses.append(
                (recv.attr, True, frozenset(self.held), node.lineno))

        held = frozenset(self.held)
        if held:
            label = _classify_blocking(node)
            if label is not None:
                self.f.blocking.append((held, label, node.lineno))
            if attr is not None and recv is not None:
                kind = "self" if (isinstance(recv, ast.Name)
                                  and recv.id == "self") else "other"
                self.f.calls_under_lock.append(
                    (held, kind, attr, node.lineno))

        # torn-snapshot flow: a send-family call whose args reference a
        # get_round_state() binding
        if attr in _SEND_NAMES and self.f.grs_vars:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) \
                            and sub.id in self.f.grs_vars:
                        self.f.torn_sends.append((sub.id, node.lineno))
                        break
        self.generic_visit(node)

    # nested function/lambda bodies execute in an unknown lock context:
    # walk them with an empty held stack but the same fact sink, so
    # their accesses/sends still attribute to the enclosing method
    def visit_FunctionDef(self, node):
        inner = _FuncWalker(self.f, self.cls, frozenset())
        for stmt in node.body:
            inner.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        inner = _FuncWalker(self.f, self.cls, frozenset())
        inner.visit(node.body)


def _collect_lock_fields(cls_node: ast.ClassDef) -> Dict[str, str]:
    fields: Dict[str, str] = {}
    for sub in ast.walk(cls_node):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            kind = _is_threading_lock_call(sub.value)
            if kind is None:
                continue
            for tgt in sub.targets:
                d = _dotted(tgt)
                if d is not None and d.startswith("self.") \
                        and d.count(".") == 1:
                    fields[d.split(".", 1)[1]] = kind
    return fields


def analyze_file(path: str, relpath: str) -> Tuple[List[ClassFacts],
                                                   List[MethodFacts]]:
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    classes: List[ClassFacts] = []
    mod_funcs: List[MethodFacts] = []

    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            cf = ClassFacts(node.name, relpath, node.lineno)
            cf.bases = [b for b in
                        (_last_attr(x) for x in node.bases) if b]
            cf.lock_fields = _collect_lock_fields(node)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    mf = MethodFacts(sub.name)
                    assume = frozenset(cf.lock_fields) \
                        if sub.name.endswith(_ASSUME_HELD_SUFFIX) \
                        else frozenset()
                    w = _FuncWalker(mf, cf, assume)
                    for stmt in sub.body:
                        w.visit(stmt)
                    cf.methods[sub.name] = mf
            classes.append(cf)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mf = MethodFacts(node.name)
            w = _FuncWalker(mf, None, frozenset())
            for stmt in node.body:
                w.visit(stmt)
            mod_funcs.append(mf)
    return classes, mod_funcs


# --- checks -----------------------------------------------------------


class Finding:
    def __init__(self, rule: str, key: str, path: str, line: int,
                 message: str):
        self.rule = rule
        self.key = key
        self.path = path
        self.line = line
        self.message = message
        self.suppressed_by: Optional[str] = None

    def as_dict(self) -> dict:
        return {"rule": self.rule, "key": self.key, "path": self.path,
                "line": self.line, "message": self.message,
                "suppressed": self.suppressed_by is not None}


def check_guarded_fields(cls: ClassFacts) -> List[Finding]:
    if not cls.lock_fields:
        return []
    out: List[Finding] = []
    # guard inference: writes under a self lock, outside construction
    guards: Dict[str, Set[str]] = {}
    for mname, mf in cls.methods.items():
        if mname in ("__init__", "__post_init__"):
            continue
        for field, is_write, held, _ in mf.accesses:
            if not is_write or field in cls.lock_fields:
                continue
            own = {h for h in held if h in cls.lock_fields}
            if own:
                guards.setdefault(field, set()).update(own)
    for field, locks in sorted(guards.items()):
        bad: List[str] = []
        for mname, mf in cls.methods.items():
            if mname in ("__init__", "__post_init__"):
                continue
            for f2, is_write, held, line in mf.accesses:
                if f2 != field:
                    continue
                if not (set(held) & locks):
                    kind = "write" if is_write else "read"
                    bad.append(f"{mname}:{line}({kind})")
        if bad:
            lockdesc = "/".join(f"self.{l}" for l in sorted(locks))
            out.append(Finding(
                "CC-GUARD",
                f"CC-GUARD:{cls.path}:{cls.name}.{field}",
                cls.path, cls.lineno,
                f"{cls.name}.{field} is written under {lockdesc} but "
                f"accessed bare in: {', '.join(sorted(set(bad))[:6])}"
                + (" …" if len(set(bad)) > 6 else "")))
    return out


def _lock_node(cls: ClassFacts, lock: str) -> str:
    return f"{cls.name}.{lock}"


def build_lock_graph(all_classes: List[ClassFacts]) -> Dict[str, dict]:
    """Edges {(a, b): witness} from nested withs + one-hop cross-class
    calls made while holding a lock."""
    # method name -> [(class, direct locks it acquires)]
    method_index: Dict[str, List[Tuple[ClassFacts, Set[str]]]] = {}
    for cls in all_classes:
        for mname, mf in cls.methods.items():
            own = {l for l in mf.acquires if l in cls.lock_fields}
            if own:
                method_index.setdefault(mname, []).append((cls, own))

    edges: Dict[Tuple[str, str], dict] = {}

    def add_edge(a: str, b: str, path: str, line: int, why: str):
        if a == b:
            return
        edges.setdefault((a, b), {"path": path, "line": line, "why": why})

    for cls in all_classes:
        for mname, mf in cls.methods.items():
            for outer, inner, line in mf.nested:
                if outer in cls.lock_fields and inner in cls.lock_fields:
                    add_edge(_lock_node(cls, outer), _lock_node(cls, inner),
                             cls.path, line, f"nested with in {mname}")
            for held, kind, callee, line in mf.calls_under_lock:
                own_held = [h for h in held if h in cls.lock_fields]
                if not own_held:
                    continue
                if kind == "self":
                    targets = [(cls, {l for l in
                                      cls.methods.get(callee,
                                                      MethodFacts(callee))
                                      .acquires if l in cls.lock_fields})] \
                        if callee in cls.methods else []
                else:
                    cands = method_index.get(callee, [])
                    # only unambiguous one-class resolutions
                    targets = cands if len(cands) == 1 else []
                for tcls, tlocks in targets:
                    for tl in tlocks:
                        for h in own_held:
                            add_edge(
                                _lock_node(cls, h), _lock_node(tcls, tl),
                                cls.path, line,
                                f"{cls.name}.{mname} holds self.{h} and "
                                f"calls {tcls.name}.{callee}")
    return edges


def check_lock_order(all_classes: List[ClassFacts]) -> List[Finding]:
    out: List[Finding] = []
    # unconditional deadlock: nested re-entry of a plain Lock
    for cls in all_classes:
        for mname, mf in cls.methods.items():
            for outer, inner, line in mf.nested:
                if outer == inner and cls.lock_fields.get(outer) == "Lock":
                    out.append(Finding(
                        "CC-ORDER",
                        f"CC-ORDER:{cls.path}:{cls.name}.{mname}:"
                        f"reentry.{outer}",
                        cls.path, line,
                        f"{cls.name}.{mname} re-enters non-reentrant "
                        f"Lock self.{outer} (guaranteed deadlock)"))
    edges = build_lock_graph(all_classes)
    # cycle detection over the directed edge set
    adj: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) > 1:
                    cyc = tuple(sorted(path))
                    if cyc not in seen_cycles:
                        seen_cycles.add(cyc)
                        w = edges.get((path[-1], start)) or {}
                        out.append(Finding(
                            "CC-ORDER",
                            "CC-ORDER:cycle:" + "|".join(cyc),
                            w.get("path", "?"), w.get("line", 0),
                            "lock-order cycle: "
                            + " -> ".join(path + [start])
                            + " (" + "; ".join(
                                (edges.get((path[i], path[i + 1]),
                                           edges.get((path[-1], start), {}))
                                 .get("why", "?"))
                                for i in range(len(path) - 1)) + ")"))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))

    for n in list(adj):
        dfs(n)
    return out


def check_blocking(all_classes: List[ClassFacts],
                   mod_funcs_by_file) -> List[Finding]:
    out: List[Finding] = []
    for cls in all_classes:
        for mname, mf in cls.methods.items():
            for held, label, line in mf.blocking:
                own = sorted(h for h in held if h in cls.lock_fields) \
                    or sorted(held)
                out.append(Finding(
                    "CC-BLOCK",
                    f"CC-BLOCK:{cls.path}:{cls.name}.{mname}:{label}",
                    cls.path, line,
                    f"{cls.name}.{mname} calls {label} while holding "
                    + "/".join(f"self.{h}" if "." not in h else h
                               for h in own)))
    for relpath, funcs in mod_funcs_by_file.items():
        for mf in funcs:
            for held, label, line in mf.blocking:
                out.append(Finding(
                    "CC-BLOCK",
                    f"CC-BLOCK:{relpath}:{mf.name}:{label}",
                    relpath, line,
                    f"{mf.name} calls {label} while holding "
                    + "/".join(sorted(held))))
    return out


def check_threads(all_classes: List[ClassFacts],
                  mod_funcs_by_file) -> List[Finding]:
    out: List[Finding] = []
    for cls in all_classes:
        creates = [(m, line, stored)
                   for m, mf in cls.methods.items()
                   for line, stored in mf.thread_creates]
        if not creates:
            continue
        joins: Set[str] = set()
        stop_ok = False
        for mname, mf in cls.methods.items():
            joins |= mf.joins
            if any(mname == s or mname.startswith(s + "_")
                   for s in _STOP_NAMES):
                if mf.joins or mf.signals:
                    stop_ok = True
        # dedup anonymous+stored records for the same line
        seen_lines: Set[int] = set()
        for mname, line, stored in creates:
            if line in seen_lines:
                continue
            seen_lines.add(line)
            if stored is not None and stored in joins:
                continue
            local_join = stored is not None \
                and stored in cls.methods[mname].joins
            if local_join or stop_ok:
                continue
            out.append(Finding(
                "CC-THREAD",
                f"CC-THREAD:{cls.path}:{cls.name}.{mname}",
                cls.path, line,
                f"{cls.name}.{mname} creates a Thread"
                + (f" (stored as {stored})" if stored else "")
                + " but the class has no stop()/shutdown()/close() "
                  "path that joins or signals it"))
    for relpath, funcs in mod_funcs_by_file.items():
        for mf in funcs:
            seen_lines = set()
            for line, stored in mf.thread_creates:
                if line in seen_lines:
                    continue
                seen_lines.add(line)
                if stored is not None and stored in mf.joins:
                    continue
                out.append(Finding(
                    "CC-THREAD",
                    f"CC-THREAD:{relpath}:{mf.name}",
                    relpath, line,
                    f"module function {mf.name} creates a Thread it "
                    f"never joins"))
    return out


def check_torn(all_classes: List[ClassFacts],
               mod_funcs_by_file) -> List[Finding]:
    out: List[Finding] = []

    def scan(mf: MethodFacts, owner: str, relpath: str):
        if not mf.torn_sends or mf.mentions_gate:
            return
        var, line = mf.torn_sends[0]
        out.append(Finding(
            "CC-TORN",
            f"CC-TORN:{relpath}:{owner}",
            relpath, line,
            f"{owner} sends wire data derived from a get_round_state() "
            f"snapshot ({var}) without checking snapshot_consistent "
            f"(PR-10 torn-read idiom, rule CD-5)"))

    for cls in all_classes:
        for mname, mf in cls.methods.items():
            scan(mf, f"{cls.name}.{mname}", cls.path)
    for relpath, funcs in mod_funcs_by_file.items():
        for mf in funcs:
            scan(mf, mf.name, relpath)
    return out


# --- allowlist + driver ----------------------------------------------


DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "concurrency_allowlist.json")

# the justification/stale-entry discipline is shared with
# check_determinism (scripts/allowlist_util.py) so the gates can't
# drift; load_allowlist stays exported under its historical name
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import allowlist_util  # noqa: E402

load_allowlist = allowlist_util.load_allowlist


def collect_files(paths: List[str], root: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            out.append((ap, os.path.relpath(ap, root)))
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__",)]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        fp = os.path.join(dirpath, fn)
                        out.append((fp, os.path.relpath(fp, root)))
    return out


def run_check(paths: List[str], root: str,
              allowlist: Dict[str, str]) -> Tuple[List[Finding], dict]:
    all_classes: List[ClassFacts] = []
    mod_funcs_by_file: Dict[str, List[MethodFacts]] = {}
    files = collect_files(paths, root)
    errors: List[str] = []
    for path, rel in files:
        try:
            classes, mod_funcs = analyze_file(path, rel)
        except SyntaxError as e:
            errors.append(f"{rel}: {e}")
            continue
        all_classes.extend(classes)
        if mod_funcs:
            mod_funcs_by_file[rel] = mod_funcs

    findings: List[Finding] = []
    for cls in all_classes:
        findings.extend(check_guarded_fields(cls))
    findings.extend(check_lock_order(all_classes))
    findings.extend(check_blocking(all_classes, mod_funcs_by_file))
    findings.extend(check_threads(all_classes, mod_funcs_by_file))
    findings.extend(check_torn(all_classes, mod_funcs_by_file))

    stale = allowlist_util.apply_allowlist(findings, allowlist)
    summary = allowlist_util.summarize(
        findings, len(files),
        {"classes": len(all_classes), "stale_allowlist": stale,
         "parse_errors": errors})
    return findings, summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: tendermint_tpu/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings (baseline mode)")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    ap.add_argument("--all", action="store_true",
                    help="show suppressed findings too")
    args = ap.parse_args(argv)

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = args.paths or [os.path.join(root, "tendermint_tpu")]
    t0 = time.time()
    try:
        allowlist = load_allowlist(args.allowlist)
    except ValueError as e:
        print(f"check_concurrency: FAIL: {e}", file=sys.stderr)
        return 2
    findings, summary = run_check(paths, root, allowlist)
    elapsed = time.time() - t0

    if args.json:
        print(json.dumps(
            {"findings": [f.as_dict() for f in findings],
             "summary": summary, "elapsed_s": round(elapsed, 3)},
            indent=1))
    else:
        shown = [f for f in findings
                 if args.all or f.suppressed_by is None]
        shown.sort(key=lambda f: (f.rule, f.path, f.line))
        for f in shown:
            tag = " [allowlisted]" if f.suppressed_by else ""
            print(f"{f.rule}{tag} {f.path}:{f.line}\n  {f.message}\n"
                  f"  key: {f.key}")
        for s in summary["stale_allowlist"]:
            print(f"WARNING: stale allowlist entry (no matching finding):"
                  f" {s}")
        for e in summary["parse_errors"]:
            print(f"WARNING: parse error: {e}")
        verdict = ("OK" if summary["unsuppressed"] == 0 else "FAIL")
        print(f"check_concurrency: {verdict} — {summary['files']} files, "
              f"{summary['classes']} classes, "
              f"{summary['findings']} findings "
              f"({summary['suppressed']} allowlisted, "
              f"{summary['unsuppressed']} unsuppressed) "
              f"in {elapsed:.2f}s")
    return 0 if summary["unsuppressed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
