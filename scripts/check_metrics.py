#!/usr/bin/env python
"""check_metrics — boot a node in-process, scrape /metrics, and validate
the exposition.

Guards the observability subsystem end-to-end: a single-validator
kvstore node runs until it has committed a few blocks, then the
Prometheus endpoint is scraped and the body is run through a *strict*
text-exposition (v0.0.4) parser — the kind of errors a real Prometheus
server would reject (samples for undeclared families, labeled families
rendering label-less samples, duplicate series, non-monotonic histogram
buckets, `_count` != `+Inf` bucket) fail the check, not just malformed
lines. Finally the families the hot path must expose (crypto
batch-verify, consensus step durations) are asserted present.

Wired into the test suite as a tier-1 test (tests/test_check_metrics.py)
and runnable standalone:

    python scripts/check_metrics.py [--blocks N] [--timeout SECS]
"""

from __future__ import annotations

import argparse
import math
import re
import sys
import tempfile
import time
import urllib.request

_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^(?P<name>{_NAME_RE})"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)"
    r"(?: (?P<ts>-?[0-9]+))?$"
)
_LABEL_RE = re.compile(
    rf'\s*(?P<name>{_NAME_RE})="(?P<value>(?:[^"\\]|\\.)*)"\s*(?:,|$)'
)
_HELP_RE = re.compile(rf"^# HELP (?P<name>{_NAME_RE}) (?P<doc>.*)$")
_TYPE_RE = re.compile(
    rf"^# TYPE (?P<name>{_NAME_RE}) "
    r"(?P<type>counter|gauge|histogram|summary|untyped)$"
)


class ExpositionError(Exception):
    """One strict-parse violation, with the offending line number."""


def _parse_labels(raw: str, lineno: int) -> tuple:
    labels, pos = [], 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise ExpositionError(f"line {lineno}: bad label syntax: {{{raw}}}")
        labels.append((m.group("name"), m.group("value")))
        pos = m.end()
    names = [n for n, _ in labels]
    if len(names) != len(set(names)):
        raise ExpositionError(f"line {lineno}: duplicate label name: {{{raw}}}")
    return tuple(sorted(labels))


def _parse_value(raw: str, lineno: int) -> float:
    try:
        return float(raw)  # accepts Inf/-Inf/NaN spellings too
    except ValueError:
        raise ExpositionError(f"line {lineno}: bad sample value: {raw!r}")


def parse_exposition(text: str) -> dict:
    """Strictly parse Prometheus text format v0.0.4.

    Returns {family: {"type": str, "samples": {(name, labelset): value}}}.
    Raises ExpositionError on the first violation.
    """
    if not text.endswith("\n"):
        raise ExpositionError("exposition must end with a newline")
    families: dict = {}
    seen_series: set = set()

    def family_of(name: str):
        fam = families.get(name)
        if fam is not None:
            return name, fam
        # histogram/summary component samples
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                fam = families.get(base)
                if fam is not None and fam["type"] in ("histogram", "summary"):
                    if suffix == "_bucket" and fam["type"] == "summary":
                        break
                    return base, fam
        return None, None

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m:
                fam = families.setdefault(
                    m.group("name"),
                    {"type": None, "help": None, "samples": {}})
                if fam["help"] is not None:
                    raise ExpositionError(
                        f"line {lineno}: second HELP for {m.group('name')}")
                fam["help"] = m.group("doc")
                continue
            m = _TYPE_RE.match(line)
            if m:
                fam = families.setdefault(
                    m.group("name"),
                    {"type": None, "help": None, "samples": {}})
                if fam["type"] is not None:
                    raise ExpositionError(
                        f"line {lineno}: second TYPE for {m.group('name')}")
                if fam["samples"]:
                    raise ExpositionError(
                        f"line {lineno}: TYPE after samples for "
                        f"{m.group('name')}")
                fam["type"] = m.group("type")
                continue
            raise ExpositionError(f"line {lineno}: malformed comment: {line}")
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ExpositionError(f"line {lineno}: malformed sample: {line}")
        name = m.group("name")
        labels = _parse_labels(m.group("labels") or "", lineno)
        value = _parse_value(m.group("value"), lineno)
        base, fam = family_of(name)
        if fam is None or fam["type"] is None:
            raise ExpositionError(
                f"line {lineno}: sample {name} has no preceding # TYPE")
        series = (name, labels)
        if series in seen_series:
            raise ExpositionError(f"line {lineno}: duplicate series: {line}")
        seen_series.add(series)
        fam["samples"][series] = value

    _check_histograms(families)
    return families


def _check_histograms(families: dict) -> None:
    for base, fam in families.items():
        if fam["type"] != "histogram":
            continue
        # group buckets by their non-le labelset
        groups: dict = {}
        for (name, labels), value in fam["samples"].items():
            rest = tuple(l for l in labels if l[0] != "le")
            g = groups.setdefault(rest, {"buckets": [], "sum": None,
                                         "count": None})
            if name == base + "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    raise ExpositionError(
                        f"{base}: bucket sample without le label")
                g["buckets"].append((float(le), value))
            elif name == base + "_sum":
                g["sum"] = value
            elif name == base + "_count":
                g["count"] = value
        for rest, g in groups.items():
            where = f"{base}{dict(rest) if rest else ''}"
            if not g["buckets"]:
                raise ExpositionError(f"{where}: histogram with no buckets")
            g["buckets"].sort(key=lambda b: b[0])
            counts = [c for _, c in g["buckets"]]
            if any(b > a for a, b in zip(counts[1:], counts)):
                raise ExpositionError(
                    f"{where}: bucket counts not monotonic: {counts}")
            les = [le for le, _ in g["buckets"]]
            if not math.isinf(les[-1]):
                raise ExpositionError(f"{where}: missing +Inf bucket")
            if g["count"] is None or g["sum"] is None:
                raise ExpositionError(f"{where}: missing _count/_sum")
            if counts[-1] != g["count"]:
                raise ExpositionError(
                    f"{where}: +Inf bucket {counts[-1]:g} != "
                    f"_count {g['count']:g}")


# families the observability PR promises; the check fails if the node
# stops exposing any of them (namespace-prefixed at runtime)
REQUIRED_FAMILIES = (
    "consensus_height",
    "consensus_step_duration_seconds",
    "crypto_batch_verify_seconds",
    "crypto_batch_size",
    "crypto_signatures_verified_total",
    # PR-2 async/cache families (declaration only: a node that commits
    # blocks without duplicate gossip may legitimately record no hits)
    "crypto_sig_cache_hits_total",
    "crypto_sig_cache_misses_total",
    "crypto_inflight_batches",
    "crypto_pipeline_overlap_seconds",
    "state_block_processing_time",
    # PR-3 watchdog + per-peer network telemetry (peer-labeled families
    # legitimately render no samples on a peerless node — declaration
    # presence is the contract; pruning removes series, never families)
    "consensus_round_dwell_seconds",
    "consensus_stalls_total",
    "p2p_peers",
    "p2p_peer_receive_bytes_total",
    "p2p_peer_send_bytes_total",
    "p2p_peer_msg_recv_total",
    "p2p_peer_lag_blocks",
    # PR-4 state sync (declaration presence: a node that never produces
    # or restores snapshots legitimately records no samples)
    "statesync_snapshots",
    "statesync_snapshot_height",
    "statesync_chunks_served_total",
    "statesync_chunks_received_total",
    "statesync_chunks_rejected_total",
    "statesync_restore_chunks_applied",
    "statesync_restore_phase_seconds",
    # PR-5 ABCI resilience: per-request deadlines + supervised reconnect
    # (timeouts/reconnects legitimately record nothing on a healthy
    # node; conn_state and request durations are always live)
    "abci_request_duration_seconds",
    "abci_request_timeouts_total",
    "abci_reconnects_total",
    "abci_conn_state",
    "mempool_recheck_failures_total",
    "wal_corrupted_records_total",
    # PR-6 high-throughput mempool (lane/ingest families legitimately
    # record no samples until txs flow; declaration presence is the
    # contract, as with the other families above)
    "mempool_size",
    "mempool_recheck_times",
    "mempool_lane_depth",
    "mempool_checktx_batch_size",
    "mempool_ingest_queue_wait_seconds",
    "mempool_preverify_cache_hits_total",
    "mempool_preverify_rejected_total",
    "mempool_recheck_skipped_total",
    # PR-7 BLS aggregate fast lane (declaration presence: Ed25519 chains
    # legitimately never record aggregate samples)
    "crypto_agg_verify_seconds",
    "crypto_agg_signers",
    "consensus_agg_gossip_merges_total",
    "agg_commit_size_bytes",
    # PR-8 compile-once kernels (declaration presence: a cpu-backend
    # node never compiles and a fully warm node never misses; the
    # coalescer records nothing with the window at its default 0)
    "crypto_compile_seconds",
    "crypto_compile_cache_hits_total",
    "crypto_compile_cache_misses_total",
    "crypto_coalesced_calls_total",
    # PR-9 RPC fan-out serving (declaration presence: a node with
    # caching off or no websocket subscribers legitimately records no
    # samples; rpc_ws_dropped_total only fires under slow clients)
    "rpc_cache_hits_total",
    "rpc_cache_misses_total",
    "rpc_cache_bytes",
    "rpc_ws_subscribers",
    "rpc_ws_dropped_total",
    "rpc_events_rendered_total",
    # PR-10 chaos engine + churn workload (declaration presence: a node
    # with no installed fault plan injects nothing, a stable valset
    # records no churn, and reconnect attempts need a dropped
    # persistent peer)
    "chaos_injected_total",
    "chaos_active_rules",
    "churn_validator_updates_total",
    "churn_valset_changes_total",
    "p2p_reconnect_attempts_total",
    # PR-11 runtime lockdep (declaration presence: samples flow only
    # under [instrumentation] lockdep = true — the chaos-under-lockdep
    # scenarios are where these families go live)
    "lockdep_hold_seconds",
    "lockdep_inversions_total",
    # PR-12 parallel block execution (declaration presence: with the
    # default [execution] serial config, lanes reads 1 and the conflict/
    # speculation counters legitimately never record)
    "exec_parallel_lanes",
    "exec_conflicts_total",
    "exec_speculation_hits_total",
    "exec_speculation_wasted_total",
    # PR-13 commit-path batching: per-stage commit profiler (live once
    # blocks commit — execute/events/mempool_update record on every
    # apply_block; index needs an indexing node, wal a consensus WAL)
    "commit_stage_seconds",
    # PR-14 crash-consistency engine (declaration presence: a clean
    # boot replays nothing, recovery_time records one sample per boot,
    # and storage faults flow only under an armed [storage] fault_plan)
    "recovery_replayed_blocks_total",
    "recovery_time_seconds",
    "storage_faults_injected_total",
    # PR-15 determinism gate (declaration presence: samples flow only
    # when a check_determinism lint or detcheck oracle run is driven
    # in-process — bench.py detcheck, the test gates, scenario runs;
    # divergence counters staying at zero IS the healthy signal)
    "detlint_findings_total",
    "detcheck_runs_total",
    "detcheck_divergence_total",
    # PR-16 exec-lane flight recorder (declaration presence: samples
    # flow only on the threaded exec path — parallel_lanes=1 nodes
    # structurally never record, which is the zero-overhead contract)
    "exec_lane_wakeup_seconds",
    "exec_lane_busy_ratio",
    # PR-17 Block-STM engine: conflict-cone retry + work-stealing pool
    "exec_lane_retries_total",
    "exec_lane_steals_total",
    # PR-18 incident observatory (declaration presence: MTTD/MTTR
    # histograms record only when the ledger pairs an injected fault
    # with a detection/fresh-commit; a fault-free node records nothing
    # and incident_open reads 0 — the healthy signal)
    "incident_detection_seconds",
    "incident_recovery_seconds",
    "incident_open",
    # PR-19 Handel aggregation overlay (declaration presence: every
    # family stays silent on Ed25519 chains and with [handel] off —
    # absence of samples is the disabled signal)
    "handel_level",
    "handel_contributions_total",
    "handel_verify_seconds",
    "handel_pruned_peers_total",
    # PR-20 replica fan-out tree (declaration presence: every family
    # stays silent on full nodes — absence of samples is the
    # flat-topology signal)
    "replica_tree_depth",
    "replica_parent_switches_total",
    "replica_lag_blocks",
)

# ...and of those, the hot-path families that must have RECORDED samples
# after blocks committed — HELP/TYPE render for registered metrics even
# with no children, so a declaration check alone would pass with the
# crypto/step wiring (batch.set_metrics, _step_span) silently broken
REQUIRED_LIVE_FAMILIES = (
    "consensus_step_duration_seconds",
    "crypto_batch_verify_seconds",
    "crypto_signatures_verified_total",
)


def check_body(body: str, namespace: str = "tendermint",
               require_live: bool = True) -> dict:
    """Parse + validate one /metrics body; returns the parsed families.

    require_live additionally demands a positive sample in each hot-path
    family — only meaningful for a scrape taken after ≥1 committed block."""
    families = parse_exposition(body)
    missing = [f"{namespace}_{f}" for f in REQUIRED_FAMILIES
               if f"{namespace}_{f}" not in families]
    if missing:
        raise ExpositionError(f"missing metric families: {missing}")
    # help-text lint: every registered family must document itself —
    # a scrape full of nameless numbers is unusable at 3am
    undocumented = [name for name, fam in families.items()
                    if not (fam.get("help") or "").strip()]
    if undocumented:
        raise ExpositionError(
            f"metric families without help text: {undocumented}")
    if require_live:
        dead = [f"{namespace}_{f}" for f in REQUIRED_LIVE_FAMILIES
                if not any(v > 0 for v in
                           families[f"{namespace}_{f}"]["samples"].values())]
        if dead:
            raise ExpositionError(
                f"metric families declared but never recorded: {dead}")
    return families


# --- README drift lint ----------------------------------------------
#
# The README's metric tables and REQUIRED_FAMILIES drift independently:
# a new PR adds a family here and forgets the docs, or a doc row
# outlives a renamed metric. The lint closes the loop both ways:
#   1. every REQUIRED_FAMILIES entry must appear in some README table
#      row (first cell, backticked, `tendermint_` prefix optional);
#   2. every README table row written WITH the `tendermint_` prefix
#      (the explicit "this is a contract family" spelling, used by the
#      reference table) must still be in REQUIRED_FAMILIES.
# Unprefixed rows not in REQUIRED_FAMILIES are fine — the README also
# documents real-but-unrequired families (e.g. flowrate gauges).

_TABLE_NAME_RE = re.compile(r"`(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)`")


def readme_metric_rows(readme_text: str) -> list:
    """Backticked metric names from the FIRST cell of markdown table
    rows, as (name, was_prefixed) pairs with the namespace stripped."""
    rows = []
    for line in readme_text.splitlines():
        if not line.lstrip().startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 3:
            continue
        first = cells[1]
        if set(first.strip()) <= {"-", ":", " "}:  # separator row
            continue
        for m in _TABLE_NAME_RE.finditer(first):
            name = m.group("name")
            prefixed = name.startswith("tendermint_")
            if prefixed:
                name = name[len("tendermint_"):]
            rows.append((name, prefixed))
    return rows


def check_readme_drift(readme_text: str,
                       families=REQUIRED_FAMILIES) -> list:
    """Both directions of REQUIRED_FAMILIES <-> README drift; returns a
    list of human-readable problems (empty = in sync)."""
    rows = readme_metric_rows(readme_text)
    documented = {name for name, _ in rows}
    problems = []
    undocumented = sorted(f for f in families if f not in documented)
    if undocumented:
        problems.append(
            "families required by check_metrics but missing from the "
            f"README metric tables: {undocumented}")
    stale = sorted({name for name, prefixed in rows
                    if prefixed and name not in families})
    if stale:
        problems.append(
            "tendermint_-prefixed README table rows not in "
            f"REQUIRED_FAMILIES (renamed or removed?): {stale}")
    return problems


def run_readme_drift(readme_path: str = None) -> list:
    import os

    if readme_path is None:
        readme_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "README.md")
    with open(readme_path, encoding="utf-8") as f:
        return check_readme_drift(f.read())


def run_node_and_scrape(blocks: int = 3, timeout: float = 60.0) -> str:
    """Boot a single-validator kvstore node with instrumentation on,
    wait for `blocks` commits, return the /metrics body."""
    import os

    os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
    os.environ.setdefault("TM_TPU_WARMUP", "0")

    # standalone `python scripts/check_metrics.py` from anywhere
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)

    from tendermint_tpu import config as cfg
    from tendermint_tpu.node import default_new_node
    from tendermint_tpu.p2p import NodeKey
    from tendermint_tpu.privval import load_or_gen_file_pv
    from tendermint_tpu.types import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.event_bus import (
        EVENT_NEW_BLOCK,
        query_for_event,
    )

    with tempfile.TemporaryDirectory(prefix="check_metrics_") as root:
        c = cfg.test_config()
        c.set_root(root)
        c.base.proxy_app = "kvstore"
        c.base.moniker = "check-metrics"
        c.rpc.laddr = ""
        c.p2p.laddr = "tcp://127.0.0.1:0"
        c.consensus.wal_path = "data/cs.wal/wal"
        c.instrumentation.prometheus = True
        c.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        cfg.ensure_root(root)
        NodeKey.load_or_gen(c.base.node_key_path())
        pv = load_or_gen_file_pv(c.base.priv_validator_path())
        GenesisDoc(
            chain_id="check-metrics-chain",
            genesis_time=time.time_ns() - 10**9,
            validators=[GenesisValidator(pv.get_pub_key(), 10)],
        ).save(c.base.genesis_path())

        node = default_new_node(c)
        sub = node.event_bus.subscribe(
            "check-metrics", query_for_event(EVENT_NEW_BLOCK), 16)
        node.start()
        try:
            height, deadline = 0, time.time() + timeout
            while height < blocks and time.time() < deadline:
                msg = sub.get(timeout=1.0)
                if msg is not None:
                    height = msg.data["block"].header.height
            if height < blocks:
                raise RuntimeError(
                    f"node committed only {height}/{blocks} blocks "
                    f"in {timeout:g}s")
            addr = node._metrics_server.listen_addr
            with urllib.request.urlopen(
                    f"http://{addr}/metrics", timeout=10) as resp:
                ctype = resp.headers.get("Content-Type", "")
                if "text/plain" not in ctype:
                    raise RuntimeError(f"bad content type: {ctype}")
                return resp.read().decode()
        finally:
            node.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--blocks", type=int, default=3,
                    help="blocks to commit before scraping (default 3)")
    ap.add_argument("--timeout", type=float, default=60.0,
                    help="seconds to wait for the blocks (default 60)")
    args = ap.parse_args(argv)
    drift = run_readme_drift()
    if drift:
        for p in drift:
            print(f"check_metrics: README drift: {p}", file=sys.stderr)
        return 1
    try:
        body = run_node_and_scrape(args.blocks, args.timeout)
        families = check_body(body)
    except (ExpositionError, RuntimeError) as e:
        print(f"check_metrics: FAIL: {e}", file=sys.stderr)
        return 1
    n_series = sum(len(f["samples"]) for f in families.values())
    print(f"check_metrics: OK — {len(families)} families, "
          f"{n_series} series, README tables in sync, "
          f"strict exposition parse clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
