"""Split device time of the packed verify pipeline: XLA prelude (unpack,
SHA-512, scalar reduce, window build) vs the fused pallas tail.

Run on real TPU (no platform override). Slope-timed like prof_calls.py.
"""

import os
import secrets
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto import keys
from tendermint_tpu.crypto.jaxed25519 import pack, pallas_kernels, scalar, sha512
from tendermint_tpu.crypto.jaxed25519 import verify as V
from tendermint_tpu.crypto.jaxed25519.curve import _windows_msb_first

N = int(sys.argv[1]) if len(sys.argv) > 1 else 10000

sks = [keys.PrivKeyEd25519.generate() for _ in range(256)]
msgs, sigs, pks = [], [], []
for i in range(N):
    sk = sks[i % len(sks)]
    m = secrets.token_bytes(110)
    msgs.append(m)
    sigs.append(sk.sign(m))
    pks.append(sk.pub_key().bytes())

sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(N, 64)
pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(N, 32)
buf, nb, mrows, bpad = V.pack_buffer(msgs, sig_arr, pk_arr, 1)
dbuf = jax.device_put(buf)


def prelude(buf):
    """Everything _verify_packed_core does before the pallas tail,
    ending in the tail's actual inputs."""
    bdim = buf.shape[-1]
    mlen = buf[0]
    sig_bytes = V._bytes_from_rows(buf[1:17], 64)
    pk_bytes = V._bytes_from_rows(buf[17:25], 32)
    msg_bytes = V._bytes_from_rows(buf[25:], mrows * 4)
    region_len = nb * 128 - 64
    if mrows * 4 < region_len:
        msg_bytes = jnp.concatenate(
            [msg_bytes, jnp.zeros((region_len - mrows * 4, bdim), jnp.int32)], axis=0)
    j = jnp.arange(region_len, dtype=jnp.int32)[:, None]
    inb = (mlen + 64 + 17 + 127) // 128
    region = jnp.where(j < mlen[None, :], msg_bytes, 0)
    region = region + jnp.where(j == mlen[None, :], 0x80, 0)
    bitlen = (mlen + 64) * 8
    base = inb * 128 - 72
    for t in range(8):
        v = (bitlen >> (8 * (7 - t))) & 0xFF
        region = region + jnp.where(j == (base + t)[None, :], v[None, :], 0)
    full = jnp.concatenate([sig_bytes[:32], pk_bytes, region], axis=0)
    f4 = full.astype(jnp.uint32).reshape(nb * 32, 4, bdim)
    words32 = (f4[:, 0] << 24) | (f4[:, 1] << 16) | (f4[:, 2] << 8) | f4[:, 3]
    words = words32.reshape(nb, 16, 2, bdim)
    r_y = V._limbs_from_bytes(sig_bytes[:32])
    r_sign = (r_y[19] >> 8) & 1
    r_y = r_y.at[19].set(r_y[19] & 0xFF)
    s_limbs = V._limbs_from_bytes(sig_bytes[32:64])
    a_y = V._limbs_from_bytes(pk_bytes)
    a_sign = (a_y[19] >> 8) & 1
    a_y = a_y.at[19].set(a_y[19] & 0xFF)
    digest = sha512.sha512_batch(words, inb)
    k = scalar.reduce_512(sha512.digest_to_scalar_limbs(digest))
    s_win = _windows_msb_first(s_limbs, bdim)
    k_win = _windows_msb_first(k, bdim)
    return a_y, a_sign, r_y, r_sign, s_win, k_win


prelude_j = jax.jit(prelude)


def tail(a_y, a_sign, r_y, r_sign, s_win, k_win):
    bdim = a_y.shape[-1]
    btab = jnp.asarray(pallas_kernels._btab_np())
    mask = pallas_kernels._verify_tail_call(bdim, False)(
        a_y, a_sign.reshape(1, bdim), r_y, r_sign.reshape(1, bdim),
        s_win, k_win, btab)
    return mask


tail_j = jax.jit(tail)


def slope(fn, args, k=6):
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: np.asarray(x), out)
    t0 = time.perf_counter()
    out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    t1 = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(k):
        out = fn(*args)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    tk = time.perf_counter() - t0
    return (tk - t1) / (k - 1) * 1000


pre_ms = slope(prelude_j, (dbuf,))
pre_out = prelude_j(dbuf)
pre_out = tuple(jnp.asarray(x) for x in pre_out)
tail_ms = slope(tail_j, pre_out)
full = V._jitted_packed(nb, mrows, bpad, 1)
full_ms = slope(full, (dbuf,))
print(f"N={N} bpad={bpad}: prelude {pre_ms:.1f} ms, pallas tail {tail_ms:.1f} ms, "
      f"full pipeline {full_ms:.1f} ms")
