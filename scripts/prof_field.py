"""Microbenchmark: decompose the 10k-sig verify cost on the real chip.

Times dependent chains of each primitive at the bench batch size so the
per-op device cost (including any HBM round-trips XLA fails to fuse) is
visible. Run: python scripts/prof_field.py [B]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.crypto.jaxed25519 import curve, field

B = int(sys.argv[1]) if len(sys.argv) > 1 else 10240


def _sync(out):
    # d2h fetch of one element: block_until_ready alone does not appear to
    # wait through the axon tunnel
    leaves = jax.tree_util.tree_leaves(out)
    return np.asarray(leaves[0]).ravel()[0]


def timeit(name, fn, *args, n=3):
    _sync(fn(*args))
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        _sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    ms = min(ts) * 1000
    print(f"{name:38s} {ms:9.3f} ms")
    return ms


rng = np.random.default_rng(0)
a = jnp.asarray(rng.integers(0, 8191, size=(20, B), dtype=np.int32))
b = jnp.asarray(rng.integers(0, 8191, size=(20, B), dtype=np.int32))


from functools import partial


@partial(jax.jit, static_argnums=2)
def mul_chain(a, b, n):
    def body(i, v):
        return field.mul(v, b)
    return jax.lax.fori_loop(0, n, body, a)


@partial(jax.jit, static_argnums=1)
def sq_chain(a, n):
    def body(i, v):
        return field.square(v)
    return jax.lax.fori_loop(0, n, body, a)


@partial(jax.jit, static_argnums=2)
def add_chain(a, b, n):
    def body(i, v):
        return field.add(v, b)
    return jax.lax.fori_loop(0, n, body, a)


@jax.jit
def dbl_chain(a, b):
    p = (a, b, a, b)
    def body(i, p):
        return curve.double(p)
    return jax.lax.fori_loop(0, 20, body, p)


@jax.jit
def straus(a, b):
    pt = curve.identity_p3_like(a)
    pt = (a, b, pt[1], a)  # junk point; cost is shape-driven
    return curve.straus_mul_sub(a, b, pt)


rt = timeit("pure d2h fetch (round trip)", lambda x: x, a)
m100 = timeit("100x field.mul (dependent)", mul_chain, a, b, 100)
m1k = timeit("1000x field.mul", mul_chain, a, b, 1000)
s1k = timeit("1000x field.square", sq_chain, a, 1000)
a1k = timeit("1000x field.add", add_chain, a, b, 1000)
d20 = timeit("20x curve.double", dbl_chain, a, b)
st = timeit("straus_mul_sub (full)", straus, a, b)

mul_us = (m1k - m100) / 900 * 1000
print(f"\nround-trip overhead : {rt:8.1f} ms")
print(f"per field.mul (slope): {mul_us:8.1f} us")
print(f"per field.square     : {(s1k-rt)/1000*1000:8.1f} us")
print(f"per field.add        : {(a1k-rt)/1000*1000:8.1f} us")
print(f"straus compute       : {st-rt:8.1f} ms  (expect ~{(252*7+64*8+64*8)*mul_us/1000:.0f} ms if mul-bound)")

# HBM roofline: one mul reads 2x(20,B)x4B, writes (20,B)x4B
bytes_per_mul = 3 * 20 * B * 4
print(f"min HBM traffic/mul: {bytes_per_mul/1e6:.2f} MB -> at 800GB/s = {bytes_per_mul/800e9*1e6:.1f} us")
