// nativedb — C++ log-structured KV store with a C API for ctypes.
//
// Native-equivalent of the reference's cgo→C++ LevelDB binding
// (libs/db/c_level_db.go, build tag `gcc`): same DB-interface surface
// (get/put/delete/ordered iteration/batch/sync) behind a tiny C ABI.
//
// Design: append-only data log + in-memory ordered index
// (std::map<string,loc>). Records are crc32-framed; recovery scans the
// log and truncates at the first corrupt/short record. Deletes are
// tombstones; compact() rewrites the live set. One mutex per DB — the
// store targets correctness + sequential-scan speed, not concurrency
// (callers in this framework serialize per-store anyway).
//
// Build: g++ -O2 -shared -fPIC -o libnativedb.so nativedb.cpp

#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kTombstone = 0xFFFFFFFFu;

// crc32 (IEEE, table-driven) — matches Python's binascii.crc32
uint32_t crc32(const uint8_t* data, size_t n, uint32_t crc = 0) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  crc = ~crc;
  for (size_t i = 0; i < n; i++)
    crc = table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

void put_u32(std::string& out, uint32_t v) {
  out.push_back(static_cast<char>(v >> 24));
  out.push_back(static_cast<char>(v >> 16));
  out.push_back(static_cast<char>(v >> 8));
  out.push_back(static_cast<char>(v));
}

uint32_t get_u32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

struct DB {
  std::mutex mu;
  std::string path;
  FILE* log = nullptr;
  // key -> value (values live in memory; the log is the durable copy.
  // For this framework's stores — blocks, state, index — working sets
  // are modest and the memory index keeps gets O(log n) with zero
  // read-path IO, like a memtable that never flushes).
  std::map<std::string, std::string> index;
  uint64_t live_bytes = 0;
  uint64_t total_bytes = 0;

  bool recover();
  bool append(const std::string& key, const std::string* val);
  bool compact();
};

// record: crc32(4) | klen(4) | vlen(4) | key | value
bool DB::recover() {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return true;  // fresh db
  std::vector<uint8_t> hdr(12);
  long good_end = 0;
  for (;;) {
    if (fread(hdr.data(), 1, 12, f) != 12) break;
    uint32_t crc = get_u32(hdr.data());
    uint32_t klen = get_u32(hdr.data() + 4);
    uint32_t vlen = get_u32(hdr.data() + 8);
    uint32_t real_vlen = (vlen == kTombstone) ? 0 : vlen;
    if (klen > (1u << 30) || real_vlen > (1u << 30)) break;
    std::vector<uint8_t> payload(8 + klen + real_vlen);
    memcpy(payload.data(), hdr.data() + 4, 8);
    if (fread(payload.data() + 8, 1, klen + real_vlen, f) !=
        klen + real_vlen)
      break;
    if (crc32(payload.data(), payload.size()) != crc) break;
    std::string key(reinterpret_cast<char*>(payload.data() + 8), klen);
    if (vlen == kTombstone) {
      index.erase(key);
    } else {
      index[key] = std::string(
          reinterpret_cast<char*>(payload.data() + 8 + klen), real_vlen);
    }
    good_end = ftell(f);
  }
  fclose(f);
  // truncate torn tail so future appends start at a clean record edge
  long sz = 0;
  {
    FILE* g = fopen(path.c_str(), "rb");
    if (g) { fseek(g, 0, SEEK_END); sz = ftell(g); fclose(g); }
  }
  if (sz > good_end) {
    if (truncate(path.c_str(), good_end) != 0) return false;
  }
  total_bytes = static_cast<uint64_t>(good_end);
  live_bytes = 0;
  for (auto& kv : index) live_bytes += 12 + kv.first.size() + kv.second.size();
  return true;
}

bool DB::append(const std::string& key, const std::string* val) {
  std::string payload;
  put_u32(payload, static_cast<uint32_t>(key.size()));
  put_u32(payload, val ? static_cast<uint32_t>(val->size()) : kTombstone);
  payload += key;
  if (val) payload += *val;
  std::string rec;
  put_u32(rec, crc32(reinterpret_cast<const uint8_t*>(payload.data()),
                     payload.size()));
  rec += payload;
  if (fwrite(rec.data(), 1, rec.size(), log) != rec.size()) return false;
  total_bytes += rec.size();
  return true;
}

bool DB::compact() {
  // rewrite live set to a temp log, atomically swap
  std::string tmp = path + ".compact";
  FILE* out = fopen(tmp.c_str(), "wb");
  if (!out) return false;
  FILE* old = log;
  uint64_t old_total = total_bytes;
  log = out;
  bool ok = true;
  total_bytes = 0;
  for (auto& kv : index)
    if (!append(kv.first, &kv.second)) { ok = false; break; }
  log = old;
  // make the rewritten log durable before the rename makes it live; a
  // failed flush (e.g. ENOSPC) must not let a truncated file go live
  if (ok && (fflush(out) != 0 || fsync(fileno(out)) != 0)) ok = false;
  fclose(out);
  if (!ok) {
    // the old log stays live — restore its accounting too
    total_bytes = old_total;
    remove(tmp.c_str());
    return false;
  }
  if (log) fclose(log);
  if (rename(tmp.c_str(), path.c_str()) != 0) {
    log = fopen(path.c_str(), "ab");
    return false;
  }
  // persist the rename itself (directory entry)
  std::string dir = ".";
  auto slash = path.find_last_of('/');
  if (slash != std::string::npos) dir = path.substr(0, slash);
  int dfd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd >= 0) { fsync(dfd); close(dfd); }
  log = fopen(path.c_str(), "ab");
  live_bytes = 0;
  for (auto& kv : index) live_bytes += 12 + kv.first.size() + kv.second.size();
  return log != nullptr;
}

struct Iter {
  std::vector<std::pair<std::string, std::string>> items;  // snapshot
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* ndb_open(const char* path) {
  auto db = std::make_unique<DB>();
  db->path = path;
  if (!db->recover()) return nullptr;
  db->log = fopen(path, "ab");
  if (!db->log) return nullptr;
  return db.release();
}

void ndb_close(void* h) {
  auto* db = static_cast<DB*>(h);
  {
    std::lock_guard<std::mutex> g(db->mu);
    // compact on close when >50% of the log is garbage
    if (db->total_bytes > 2 * db->live_bytes && db->total_bytes > 1 << 20)
      db->compact();
    if (db->log) { fflush(db->log); fclose(db->log); db->log = nullptr; }
  }
  delete db;
}

int ndb_put(void* h, const uint8_t* k, uint32_t klen, const uint8_t* v,
            uint32_t vlen) {
  auto* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  std::string key(reinterpret_cast<const char*>(k), klen);
  std::string val(reinterpret_cast<const char*>(v), vlen);
  if (!db->append(key, &val)) return -1;
  auto it = db->index.find(key);
  if (it != db->index.end())
    db->live_bytes -= 12 + key.size() + it->second.size();
  db->live_bytes += 12 + key.size() + val.size();
  db->index[key] = std::move(val);
  return 0;
}

int ndb_delete(void* h, const uint8_t* k, uint32_t klen) {
  auto* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  std::string key(reinterpret_cast<const char*>(k), klen);
  auto it = db->index.find(key);
  if (it == db->index.end()) return 0;  // delete of absent key is a no-op
  if (!db->append(key, nullptr)) return -1;
  db->live_bytes -= 12 + key.size() + it->second.size();
  db->index.erase(it);
  return 0;
}

// 0 = found (copy into malloc'd buffer), 1 = not found, -1 = error
int ndb_get(void* h, const uint8_t* k, uint32_t klen, uint8_t** val,
            uint32_t* vlen) {
  auto* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  auto it = db->index.find(
      std::string(reinterpret_cast<const char*>(k), klen));
  if (it == db->index.end()) return 1;
  *vlen = static_cast<uint32_t>(it->second.size());
  *val = static_cast<uint8_t*>(malloc(it->second.size()));
  if (!*val && !it->second.empty()) return -1;
  memcpy(*val, it->second.data(), it->second.size());
  return 0;
}

void ndb_free(uint8_t* p) { free(p); }

int ndb_sync(void* h) {
  auto* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  // durable like the reference's LevelDB SetSync: flush userspace
  // buffers AND force the kernel to persist to the device —
  // consensus-critical stores rely on surviving power loss
  if (fflush(db->log) != 0) return -1;
  if (fsync(fileno(db->log)) != 0) return -1;
  return 0;
}

int ndb_compact(void* h) {
  auto* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return db->compact() ? 0 : -1;
}

uint64_t ndb_count(void* h) {
  auto* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  return db->index.size();
}

// iterator over [start, end); empty start/end = unbounded
void* ndb_iter_new(void* h, const uint8_t* start, uint32_t slen,
                   const uint8_t* end, uint32_t elen, int reverse) {
  auto* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  auto it = std::make_unique<Iter>();
  std::string s(reinterpret_cast<const char*>(start), slen);
  std::string e(reinterpret_cast<const char*>(end), elen);
  auto lo = slen ? db->index.lower_bound(s) : db->index.begin();
  auto hi = elen ? db->index.lower_bound(e) : db->index.end();
  for (auto p = lo; p != hi; ++p) it->items.emplace_back(p->first, p->second);
  if (reverse) std::reverse(it->items.begin(), it->items.end());
  return it.release();
}

// 0 = item produced, 1 = exhausted
int ndb_iter_next(void* hi, uint8_t** k, uint32_t* klen, uint8_t** v,
                  uint32_t* vlen) {
  auto* it = static_cast<Iter*>(hi);
  if (it->pos >= it->items.size()) return 1;
  auto& kv = it->items[it->pos++];
  *klen = static_cast<uint32_t>(kv.first.size());
  *k = static_cast<uint8_t*>(malloc(kv.first.size()));
  memcpy(*k, kv.first.data(), kv.first.size());
  *vlen = static_cast<uint32_t>(kv.second.size());
  *v = static_cast<uint8_t*>(malloc(kv.second.size()));
  memcpy(*v, kv.second.data(), kv.second.size());
  return 0;
}

void ndb_iter_free(void* hi) { delete static_cast<Iter*>(hi); }

}  // extern "C"
