#!/bin/sh
# Init-on-first-run entrypoint (reference DOCKER/Dockerfile CMD +
# docs/examples): a mounted empty $TMHOME gets a fresh single-validator
# setup; an existing config/genesis.json is left untouched.
set -e

TMHOME="${TMHOME:-/tendermint_tpu}"

if [ ! -f "$TMHOME/config/genesis.json" ]; then
    echo "entrypoint: no genesis found, initializing $TMHOME"
    tendermint-tpu --home "$TMHOME" init ${CHAIN_ID:+--chain-id "$CHAIN_ID"}
fi

exec tendermint-tpu --home "$TMHOME" "$@"
