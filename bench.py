"""Benchmark: the north-star hot path — VerifyCommit at 10k validators.

BASELINE.json config 5: "10k-validator mega-commit VerifyCommit on TPU,
mixed valid/invalid sigs". Baseline stand-in for the reference's serial Go
ed25519 path (types/validator_set.go:345-371): a serial OpenSSL
verify loop (measured on a subset, extrapolated linearly — per-signature
cost is constant).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
vs_baseline > 1 means faster than the serial baseline.
"""

import json
import secrets
import sys
import time


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
    from tendermint_tpu.crypto import keys
    from tendermint_tpu.crypto.jaxed25519.verify import verify_batch

    # build a synthetic 10k-validator commit: distinct keys, vote-sized
    # messages (~110B canonical sign-bytes), ~1% corrupted signatures
    sks = [keys.PrivKeyEd25519.generate() for _ in range(min(n, 2000))]
    msgs, sigs, pks, want = [], [], [], []
    for i in range(n):
        sk = sks[i % len(sks)]
        msg = secrets.token_bytes(110)
        sig = sk.sign(msg)
        if i % 100 == 37:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
            want.append(False)
        else:
            want.append(True)
        msgs.append(msg)
        sigs.append(sig)
        pks.append(sk.pub_key().bytes())

    # serial CPU baseline (subset of 300, extrapolated)
    sub = 300
    t0 = time.perf_counter()
    for i in range(sub):
        keys.PubKeyEd25519(pks[i]).verify_bytes(msgs[i], sigs[i])
    serial_ms = (time.perf_counter() - t0) / sub * n * 1000

    # TPU batch path: one warmup (compile), then timed runs
    got = verify_batch(msgs, sigs, pks)
    assert got == want, "TPU verify mask mismatch vs expected"
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        verify_batch(msgs, sigs, pks)
        times.append((time.perf_counter() - t0) * 1000)
    batch_ms = min(times)

    print(
        json.dumps(
            {
                "metric": f"verify_commit_{n}_sigs_wall_ms",
                "value": round(batch_ms, 3),
                "unit": "ms",
                "vs_baseline": round(serial_ms / batch_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
