"""Benchmark: the north-star hot path — VerifyCommit at 10k validators.

Default run = BASELINE.json config 5: "10k-validator mega-commit
VerifyCommit on TPU, mixed valid/invalid sigs". Baseline stand-in for the
reference's serial Go ed25519 path (types/validator_set.go:345-371): a
serial OpenSSL verify loop (measured on a subset, extrapolated linearly —
per-signature cost is constant).

The other BASELINE.json configs map to modes:
  1 "VerifyCommit on a 4-validator genesis commit"  -> `bench.py commit4`
  2 "1k random triples, serial vs JAX-CPU backend"  ->
        `TM_TPU_BENCH_FORCE_CPU=1 python bench.py 1000`
  3 "150-validator prevote+precommit round replay"  -> `bench.py votes`
  4 "fast-sync block validation, 500-val commits"   -> `bench.py fastsync`
  5 "10k-validator mega-commit, mixed validity"     -> default

Async/cache modes (PR 2):
  `bench.py fastsync --pipeline` — two-stage pipeline: verify(k+1)
        dispatched async while apply(k) runs; reports serial AND
        pipelined wall plus the pipeline-overlap histogram count
  `bench.py cache` — duplicate-heavy deliveries through the verified-
        signature cache; reports hit rate and wall vs the uncached run

Compile-once modes (PR 8):
  `bench.py warmstart` — kernel READINESS, cold process (XLA compile +
        AOT artifact write) vs a second process on the same machine
        (AOT load); vs_baseline = cold/warm readiness
  `bench.py mega` — the default verify-commit benchmark at the
        100k-signature mega-committee point (10k validators x many
        heights in flight); `bench.py 100000` spelled as a mode

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
vs_baseline > 1 means faster than the serial baseline.

Robustness: the TPU platform (axon) is probed in a SUBPROCESS with a hard
timeout first — its init can hang indefinitely when the chip is held or
the tunnel is down, and a hung init must not prevent the JSON line. On
probe failure the kernel runs on an 8-device virtual CPU mesh and the
line is emitted with "degraded": "cpu8" (honest, slower number). Any
other failure still emits a parseable line with value -1.
"""

import json
import os
import secrets
import subprocess
import sys
import time

RLC_MODE = "rlc" in sys.argv[1:]
VOTES_MODE = "votes" in sys.argv[1:]  # BASELINE.json config 3
FASTSYNC_MODE = "fastsync" in sys.argv[1:]  # BASELINE.json config 4 (scaled)
COMMIT4_MODE = "commit4" in sys.argv[1:]  # BASELINE.json config 1
CACHE_MODE = "cache" in sys.argv[1:]  # duplicate-heavy sig-cache mode
STATESYNC_MODE = "statesync" in sys.argv[1:]  # restore vs replay (PR 4)
CHAOS_MODE = "chaos" in sys.argv[1:]  # ABCI reconnect recovery (PR 5)
LOAD_MODE = "load" in sys.argv[1:]  # sustained-TPS mempool localnet (PR 6)
PREVERIFY_MODE = "preverify" in sys.argv[1:]  # batched vs serial CheckTx
AGGVERIFY_MODE = "aggverify" in sys.argv[1:]  # BLS aggregate cert (PR 7)
RPCLOAD_MODE = "rpcload" in sys.argv[1:]  # RPC fan-out serving (PR 9)
WARMSTART_MODE = "warmstart" in sys.argv[1:]  # compile-once readiness (PR 8)
MEGA_MODE = "mega" in sys.argv[1:]  # 100k-sig mega-committee batch point
CHAOSNET_MODE = "chaosnet" in sys.argv[1:]  # partition-heal recovery (PR 10)
CRASHREC_MODE = "crashrecovery" in sys.argv[1:]  # kill->committing (PR 14)
DETCHECK_MODE = "detcheck" in sys.argv[1:]  # replay-divergence oracle (PR 15)
PROPTRACE_MODE = "proptrace" in sys.argv[1:]  # fleet causal tracing (PR 16)
INCIDENT_MODE = "incident" in sys.argv[1:]  # incident MTTD/MTTR (PR 18)
HANDEL_MODE = "handel" in sys.argv[1:]  # aggregation overlay (PR 19)
FLEET_MODE = "fleet" in sys.argv[1:]  # replica fan-out serving (PR 20)
PIPELINE_FLAG = "--pipeline" in sys.argv[1:]  # fastsync: 2-stage pipeline
PARALLEL_FLAG = "--parallel" in sys.argv[1:]  # load: parallel exec lanes
_args = [a for a in sys.argv[1:]
         if a not in ("rlc", "votes", "fastsync", "commit4", "cache",
                      "statesync", "chaos", "load", "preverify",
                      "aggverify", "warmstart", "mega", "chaosnet",
                      "crashrecovery", "detcheck", "proptrace",
                      "incident", "handel", "fleet",
                      "--pipeline", "--parallel")]
try:
    METRIC_N = int(_args[0]) if _args else (100000 if MEGA_MODE else 10000)
except ValueError:
    METRIC_N = 100000 if MEGA_MODE else 10000

def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# mode scales + metric names, shared by the success and failure paths so
# they cannot diverge when the scale constants change. The fastsync
# scale is env-overridable (metric names track the actual values) so
# hosts without OpenSSL — where the serial stand-in runs the ~7.5ms/sig
# pure-Python fallback — can still exercise the mode end-to-end.
VOTES_NVAL = 150
VOTES_METRIC = f"voteset_replay_{VOTES_NVAL}val_2rounds_wall_ms"
FS_NVAL = _env_int("TM_TPU_BENCH_FS_NVAL", 500)
FS_NBLOCKS = _env_int("TM_TPU_BENCH_FS_BLOCKS", 20)
FS_METRIC = f"fastsync_{FS_NBLOCKS}x{FS_NVAL}val_wall_ms"
FS_PIPE_METRIC = f"fastsync_pipeline_{FS_NBLOCKS}x{FS_NVAL}val_wall_ms"
COMMIT4_METRIC = "verify_commit_4val_wall_ms"
CACHE_NVAL, CACHE_DUPS = 500, 3
CACHE_METRIC = f"sig_cache_{CACHE_DUPS}x{CACHE_NVAL}dup_wall_ms"
SS_NBLOCKS = _env_int("TM_TPU_BENCH_SS_BLOCKS", 20)
SS_NVAL = _env_int("TM_TPU_BENCH_SS_NVAL", 100)
SS_METRIC = f"statesync_restore_vs_replay_{SS_NBLOCKS}x{SS_NVAL}val_wall_ms"
CHAOS_ROUNDS = _env_int("TM_TPU_BENCH_CHAOS_ROUNDS", 10)
CHAOS_METRIC = f"abci_reconnect_recovery_{CHAOS_ROUNDS}rounds_ms"
LOAD_TPS = _env_int("TM_TPU_BENCH_LOAD_TPS", 200)
LOAD_SECS = _env_int("TM_TPU_BENCH_LOAD_SECS", 5)
LOAD_METRIC = f"mempool_load_{LOAD_TPS}tps_{LOAD_SECS}s_p99_commit_ms"
# parallel-execution load mode (`bench.py load --parallel`, PR 12):
# the same single-validator localnet drives a sharded kvstore app with
# EXEC_IO_US of simulated per-tx backend latency (storage/remote-call
# wait — the GIL-released stall parallel lanes overlap) twice: serial
# execution ([execution] defaults, the committed baseline) and then
# EXEC_LANES optimistic lanes + speculative execution
EXEC_IO_US = _env_int("TM_TPU_BENCH_EXEC_IO_US", 10000)
EXEC_LANES = _env_int("TM_TPU_BENCH_EXEC_LANES", 64)
EXEC_SERIAL_TPS = _env_int("TM_TPU_BENCH_EXEC_SERIAL_TPS", 300)
EXEC_PAR_TPS = _env_int("TM_TPU_BENCH_EXEC_PAR_TPS", 4000)
EXEC_SECS = _env_int("TM_TPU_BENCH_EXEC_SECS", 4)
EXEC_METRIC = (f"exec_parallel_{EXEC_LANES}lanes_"
               f"{EXEC_IO_US}us_committed_tps")
# high-conflict legs (PR 17): EXEC_CONFLICT_PCT percent of txs carry a
# LYING access hint and actually touch one of EXEC_HOT_KEYS shared
# keys, so the planner spreads them across lanes and the merge sees
# real read/write overlap. Run once on the PR-16 engine (segment
# re-run + whole-block serial fallback) and once on the retry-DAG +
# lane-pool engine; the ratio is the conflict-path speedup.
EXEC_HC_TPS = _env_int("TM_TPU_BENCH_EXEC_HC_TPS", 800)
EXEC_HC_SECS = _env_int("TM_TPU_BENCH_EXEC_HC_SECS", 3)
EXEC_CONFLICT_PCT = _env_int("TM_TPU_BENCH_EXEC_CONFLICT_PCT", 30)
EXEC_HOT_KEYS = _env_int("TM_TPU_BENCH_EXEC_HOT_KEYS", 16)
EXEC_RETRY_ROUNDS = _env_int("TM_TPU_BENCH_EXEC_RETRY_ROUNDS", 3)
PREVERIFY_N = _env_int("TM_TPU_BENCH_PREVERIFY_N", 2000)
PREVERIFY_METRIC = f"mempool_preverify_{PREVERIFY_N}tx_wall_ms"
AGG_NVAL = _env_int("TM_TPU_BENCH_AGG_NVAL", 10000)
AGG_METRIC = f"aggverify_{AGG_NVAL}val_commit_wall_ms"
WARM_N = _env_int("TM_TPU_BENCH_WARM_N", 10000)
WARM_METRIC = f"warmstart_ready_{WARM_N}sigs_wall_ms"
RPC_SUBS = _env_int("TM_TPU_BENCH_RPC_SUBS", 100)
RPC_QUERIES = _env_int("TM_TPU_BENCH_RPC_QUERIES", 2000)
RPC_THREADS = _env_int("TM_TPU_BENCH_RPC_THREADS", 4)
RPCLOAD_METRIC = f"rpc_serving_{RPC_SUBS}subs_hot_status_p50_ms"
CHAOSNET_NVAL = _env_int("TM_TPU_BENCH_CHAOSNET_NVAL", 4)
CHAOSNET_SEED = _env_int("TM_TPU_BENCH_CHAOSNET_SEED", 1)
CHAOSNET_METRIC = (
    f"chaosnet_partition_heal_{CHAOSNET_NVAL}node_recovery_ms")
CRASHREC_ROUNDS = _env_int("TM_TPU_BENCH_CRASHREC_ROUNDS", 3)
CRASHREC_METRIC = (
    f"crash_recovery_kill_to_committing_{CRASHREC_ROUNDS}rounds_ms")
DETCHECK_BLOCKS = _env_int("TM_TPU_BENCH_DETCHECK_BLOCKS", 10)
DETCHECK_METRIC = f"detcheck_oracle_{DETCHECK_BLOCKS}blocks_wall_ms"
PROPTRACE_NVAL = _env_int("TM_TPU_BENCH_PROPTRACE_NVAL", 4)
PROPTRACE_SEED = _env_int("TM_TPU_BENCH_PROPTRACE_SEED", 8)
PROPTRACE_METRIC = (
    f"proptrace_{PROPTRACE_NVAL}node_commit_attribution_coverage_pct")
INCIDENT_NVAL = _env_int("TM_TPU_BENCH_INCIDENT_NVAL", 4)
INCIDENT_SEED = _env_int("TM_TPU_BENCH_INCIDENT_SEED", 9)
INCIDENT_METRIC = (
    f"incident_{INCIDENT_NVAL}node_composed_mttr_p50_ms")
HANDEL_NVAL = _env_int("TM_TPU_BENCH_HANDEL_NVAL", 1024)
HANDEL_METRIC = f"handel_overlay_{HANDEL_NVAL}val_per_node_verify_ops"
# replica fan-out tree serving (PR 20): N in-process replicas behind
# one validator, tiered via [replica] prefer_replicas, answering a
# round-robin read load while tailing live
FLEET_REPLICAS = _env_int("TM_TPU_BENCH_FLEET_REPLICAS", 4)
FLEET_SECS = _env_int("TM_TPU_BENCH_FLEET_SECS", 6)
FLEET_CLIENTS = _env_int("TM_TPU_BENCH_FLEET_CLIENTS", 8)
FLEET_METRIC = f"fleet_serve_{FLEET_REPLICAS}replica_tree_rpc_p50_ms"


def _best_of(fn, reps: int) -> float:
    """Best-of-N wall time in ms (same outlier discipline for serial
    baselines and batch paths, so vs_baseline compares like with like)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, (time.perf_counter() - t0) * 1000)
    return best


def _tpu_probe_once(timeout: float) -> str:
    """Probe backend init + one tiny op in a subprocess with a timeout.

    Returns "ok", "no-tpu" (deterministic: backend came up CPU-only, no
    TPU plugin — retrying is futile), or "down" (init hang/timeout or
    backend error such as axon UNAVAILABLE — the tunnel-outage signature,
    worth retrying)."""
    code = (
        "import jax, jax.numpy as jnp\n"
        "devs = jax.devices()\n"
        "assert devs and devs[0].platform.lower() != 'cpu', 'CPU-ONLY'\n"
        "x = jnp.ones((8, 8))\n"
        "print(float((x @ x).sum()))\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        if r.returncode == 0:
            return "ok"
        err = r.stderr or b""
        if (b"CPU-ONLY" in err or b"ImportError" in err
                or b"ModuleNotFoundError" in err):
            return "no-tpu"  # deterministic, not a tunnel flap
        return "down"
    except Exception:
        return "down"


def _tpu_available(timeout: float = 240.0, retry_timeout: float = 60.0,
                   retries: int = 3, wait: float = 30.0) -> bool:
    """Bounded retry window so a flapping tunnel doesn't degrade the
    official number on a single failed first probe. The FIRST probe keeps
    the generous 240s budget (cold backend init on this box can
    legitimately take minutes); later probes only need to catch a tunnel
    that has come back, so they get 60s. Worst case ~7 min total.
    TM_TPU_BENCH_PROBE_RETRIES=1 restores single-probe behavior for
    quick local iteration."""
    try:
        retries = int(os.environ.get("TM_TPU_BENCH_PROBE_RETRIES", retries))
    except ValueError:
        pass
    for attempt in range(max(1, retries)):
        got = _tpu_probe_once(timeout if attempt == 0 else retry_timeout)
        if got == "ok":
            return True
        if got == "no-tpu":
            return False  # deterministic: no TPU plugin; don't burn waits
        if attempt < retries - 1:
            time.sleep(wait)
    return False


# Last good-TPU results, committed to the repo so a tunnel outage in a
# later round never erases the perf story: degraded output embeds the
# most recent clean TPU record for the same metric with stale=true.
LAST_GOOD_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_LAST_GOOD.json")


def _load_last_good(metric: str):
    try:
        with open(LAST_GOOD_PATH) as f:
            rec = json.load(f).get(metric)
        return rec if isinstance(rec, dict) else None
    except Exception:
        return None


def _save_last_good(out: dict) -> None:
    try:
        try:
            with open(LAST_GOOD_PATH) as f:
                store = json.load(f)
        except Exception:
            store = {}
        rec = {k: v for k, v in out.items() if k != "tunnel_note"}
        if rec.get("device_ms", 1) <= 0:
            # a failed device-only measurement must not overwrite the
            # stored record's real device number
            rec.pop("device_ms", None)
            prev = store.get(out["metric"]) or {}
            if prev.get("device_ms", 0) > 0:
                rec["device_ms"] = prev["device_ms"]
                rec["device_ms_stale"] = True
        rec["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        store[out["metric"]] = rec
        tmp = LAST_GOOD_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(store, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, LAST_GOOD_PATH)  # atomic: no torn store on kill
    except Exception:
        pass  # the JSON line matters more than the cache


def _emit(out: dict, degraded) -> None:
    """Print the one JSON line; maintain the last-good-TPU store.

    Forced-CPU runs ("cpu8-forced", BASELINE config 2) are a by-design
    mode, not an outage — no last_good_tpu embedding for them."""
    if degraded:
        out["degraded"] = degraded
        if not degraded.endswith("-forced"):
            last = _load_last_good(out["metric"])
            if last:
                out["last_good_tpu"] = dict(last, stale=True)
    elif out.get("value", -1) > 0:
        _save_last_good(out)
    print(json.dumps(out))


def _signed_vote(chain_id, keys_list, vals, idx, height, round_, type_, block_id):
    from tendermint_tpu.types import Vote

    addr, _ = vals.get_by_index(idx)
    v = Vote(
        validator_address=addr,
        validator_index=idx,
        height=height,
        round=round_,
        timestamp=1_700_000_000_000_000_000 + idx,
        type=type_,
        block_id=block_id,
    )
    v.signature = keys_list[idx].sign(v.sign_bytes(chain_id))
    return v


def votes_main(degraded):
    """BASELINE.json config 3: a 150-validator prevote+precommit round
    replayed through VoteSet.add_votes (the live batched tally path).
    Baseline stand-in: per-vote serial add_vote (one OpenSSL verify per
    vote), the reference's one-at-a-time types/vote_set.go:189 flow."""
    from tendermint_tpu.types import (
        VOTE_TYPE_PRECOMMIT,
        VOTE_TYPE_PREVOTE,
        BlockID,
    )
    from tendermint_tpu.types.basic import PartSetHeader
    from tendermint_tpu.types.validator_set import random_validator_set
    from tendermint_tpu.types.vote_set import VoteSet

    chain = "bench-votes"
    nval = VOTES_NVAL
    vals, keys_list = random_validator_set(nval, 10)
    bid = BlockID(b"\x0b" * 20, PartSetHeader(1, b"\x0c" * 20))
    rounds = [
        (VOTE_TYPE_PREVOTE, [
            _signed_vote(chain, keys_list, vals, i, 1, 0, VOTE_TYPE_PREVOTE, bid)
            for i in range(nval)
        ]),
        (VOTE_TYPE_PRECOMMIT, [
            _signed_vote(chain, keys_list, vals, i, 1, 0, VOTE_TYPE_PRECOMMIT, bid)
            for i in range(nval)
        ]),
    ]

    # serial baseline: add_vote one at a time (fresh sets), same
    # best-of-N outlier discipline as the batch path
    def serial():
        for type_, votes in rounds:
            vs = VoteSet(chain, 1, 0, type_, vals)
            for v in votes:
                vs.add_vote(v)
            assert vs.has_two_thirds_majority()

    serial_ms = _best_of(serial, 3)

    if not degraded:
        # production flow: warmup compiles the bucket this batch uses AND
        # calibrates the adaptive cutoff to the measured dispatch-vs-serial
        # break-even — through a high-latency tunnel the 150-vote batch
        # correctly DECLINES the device (vs_baseline ≈ 1.0 instead of the
        # old guaranteed loss); on direct-attached TPU it rides the device
        from tendermint_tpu.crypto.jaxed25519.verify import warmup

        warmup(buckets=(nval,))

    # batched path (warm once, then best of N)
    def run():
        for type_, votes in rounds:
            vs = VoteSet(chain, 1, 0, type_, vals)
            vs.add_votes(votes)
            assert vs.has_two_thirds_majority()

    run()
    best = _best_of(run, 3 if degraded else 5)

    out = {
        "metric": VOTES_METRIC,
        "value": round(best, 3),
        "unit": "ms",
        "vs_baseline": round(serial_ms / best, 2),
    }
    if not degraded:
        from tendermint_tpu.crypto import batch as crypto_batch

        # effective_batch_min already folds in env-override precedence, so
        # the reported cutoff always matches the actual routing decision
        eff = crypto_batch.effective_batch_min()
        out["batch_cutoff"] = eff
        if nval >= eff:
            # 2 dispatches x ~64ms tunnel latency dominate at 150-vote
            # scale when the device is used
            out["tunnel_note"] = "wall includes 2 remote-TPU round trips"
        else:
            out["note"] = "calibrated cutoff routed this batch to host CPU"
    _emit(out, degraded)


def _hist_count(registry, name: str) -> int:
    """Sample count of a label-less histogram in a metrics Registry."""
    for line in registry.render().splitlines():
        if line.startswith(name + "_count"):
            try:
                return int(float(line.rsplit(" ", 1)[1]))
            except ValueError:
                return 0
    return 0


def fastsync_pipeline_main(degraded, chain, vs, commits, serial_extrap_ms,
                           warm_wall_ms):
    """`bench.py fastsync --pipeline` — the two-stage fast-sync pipeline
    (blockchain/reactor._try_sync_batch_pipelined shape): block k's
    apply runs on the host while block k+1's commit batch is already
    dispatched (begin_verify_commit -> verify_async). The apply stand-in
    is a sleep sized to the measured per-block verify cost — the
    'comparable verify/apply cost' regime of the acceptance criterion,
    where pipelining approaches 2x. Reports BOTH modes (serial_ms vs
    value) plus the pipeline-overlap histogram count."""
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.metrics import prometheus_metrics

    nblocks = len(commits)
    verify_ms = warm_wall_ms / nblocks  # measured per-block verify wall
    apply_s = verify_ms / 1000.0

    def serial_run():
        for h, bid, commit in commits:
            vs.verify_commit(chain, bid, h, commit)
            time.sleep(apply_s)  # apply(k) stand-in

    def pipelined_run():
        h0, bid0, commit0 = commits[0]
        pend = vs.begin_verify_commit(chain, bid0, h0, commit0)
        for i in range(nblocks):
            pend.result()  # verify(k) must complete before apply(k)
            nxt = None
            if i + 1 < nblocks:
                h, bid, commit = commits[i + 1]
                nxt = vs.begin_verify_commit(chain, bid, h, commit)
            time.sleep(apply_s)  # apply(k) overlaps verify(k+1)
            pend = nxt

    m = prometheus_metrics("bench")
    crypto_batch.set_metrics(m.crypto)
    prev_async = crypto_batch.async_enabled()
    crypto_batch.set_async_enabled(True)
    try:
        pipelined_run()  # warm the dispatcher
        reps = 1 if degraded else 3
        serial_wall = _best_of(serial_run, reps)
        pipe_wall = _best_of(pipelined_run, reps)
    finally:
        crypto_batch.set_metrics(None)
        crypto_batch.set_async_enabled(prev_async)
        crypto_batch.shutdown_dispatchers()

    overlap_n = _hist_count(m.registry,
                            "bench_crypto_pipeline_overlap_seconds")
    out = {
        "metric": FS_PIPE_METRIC,
        "value": round(pipe_wall, 3),
        "unit": "ms",
        # headline ratio: pipelined vs the serial verify+apply loop
        "vs_baseline": round(serial_wall / pipe_wall, 2),
        "serial_ms": round(serial_wall, 3),
        "per_block_ms": round(pipe_wall / nblocks, 3),
        "apply_stub_ms": round(verify_ms, 3),
        "overlap_samples": overlap_n,
        "vs_serial_openssl": round(
            (serial_extrap_ms + nblocks * verify_ms) / pipe_wall, 2),
    }
    if not degraded:
        out["tunnel_note"] = (
            f"wall includes {nblocks} remote-TPU round trips, "
            "overlapped with apply")
    _emit(out, degraded)


def cache_main(degraded):
    """`bench.py cache` — duplicate-heavy verification: CACHE_NVAL
    unique vote-sized triples (with ~1% invalid) delivered CACHE_DUPS
    times, the gossip re-delivery pattern. Baseline: same deliveries
    with the verified-signature cache off (every delivery re-dispatches
    to the backend). Reports hit rate alongside wall-ms in the standard
    BENCH schema."""
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.crypto import keys as ck
    from tendermint_tpu.crypto.sigcache import SigCache

    nval, dups = CACHE_NVAL, CACHE_DUPS
    sks = [ck.PrivKeyEd25519.gen_from_secret(b"cache-%d" % i)
           for i in range(nval)]
    triples = []
    for i, sk in enumerate(sks):
        msg = b"vote-%d-" % i + b"\x00" * 100
        sig = sk.sign(msg)
        if i % 100 == 37:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
        triples.append((msg, sig, sk.pub_key().bytes()))
    deliveries = [list(triples) for _ in range(dups)]

    def run_all():
        for d in deliveries:
            crypto_batch.batch_verify(d)

    crypto_batch.set_sig_cache(None)
    run_all()  # warm (compile, key tables)
    nocache_ms = _best_of(run_all, 2 if degraded else 3)

    last_cache = [None]

    def run_cached():
        # fresh cache per rep: hits come from the duplicate deliveries
        # within one run, exactly the per-block gossip pattern
        cache = SigCache(4 * nval)
        last_cache[0] = cache
        crypto_batch.set_sig_cache(cache)
        run_all()

    try:
        run_cached()
        cached_ms = _best_of(run_cached, 2 if degraded else 3)
        cache = last_cache[0]
        hit_rate = cache.hits / max(1, cache.hits + cache.misses)
    finally:
        crypto_batch.set_sig_cache(None)

    _emit({
        "metric": CACHE_METRIC,
        "value": round(cached_ms, 3),
        "unit": "ms",
        "vs_baseline": round(nocache_ms / cached_ms, 2),
        "nocache_ms": round(nocache_ms, 3),
        "hit_rate": round(hit_rate, 4),
    }, degraded)


def fastsync_main(degraded):
    """BASELINE.json config 4 (scaled to this box): fast-sync block
    validation — sequential verify_commit of 20 blocks x 500-validator
    commits (10k signatures), the blockchain/reactor.go:310 loop.
    Baseline stand-in: serial OpenSSL verifies extrapolated. With
    --pipeline, additionally measures the two-stage verify/apply
    pipeline (fastsync_pipeline_main)."""
    from tendermint_tpu.types import BlockID
    from tendermint_tpu.types.basic import PartSetHeader

    chain = "bench-fastsync"
    nval, nblocks = FS_NVAL, FS_NBLOCKS
    vs, sorted_sks = _build_valset(nval, b"fs")

    commits = []
    for h in range(1, nblocks + 1):
        bid = BlockID(bytes([h % 256]) * 20, PartSetHeader(1, b"\x0c" * 20))
        commits.append((h, bid, _build_commit(chain, vs, sorted_sks, h, bid)))

    # serial baseline (subset of 300 verifies, extrapolated to all sigs;
    # best-of-3 like the batch path)
    sub = 300

    def serial():
        h, bid, commit = commits[0]
        for i in range(sub):
            v = commit.precommits[i % nval]
            vs.validators[v.validator_index].pub_key.verify_bytes(
                v.sign_bytes(chain), v.signature)

    serial_ms = _best_of(serial, 3) / sub * nval * nblocks

    def run():
        for h, bid, commit in commits:
            vs.verify_commit(chain, bid, h, commit)

    run()  # warm the 512-bucket compile
    best = _best_of(run, 1 if degraded else 3)

    if PIPELINE_FLAG:
        return fastsync_pipeline_main(degraded, chain, vs, commits,
                                      serial_ms, best)

    out = {
        "metric": FS_METRIC,
        "value": round(best, 3),
        "unit": "ms",
        "vs_baseline": round(serial_ms / best, 2),
        "per_block_ms": round(best / nblocks, 2),
    }
    if not degraded:
        out["tunnel_note"] = f"wall includes {nblocks} remote-TPU round trips"
    _emit(out, degraded)


def statesync_main(degraded):
    """`bench.py statesync` — bootstrap-cost comparison: restoring a
    fresh node from a chunked snapshot at height N (light-verify the
    anchor via DynamicVerifier — a handful of batched verify_commits —
    then hash-check + apply chunks) vs replaying blocks 1..N (one
    verify_commit per block plus tx re-execution). This is the whole
    point of the subsystem: replay cost grows linearly in chain height,
    restore cost doesn't."""
    from tendermint_tpu.abci import types as abci
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.crypto import merkle
    from tendermint_tpu.lite import (
        DynamicVerifier,
        FullCommit,
        MemProvider,
        SignedHeader,
    )
    from tendermint_tpu.statesync import chunker
    from tendermint_tpu.types.basic import BlockID, PartSetHeader
    from tendermint_tpu.types.block import Header

    chain = "bench-statesync"
    nval, nblocks = SS_NVAL, SS_NBLOCKS
    txs_per_block = 10
    chunk_size = 4096
    vs, sorted_sks = _build_valset(nval, b"ss")

    # the sig cache would let the restore path ride verifications the
    # replay path already paid for — disable it for a fair comparison
    crypto_batch.set_sig_cache(None)

    def _header(h):
        return Header(
            chain_id=chain, height=h,
            time=1_700_000_000_000_000_000 + h,
            num_txs=txs_per_block, total_txs=txs_per_block * h,
            last_commit_hash=b"\x02" * 32,
            data_hash=merkle.hash_from_byte_slices([]),
            validators_hash=vs.hash(), next_validators_hash=vs.hash(),
            consensus_hash=b"\x03" * 32, app_hash=b"",
            last_results_hash=b"", evidence_hash=b"",
            proposer_address=vs.validators[0].address,
        )

    # synthetic chain: header+commit per height, same valset throughout
    commits, source = [], MemProvider()
    for h in range(1, nblocks + 1):
        hdr = _header(h)
        bid = BlockID(hdr.hash(), PartSetHeader(1, b"\x0c" * 20))
        commit = _build_commit(chain, vs, sorted_sks, h, bid)
        commits.append((h, bid, commit))
        source.save_full_commit(FullCommit(
            signed_header=SignedHeader(header=hdr, commit=commit),
            validators=vs, next_validators=vs))

    block_txs = [[b"k%d-%d=v" % (h, i) for i in range(txs_per_block)]
                 for h in range(1, nblocks + 1)]

    # producer app at height N, snapshotted
    producer = KVStoreApplication()
    producer.snapshot_interval = nblocks
    producer.snapshot_chunk_size = chunk_size
    for txs in block_txs:
        for tx in txs:
            producer.deliver_tx(tx)
        producer.commit()
    snap = producer.list_snapshots(abci.RequestListSnapshots()).snapshots[-1]

    def replay_run():
        app = KVStoreApplication()
        for (h, bid, commit), txs in zip(commits, block_txs):
            vs.verify_commit(chain, bid, h, commit)  # fast-sync's check
            for tx in txs:
                app.deliver_tx(tx)
            app.commit()
        return app

    def restore_run():
        verifier = DynamicVerifier(chain, MemProvider(), source)
        verifier.init_trust(source.latest_full_commit(chain, 1))
        # the real restore light-verifies headers H and H+1 (the anchor
        # pair); each is one batched verify_commit
        for h in (nblocks - 1, nblocks):
            verifier.verify(
                source.latest_full_commit(chain, h).signed_header)
        app = KVStoreApplication()
        res = app.offer_snapshot(abci.RequestOfferSnapshot(
            snapshot=snap, app_hash=producer.app_hash))
        assert res.result == abci.OFFER_ACCEPT
        for i in range(snap.chunks):
            data = producer.load_snapshot_chunk(
                abci.RequestLoadSnapshotChunk(
                    height=snap.height, format=snap.format, chunk=i)).chunk
            assert chunker.verify_chunk(data, i, snap.chunk_hashes)
            r = app.apply_snapshot_chunk(abci.RequestApplySnapshotChunk(
                index=i, chunk=data, sender="bench"))
            assert r.result == abci.APPLY_ACCEPT
        return app

    # warm (compiles, key tables), then sanity: both paths land on the
    # producer's app hash
    assert replay_run().app_hash == producer.app_hash
    assert restore_run().app_hash == producer.app_hash

    reps = 2 if degraded else 3
    replay_ms = _best_of(replay_run, reps)
    restore_ms = _best_of(restore_run, reps)

    _emit({
        "metric": SS_METRIC,
        "value": round(restore_ms, 3),
        "unit": "ms",
        "vs_baseline": round(replay_ms / restore_ms, 2),
        "replay_ms": round(replay_ms, 3),
        "chunks": snap.chunks,
        "note": "baseline = fast-sync replay of the same height range",
    }, degraded)


def _build_valset(nval: int, seed: bytes):
    """(validator_set, secret keys aligned to address-sorted order) —
    fixture shared by the commit4 and fastsync modes."""
    from tendermint_tpu.crypto import keys as ck
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet

    sks = [ck.PrivKeyEd25519.gen_from_secret(seed + b"-%d" % i)
           for i in range(nval)]
    vs = ValidatorSet([Validator.new(sk.pub_key(), 10) for sk in sks])
    by_addr = {sk.pub_key().address(): sk for sk in sks}
    return vs, [by_addr[v.address] for v in vs.validators]


def _build_commit(chain: str, vs, sorted_sks, height: int, bid):
    """A full commit for `bid` at `height`, every validator signing."""
    from tendermint_tpu.types import VOTE_TYPE_PRECOMMIT
    from tendermint_tpu.types.block import Commit

    pre = [
        _signed_vote(chain, sorted_sks, vs, i, height, 0,
                     VOTE_TYPE_PRECOMMIT, bid)
        for i in range(len(sorted_sks))
    ]
    return Commit(bid, pre)


def commit4_main():
    """BASELINE.json config 1: VerifyCommit on a 4-validator genesis-style
    commit. At 4 signatures the serial CPU path is the point — this
    measures the small-commit common case every block pays, not the
    batch kernel. The cpu backend is FORCED so no env tuning
    (TM_TPU_BATCH_MIN, TM_TPU_CRYPTO_BACKEND=jax) can route the
    benchmarked call into an unguarded jax init (this mode skips the
    TPU probe and its hang protection)."""
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.types import BlockID
    from tendermint_tpu.types.basic import PartSetHeader

    crypto_batch.set_default_backend("cpu")
    chain = "bench-commit4"
    bid = BlockID(b"\x04" * 20, PartSetHeader(1, b"\x0c" * 20))
    vs, sorted_sks = _build_valset(4, b"c4")
    commit = _build_commit(chain, vs, sorted_sks, 1, bid)

    def run():
        vs.verify_commit(chain, bid, 1, commit)

    run()
    reps = 50
    best = _best_of(lambda: [run() for _ in range(reps)], 3) / reps
    print(json.dumps({
        "metric": COMMIT4_METRIC,
        "value": round(best, 3),
        "unit": "ms",
        "vs_baseline": 1.0,
        "note": "serial CPU path forced by design at 4 sigs",
    }))


class _NullApp:
    """Zero-cost app stand-in: isolates the mempool's own ingest cost
    (signature verification, locks, batching) from app logic."""

    def check_tx(self, tx):
        from tendermint_tpu.abci import types as abci_types

        return abci_types.ResponseCheckTx(code=0, gas_wanted=1)

    def flush(self):
        pass


def preverify_main():
    """`bench.py preverify` — batched CheckTx signature pre-verification
    (the ingest queue draining into ONE crypto/batch call riding the
    verified-signature cache) vs the serial per-tx verify path, same
    txs, same app. The cache is warmed first — the batched path's win
    is exactly the PR-2 vote trick applied to tx ingest: a warm cache
    turns the whole signature batch into sha256 lookups while the
    serial path re-verifies every tx. cpu backend forced: this mode
    must not pay (or hang on) a jax init."""
    from tendermint_tpu import config as cfg
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.crypto import keys
    from tendermint_tpu.crypto.sigcache import SigCache
    from tendermint_tpu.mempool import Mempool, make_signed_tx

    crypto_batch.set_default_backend("cpu")
    crypto_batch.set_sig_cache(SigCache(4 * PREVERIFY_N))
    sks = [keys.PrivKeyEd25519.generate() for _ in range(32)]
    txs = [make_signed_tx(sks[i % len(sks)], b"load-%06d" % i,
                          priority=i % 4)
           for i in range(PREVERIFY_N)]

    def serial_run():
        # the serial baseline is the REFERENCE semantics: one full
        # Ed25519 verify per tx, no cache (the serial mempool path
        # itself rides the sig cache when installed — uninstall it for
        # the baseline so the measured contrast is architectural)
        cache = crypto_batch.get_sig_cache()
        crypto_batch.set_sig_cache(None)
        try:
            mp = Mempool(cfg.MempoolConfig(size=PREVERIFY_N + 1), _NullApp())
            for tx in txs:
                assert mp.check_tx(tx).code == 0
            return mp
        finally:
            crypto_batch.set_sig_cache(cache)

    def batched_run():
        mp = Mempool(
            cfg.MempoolConfig(size=PREVERIFY_N + 1, preverify_batch=True,
                              preverify_batch_max=256,
                              ingest_queue_size=2 * PREVERIFY_N),
            _NullApp())
        futs = [mp.check_tx_nowait(tx) for tx in txs]
        for f in futs:
            assert f.result(timeout=60).code == 0
        mp.stop()
        return mp

    batched_run()  # warm: fills the verified-signature cache
    serial_ms = _best_of(serial_run, 3)
    batched_ms = _best_of(batched_run, 3)
    crypto_batch.shutdown_dispatchers()
    crypto_batch.set_sig_cache(None)
    print(json.dumps({
        "metric": PREVERIFY_METRIC,
        "value": round(batched_ms, 3),
        "unit": "ms",
        "vs_baseline": round(serial_ms / batched_ms, 2),
        "serial_ms": round(serial_ms, 3),
        "note": ("batched ingest (one verify_async per drain, warm sig "
                 "cache) vs serial per-tx Ed25519 verify; cpu backend"),
    }))
    return 0


def load_main():
    """`bench.py load` — sustained-load harness: drive an in-process
    single-validator localnet at a target TPS through the batched
    ingest path and report accepted TPS plus p50/p99 commit latency
    (submit -> the NewBlock event carrying the tx). Pure host path."""
    import hashlib
    import threading

    from tendermint_tpu import config as cfg
    from tendermint_tpu import state as sm
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.consensus import ConsensusState
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.crypto import keys
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.mempool import Mempool, make_signed_tx
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.proxy import AppConns, local_client_creator
    from tendermint_tpu.types import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.event_bus import (
        EVENT_NEW_BLOCK, EventBus, query_for_event)
    from tendermint_tpu.types.validator_set import random_validator_set

    crypto_batch.set_default_backend("cpu")
    vs, vkeys = random_validator_set(1, 10)
    doc = GenesisDoc(
        chain_id="bench-load",
        genesis_time=time.time_ns() - 10**9,
        validators=[GenesisValidator(v.pub_key, v.voting_power)
                    for v in vs.validators],
    )
    db = MemDB()
    state = sm.load_state_from_db_or_genesis(db, doc)
    conns = AppConns(local_client_creator(KVStoreApplication()))
    conns.start()
    mp = Mempool(
        cfg.MempoolConfig(size=50000, lanes=2, preverify_batch=True,
                          ingest_queue_size=50000, recheck=False),
        conns.mempool)
    bus = EventBus()
    bus.start()
    block_exec = sm.BlockExecutor(db, conns.consensus, mempool=mp,
                                  event_bus=bus)
    ccfg = cfg.test_config().consensus
    cs = ConsensusState(
        ccfg, state, block_exec, BlockStore(MemDB()),
        mempool=mp, event_bus=bus, priv_validator=FilePV(vkeys[0], None),
    )
    sub = bus.subscribe("bench-load", query_for_event(EVENT_NEW_BLOCK), 4096)
    cs.start()

    sk = keys.PrivKeyEd25519.generate()
    submit_at = {}
    latencies_ms = []
    committed = set()

    def _drain(timeout):
        msg = sub.get(timeout=timeout)
        if msg is None:
            return
        now = time.perf_counter()
        for tx in msg.data["block"].data.txs:
            k = hashlib.sha256(tx).digest()
            t0 = submit_at.get(k)
            if t0 is not None and k not in committed:
                committed.add(k)
                latencies_ms.append((now - t0) * 1000)

    # pre-generate OUTSIDE the timed window: pure-Python Ed25519
    # signing costs ~ms/tx on fallback-crypto hosts and was previously
    # billed to the submit loop, understating the node's own ceiling
    n_target = LOAD_TPS * LOAD_SECS
    txs = [make_signed_tx(sk, b"bench-load-%08d" % i, priority=i % 2)
           for i in range(n_target)]

    futs = []
    t_start = time.perf_counter()
    for i, tx in enumerate(txs):
        k = hashlib.sha256(tx).digest()
        submit_at[k] = time.perf_counter()
        futs.append(mp.check_tx_nowait(tx))
        # pace to the target, absorbing drain time into the schedule
        next_t = t_start + (i + 1) / LOAD_TPS
        while time.perf_counter() < next_t:
            _drain(timeout=max(0.0, next_t - time.perf_counter()))
    accepted = 0
    for f in futs:
        try:
            if f.result(timeout=30).code == 0:
                accepted += 1
        except Exception:  # noqa: BLE001 - full pool counts as rejected
            pass
    # grace: let the tail commit
    deadline = time.time() + max(10.0, 2 * LOAD_SECS)
    while len(committed) < accepted and time.time() < deadline:
        _drain(timeout=0.25)
    wall_s = time.perf_counter() - t_start

    cs.stop()
    bus.stop()
    mp.stop()
    conns.stop()
    crypto_batch.shutdown_dispatchers()

    lat = sorted(latencies_ms)

    def _pct(p):
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else -1.0

    accepted_tps = accepted / max(wall_s, 1e-9)
    loop_ms, batch_ms = _socket_deliver_measure()
    print(json.dumps({
        "metric": LOAD_METRIC,
        "value": round(_pct(0.99), 3),
        "unit": "ms",
        "vs_baseline": round(accepted_tps / LOAD_TPS, 2),
        "target_tps": LOAD_TPS,
        "accepted_tps": round(accepted_tps, 1),
        "committed": len(committed),
        "p50_ms": round(_pct(0.50), 3),
        "p99_ms": round(_pct(0.99), 3),
        # the DeliverTx socket-pipelining micro-point (batch-written
        # request frames vs one round trip per tx, same app):
        "socket_deliver_loop_ms": round(loop_ms, 2),
        "socket_deliver_batch_ms": round(batch_ms, 2),
        "socket_deliver_speedup": round(loop_ms / max(batch_ms, 1e-9), 2),
        "note": ("single-validator in-process localnet, batched ingest, "
                 "2 lanes, txs pre-generated outside the timed window; "
                 "vs_baseline = accepted/target TPS"),
    }))
    return 0


def _socket_deliver_measure(n: int = 256):
    """Satellite micro-point: DeliverTx over a REAL ABCI socket, per-tx
    round-trip loop vs the batch-written pipeline (deliver_tx_batch).
    Returns (loop_ms, batch_ms)."""
    from tendermint_tpu.abci.client import SocketClient
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.abci.server import ABCIServer

    srv = ABCIServer("tcp://127.0.0.1:0", KVStoreApplication())
    srv.start()
    try:
        addr = f"tcp://127.0.0.1:{srv.local_port()}"
        txs = [b"sock-%05d=v" % i for i in range(n)]
        c = SocketClient(addr)
        try:
            c.deliver_tx(b"warm=1")
            t0 = time.perf_counter()
            for tx in txs:
                c.deliver_tx(tx)
            loop_ms = (time.perf_counter() - t0) * 1000
            t0 = time.perf_counter()
            c.deliver_tx_batch(txs)
            batch_ms = (time.perf_counter() - t0) * 1000
        finally:
            c.close()
    finally:
        srv.stop()
    return loop_ms, batch_ms


def _exec_load_leg(app_addr: str, exec_cfg, target_tps: int, secs: int,
                   mp_size: int = 200000, conflict_pct: int = 0,
                   hot_keys: int = EXEC_HOT_KEYS):
    """One parallel-exec load leg: a single-validator in-process
    localnet against `app_addr`, plain `k=v` txs (footprints come from
    the app's inference — no signing/verify on the measurement path),
    paced at target_tps for secs. conflict_pct > 0 swaps that share of
    the stream for signed txs with LYING access hints that really
    touch one of `hot_keys` shared keys (alternating writers and
    readers), so the planner schedules them concurrently and the merge
    observes genuine conflicts. Returns a stats dict."""
    import hashlib

    from tendermint_tpu import config as cfg
    from tendermint_tpu import state as sm
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.consensus import ConsensusState
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.libs.db import MemDB
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.privval import FilePV
    from tendermint_tpu.proxy import AppConns, default_client_creator
    from tendermint_tpu.types import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.event_bus import (
        EVENT_NEW_BLOCK, EventBus, query_for_event)
    from tendermint_tpu.types.validator_set import random_validator_set

    crypto_batch.set_default_backend("cpu")
    vs, vkeys = random_validator_set(1, 10)
    doc = GenesisDoc(
        chain_id="bench-exec",
        genesis_time=time.time_ns() - 10**9,
        validators=[GenesisValidator(v.pub_key, v.voting_power)
                    for v in vs.validators],
    )
    db = MemDB()
    state = sm.load_state_from_db_or_genesis(db, doc)
    conns = AppConns(default_client_creator(app_addr))
    conns.start()
    mp = Mempool(
        cfg.MempoolConfig(size=mp_size, lanes=2, preverify_batch=True,
                          ingest_queue_size=mp_size, recheck=False),
        conns.mempool)
    bus = EventBus()
    bus.start()

    class _Ctr:  # counting stub so the leg can report exec counters
        def __init__(self):
            self.value = 0

        def inc(self, n=1):
            self.value += n

        def set(self, v):
            self.value = v

        def observe(self, v):
            pass

    from tendermint_tpu.metrics import StateMetrics
    st_metrics = StateMetrics(
        block_processing_time=_Ctr(), validator_updates=_Ctr(),
        valset_changes=_Ctr(), exec_parallel_lanes=_Ctr(),
        exec_conflicts=_Ctr(), exec_speculation_hits=_Ctr(),
        exec_speculation_wasted=_Ctr())
    # fresh flight-recorder rings so the leg's wakeup percentiles and
    # busy ratios describe THIS leg only (serial legs record nothing —
    # the inline path is not instrumented)
    from tendermint_tpu.state.parallel import get_flight_recorder
    recorder = get_flight_recorder()
    recorder.reset()
    block_exec = sm.BlockExecutor(db, conns.consensus, mempool=mp,
                                  event_bus=bus, exec_config=exec_cfg,
                                  metrics=st_metrics)
    # a real kv tx indexer rides the run so the commit-stage breakdown
    # covers the index stage (block-at-a-time ingest, like a node)
    from tendermint_tpu.state.txindex import IndexerService, KVTxIndexer
    indexer = KVTxIndexer(MemDB())
    indexer_svc = IndexerService(indexer, bus,
                                 stage_profile=block_exec.stage_profile)
    indexer_svc.start()
    ccfg = cfg.test_config().consensus
    cs = ConsensusState(
        ccfg, state, block_exec, BlockStore(MemDB()),
        mempool=mp, event_bus=bus, priv_validator=FilePV(vkeys[0], None),
    )
    sub = bus.subscribe("bench-exec", query_for_event(EVENT_NEW_BLOCK), 4096)
    cs.start()

    n = target_tps * secs
    if conflict_pct > 0:
        from tendermint_tpu.crypto.keys import PrivKeyEd25519
        from tendermint_tpu.mempool.preverify import make_signed_tx
        signer = PrivKeyEd25519.gen_from_secret(b"bench-exec-conflict")
        txs = []
        j = 0  # running conflict-tx index; j//3 numbers the triple
        for i in range(n):
            if i % 100 >= conflict_pct:
                txs.append(b"bench-exec-%08d=v" % i)
                continue
            # conflict triples with LYING hints, all landing in
            # different groups: (A) points p_t at a hot key, (B) an
            # indirect write THROUGH p_t — its re-run retargets to the
            # hot key, a write that only appears on re-execution — and
            # (C) an honest-looking read OF that hot key. On the PR-16
            # engine B's re-run invalidates clean C → whole-block
            # serial fallback; the retry DAG converges in two rounds
            # re-running only the cone.
            t, role = j // 3, j % 3
            hot = b"h%02d" % (t % hot_keys)
            if role == 0:
                txs.append(make_signed_tx(
                    signer, b"p%05d=" % t + hot,
                    hints=[b"kv:a%05d" % t]))
            elif role == 1:
                txs.append(make_signed_tx(
                    signer, b"ind:p%05d:V%05d" % (t, t),
                    hints=[b"kv:b%05d" % t]))
            else:
                txs.append(make_signed_tx(
                    signer, b"cp:" + hot + b":c%05d" % t,
                    hints=[b"kv:c%05d" % t]))
            j += 1
    else:
        txs = [b"bench-exec-%08d=v" % i for i in range(n)]
    submit_at = {}
    latencies_ms = []
    committed = set()
    blocks = [0]

    def _drain(timeout):
        msg = sub.get(timeout=timeout)
        if msg is None:
            return
        blocks[0] += 1
        now = time.perf_counter()
        for tx in msg.data["block"].data.txs:
            k = hashlib.sha256(tx).digest()
            t0 = submit_at.get(k)
            if t0 is not None and k not in committed:
                committed.add(k)
                latencies_ms.append((now - t0) * 1000)

    futs = []
    t_start = time.perf_counter()
    for i, tx in enumerate(txs):
        submit_at[hashlib.sha256(tx).digest()] = time.perf_counter()
        futs.append(mp.check_tx_nowait(tx))
        next_t = t_start + (i + 1) / target_tps
        while time.perf_counter() < next_t:
            _drain(timeout=max(0.0, next_t - time.perf_counter()))
    accepted = 0
    for f in futs:
        try:
            if f.result(timeout=60).code == 0:
                accepted += 1
        except Exception:  # noqa: BLE001 - full pool counts as rejected
            pass
    deadline = time.time() + max(30.0, 6 * secs)
    while len(committed) < accepted and time.time() < deadline:
        _drain(timeout=0.25)
    wall_s = time.perf_counter() - t_start

    cs.stop()
    indexer_svc.stop()
    bus.stop()
    mp.stop()
    conns.stop()
    crypto_batch.shutdown_dispatchers()

    lat = sorted(latencies_ms)

    def _pct(p):
        return lat[min(len(lat) - 1, int(p * len(lat)))] if lat else -1.0

    m = block_exec.metrics
    # exec-lane flight-recorder summary for the leg (PR 16): wakeup
    # percentiles across lanes plus per-lane busy ratios. Serial legs
    # report count=0 — the inline path records nothing.
    wake = recorder.wakeup_percentiles()
    disp = recorder.dispatch_percentiles()
    full_report = recorder.report()
    lane_report = full_report["lanes"]
    rstats = recorder.retry_stats()
    return {
        "target_tps": target_tps,
        "accepted": accepted,
        "committed": len(committed),
        "committed_tps": round(len(committed) / max(wall_s, 1e-9), 1),
        "blocks": blocks[0],
        "p50_ms": round(_pct(0.50), 1),
        "p99_ms": round(_pct(0.99), 1),
        "conflict_reruns": m.exec_conflicts.value,
        # observed-conflict rate over the committed stream, plus the
        # PR-17 engine counters (all zero when retry/pool are off)
        "conflict_rate": round(
            m.exec_conflicts.value / max(len(committed), 1), 4),
        "retry_rounds_p99": rstats["retry_rounds_p99"],
        "retried_txs": rstats["retried_txs"],
        "steals": rstats["steals"],
        "steal_ratio": rstats["steal_ratio"],
        "serial_fallbacks": full_report["blocks"]["serial_fallbacks"],
        "speculation_hits": m.exec_speculation_hits.value,
        "speculation_wasted": m.exec_speculation_wasted.value,
        # the commit-path profiler's per-stage breakdown (the PR-13
        # point: the ceiling is attributable, not anecdotal)
        "stages": block_exec.stage_profile.snapshot(),
        "indexed_height": indexer.indexed_height(),
        "lane_wakeup_samples": wake["count"],
        "lane_wakeup_p50_us": round(wake["p50_s"] * 1e6, 3),
        "lane_wakeup_p99_us": round(wake["p99_s"] * 1e6, 3),
        # per-run critical-path lane-launch cost (PR 17): the wall time
        # the submitter spends getting all lanes going — serialized
        # blocking t.start() calls on the spawn engine vs non-blocking
        # pokes on the pool. This is the convoy number the two engines
        # can be compared on; per-lane wakeup samples can't be, because
        # t.start() blocks until the thread runs and so hides the spawn
        # convoy inside the submit loop.
        "dispatch_samples": disp["count"],
        "dispatch_p50_us": round(disp["p50_s"] * 1e6, 3),
        "dispatch_p99_us": round(disp["p99_s"] * 1e6, 3),
        "lane_busy_ratio": {
            lane: rep["busy_ratio"] for lane, rep in lane_report.items()},
    }


def load_parallel_main():
    """`bench.py load --parallel` — the PR-12 tentpole point, extended
    by PR 17: the same sharded kvstore workload (EXEC_IO_US of
    simulated per-tx backend latency) executed serially ([execution]
    defaults — the committed baseline, BENCH_LOAD_SERIAL.json), with
    the PR-16 spawn-per-block engine, and with the PR-17 persistent
    lane pool + retry DAG. Two extra high-conflict legs
    (EXEC_CONFLICT_PCT% of txs carrying lying hints over EXEC_HOT_KEYS
    shared keys) compare the old conflict path (segment re-run /
    whole-block serial fallback) against the conflict-cone retry
    engine. vs_baseline is pooled-parallel/serial committed TPS, both
    measured in THIS run so the ratio is like-for-like on the box."""
    from tendermint_tpu.config import ExecutionConfig

    app = f"sharded_kvstore:shards=64,io_us={EXEC_IO_US}"
    spawn_cfg = dict(parallel_lanes=EXEC_LANES, speculative=True)
    pool_cfg = dict(parallel_lanes=EXEC_LANES, speculative=True,
                    lane_pool=True, retry_max_rounds=EXEC_RETRY_ROUNDS)
    serial = _exec_load_leg(app, ExecutionConfig(), EXEC_SERIAL_TPS,
                            EXEC_SECS)
    spawn = _exec_load_leg(app, ExecutionConfig(**spawn_cfg),
                           EXEC_PAR_TPS, EXEC_SECS)
    pooled = _exec_load_leg(app, ExecutionConfig(**pool_cfg),
                            EXEC_PAR_TPS, EXEC_SECS)
    hc_spawn = _exec_load_leg(app, ExecutionConfig(**spawn_cfg),
                              EXEC_HC_TPS, EXEC_HC_SECS,
                              conflict_pct=EXEC_CONFLICT_PCT)
    hc_retry = _exec_load_leg(app, ExecutionConfig(**pool_cfg),
                              EXEC_HC_TPS, EXEC_HC_SECS,
                              conflict_pct=EXEC_CONFLICT_PCT)
    s_tps = max(serial["committed_tps"], 1e-9)
    print(json.dumps({
        "metric": EXEC_METRIC,
        "value": pooled["committed_tps"],
        "unit": "tps",
        "vs_baseline": round(pooled["committed_tps"] / s_tps, 2),
        # exec-lane flight recorder (PR 16/17): the wakeup convoy is
        # compared on the per-run DISPATCH span — the submitter-side
        # critical path of getting every lane going. On the spawn
        # engine that is n_lanes serialized blocking t.start() calls;
        # on the pool it is the non-blocking per-lane poke loop.
        # (Per-lane wakeup samples are reported per leg but are NOT
        # comparable across engines: t.start() blocks until the new
        # thread runs, so the spawn path's per-thread samples hide the
        # convoy the submit loop pays.)
        "lane_wakeup_p50_us": pooled["lane_wakeup_p50_us"],
        "lane_wakeup_p99_us": pooled["lane_wakeup_p99_us"],
        "lane_wakeup_samples": pooled["lane_wakeup_samples"],
        "dispatch_p99_us": pooled["dispatch_p99_us"],
        "spawn_dispatch_p99_us": spawn["dispatch_p99_us"],
        "wakeup_p99_speedup": round(
            spawn["dispatch_p99_us"]
            / max(pooled["dispatch_p99_us"], 1e-9), 2),
        # PR-17 conflict-path summary (from the retry-DAG high-conflict
        # leg; hc_speedup = retry-DAG tps / PR-16-engine tps on the
        # identical lying-hint stream)
        "conflict_rate": hc_retry["conflict_rate"],
        "retry_rounds_p99": hc_retry["retry_rounds_p99"],
        "steal_ratio": hc_retry["steal_ratio"],
        "hc_speedup": round(
            hc_retry["committed_tps"]
            / max(hc_spawn["committed_tps"], 1e-9), 2),
        "serial": serial,
        "parallel": spawn,
        "pooled": pooled,
        "hc_spawn": hc_spawn,
        "hc_retry": hc_retry,
        "io_us": EXEC_IO_US,
        "lanes": EXEC_LANES,
        "conflict_pct": EXEC_CONFLICT_PCT,
        "hot_keys": EXEC_HOT_KEYS,
        "retry_rounds": EXEC_RETRY_ROUNDS,
        "note": ("single-validator in-process localnet, sharded_kvstore "
                 f"with {EXEC_IO_US}us simulated per-tx backend latency "
                 "(GIL-released stall), plain k=v txs partitioned via "
                 "app footprint inference; serial leg = [execution] "
                 "defaults (the conformance oracle), parallel legs = "
                 f"{EXEC_LANES} lanes + speculative execution, spawn-"
                 "per-block vs persistent work-stealing lane pool + "
                 f"retry DAG; hc_* legs add {EXEC_CONFLICT_PCT}% lying-"
                 f"hint txs over {EXEC_HOT_KEYS} hot keys; vs_baseline "
                 "= pooled/serial committed TPS; wakeup_p99_speedup = "
                 "spawn/pooled per-run lane-launch (dispatch) p99 — "
                 "the submit-side convoy, comparable across engines"),
    }))
    return 0


def rpcload_main():
    """`bench.py rpcload` — RPC serving at fan-out scale: a single-
    validator in-process node answers a concurrent mixed read load
    (status/block/validators) through the serving layer twice — once
    with the height/generation byte cache on, once bypassed — and then
    fans NewBlock events out to RPC_SUBS live websocket subscribers,
    reporting the render-once funnel (renders vs frames delivered).
    Pure host path; the JSON line is the hot-status p50 with
    vs_baseline = uncached_p50 / cached_p50."""
    import tempfile
    import threading

    os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
    os.environ.setdefault("TM_TPU_WARMUP", "0")

    from tendermint_tpu import config as cfg
    from tendermint_tpu.node import default_new_node
    from tendermint_tpu.p2p import NodeKey
    from tendermint_tpu.privval import load_or_gen_file_pv
    from tendermint_tpu.rpc import core as rpc_core
    from tendermint_tpu.rpc.client import WSClient
    from tendermint_tpu.types import GenesisDoc, GenesisValidator
    from tendermint_tpu.types.event_bus import (
        EVENT_NEW_BLOCK, query_for_event)

    with tempfile.TemporaryDirectory(prefix="bench_rpcload_") as root:
        c = cfg.test_config()
        c.set_root(root)
        c.base.proxy_app = "kvstore"
        c.base.moniker = "bench-rpcload"
        c.rpc.laddr = "tcp://127.0.0.1:0"
        c.rpc.cache_bytes = 32 << 20
        c.rpc.ws_send_queue = 512
        c.p2p.laddr = "tcp://127.0.0.1:0"
        # a slow-ish cadence leaves clear gaps between blocks, so the
        # fan-out phase can align its counting window to the block
        # schedule and compare renders vs deliveries exactly
        c.consensus.create_empty_blocks_interval = 0.6
        cfg.ensure_root(root)
        NodeKey.load_or_gen(c.base.node_key_path())
        pv = load_or_gen_file_pv(c.base.priv_validator_path())
        GenesisDoc(
            chain_id="bench-rpcload",
            genesis_time=time.time_ns() - 10**9,
            validators=[GenesisValidator(pv.get_pub_key(), 10)],
        ).save(c.base.genesis_path())
        node = default_new_node(c)
        sub = node.event_bus.subscribe(
            "bench-rpcload", query_for_event(EVENT_NEW_BLOCK), 64)
        node.start()
        try:
            deadline = time.time() + 60
            while node.block_store.height() < 2 and time.time() < deadline:
                sub.get(timeout=0.5)
            if node.block_store.height() < 2:
                raise RuntimeError("node never committed 2 blocks")
            srv = node._rpc_server

            queries = [("status", {}), ("block", {"height": 1}),
                       ("validators", {})]

            def run_load():
                """RPC_QUERIES mixed calls across RPC_THREADS threads
                through the serving layer; returns {method: [ms...]}."""
                lats = {m: [] for m, _ in queries}
                lock = threading.Lock()
                per_thread = RPC_QUERIES // RPC_THREADS

                def worker():
                    local = {m: [] for m, _ in queries}
                    for i in range(per_thread):
                        m, p = queries[i % len(queries)]
                        t0 = time.perf_counter()
                        srv.call_bytes(m, p)
                        local[m].append(
                            (time.perf_counter() - t0) * 1000)
                    with lock:
                        for m in local:
                            lats[m].extend(local[m])

                ts = [threading.Thread(target=worker)
                      for _ in range(RPC_THREADS)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return lats

            def _pct(samples, p):
                s = sorted(samples)
                return s[min(len(s) - 1, int(p * len(s)))] if s else -1.0

            # warm the cache, then the cached run; then bypass the
            # cache entirely for the baseline (same handlers, full
            # render + encode per call — today's serving path)
            for m, p in queries:
                srv.call_bytes(m, p)
            cached = run_load()
            saved_cache, srv.cache = srv.cache, None
            try:
                uncached = run_load()
            finally:
                srv.cache = saved_cache

            # fan-out: RPC_SUBS real websocket subscribers, NewBlock
            clients = []
            for _ in range(RPC_SUBS):
                w = WSClient(node.rpc_listen_addr)
                w.connect(timeout=10.0)
                w.subscribe("tm.event = 'NewBlock'")
                clients.append(w)

            delivered = {}  # height -> frames read

            def drain_all(record=True) -> int:
                got = 0
                for w in clients:
                    while True:
                        ev = w.next_event(timeout=0)
                        if ev is None:
                            break
                        got += 1
                        if record:
                            try:
                                h = (ev["data"]["value"]["block"]
                                     ["header"]["height"])
                            except (KeyError, TypeError):
                                continue
                            delivered[h] = delivered.get(h, 0) + 1
                return got

            def settle():
                """Align to the block schedule: wait for the next
                NewBlock on the node bus (render + delivery start at
                that instant), give its frames a beat to reach every
                client reader, and drain them — the next block is then
                a comfortable fraction of the 0.6s interval away, so a
                snapshot taken now sits in quiet air with nothing in
                flight between renderer, queues, and clients."""
                while sub.get(timeout=0.0) is not None:
                    pass  # clear bus backlog
                if sub.get(timeout=10.0) is None:
                    raise RuntimeError("chain stopped producing blocks")
                time.sleep(0.2)
                drain_all()

            # discard the connect-phase boundary (clients subscribed
            # at different instants), then count a clean window
            settle()
            delivered.clear()
            renders0 = rpc_core.events_rendered_count()
            t0 = time.perf_counter()
            window_s = 3.0
            end = time.perf_counter() + window_s
            while time.perf_counter() < end:
                drain_all()
                time.sleep(0.02)
            settle()
            renders = rpc_core.events_rendered_count() - renders0
            frames = sum(delivered.values())
            for w in clients:
                w.close()
            fanout_s = time.perf_counter() - t0

            cached_p50 = _pct(cached["status"], 0.50)
            uncached_p50 = _pct(uncached["status"], 0.50)
            print(json.dumps({
                "metric": RPCLOAD_METRIC,
                "value": round(cached_p50, 4),
                "unit": "ms",
                "vs_baseline": round(uncached_p50 / max(cached_p50, 1e-9),
                                     2),
                "status_p50_ms": round(cached_p50, 4),
                "status_p99_ms": round(_pct(cached["status"], 0.99), 4),
                "status_uncached_p50_ms": round(uncached_p50, 4),
                "status_uncached_p99_ms": round(
                    _pct(uncached["status"], 0.99), 4),
                "block_p50_ms": round(_pct(cached["block"], 0.50), 4),
                "block_uncached_p50_ms": round(
                    _pct(uncached["block"], 0.50), 4),
                "validators_p50_ms": round(
                    _pct(cached["validators"], 0.50), 4),
                "validators_uncached_p50_ms": round(
                    _pct(uncached["validators"], 0.50), 4),
                "cache_hit_rate": srv.cache.stats()["hit_rate"],
                "subscribers": RPC_SUBS,
                "fanout_events": len(delivered),
                "fanout_renders": renders,
                "fanout_frames_delivered": frames,
                "renders_per_event": round(
                    renders / max(len(delivered), 1), 2),
                "fanout_window_s": round(fanout_s, 2),
                "note": ("in-process node; mixed status/block/validators"
                         f" x{RPC_QUERIES} over {RPC_THREADS} threads, "
                         "cached (pre-encoded bytes) vs uncached "
                         "(handler+encode); render-once websocket "
                         "fan-out — renders advance per event, frames "
                         "per (event x subscriber)"),
            }))
        finally:
            node.stop()
    return 0


def aggverify_main():
    """`bench.py aggverify` — the aggregate-signature fast lane: ONE
    BLS commit certificate (signer bitmap + 96-byte aggregate) verified
    with one pubkey aggregation + one 2-pairing product check, against
    the Ed25519 batch path (verify_commit over N per-vote signatures)
    at the same committee size. cpu backend forced (pure host path —
    this mode must not pay, or hang on, a jax init); the BLS pubkey
    MSM runs the registry default (python unless TM_TPU_BLS_MSM=jax).

    Fixture note: the BLS committee uses consecutive secret scalars so
    the 10k pubkeys come from incremental generator additions, and the
    aggregate signature is [sum sk_i] H(m) — mathematically identical
    to aggregating per-validator signatures, without 10k G2 scalar
    multiplications of fixture setup."""
    from tendermint_tpu.crypto import batch as crypto_batch
    from tendermint_tpu.crypto import bls
    from tendermint_tpu.crypto.bls import curve as bc
    from tendermint_tpu.crypto.bls.fields import R_ORDER
    from tendermint_tpu.crypto.bls.hash_to_curve import hash_to_g2
    from tendermint_tpu.libs.bit_array import BitArray
    from tendermint_tpu.types import BlockID
    from tendermint_tpu.types.basic import PartSetHeader
    from tendermint_tpu.types.block import AggregateCommit
    from tendermint_tpu.types.validator_set import Validator, ValidatorSet

    crypto_batch.set_default_backend("cpu")
    crypto_batch.set_sig_cache(None)  # the certificate never hits the
    # sig cache anyway; the ed25519 baseline must not either
    chain = "bench-aggverify"
    nval = AGG_NVAL
    bid = BlockID(b"\x07" * 20, PartSetHeader(1, b"\x0c" * 20))

    # --- BLS committee: pk_i = [s0 + i] G1, built incrementally -------
    s0 = 7_777_777
    pt = bc.g1_mul(bc.G1_GEN, s0)
    jac_points = []
    for _ in range(nval):
        jac_points.append(pt)
        pt = bc.g1_add(pt, bc.G1_GEN)
    # batch-normalize via one shared inversion chain (affine pubkeys)
    from tendermint_tpu.crypto.bls.fields import P as _P, fp_inv

    zs = [p[2] for p in jac_points]
    prefix, acc = [], 1
    for z in zs:
        prefix.append(acc)
        acc = acc * z % _P
    inv = fp_inv(acc)
    pubs = [None] * nval
    for i in range(nval - 1, -1, -1):
        zi = inv * prefix[i] % _P
        inv = inv * zs[i] % _P
        zi2 = zi * zi % _P
        X, Y, _ = jac_points[i]
        pubs[i] = bls.PubKeyBLS12381(
            bc.g1_compress((X * zi2 % _P, Y * zi2 * zi % _P, 1)))
    vals_bls = ValidatorSet([Validator.new(pk, 10) for pk in pubs])

    signers = BitArray(nval)
    for i in range(nval):
        signers.set_index(i, True)
    cert = AggregateCommit(block_id=bid, agg_height=1, agg_round=0,
                           signers=signers, agg_sig=b"\x00" * 96)
    sum_sk = sum(s0 + i for i in range(nval)) % R_ORDER
    hm = hash_to_g2(cert.sign_bytes(chain), bls.DST_SIG)
    cert.agg_sig = bc.g2_compress(bc.g2_mul(hm, sum_sk))

    def bls_run():
        vals_bls.verify_commit(chain, bid, 1, cert)

    # --- Ed25519 baseline: the existing batch path, same size ---------
    vs_ed, sorted_sks = _build_valset(nval, b"agg-ed")
    commit_ed = _build_commit(chain, vs_ed, sorted_sks, 1, bid)

    def ed_run():
        vs_ed.verify_commit(chain, bid, 1, commit_ed)

    bls_run()  # warm (point parse caches)
    bls_ms = _best_of(bls_run, 3)
    ed_ms = _best_of(ed_run, 2)

    cert_bytes = cert.size_bytes()
    print(json.dumps({
        "metric": AGG_METRIC,
        "value": round(bls_ms, 3),
        "unit": "ms",
        "vs_baseline": round(ed_ms / bls_ms, 2),
        "ed25519_batch_ms": round(ed_ms, 3),
        "cert_bytes": cert_bytes,
        "signature_bytes_ed25519": 64 * nval,
        "msm_backend": __import__(
            "tendermint_tpu.crypto.bls.msm", fromlist=["msm"]
        ).default_msm_backend(),
        "note": ("one fast_aggregate_verify (bitmap MSM + 2-pairing "
                 "check) vs verify_commit over %d per-vote Ed25519 "
                 "signatures; cpu backend forced" % nval),
    }))
    return 0


def handel_main():
    """`bench.py handel` — the Handel aggregation overlay vs the flat
    per-vote lane at committee size HANDEL_NVAL (default 1024): run the
    REAL per-session state machine for every committee member (actual
    binomial-tree routing, windowed sends, wire-encoded contribution
    messages, real G2 aggregation) and count what one node pays to
    assemble a full-committee certificate.

    The verify_fn is a counting stub — per-item pairing work is what
    the mode MEASURES, and correctness is enforced end-to-end by the
    oracle instead: every session's final certificate must byte-equal
    the flat-lane aggregate [sum sk_i]H(m) for the same vote set, or
    the metric value is -1. Signature fixtures use consecutive scalars
    (sig_i = [s0+i]H(m), built by incremental G2 adds) so setup stays
    O(n) adds instead of n scalar multiplications."""
    from tendermint_tpu.consensus.handel import HandelSession, num_levels
    from tendermint_tpu.consensus.messages import (
        HandelContributionMessage,
        VoteMessage,
    )
    from tendermint_tpu.consensus.reactor import encode_msg
    from tendermint_tpu.crypto import bls
    from tendermint_tpu.crypto.bls import curve as bc
    from tendermint_tpu.crypto.bls.fields import R_ORDER
    from tendermint_tpu.crypto.bls.hash_to_curve import hash_to_g2
    from tendermint_tpu.types.basic import (
        VOTE_TYPE_PRECOMMIT,
        BlockID,
        PartSetHeader,
        Vote,
        canonical_vote_sign_bytes,
    )

    n = HANDEL_NVAL
    chain = "bench-handel"
    bid = BlockID(b"\x07" * 32, PartSetHeader(1, b"\x0c" * 32))
    msg = canonical_vote_sign_bytes(
        chain, VOTE_TYPE_PRECOMMIT, 1, 0, bid, 0)
    hm = hash_to_g2(msg, bls.DST_SIG)

    # per-validator precommit signatures sig_i = [s0+i] H(m)
    s0 = 424_242
    pts, pt = [], bc.g2_mul(hm, s0)
    for _ in range(n):
        pts.append(pt)
        pt = bc.g2_add(pt, hm)
    sigs = [bc.g2_compress(p) for p in pts]
    # flat-lane reference certificate over the same vote set
    sum_sk = sum(s0 + i for i in range(n)) % R_ORDER
    flat_cert = bc.g2_compress(bc.g2_mul(hm, sum_sk))

    counters = {"calls": 0, "items": 0}

    def verify_fn(items):
        counters["calls"] += 1
        counters["items"] += len(items)
        return [True] * len(items)

    t0 = time.perf_counter()
    sessions = [
        HandelSession(
            n, i, [1] * n, sigs[i], verify_fn=verify_fn,
            parse_fn=bls._parse_signature_point, add_fn=bc.g2_add,
            compress_fn=bc.g2_compress, seed=1, height=1, round_=0,
            window=4, level_timeout_s=1e9, resend_ticks=2,
            reshuffle_ticks=8)
        for i in range(n)
    ]
    sent_bytes = 0
    inboxes = [[] for _ in range(n)]
    certs = {}
    now = 0.0
    rounds = 0
    max_rounds = 6 * num_levels(n) + 8
    while rounds < max_rounds:
        rounds += 1
        now += 0.05
        for i, s in enumerate(sessions):
            for target, level, bits, sig in s.tick(now):
                sent_bytes += len(encode_msg(HandelContributionMessage(
                    1, 0, level, i, bid, bits, sig)))
                inboxes[target].append((i, level, bits, sig))
        for i, s in enumerate(sessions):
            if inboxes[i]:
                s.add_contributions(inboxes[i], now)
                inboxes[i] = []
            c = s.take_certificate()
            if c is not None:
                certs[i] = c
        if len(certs) == n and all(
                b.num_true() == n for b, _ in certs.values()):
            break
    wall_ms = (time.perf_counter() - t0) * 1000

    byte_equal = len(certs) == n and all(
        bits.num_true() == n and sig == flat_cert
        for bits, sig in certs.values())

    # per-node accounting. Overlay: measured from the run (verify items
    # feed ONE multi-pair check per absorb batch -> items + calls
    # Miller loops). Flat lane: every node verifies n-1 individual
    # precommits (2 pairings each) and receives n-1 wire votes.
    ov_verify = counters["items"] / n
    ov_pairings = (counters["items"] + counters["calls"]) / n
    ov_bytes = sent_bytes / n
    flat_verify = n - 1
    flat_pairings = 2 * (n - 1)
    vote_wire = len(encode_msg(VoteMessage(Vote(
        b"\x01" * 20, 0, 1, 0, 0, VOTE_TYPE_PRECOMMIT, bid, sigs[0]))))
    flat_bytes = (n - 1) * vote_wire

    print(json.dumps({
        "metric": HANDEL_METRIC,
        "value": round(ov_verify, 2) if byte_equal else -1,
        "unit": "aggregate verifications/node/round",
        "oracle_cert_byte_equal": byte_equal,
        "converged_sessions": len(certs),
        "rounds": rounds,
        "wall_ms": round(wall_ms, 1),
        "flat_verify_ops": flat_verify,
        "verify_ops_ratio": round(flat_verify / max(ov_verify, 1e-9), 1),
        "overlay_pairings": round(ov_pairings, 2),
        "flat_pairings": flat_pairings,
        "pairings_ratio": round(flat_pairings / max(ov_pairings, 1e-9), 1),
        "overlay_gossip_bytes": round(ov_bytes),
        "flat_gossip_bytes": flat_bytes,
        "gossip_bytes_ratio": round(flat_bytes / max(ov_bytes, 1e-9), 1),
        "note": ("%d real HandelSessions to full-committee certificate; "
                 "flat lane = n-1 per-vote verifies (2 pairings each) + "
                 "n-1 wire votes (%dB each) per node; value -1 unless "
                 "every overlay certificate byte-equals the flat "
                 "aggregate" % (n, vote_wire)),
    }))

    # -- satellite line: verify_aggregates_many batching at k=8 --------
    # (the Handel level-verify workhorse: one 2k-pair Miller loop vs k
    # sequential fast_aggregate_verify calls, REAL pairings both ways)
    k, m = 8, 8
    t0sk = 31_337
    g1pts, gp = [], bc.g1_mul(bc.G1_GEN, t0sk)
    for _ in range(m):
        g1pts.append(gp)
        gp = bc.g1_add(gp, bc.G1_GEN)
    pks = [bc.g1_compress(p) for p in g1pts]
    sum_pk_sk = sum(t0sk + i for i in range(m)) % R_ORDER
    items = []
    for j in range(k):
        mj = b"bench-handel-batch-%d" % j
        sj = bc.g2_compress(bc.g2_mul(
            hash_to_g2(mj, bls.DST_SIG), sum_pk_sk))
        items.append((pks, mj, sj))

    def batched():
        assert all(bls.verify_aggregates_many(items))

    def sequential():
        for pk_list, mj, sj in items:
            assert bls.fast_aggregate_verify(
                pk_list, mj, sj, require_pop=False)

    batched()  # warm point-parse caches for both paths
    batch_ms = _best_of(batched, 3)
    seq_ms = _best_of(sequential, 3)
    print(json.dumps({
        "metric": f"verify_aggregates_many_k{k}_wall_ms",
        "value": round(batch_ms, 3),
        "unit": "ms",
        "vs_baseline": round(seq_ms / batch_ms, 2),
        "sequential_ms": round(seq_ms, 3),
        "note": (f"{k} aggregate certificates ({m} signers each) in one "
                 "RLC multi-pair check vs sequential 2-pairing "
                 "fast_aggregate_verify calls"),
    }))
    return 0 if byte_equal else 1


def chaos_main():
    """`bench.py chaos` — ABCI reconnect recovery latency: a real
    kvstore socket app, a ResilientClient(retry) supervising the
    connection, and a ChaosClient injecting a hard disconnect each
    round. Measures wall from the failed in-flight call to the first
    call served on the redialed connection (the window in which a
    mempool/query conn fails soft). Pure host path: no TPU."""
    import threading

    from tendermint_tpu.abci import types as abci_types
    from tendermint_tpu.abci.chaos import ChaosClient, ChaosRule
    from tendermint_tpu.abci.client import ABCIClientError, SocketClient
    from tendermint_tpu.abci.example.kvstore import KVStoreApplication
    from tendermint_tpu.abci.server import ABCIServer
    from tendermint_tpu.proxy.resilient import ResilientClient

    srv = ABCIServer("tcp://127.0.0.1:0", KVStoreApplication())
    srv.start()
    addr = f"tcp://127.0.0.1:{srv.local_port()}"

    chaos_handle = []

    def creator():
        c = ChaosClient(SocketClient(addr, request_timeout=2.0), seed=7)
        chaos_handle.append(c)
        return c

    client = ResilientClient(
        "bench", creator, policy="retry",
        backoff_base_s=0.005, backoff_max_s=0.05, retry_budget=5)
    client.start()

    recoveries_ms = []
    try:
        for round_i in range(CHAOS_ROUNDS):
            # healthy steady state
            deadline = time.time() + 10
            while time.time() < deadline:
                try:
                    client.check_tx(b"k%d=v" % round_i)
                    break
                except ABCIClientError:
                    time.sleep(0.002)
            else:
                raise RuntimeError("conn never became healthy")
            # one-shot hard disconnect on the CURRENT transport
            chaos_handle[-1].rules.append(
                ChaosRule("disconnect", methods=("echo",), max_fires=1))
            t0 = time.perf_counter()
            try:
                client.echo("boom")
            except ABCIClientError:
                pass  # the in-flight call fails soft by design
            while True:
                try:
                    client.echo("recovered?")
                    break
                except ABCIClientError:
                    time.sleep(0.001)
            recoveries_ms.append((time.perf_counter() - t0) * 1000)
    finally:
        client.close()
        srv.stop()

    mean_ms = sum(recoveries_ms) / len(recoveries_ms)
    print(json.dumps({
        "metric": CHAOS_METRIC,
        "value": round(mean_ms, 3),
        "unit": "ms",
        "vs_baseline": 1.0,
        "note": ("mean wall from injected disconnect to first call on "
                 "the redialed conn; best %.3f worst %.3f over %d rounds"
                 % (min(recoveries_ms), max(recoveries_ms),
                    len(recoveries_ms))),
        "reconnects": client.reconnects,
    }))
    return 0


# Child process for `bench.py warmstart`: measure KERNEL READINESS —
# the wall time from "I want the n-sig commit kernel" to "a compiled
# executable is dispatchable" — in a fresh process against a given
# compile-cache dir. Run twice against the same dir, the first child is
# the cold compile (writes the AOT artifact), the second the warm load.
_WARMSTART_CHILD = r'''
import json, os, sys, time
n, cache_dir = int(sys.argv[1]), sys.argv[2]
os.environ["TM_TPU_COMPILE_CACHE"] = cache_dir
t_boot = time.perf_counter()
import numpy as np
import jax
from tendermint_tpu.crypto import kernel_cache
from tendermint_tpu.crypto.jaxed25519 import verify as V
# dims of an n-sig commit batch (vote-sized ~110B messages) without
# paying n real signatures — zeros pack to the same padded shape
msgs = [b"x" * 110] * n
sig = np.zeros((n, 64), dtype=np.uint8)
pk = np.zeros((n, 32), dtype=np.uint8)
_, nb, mrows, bpad = V.pack_buffer(msgs, sig, pk, 1)
fn = V._jitted_packed(nb, mrows, bpad, 1, donate=V._donate_default())
t0 = time.perf_counter()
if hasattr(fn, "prepare"):
    fn.prepare(jax.ShapeDtypeStruct((V.ROWS_AUX + mrows, bpad),
                                    jax.numpy.int32))
else:  # cache layer disabled/unavailable: readiness = first dispatch
    np.asarray(fn(np.zeros((V.ROWS_AUX + mrows, bpad), dtype=np.int32)))
ready_s = time.perf_counter() - t0
print(json.dumps({"ready_s": ready_s, "boot_s": t0 - t_boot,
                  "stats": kernel_cache.stats()}))
'''


def warmstart_main(degraded):
    """Compile-once story end to end: a cold process pays the XLA
    compile for the WARM_N-sig commit kernel and writes the AOT
    artifact; a second process on the same machine loads it in
    milliseconds. vs_baseline = cold readiness / warm readiness."""
    import shutil
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="tmtpu-warmstart-")
    env = dict(os.environ)
    env.pop("TM_TPU_COMPILE_CACHE", None)
    if degraded:
        env["JAX_PLATFORMS"] = "cpu"

    def run_child(tag):
        t0 = time.perf_counter()
        p = subprocess.run(
            [sys.executable, "-c", _WARMSTART_CHILD, str(WARM_N), cache_dir],
            capture_output=True, text=True, env=env, timeout=1800)
        if p.returncode != 0:
            raise RuntimeError(
                f"warmstart {tag} child failed: {p.stderr[-300:]}")
        res = json.loads(p.stdout.strip().splitlines()[-1])
        return res, time.perf_counter() - t0

    try:
        cold, cold_wall = run_child("cold")
        warm, warm_wall = run_child("warm")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    out = {
        "metric": WARM_METRIC,
        "value": round(warm["ready_s"] * 1000, 1),
        "unit": "ms",
        "vs_baseline": round(cold["ready_s"] / max(warm["ready_s"], 1e-9), 2),
        "cold_ready_ms": round(cold["ready_s"] * 1000, 1),
        "cold_wall_ms": round(cold_wall * 1000, 1),
        "warm_wall_ms": round(warm_wall * 1000, 1),
        # the warm child must have LOADED the artifact, not recompiled
        "warm_cache_hit": bool(warm["stats"].get("hits", 0) >= 1
                               and warm["stats"].get("compiles", 0) == 0),
    }
    _emit(out, degraded)


def chaosnet_main():
    """`bench.py chaosnet` — network-partition recovery latency: the
    partition_heal scenario (tools/scenarios.py) on an in-process
    localnet, reporting wall ms from fault removal to the first NEW
    height committed and agreed by every node. Pure host path: no TPU.
    The scenario's oracle gates the number: a run that fails to
    converge, violates safety, or misclassifies its stall emits
    value -1 instead of a fake latency."""
    os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
    os.environ.setdefault("TM_TPU_WARMUP", "0")

    from tendermint_tpu.tools import scenarios

    res = scenarios.run("partition_heal", seed=CHAOSNET_SEED,
                        n=CHAOSNET_NVAL)
    ok = bool(res.get("ok"))
    recovery_ms = (round(res["recovery_s"] * 1000, 1)
                   if ok and res.get("recovery_s") is not None else -1)
    print(json.dumps({
        "metric": CHAOSNET_METRIC,
        "value": recovery_ms,
        "unit": "ms",
        "vs_baseline": 1.0,
        "seed": CHAOSNET_SEED,
        "converged": res.get("converged"),
        "safety_ok": res.get("safety_ok"),
        "classified_ok": res.get("classified_ok"),
        "stall_reasons": sorted(set(res.get("stall_reasons", []))),
        "note": ("wall from partition heal to first new agreed height; "
                 "fault timeline replayable from seed "
                 f"{CHAOSNET_SEED} (netchaos FaultPlan)"),
    }))
    return 0 if ok else 1


def crashrecovery_main():
    """`bench.py crashrecovery` — kill -> recovered-and-committing
    latency: the crash-matrix harness (tools/crashmatrix.py) warms a
    FileDB-backed single-validator node, kills it in-process at
    ApplyBlock.AfterCommit (app committed, chain state unsaved — the
    stored-responses handshake path, the most intricate replay case),
    restarts from disk, and measures wall from the kill to the first
    NEW committed block. The recovery oracle gates the number: any
    failing clause (handshake, double-sign guard, index convergence,
    app-hash-vs-uncrashed-replay) emits value -1 instead of a fake
    latency. Pure host path: no TPU."""
    import shutil
    import tempfile

    os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
    os.environ.setdefault("TM_TPU_WARMUP", "0")

    from tendermint_tpu.tools import crashmatrix

    root = tempfile.mkdtemp(prefix="bench_crashrec_")
    recoveries_ms = []
    oracle_ok = True
    results = []
    try:
        for i in range(CRASHREC_ROUNDS):
            # one matrix cell per round: run_case owns the warm/kill/
            # restart sequence AND the full recovery oracle (handshake,
            # progression, double-sign guard vs the release ledger,
            # index convergence, app-hash-vs-uncrashed-replay), so the
            # published latency can never outlive the oracle's rigor
            res = crashmatrix.run_case(
                os.path.join(root, f"round{i}"),
                "ApplyBlock.AfterCommit", mode="clean", nth=1,
                timeout=60)
            ok = bool(res.get("ok"))
            oracle_ok = oracle_ok and ok
            if ok and res.get("recommit_s"):
                recoveries_ms.append(res["recommit_s"] * 1000)
            results.append({"round": i,
                            "crash_height": res.get("crash_height"),
                            "oracle_ok": ok})
    finally:
        shutil.rmtree(root, ignore_errors=True)

    mean_ms = (sum(recoveries_ms) / len(recoveries_ms)
               if recoveries_ms else -1)
    print(json.dumps({
        "metric": CRASHREC_METRIC,
        "value": round(mean_ms, 1) if oracle_ok and recoveries_ms else -1,
        "unit": "ms",
        "vs_baseline": 1.0,
        "rounds": results,
        "note": ("wall from in-process kill at ApplyBlock.AfterCommit "
                 "to the first NEW committed block after restart; "
                 "best %.1f worst %.1f over %d rounds"
                 % (min(recoveries_ms), max(recoveries_ms),
                    len(recoveries_ms))) if recoveries_ms else
                "no recovery completed",
    }))
    return 0 if oracle_ok else 1


def detcheck_main():
    """`bench.py detcheck` — the replay-divergence oracle as a gated
    BENCH line: the churn+sharded workload executed under serial,
    parallel(2), parallel(4), speculative, and two cross-PYTHONHASHSEED
    subprocess engines, every consensus-visible surface (app hashes,
    DeliverTx results, event stream, tx-index rows, durable FileDB
    image) diffed byte-for-byte. Any divergence gates the metric to -1:
    a wall time is only worth publishing for a matrix that agrees.
    Pure host path: no TPU."""
    os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
    os.environ.setdefault("TM_TPU_WARMUP", "0")

    from tendermint_tpu.tools import detcheck

    t0 = time.perf_counter()
    rep = detcheck.run_oracle(n_blocks=DETCHECK_BLOCKS)
    wall_ms = (time.perf_counter() - t0) * 1000
    ok = not rep["divergences"]
    print(json.dumps({
        "metric": DETCHECK_METRIC,
        "value": round(wall_ms, 1) if ok else -1,
        "unit": "ms",
        "vs_baseline": 1.0 if ok else 0.0,
        "engines": rep["engines"],
        "divergences": rep["divergences"],
        "app_hash": rep["app_hash"][:16],
        "note": ("serial==parallel(2,4)==speculative==cross-hashseed "
                 "subprocesses on app_hashes/results/events/index/image"
                 if ok else "DIVERGENT — see divergences"),
    }))
    return 0 if ok else 1


def proptrace_main():
    """`bench.py proptrace` — fleet causal tracing as a gated BENCH
    line: the proptrace scenario (tools/scenarios.py) runs a 4-node
    in-process localnet with ±0.5s synthetic clock skew, probes each
    node's /debug/clock over real HTTP (NTP-style min-RTT offset
    estimation), stitches per-height propagation trees and the
    proposal→commit stage waterfall from the nodes' rebased timelines,
    and reports the MINIMUM attributed-coverage fraction across the
    traced heights as a percentage. The scenario's oracle gates the
    number: offsets recovered worse than the tolerance, missing
    heights, or coverage under 95% emit value -1. Pure host path:
    no TPU."""
    os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
    os.environ.setdefault("TM_TPU_WARMUP", "0")

    from tendermint_tpu.tools import scenarios

    res = scenarios.run("proptrace", seed=PROPTRACE_SEED,
                        n=PROPTRACE_NVAL)
    ok = bool(res.get("ok"))
    coverage_min = res.get("coverage_min")
    value = (round(coverage_min * 100, 2)
             if ok and coverage_min is not None else -1)
    print(json.dumps({
        "metric": PROPTRACE_METRIC,
        "value": value,
        "unit": "pct",
        "vs_baseline": 1.0 if ok else 0.0,
        "seed": PROPTRACE_SEED,
        "offset_error_ms": res.get("offset_error_ms"),
        "offset_tol_ms": res.get("offset_tol_ms"),
        "offsets_ok": res.get("offsets_ok"),
        "coverages": res.get("coverages"),
        "coverage_ok": res.get("coverage_ok"),
        "stitched_heights": res.get("stitched_heights"),
        "max_hop": res.get("max_hop"),
        "converged": res.get("converged"),
        "safety_ok": res.get("safety_ok"),
        "note": ("min share of proposal->commit wall attributed to a "
                 "named waterfall stage across traced heights; clock "
                 "offsets recovered via /debug/clock min-RTT probes "
                 "against ±0.5s synthetic skew"
                 if ok else "ORACLE FAILED — see offsets/coverages"),
    }))
    return 0 if ok else 1


def incident_main():
    """`bench.py incident` — the incident observatory as a gated BENCH
    line: the incident scenario (tools/scenarios.py) composes a seeded
    netchaos partition with a seeded torn-WAL crash on a 4-node
    subprocess localnet, scrapes every node's /debug/incidents, stitches
    the fleet incident report (tools/fleettrace.py) with the
    orchestrator's kill stamp merged in, and reports the p50 MTTR
    (heal -> first fresh-height commit) in ms, with p50 MTTD alongside.
    The scenario's oracle gates the number: every injected phase must be
    detected AND classified correctly (partition stall reasons for the
    net phase, unclean_shutdown for the crash), zero double-commits, and
    each survivor's seeded ledger projection byte-identical to the
    plan-derived prediction — otherwise value -1. Pure host path:
    no TPU."""
    os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
    os.environ.setdefault("TM_TPU_WARMUP", "0")

    from tendermint_tpu.tools import scenarios

    res = scenarios.run("incident", seed=INCIDENT_SEED, n=INCIDENT_NVAL)
    ok = bool(res.get("ok"))
    mttr_p50 = res.get("mttr_p50_s")
    mttd_p50 = res.get("mttd_p50_s")
    value = (round(mttr_p50 * 1000, 1)
             if ok and mttr_p50 is not None else -1)
    print(json.dumps({
        "metric": INCIDENT_METRIC,
        "value": value,
        "unit": "ms",
        "vs_baseline": 1.0 if ok else 0.0,
        "seed": INCIDENT_SEED,
        "mttd_p50_ms": (round(mttd_p50 * 1000, 1)
                        if mttd_p50 is not None else -1),
        "total_phases": res.get("total_phases"),
        "attribution": res.get("attribution"),
        "replay_identical": res.get("replay_identical"),
        "safety_ok": res.get("safety_ok"),
        "classified_ok": res.get("classified_ok"),
        "recovered_ok": res.get("recovered_ok"),
        "note": ("p50 heal->fresh-commit MTTR across a composed "
                 "partition + torn-WAL timeline; fault ledger "
                 f"replayable from seed {INCIDENT_SEED} "
                 "(canonical projection byte-checked per survivor)"
                 if ok else "ORACLE FAILED — see attribution/replay"),
    }))
    return 0 if ok else 1


def fleet_main():
    """`bench.py fleet` — the replica fan-out tree as a serving
    benchmark: FLEET_REPLICAS in-process replicas tier up behind ONE
    validator ([replica] prefer_replicas: deeper replicas tail other
    replicas, never the validator), then FLEET_CLIENTS round-robin
    clients hammer the replicas' RPC serving layer for FLEET_SECS while
    the tree keeps tailing live blocks. The BENCH value is the hot
    /status p50 across the round-robin load; the oracle gates it on
    ZERO stale tips (every replica within lag_budget_blocks of the
    validator tip at the end), every replica parented, and the
    validator carrying only O(fan-in) peer connections — the point of
    the tree. Pure host path: no TPU."""
    import tempfile
    import threading

    os.environ.setdefault("TM_TPU_CRYPTO_BACKEND", "cpu")
    os.environ.setdefault("TM_TPU_WARMUP", "0")

    from tendermint_tpu import config as cfg
    from tendermint_tpu.node import default_new_node
    from tendermint_tpu.p2p import NodeKey
    from tendermint_tpu.privval import load_or_gen_file_pv
    from tendermint_tpu.types import GenesisDoc, GenesisValidator

    n = max(2, FLEET_REPLICAS)
    tier1_n = min(2, n)

    def _mk_config(root, name, mode):
        c = cfg.test_config()
        c.set_root(os.path.join(root, name))
        c.base.proxy_app = "kvstore"
        c.base.moniker = name
        c.base.mode = mode
        c.rpc.laddr = "tcp://127.0.0.1:0"
        c.p2p.laddr = "tcp://127.0.0.1:0"
        c.p2p.pex = False
        c.consensus.create_empty_blocks_interval = 0.5
        c.statesync.enable = False
        c.statesync.snapshot_interval = 0
        c.replica.prefer_replicas = True
        c.replica.lag_budget_blocks = 8
        c.replica.silence_budget_s = 5.0
        cfg.ensure_root(c.root_dir)
        NodeKey.load_or_gen(c.base.node_key_path())
        return c

    started = []
    with tempfile.TemporaryDirectory(prefix="bench_fleet_") as root:
        vc = _mk_config(root, "fleet-val", "full")
        pv = load_or_gen_file_pv(vc.base.priv_validator_path())
        genesis = GenesisDoc(
            chain_id="bench-fleet",
            genesis_time=time.time_ns() - 10**9,
            validators=[GenesisValidator(pv.get_pub_key(), 10)],
        )
        genesis.save(vc.base.genesis_path())
        validator = default_new_node(vc)
        validator.start()
        started.append(validator)
        try:
            deadline = time.time() + 60
            while validator.block_store.height() < 2 \
                    and time.time() < deadline:
                time.sleep(0.1)
            if validator.block_store.height() < 2:
                raise RuntimeError("validator never warmed")
            val_peer = (f"{validator.node_key.id}@"
                        f"{validator.transport.listen_addr}")

            # tier-1 replicas dial the validator; deeper replicas dial
            # ONLY the tier-1 replicas (prefer_replicas then keeps them
            # parented inside the tree)
            replicas = []
            for i in range(n):
                c = _mk_config(root, f"fleet-rep{i}", "replica")
                load_or_gen_file_pv(c.base.priv_validator_path())
                genesis.save(c.base.genesis_path())
                if i < tier1_n:
                    c.p2p.persistent_peers = val_peer
                else:
                    c.p2p.persistent_peers = ",".join(
                        f"{r.node_key.id}@{r.transport.listen_addr}"
                        for r in replicas[:tier1_n])
                node = default_new_node(c)
                node.start()
                started.append(node)
                replicas.append(node)

            # the tree settles: every replica parented + tailing near
            # the validator tip
            deadline = time.time() + 90
            settled = False
            while time.time() < deadline:
                sts = [r.replica_tree.status() for r in replicas]
                vh = validator.block_store.height()
                if (all(not s["orphaned"] for s in sts)
                        and all(vh - r.block_store.height() <= 3
                                for r in replicas)):
                    settled = True
                    break
                time.sleep(0.2)
            if not settled:
                raise RuntimeError(
                    "fleet tree never settled: " + json.dumps(
                        [{"parent": s["parent"][:8],
                          "lag": s["lag_blocks"]}
                         for s in (r.replica_tree.status()
                                   for r in replicas)]))

            # round-robin read load across the replicas' serving layers
            servers = [r._rpc_server for r in replicas]
            lats = []
            lock = threading.Lock()
            stop_at = time.time() + FLEET_SECS

            def client(k):
                local = []
                j = k
                while time.time() < stop_at:
                    t0 = time.perf_counter()
                    servers[j % len(servers)].call_bytes("status", {})
                    local.append((time.perf_counter() - t0) * 1000)
                    servers[(j + 1) % len(servers)].call_bytes(
                        "block", {"height": 1})
                    j += 1
                with lock:
                    lats.extend(local)

            ts = [threading.Thread(target=client, args=(k,))
                  for k in range(FLEET_CLIENTS)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()

            sts = [r.replica_tree.status() for r in replicas]
            budget = sts[0]["lag_budget_blocks"]
            vh = validator.block_store.height()
            lags = [max(0, vh - r.block_store.height())
                    for r in replicas]
            stale = sum(1 for lag in lags if lag > budget)
            orphans = sum(1 for s in sts if s["orphaned"])
            out_p, in_p, _ = validator.sw.num_peers()
            val_conns = out_p + in_p
            # per-node subscriber ceiling: children each upstream serves
            children = {r.node_key.id: 0 for r in replicas}
            children[validator.node_key.id] = 0
            for s in sts:
                if s["parent"] in children:
                    children[s["parent"]] += 1
            max_children = max(children.values())
            depths = [s["depth"] for s in sts]

            s_lats = sorted(lats)
            p50 = s_lats[len(s_lats) // 2] if s_lats else -1.0
            p99 = (s_lats[min(len(s_lats) - 1, int(0.99 * len(s_lats)))]
                   if s_lats else -1.0)
            ok = bool(stale == 0 and orphans == 0 and s_lats
                      and val_conns <= tier1_n
                      and (n <= tier1_n or max(depths) >= 2))
            _emit({
                "metric": FLEET_METRIC,
                "value": round(p50, 3) if ok else -1,
                "unit": "ms",
                "vs_baseline": 1.0 if ok else 0.0,
                "p99_ms": round(p99, 3),
                "queries": 2 * len(lats),
                "qps": round(2 * len(lats) / FLEET_SECS, 1),
                "replicas": n,
                "clients": FLEET_CLIENTS,
                "depths": depths,
                "validator_conns": val_conns,
                "tier1": tier1_n,
                "max_children": max_children,
                "lag_blocks": lags,
                "lag_budget_blocks": budget,
                "stale_tips": stale,
                "orphaned": orphans,
                "note": ("hot /status p50 over a round-robin read load "
                         f"across {n} tree replicas; validator serves "
                         f"{val_conns} conns (O(fan-in), not O(N))"
                         if ok else "ORACLE FAILED — see stale_tips/"
                                    "orphaned/validator_conns"),
            }, None)
            return 0 if ok else 1
        finally:
            for node in reversed(started):
                try:
                    node.stop()
                except Exception:
                    pass


def main():
    n = METRIC_N
    if COMMIT4_MODE:
        # pure host path: never touch (or wait for) the TPU backend
        return commit4_main()
    if DETCHECK_MODE:
        # in-process + subprocess oracle: pure host path, no TPU probe
        return detcheck_main()
    if PROPTRACE_MODE:
        # in-process localnet + loopback HTTP: pure host path, no TPU
        return proptrace_main()
    if INCIDENT_MODE:
        # subprocess localnet + loopback HTTP: pure host path, no TPU
        return incident_main()
    if CHAOS_MODE:
        return chaos_main()
    if CHAOSNET_MODE:
        # in-process localnet: pure host path, no TPU probe
        return chaosnet_main()
    if CRASHREC_MODE:
        # crash-matrix harness: pure host path, no TPU probe
        return crashrecovery_main()
    if LOAD_MODE:
        if PARALLEL_FLAG:
            return load_parallel_main()
        return load_main()
    if PREVERIFY_MODE:
        return preverify_main()
    if AGGVERIFY_MODE:
        # pure host path like commit4/preverify: no TPU probe
        return aggverify_main()
    if HANDEL_MODE:
        # in-process overlay simulation: pure host path, no TPU probe
        return handel_main()
    if FLEET_MODE:
        # in-process replica tree + serving layer: pure host, no TPU
        return fleet_main()
    if RPCLOAD_MODE:
        # pure host serving path: no TPU probe
        return rpcload_main()
    degraded = None
    if os.environ.get("TM_TPU_BENCH_FORCE_CPU"):
        degraded = "cpu8-forced"  # BASELINE config 2: by-design CPU mode
    elif not _tpu_available():
        degraded = "cpu8"
    if degraded:
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:
            # older jax: the XLA_FLAGS knob is the only way to get
            # virtual devices, and it must be set before backend init —
            # fall back to however many devices the platform has
            pass

    if WARMSTART_MODE:
        return warmstart_main(degraded)
    if VOTES_MODE:
        return votes_main(degraded)
    if FASTSYNC_MODE:
        return fastsync_main(degraded)
    if CACHE_MODE:
        return cache_main(degraded)
    if STATESYNC_MODE:
        return statesync_main(degraded)

    from tendermint_tpu.crypto import keys
    from tendermint_tpu.crypto.jaxed25519.verify import (
        verify_batch,
        verify_batch_rlc,
    )

    if RLC_MODE:
        # aggregate mode benchmarks the fast-sync scenario: all-valid
        # commits where the RLC group equation shares one doubling chain
        verify_fn = lambda m, s, p: verify_batch_rlc(m, s, p)
    else:
        verify_fn = verify_batch

    # build a synthetic 10k-validator commit: distinct keys, vote-sized
    # messages (~110B canonical sign-bytes), ~1% corrupted signatures
    # (all-valid in rlc mode — its fast path is the valid-heavy batch)
    sks = [keys.PrivKeyEd25519.generate() for _ in range(min(n, 2000))]
    msgs, sigs, pks, want = [], [], [], []
    for i in range(n):
        sk = sks[i % len(sks)]
        msg = secrets.token_bytes(110)
        sig = sk.sign(msg)
        if not RLC_MODE and i % 100 == 37:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
            want.append(False)
        else:
            want.append(True)
        msgs.append(msg)
        sigs.append(sig)
        pks.append(sk.pub_key().bytes())

    # serial CPU baseline (subset of 300, extrapolated)
    sub = min(300, n)
    t0 = time.perf_counter()
    for i in range(sub):
        keys.PubKeyEd25519(pks[i]).verify_bytes(msgs[i], sigs[i])
    serial_ms = (time.perf_counter() - t0) / sub * n * 1000

    # batch path: one warmup (compile; persistent cache warms later runs),
    # then timed runs — fewer on the slow degraded path. On real TPU the
    # chunked dispatch (TM_TPU_VERIFY_CHUNKS) can hide transfer behind
    # compute: sweep chunk counts (seeded with any user-set value) and
    # report the best COMPLETE verify. Sweeping only makes sense where
    # verify_batch actually chunks: single device, n >= chunk_min.
    import jax as _jax

    prev_chunks = os.environ.get("TM_TPU_VERIFY_CHUNKS")
    try:
        chunk_min = int(os.environ.get("TM_TPU_VERIFY_CHUNK_MIN", "2048"))
    except ValueError:
        chunk_min = 2048  # same fallback verify_batch uses
    can_chunk = (not degraded and not RLC_MODE
                 and len(_jax.devices()) == 1 and n >= chunk_min)
    sweep = [1]
    if can_chunk:
        sweep = [1, 2, 4]
        if (prev_chunks and prev_chunks.isdigit() and int(prev_chunks) >= 2
                and int(prev_chunks) not in sweep):
            sweep.append(int(prev_chunks))
    batch_ms, best_chunks = float("inf"), 1
    for ck in sweep:
        os.environ["TM_TPU_VERIFY_CHUNKS"] = str(ck)
        got = verify_fn(msgs, sigs, pks)
        assert got == want, "batch verify mask mismatch vs expected"
        times = []
        for _ in range(2 if degraded else 7):
            t0 = time.perf_counter()
            verify_fn(msgs, sigs, pks)
            times.append((time.perf_counter() - t0) * 1000)
        if min(times) < batch_ms:
            batch_ms, best_chunks = min(times), ck
    if prev_chunks is None:
        os.environ.pop("TM_TPU_VERIFY_CHUNKS", None)
    else:
        os.environ["TM_TPU_VERIFY_CHUNKS"] = prev_chunks

    mode = "_rlc" if RLC_MODE else ""
    out = {
        "metric": f"verify_commit_{n}_sigs{mode}_wall_ms",
        "value": round(batch_ms, 3),
        "unit": "ms",
        "vs_baseline": round(serial_ms / batch_ms, 2),
    }
    if RLC_MODE:
        out["note"] = (
            "experimental: dispatch-bound, slower than the per-item "
            "kernel at this scale (PROFILE.md); not used on consensus paths"
        )
    if not RLC_MODE:
        # breakdown: the axon tunnel charges ~64ms latency per sync round
        # trip + ~10-30ms/MB, none of which exists on direct-attached TPU.
        # device_ms = slope over back-to-back dispatches (pure device time).
        # The key is ALWAYS in the parsed line; -1 = not measured (degraded
        # runs skip the ~8 extra full dispatches — a CPU number under the
        # TPU device key would mislead anyway; the real TPU device_ms
        # arrives via last_good_tpu) or measurement failed.
        if can_chunk:
            out["chunks"] = best_chunks
        if degraded == "cpu8":
            out["device_ms"] = -1.0
        elif not degraded:
            try:
                out["device_ms"] = round(_device_ms(msgs, sigs, pks), 1)
            except Exception:
                out["device_ms"] = -1.0
            out["tunnel_note"] = "wall includes h2d+latency of remote-TPU tunnel"
    _emit(out, degraded)


def _device_ms(msgs, sigs, pks, k: int = 6) -> float:
    """Device-only time of the verify kernel: slope of k back-to-back
    dispatches on resident data (removes tunnel latency + transfer)."""
    import jax
    import numpy as np

    from tendermint_tpu.crypto.jaxed25519 import verify as V

    n = len(msgs)
    sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
    pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(n, 32)
    buf, nb, mrows, bpad = V.pack_buffer(msgs, sig_arr, pk_arr, 1)
    fn = V._jitted_packed(nb, mrows, bpad, 1)
    d = jax.device_put(buf)

    def run(reps):
        out = None
        for _ in range(reps):
            out = fn(d)
        np.asarray(out)

    run(1)
    t0 = time.perf_counter(); run(1); t1 = time.perf_counter() - t0
    t0 = time.perf_counter(); run(k); tk = time.perf_counter() - t0
    return (tk - t1) / (k - 1) * 1000


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 - the JSON line must still appear
        import traceback

        traceback.print_exc()
        if VOTES_MODE:
            metric = VOTES_METRIC
        elif FASTSYNC_MODE:
            metric = FS_PIPE_METRIC if PIPELINE_FLAG else FS_METRIC
        elif CACHE_MODE:
            metric = CACHE_METRIC
        elif COMMIT4_MODE:
            metric = COMMIT4_METRIC
        elif AGGVERIFY_MODE:
            metric = AGG_METRIC
        elif WARMSTART_MODE:
            metric = WARM_METRIC
        elif CRASHREC_MODE:
            metric = CRASHREC_METRIC
        elif DETCHECK_MODE:
            metric = DETCHECK_METRIC
        else:
            mode = "_rlc" if RLC_MODE else ""
            metric = f"verify_commit_{METRIC_N}_sigs{mode}_wall_ms"
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": -1,
                    "unit": "ms",
                    "vs_baseline": 0,
                    "error": str(e)[-200:],
                }
            )
        )
