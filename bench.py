"""Benchmark: the north-star hot path — VerifyCommit at 10k validators.

BASELINE.json config 5: "10k-validator mega-commit VerifyCommit on TPU,
mixed valid/invalid sigs". Baseline stand-in for the reference's serial Go
ed25519 path (types/validator_set.go:345-371): a serial OpenSSL
verify loop (measured on a subset, extrapolated linearly — per-signature
cost is constant).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N}
vs_baseline > 1 means faster than the serial baseline.

Robustness: the TPU platform (axon) is probed in a SUBPROCESS with a hard
timeout first — its init can hang indefinitely when the chip is held or
the tunnel is down, and a hung init must not prevent the JSON line. On
probe failure the kernel runs on an 8-device virtual CPU mesh and the
line is emitted with "degraded": "cpu8" (honest, slower number). Any
other failure still emits a parseable line with value -1.
"""

import json
import os
import secrets
import subprocess
import sys
import time

RLC_MODE = "rlc" in sys.argv[1:]
_args = [a for a in sys.argv[1:] if a != "rlc"]
try:
    METRIC_N = int(_args[0]) if _args else 10000
except ValueError:
    METRIC_N = 10000


def _tpu_available(timeout: float = 240.0) -> bool:
    """Probe backend init + one tiny op in a subprocess with a timeout."""
    code = (
        "import jax, jax.numpy as jnp\n"
        "devs = jax.devices()\n"
        "assert devs and devs[0].platform.lower() != 'cpu', devs\n"
        "x = jnp.ones((8, 8))\n"
        "print(float((x @ x).sum()))\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        return r.returncode == 0
    except Exception:
        return False


def main():
    n = METRIC_N
    degraded = None
    if os.environ.get("TM_TPU_BENCH_FORCE_CPU") or not _tpu_available():
        degraded = "cpu8"
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)

    from tendermint_tpu.crypto import keys
    from tendermint_tpu.crypto.jaxed25519.verify import (
        verify_batch,
        verify_batch_rlc,
    )

    if RLC_MODE:
        # aggregate mode benchmarks the fast-sync scenario: all-valid
        # commits where the RLC group equation shares one doubling chain
        verify_fn = lambda m, s, p: verify_batch_rlc(m, s, p)
    else:
        verify_fn = verify_batch

    # build a synthetic 10k-validator commit: distinct keys, vote-sized
    # messages (~110B canonical sign-bytes), ~1% corrupted signatures
    # (all-valid in rlc mode — its fast path is the valid-heavy batch)
    sks = [keys.PrivKeyEd25519.generate() for _ in range(min(n, 2000))]
    msgs, sigs, pks, want = [], [], [], []
    for i in range(n):
        sk = sks[i % len(sks)]
        msg = secrets.token_bytes(110)
        sig = sk.sign(msg)
        if not RLC_MODE and i % 100 == 37:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
            want.append(False)
        else:
            want.append(True)
        msgs.append(msg)
        sigs.append(sig)
        pks.append(sk.pub_key().bytes())

    # serial CPU baseline (subset of 300, extrapolated)
    sub = 300
    t0 = time.perf_counter()
    for i in range(sub):
        keys.PubKeyEd25519(pks[i]).verify_bytes(msgs[i], sigs[i])
    serial_ms = (time.perf_counter() - t0) / sub * n * 1000

    # batch path: one warmup (compile; persistent cache warms later runs),
    # then timed runs — fewer on the slow degraded path. On real TPU the
    # chunked dispatch (TM_TPU_VERIFY_CHUNKS) can hide transfer behind
    # compute: sweep chunk counts (seeded with any user-set value) and
    # report the best COMPLETE verify. Sweeping only makes sense where
    # verify_batch actually chunks: single device, n >= chunk_min.
    import jax as _jax

    prev_chunks = os.environ.get("TM_TPU_VERIFY_CHUNKS")
    try:
        chunk_min = int(os.environ.get("TM_TPU_VERIFY_CHUNK_MIN", "2048"))
    except ValueError:
        chunk_min = 2048  # same fallback verify_batch uses
    can_chunk = (not degraded and not RLC_MODE
                 and len(_jax.devices()) == 1 and n >= chunk_min)
    sweep = [1]
    if can_chunk:
        sweep = [1, 2, 4]
        if (prev_chunks and prev_chunks.isdigit() and int(prev_chunks) >= 2
                and int(prev_chunks) not in sweep):
            sweep.append(int(prev_chunks))
    batch_ms, best_chunks = float("inf"), 1
    for ck in sweep:
        os.environ["TM_TPU_VERIFY_CHUNKS"] = str(ck)
        got = verify_fn(msgs, sigs, pks)
        assert got == want, "batch verify mask mismatch vs expected"
        times = []
        for _ in range(2 if degraded else 7):
            t0 = time.perf_counter()
            verify_fn(msgs, sigs, pks)
            times.append((time.perf_counter() - t0) * 1000)
        if min(times) < batch_ms:
            batch_ms, best_chunks = min(times), ck
    if prev_chunks is None:
        os.environ.pop("TM_TPU_VERIFY_CHUNKS", None)
    else:
        os.environ["TM_TPU_VERIFY_CHUNKS"] = prev_chunks

    mode = "_rlc" if RLC_MODE else ""
    out = {
        "metric": f"verify_commit_{n}_sigs{mode}_wall_ms",
        "value": round(batch_ms, 3),
        "unit": "ms",
        "vs_baseline": round(serial_ms / batch_ms, 2),
    }
    if not degraded and not RLC_MODE:
        # breakdown: the axon tunnel charges ~64ms latency per sync round
        # trip + ~10-30ms/MB, none of which exists on direct-attached TPU.
        # device_ms = slope over back-to-back dispatches (pure device time).
        try:
            if can_chunk:
                out["chunks"] = best_chunks
            out["device_ms"] = round(_device_ms(msgs, sigs, pks), 1)
            out["tunnel_note"] = "wall includes h2d+latency of remote-TPU tunnel"
        except Exception:
            pass
    if degraded:
        out["degraded"] = degraded
    print(json.dumps(out))


def _device_ms(msgs, sigs, pks, k: int = 6) -> float:
    """Device-only time of the verify kernel: slope of k back-to-back
    dispatches on resident data (removes tunnel latency + transfer)."""
    import jax
    import numpy as np

    from tendermint_tpu.crypto.jaxed25519 import verify as V

    n = len(msgs)
    sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
    pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(n, 32)
    buf, nb, mrows, bpad = V.pack_buffer(msgs, sig_arr, pk_arr, 1)
    fn = V._jitted_packed(nb, mrows, bpad, 1)
    d = jax.device_put(buf)

    def run(reps):
        out = None
        for _ in range(reps):
            out = fn(d)
        np.asarray(out)

    run(1)
    t0 = time.perf_counter(); run(1); t1 = time.perf_counter() - t0
    t0 = time.perf_counter(); run(k); tk = time.perf_counter() - t0
    return (tk - t1) / (k - 1) * 1000


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 - the JSON line must still appear
        import traceback

        traceback.print_exc()
        print(
            json.dumps(
                {
                    "metric": f"verify_commit_{METRIC_N}_sigs_wall_ms",
                    "value": -1,
                    "unit": "ms",
                    "vs_baseline": 0,
                    "error": str(e)[-200:],
                }
            )
        )
