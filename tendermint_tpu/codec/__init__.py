"""Deterministic canonical binary codec.

Replaces the reference's go-amino (types/wire.go, types/canonical.go) with a
minimal proto3-style wire format that is byte-deterministic by construction:
fields are always emitted in ascending tag order, zero values are emitted
explicitly where signedness matters for sign-bytes (height/round are
fixed64, like amino's "binary:fixed64" annotations at types/vote.go), and
maps never appear. This codec is ONLY used for hashing and sign-bytes —
inter-node wire messages use msgpack with explicit schemas (p2p layer).
"""

from __future__ import annotations

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2


def uvarint(n: int) -> bytes:
    if n < 0:
        raise ValueError("uvarint of negative")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(data: bytes, pos: int = 0):
    shift = 0
    result = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated uvarint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("uvarint too long")


def svarint(n: int) -> bytes:
    """ZigZag-encoded signed varint."""
    return uvarint((n << 1) ^ (n >> 63) if n < 0 else n << 1)


def read_svarint(data: bytes, pos: int = 0):
    u, pos = read_uvarint(data, pos)
    return (u >> 1) ^ -(u & 1), pos


def tag(field: int, wire: int) -> bytes:
    return uvarint((field << 3) | wire)


def t_uvarint(field: int, n: int) -> bytes:
    """Tagged varint; zero is skipped (proto3 default-elision)."""
    if n == 0:
        return b""
    return tag(field, WIRE_VARINT) + uvarint(n)


def t_fixed64(field: int, n: int) -> bytes:
    """Tagged fixed64 (always 8 bytes little-endian); zero skipped."""
    if n == 0:
        return b""
    return tag(field, WIRE_FIXED64) + (n & (2**64 - 1)).to_bytes(8, "little")


def t_bytes(field: int, b: bytes) -> bytes:
    if not b:
        return b""
    return tag(field, WIRE_BYTES) + uvarint(len(b)) + b


def t_string(field: int, s: str) -> bytes:
    return t_bytes(field, s.encode())


def t_message(field: int, body: bytes) -> bytes:
    """Tagged nested message. Unlike scalars, an empty message is still
    emitted (presence is meaningful, e.g. nil vs empty BlockID)."""
    return tag(field, WIRE_BYTES) + uvarint(len(body)) + body
