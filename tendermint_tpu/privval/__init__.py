"""Validator signing (reference privval/)."""

from .file_pv import FilePV, load_or_gen_file_pv  # noqa: F401
from .remote import (  # noqa: F401
    RemoteSignerError,
    RemoteSignerServer,
    SocketPV,
)
