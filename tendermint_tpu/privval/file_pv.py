"""FilePV — file-backed validator key with double-sign protection.

Reference parity: privval/priv_validator.go:43-61 (struct + persisted
last-sign state), :176-204 (SignVote/SignProposal), :206-280 (sign +
height/round/step regression checks), :302-340 (checkVotesOnlyDifferByTimestamp).
A validator that crashes and restarts must never sign conflicting votes:
the last signed (height, round, step, sign-bytes, signature) is fsync'd
to disk BEFORE the signature is released to the caller.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Optional

from ..libs import fail

from ..crypto import (
    PrivKey,
    PrivKeyEd25519,
    privkey_from_bytes,
    pubkey_from_bytes,
    pubkey_to_bytes,
)
from ..crypto.keys import KEY_TYPE_ED25519, generate_priv_key
from ..types.basic import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    Proposal,
    Vote,
    canonical_proposal_sign_bytes,
    canonical_vote_sign_bytes,
)

# sign step numbers (reference privval/priv_validator.go:27-31)
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(vote: Vote) -> int:
    if vote.type == VOTE_TYPE_PREVOTE:
        return STEP_PREVOTE
    if vote.type == VOTE_TYPE_PRECOMMIT:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type {vote.type}")


class DoubleSignError(Exception):
    pass


class FilePV:
    """Implements the PrivValidator interface (types/priv_validator.go):
    get_pub_key / sign_vote / sign_proposal."""

    def __init__(self, priv_key: PrivKey, file_path: Optional[str] = None):
        self.priv_key = priv_key
        self.file_path = file_path
        self.last_height = 0
        self.last_round = 0
        self.last_step = 0
        self.last_signature: bytes = b""
        self.last_sign_bytes: bytes = b""
        self._lock = threading.Lock()

    # --- PrivValidator interface -------------------------------------------

    def get_pub_key(self):
        return self.priv_key.pub_key()

    def get_address(self) -> bytes:
        return self.get_pub_key().address()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        """Signs vote in place; raises DoubleSignError on regression
        (reference priv_validator.go:176-183 → signVote :206-254)."""
        with self._lock:
            self._sign_vote(chain_id, vote)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        with self._lock:
            self._sign_proposal(chain_id, proposal)

    # --- internals ----------------------------------------------------------

    def _check_hrs(self, height: int, round_: int, step: int) -> bool:
        """Returns True if HRS exactly matches the last signed HRS (maybe
        re-sign case); raises on regression (reference :282-300)."""
        if self.last_height > height:
            raise DoubleSignError(f"height regression: {self.last_height} > {height}")
        if self.last_height == height:
            if self.last_round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}: {self.last_round} > {round_}"
                )
            if self.last_round == round_:
                if self.last_step > step:
                    raise DoubleSignError(
                        f"step regression at {height}/{round_}: {self.last_step} > {step}"
                    )
                if self.last_step == step:
                    if not self.last_sign_bytes:
                        raise DoubleSignError("no last_sign_bytes for repeated HRS")
                    return True
        return False

    def _sign_vote(self, chain_id: str, vote: Vote) -> None:
        height, round_, step = vote.height, vote.round, vote_to_step(vote)
        same_hrs = self._check_hrs(height, round_, step)
        sign_bytes = vote.sign_bytes(chain_id)

        if same_hrs:
            # idempotent re-sign: identical payload, or only the timestamp
            # differs (crash between sign and broadcast; reference :233-247)
            if sign_bytes == self.last_sign_bytes:
                vote.signature = self.last_signature
                return
            ts = _vote_only_differs_by_timestamp(
                chain_id, self.last_sign_bytes, vote
            )
            if ts is not None:
                vote.timestamp = ts
                vote.signature = self.last_signature
                return
            raise DoubleSignError(
                f"conflicting vote data at the same HRS {height}/{round_}/{step}"
            )

        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        vote.signature = sig

    def _sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        height, round_, step = proposal.height, proposal.round, STEP_PROPOSE
        same_hrs = self._check_hrs(height, round_, step)
        sign_bytes = proposal.sign_bytes(chain_id)

        if same_hrs:
            if sign_bytes == self.last_sign_bytes:
                proposal.signature = self.last_signature
                return
            ts = _proposal_only_differs_by_timestamp(
                chain_id, self.last_sign_bytes, proposal
            )
            if ts is not None:
                proposal.timestamp = ts
                proposal.signature = self.last_signature
                return
            raise DoubleSignError(
                f"conflicting proposal data at the same HRS {height}/{round_}/{step}"
            )

        sig = self.priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        proposal.signature = sig

    def _save_signed(self, height: int, round_: int, step: int, sign_bytes: bytes, sig: bytes) -> None:
        """Persist-before-release (reference :256-280). A crash here —
        signature computed, sign-state not yet durable — must lose the
        signature entirely: it was never released to the caller, so the
        recovered (older) last-sign state cannot enable a double sign."""
        self.last_height = height
        self.last_round = round_
        self.last_step = step
        self.last_signature = sig
        self.last_sign_bytes = sign_bytes
        fail.fail_point("Privval.BeforeSignStateSave")
        self.save()

    # --- persistence --------------------------------------------------------

    def to_json(self) -> str:
        # Ed25519 keys keep the legacy raw-64-byte spelling (existing
        # priv_validator.json files stay loadable byte-for-byte); other
        # key types (bls12381) persist type-tagged
        if isinstance(self.priv_key, PrivKeyEd25519):
            raw = self.priv_key.bytes().hex()
        else:
            from ..crypto import privkey_to_bytes

            raw = privkey_to_bytes(self.priv_key).hex()
        return json.dumps(
            {
                "address": self.get_address().hex(),
                "pub_key": pubkey_to_bytes(self.get_pub_key()).hex(),
                "priv_key": raw,
                "last_height": self.last_height,
                "last_round": self.last_round,
                "last_step": self.last_step,
                "last_signature": self.last_signature.hex(),
                "last_sign_bytes": self.last_sign_bytes.hex(),
            },
            indent=2,
        )

    def save(self) -> None:
        """Atomic persist (the kernel_cache.py pattern): a UNIQUE
        same-directory tempfile, fsync'd, then os.replace'd over the
        target. A crash at any point leaves either the previous
        complete file or the new complete file — never a truncated
        double-sign guard; a fixed tmp name would let two racing
        writers interleave into one torn tempfile before the rename."""
        if not self.file_path:
            return
        payload = self.to_json()
        d = os.path.dirname(self.file_path) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-privval-")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.file_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    @classmethod
    def load(cls, file_path: str) -> "FilePV":
        with open(file_path) as f:
            o = json.load(f)
        raw = bytes.fromhex(o["priv_key"])
        # legacy files hold the raw 64-byte Ed25519 key; anything else
        # is type-tagged (crypto.keys registry)
        key = PrivKeyEd25519(raw) if len(raw) == 64 else privkey_from_bytes(raw)
        pv = cls(key, file_path)
        pv.last_height = o.get("last_height", 0)
        pv.last_round = o.get("last_round", 0)
        pv.last_step = o.get("last_step", 0)
        pv.last_signature = bytes.fromhex(o.get("last_signature", ""))
        pv.last_sign_bytes = bytes.fromhex(o.get("last_sign_bytes", ""))
        return pv

    @classmethod
    def generate(cls, file_path: Optional[str] = None,
                 key_type: str = KEY_TYPE_ED25519) -> "FilePV":
        pv = cls(generate_priv_key(key_type), file_path)
        pv.save()
        return pv

    def reset(self) -> None:
        """Danger: wipes last-sign state (reference ResetAll; only for
        testing / `reset_priv_validator`)."""
        self.last_height = 0
        self.last_round = 0
        self.last_step = 0
        self.last_signature = b""
        self.last_sign_bytes = b""
        self.save()

    def __str__(self):
        return f"FilePV{{{self.get_address().hex()[:12]} LH:{self.last_height} LR:{self.last_round} LS:{self.last_step}}}"


def load_or_gen_file_pv(file_path: str,
                        key_type: str = KEY_TYPE_ED25519) -> FilePV:
    """Reference privval/priv_validator.go:108 LoadOrGenFilePV.
    key_type ([crypto] config) applies only when generating — an
    existing file keeps whatever key it holds."""
    if os.path.exists(file_path):
        return FilePV.load(file_path)
    return FilePV.generate(file_path, key_type=key_type)


def _vote_only_differs_by_timestamp(chain_id: str, last_sign_bytes: bytes, vote: Vote):
    """If the new vote matches the last signed vote except for timestamp,
    return the previously-signed timestamp (reference :302-320). The
    canonical codec makes this a pure byte-compare: re-encode the new vote
    with every candidate timestamp? No — we extract the old timestamp by
    re-encoding the new vote with each field identical; equality of the two
    encodings modulo the timestamp field is checked by splicing."""
    for ts in _candidate_timestamps(last_sign_bytes):
        trial = canonical_vote_sign_bytes(
            chain_id, vote.type, vote.height, vote.round, vote.block_id, ts
        )
        if trial == last_sign_bytes:
            return ts
    return None


def _proposal_only_differs_by_timestamp(chain_id: str, last_sign_bytes: bytes, p: Proposal):
    for ts in _candidate_timestamps(last_sign_bytes):
        trial = canonical_proposal_sign_bytes(
            chain_id, p.height, p.round, p.block_parts_header, p.pol_round, p.pol_block_id, ts
        )
        if trial == last_sign_bytes:
            return ts
    return None


def _candidate_timestamps(sign_bytes: bytes):
    """Candidate fixed64 timestamp values found in the old sign-bytes.
    The timestamp is a tagged fixed64; rather than fully parsing, scan for
    its tag and yield the value (at most a handful of candidates)."""
    from .. import codec

    out = []
    pos = 0
    n = len(sign_bytes)
    while pos < n:
        try:
            t, npos = codec.read_uvarint(sign_bytes, pos)
        except ValueError:
            break
        wire = t & 0x7
        if wire == codec.WIRE_FIXED64:
            if npos + 8 > n:
                break
            out.append(int.from_bytes(sign_bytes[npos : npos + 8], "little"))
            pos = npos + 8
        elif wire == codec.WIRE_VARINT:
            try:
                _, pos = codec.read_uvarint(sign_bytes, npos)
            except ValueError:
                break
        elif wire == codec.WIRE_BYTES:
            try:
                ln, p2 = codec.read_uvarint(sign_bytes, npos)
            except ValueError:
                break
            pos = p2 + ln
        else:
            break
    return out
