"""Remote signer (reference privval/tcp.go + ipc.go +
remote_signer.go + socket.go message types).

Topology matches the reference: the NODE listens on
`priv_validator_laddr`; the SIGNER process dials in and serves signing
requests. TCP connections are wrapped in SecretConnection (X25519 ECDH
+ ChaCha20-Poly1305, ed25519-authenticated — the same transport as
p2p); unix sockets are plain (local trust boundary, ipc.go).

Wire format: length-prefixed serde frames, request/response pairs:
  ["pubkey_req"]               -> ["pubkey_res", pubkey_bytes]
  ["sign_vote_req", chain, v]  -> ["sign_vote_res", vote] | ["err", s]
  ["sign_prop_req", chain, p]  -> ["sign_prop_res", prop] | ["err", s]
  ["ping_req"]                 -> ["ping_res"]
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
from typing import Optional

from ..crypto.keys import PrivKey, PrivKeyEd25519, PubKey, pubkey_from_bytes
from ..types import serde
from ..types.basic import Proposal, Vote
from .file_pv import FilePV

LOG = logging.getLogger("privval.remote")

CONN_TIMEOUT = 5.0  # tcp.go connTimeout (handshake)
REQUEST_TIMEOUT = 10.0  # per sign/pubkey request deadline (node side)
MAX_FRAME = 1 << 20


class RemoteSignerError(Exception):
    pass


def _parse_laddr(laddr: str):
    """tcp://host:port or unix:///path -> (family, addr)."""
    if laddr.startswith("unix://"):
        return socket.AF_UNIX, laddr[len("unix://"):]
    addr = laddr.split("://", 1)[-1]
    host, _, port = addr.rpartition(":")
    return socket.AF_INET, (host or "127.0.0.1", int(port))


class _FrameConn:
    """Length-prefixed frames over a raw socket or SecretConnection."""

    def __init__(self, sock, secret=None):
        self._sock = sock
        self._secret = secret
        self._wlock = threading.Lock()
        self._rlock = threading.Lock()

    def _read_exact(self, n: int) -> bytes:
        if self._secret is not None:
            return self._secret.read_exact(n)
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("remote signer conn closed")
            buf += chunk
        return buf

    def _write_all(self, data: bytes) -> None:
        if self._secret is not None:
            self._secret.write(data)
        else:
            self._sock.sendall(data)

    def send(self, obj) -> None:
        payload = serde.pack(obj)
        if len(payload) > MAX_FRAME:
            raise ValueError("remote signer frame too big")
        with self._wlock:
            self._write_all(struct.pack(">I", len(payload)) + payload)

    def recv(self):
        with self._rlock:
            ln = struct.unpack(">I", self._read_exact(4))[0]
            if ln > MAX_FRAME:
                raise ConnectionError("remote signer frame too big")
            return serde.unpack(self._read_exact(ln))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class SocketPV:
    """Node-side PrivValidator over a socket (reference TCPVal
    tcp.go:40-120 / IPCVal ipc.go): listens, accepts ONE signer
    connection, then forwards sign requests to it."""

    def __init__(self, laddr: str,
                 conn_key: Optional[PrivKey] = None,
                 accept_timeout: float = 30.0):
        self.laddr = laddr
        self.conn_key = conn_key or PrivKeyEd25519.generate()
        self.accept_timeout = accept_timeout
        self._conn: Optional[_FrameConn] = None
        self._pub_key: Optional[PubKey] = None
        self._lock = threading.Lock()
        self._listener: Optional[socket.socket] = None

    # -- lifecycle -----------------------------------------------------

    def listen(self) -> None:
        family, addr = _parse_laddr(self.laddr)
        if family == socket.AF_UNIX and isinstance(addr, str):
            try:
                os.unlink(addr)
            except FileNotFoundError:
                pass
        self._listener = socket.socket(family, socket.SOCK_STREAM)
        if family == socket.AF_INET:
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(addr)
        self._listener.listen(1)

    @property
    def listen_addr(self) -> str:
        if self._listener.family == socket.AF_UNIX:
            return self.laddr
        host, port = self._listener.getsockname()[:2]
        return f"tcp://{host}:{port}"

    def accept(self) -> None:
        """Block until the remote signer dials in (tcp.go acceptConnection)."""
        self._listener.settimeout(self.accept_timeout)
        sock, _ = self._listener.accept()
        sock.settimeout(CONN_TIMEOUT)
        secret = None
        if self._listener.family != socket.AF_UNIX:
            from ..p2p.conn.secret_connection import SecretConnection

            secret = SecretConnection(sock, self.conn_key)
        # per-request deadline: a hung signer must surface as an error,
        # not freeze the consensus loop inside recv (reference tcp.go
        # applies connTimeout per request). Requests are strictly
        # send→recv under _lock, so a socket-level timeout only fires
        # while a response is outstanding.
        sock.settimeout(REQUEST_TIMEOUT)
        self._conn = _FrameConn(sock, secret)
        # cache the signer's consensus pubkey up front (tcp.go :108)
        self._pub_key = self._request_pub_key()

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
        if self._listener is not None:
            self._listener.close()

    # -- PrivValidator interface ---------------------------------------

    def _call(self, req):
        with self._lock:
            if self._conn is None:
                raise RemoteSignerError("remote signer not connected")
            try:
                self._conn.send(req)
                res = self._conn.recv()
            except socket.timeout:
                # mid-frame state is unrecoverable: drop the connection
                self._conn.close()
                self._conn = None
                raise RemoteSignerError(
                    f"remote signer timed out after {REQUEST_TIMEOUT}s")
            except (ConnectionError, OSError) as e:
                self._conn.close()
                self._conn = None
                raise RemoteSignerError(f"remote signer conn error: {e}")
        if res and res[0] == "err":
            raise RemoteSignerError(str(res[1]))
        return res

    def _request_pub_key(self) -> PubKey:
        res = self._call(["pubkey_req"])
        if res[0] != "pubkey_res":
            raise RemoteSignerError(f"unexpected response {res[0]!r}")
        return pubkey_from_bytes(bytes(res[1]))

    def get_pub_key(self) -> PubKey:
        return self._pub_key

    def get_address(self) -> bytes:
        return self._pub_key.address()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        res = self._call(["sign_vote_req", chain_id, serde.vote_obj(vote)])
        if res[0] != "sign_vote_res":
            raise RemoteSignerError(f"unexpected response {res[0]!r}")
        signed = serde.vote_from(res[1])
        vote.signature = signed.signature
        vote.timestamp = signed.timestamp

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        res = self._call(
            ["sign_prop_req", chain_id, serde.proposal_obj(proposal)])
        if res[0] != "sign_prop_res":
            raise RemoteSignerError(f"unexpected response {res[0]!r}")
        signed = serde.proposal_from(res[1])
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp

    def ping(self) -> None:
        res = self._call(["ping_req"])
        if res[0] != "ping_res":
            raise RemoteSignerError("bad ping response")


class RemoteSignerServer:
    """Signer-side process (reference RemoteSigner remote_signer.go:23-120
    + cmd/priv_val_server): dials the node and serves its FilePV."""

    def __init__(self, laddr: str, pv: FilePV,
                 conn_key: Optional[PrivKey] = None):
        self.laddr = laddr
        self.pv = pv
        self.conn_key = conn_key or PrivKeyEd25519.generate()
        self._conn: Optional[_FrameConn] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def connect(self, timeout: float = 10.0) -> None:
        family, addr = _parse_laddr(self.laddr)
        sock = socket.socket(family, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(addr)
        secret = None
        if family != socket.AF_UNIX:
            from ..p2p.conn.secret_connection import SecretConnection

            secret = SecretConnection(sock, self.conn_key)
        sock.settimeout(None)
        self._conn = _FrameConn(sock, secret)

    def start(self) -> None:
        """Connect (if not yet) and serve in a background thread. The
        node's SocketPV.accept() requests the pubkey immediately after
        the handshake, so the serve loop must be running by then."""
        self._stop.clear()

        def run():
            if self._conn is None:
                self.connect()
            self.serve_forever()

        self._thread = threading.Thread(
            target=run, name="remote-signer", daemon=True)
        self._thread.start()

    def serve_forever(self) -> None:
        """remote_signer.go handleConnection:77-120."""
        while not self._stop.is_set():
            try:
                req = self._conn.recv()
            except (ConnectionError, OSError, struct.error):
                LOG.info("remote signer connection closed")
                return
            try:
                res = self._handle(req)
            except Exception as e:  # noqa: BLE001 - report, keep serving
                res = ["err", str(e)]
            try:
                self._conn.send(res)
            except (ConnectionError, OSError):
                return

    def _handle(self, req):
        from ..crypto.keys import pubkey_to_bytes

        kind = req[0]
        if kind == "pubkey_req":
            return ["pubkey_res", pubkey_to_bytes(self.pv.get_pub_key())]
        if kind == "ping_req":
            return ["ping_res"]
        if kind == "sign_vote_req":
            chain_id, vote = req[1], serde.vote_from(req[2])
            self.pv.sign_vote(chain_id, vote)
            return ["sign_vote_res", serde.vote_obj(vote)]
        if kind == "sign_prop_req":
            chain_id, prop = req[1], serde.proposal_from(req[2])
            self.pv.sign_proposal(chain_id, prop)
            return ["sign_prop_res", serde.proposal_obj(prop)]
        return ["err", f"unknown request {kind!r}"]

    def stop(self) -> None:
        self._stop.set()
        if self._conn is not None:
            self._conn.close()
