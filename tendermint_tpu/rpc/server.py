"""JSON-RPC server: HTTP POST + GET-URI + websocket on one port
(reference rpc/lib/server/handlers.go + http_server.go).

- POST /            JSON-RPC 2.0 body
- GET  /<method>?a=b   URI route (params from query string)
- GET  /websocket   RFC6455 upgrade; JSON-RPC frames; subscribe/
                    unsubscribe stream events to the client
- GET  /            route listing (handlers.go writes the same)

The websocket side is hand-rolled (accept-key handshake + masked
client frames) so one threaded server owns both transports, matching
the reference's single listener.

Fan-out-scale serving (ours; no reference equivalent):

- hot read responses are served as pre-encoded JSON bytes out of the
  height/generation cache (rpc/cache.py) — a cached hit skips the
  handler AND the re-encode, splicing the stored result bytes into the
  response frame by concatenation;
- every websocket event is rendered to wire bytes once (rpc/core.py
  render_event_frame) and fanned out through a bounded per-client send
  queue drained by a writer thread, so one slow client backs up only
  its own queue. The slow-client policy is explicit ([rpc]
  ws_slow_policy): "drop" sheds that client's events with a counter,
  "disconnect" hangs up so the client's reconnect logic takes over.
"""

from __future__ import annotations

import base64
import collections
import hashlib
import logging
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qsl, urlparse

from ..libs.events import Query
from . import jsonrpc
from .cache import RPCCache
from .core import ROUTES, UNSAFE_ROUTES, RPCEnvironment, cache_plan
from .jsonrpc import RPCError

LOG = logging.getLogger("rpc.server")

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# cap on POST bodies: the RPC port is public, and Content-Length is
# attacker-controlled (same spirit as the remote-signer MAX_FRAME).
# Websocket frames share the cap — the 64-bit extended length field is
# equally attacker-controlled and was previously unbounded.
MAX_BODY_BYTES = 1 << 20

WS_SLOW_POLICIES = ("drop", "disconnect")


def _result_frame(id_, result_raw: bytes) -> bytes:
    """Splice pre-encoded result bytes into a JSON-RPC response frame
    without re-encoding the result."""
    return (b'{"jsonrpc":"2.0","id":' + jsonrpc.dumps(id_)
            + b',"result":' + result_raw + b"}")


class RPCServer:
    def __init__(self, env: RPCEnvironment, host: str, port: int,
                 unsafe: bool = False, max_open_connections: int = 0,
                 cache: Optional[RPCCache] = None,
                 ws_send_queue: int = 256, ws_slow_policy: str = "drop",
                 metrics=None):
        self.env = env
        self.unsafe = unsafe
        self.routes = dict(ROUTES)
        if unsafe:
            self.routes.update(UNSAFE_ROUTES)
        self.cache = cache
        if ws_slow_policy not in WS_SLOW_POLICIES:
            raise ValueError(
                f"[rpc] ws_slow_policy must be one of {WS_SLOW_POLICIES}, "
                f"got {ws_slow_policy!r}")
        self.ws_send_queue = max(1, int(ws_send_queue))
        self.ws_slow_policy = ws_slow_policy
        self.metrics = metrics  # RPCMetrics or None
        handler = _make_handler(self)

        outer = self

        class _LimitedHTTPServer(ThreadingHTTPServer):
            """Connection-capped server (reference
            rpc/lib/server/http_server.go StartHTTPServer →
            netutil.LimitListener): beyond max_open_connections,
            new connections are closed immediately instead of
            accumulating unbounded handler threads."""

            def process_request(self, request, client_address):
                if (outer.max_open_connections > 0
                        and outer._open_conns_add() is False):
                    try:
                        request.close()
                    except OSError:
                        pass
                    return
                try:
                    super().process_request(request, client_address)
                except BaseException:
                    # thread failed to start (fd/thread exhaustion):
                    # process_request_thread never runs, so release the
                    # slot here or it leaks forever
                    if outer.max_open_connections > 0:
                        outer._open_conns_done()
                    raise

            def process_request_thread(self, request, client_address):
                try:
                    super().process_request_thread(request, client_address)
                finally:
                    if outer.max_open_connections > 0:
                        outer._open_conns_done()

        self.max_open_connections = max_open_connections
        self._open_conns = 0
        self._open_lock = threading.Lock()
        self._httpd = _LimitedHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        # live websocket connections: ThreadingHTTPServer.shutdown()
        # only stops the accept loop — established websockets would keep
        # being served (answering pings!) by their daemon threads, so a
        # "stopped" node would look alive to subscribed clients and
        # their auto-reconnect would never fire
        self._ws_conns: set = set()
        self._ws_lock = threading.Lock()
        # fan-out accounting (rpc_ws_subscribers / rpc_ws_dropped_total)
        self._subs_count = 0
        self._dropped: Dict[str, int] = {p: 0 for p in WS_SLOW_POLICIES}
        self._events_enqueued = 0
        self._stats_lock = threading.Lock()
        # cache invalidation: one NewBlock subscription per server
        self._inval_sub = None
        self._inval_thread: Optional[threading.Thread] = None
        self._inval_stop = threading.Event()

    @property
    def listen_addr(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rpc-http", daemon=True
        )
        self._thread.start()
        if self.cache is not None and self.cache.enabled:
            self._start_invalidation()
        LOG.info("RPC server listening on %s", self.listen_addr)

    def stop(self) -> None:
        self._inval_stop.set()
        if self._inval_sub is not None:
            try:
                self.env.event_bus.unsubscribe_all(self._inval_subscriber)
            except Exception:  # noqa: BLE001 - bus may already be down
                pass
            self._inval_sub = None
        self._httpd.shutdown()
        self._httpd.server_close()
        with self._ws_lock:
            conns = list(self._ws_conns)
        for c in conns:
            c.close()

    # -- cache invalidation (one EventBus NewBlock subscription) -------

    def _start_invalidation(self) -> None:
        from ..types.event_bus import EVENT_NEW_BLOCK, query_for_event

        self._inval_subscriber = f"rpc-cache-{id(self):x}"
        self._inval_sub = self.env.event_bus.subscribe(
            self._inval_subscriber, query_for_event(EVENT_NEW_BLOCK), 16)
        self._inval_stop.clear()
        # bind the cache OBJECT, not the attribute: tests/bench swap
        # self.cache to None to measure the uncached path while blocks
        # keep landing, and the object must keep seeing every bump or
        # its generational entries would survive the bypass window
        cache = self.cache

        def _drain():
            while not self._inval_stop.is_set():
                sub = self._inval_sub
                if sub is None or sub.cancelled:
                    return
                msg = sub.get(timeout=0.5)
                if msg is not None:
                    cache.on_new_block()

        self._inval_thread = threading.Thread(
            target=_drain, name="rpc-cache-inval", daemon=True)
        self._inval_thread.start()

    # -- open-connection cap -------------------------------------------

    def _open_conns_add(self) -> bool:
        with self._open_lock:
            if self._open_conns >= self.max_open_connections:
                return False
            self._open_conns += 1
            return True

    def _open_conns_done(self) -> None:
        with self._open_lock:
            self._open_conns -= 1

    def _ws_register(self, conn) -> None:
        with self._ws_lock:
            self._ws_conns.add(conn)

    def _ws_unregister(self, conn) -> None:
        with self._ws_lock:
            self._ws_conns.discard(conn)

    # -- fan-out accounting --------------------------------------------

    def _note_subs(self, delta: int) -> None:
        with self._stats_lock:
            self._subs_count = max(0, self._subs_count + delta)
            n = self._subs_count
        if self.metrics is not None:
            self.metrics.ws_subscribers.set(n)

    def _note_dropped(self, policy: str, n: int = 1) -> None:
        """Drop accounting is PER FRAME: a batch overflowing a client's
        queue by k counts k, never 1 — rpc_ws_dropped_total stays
        truthful under block-scoped bursts."""
        with self._stats_lock:
            self._dropped[policy] = self._dropped.get(policy, 0) + n
        if self.metrics is not None:
            self.metrics.ws_dropped.with_labels(policy).inc(n)

    def _note_enqueued(self, n: int = 1) -> None:
        with self._stats_lock:
            self._events_enqueued += n

    def debug_status(self) -> dict:
        """The /debug/rpc bundle: cache pressure + websocket fan-out
        state — queue occupancy against capacity is the backpressure
        signal tooling watches (tools/monitor.py)."""
        from .core import events_rendered_count

        with self._ws_lock:
            conns = list(self._ws_conns)
        depths = [c.queue_depth() for c in conns]
        hwms = [c._q_hwm for c in conns]
        with self._stats_lock:
            out_ws = {
                "conns": len(conns),
                "subscribers": self._subs_count,
                "send_queue_capacity": self.ws_send_queue,
                "max_queue_depth": max(depths, default=0),
                # high-water mark since connect: catches a queue that
                # backed up and drained between scrapes
                "max_queue_hwm": max(hwms, default=0),
                "slow_policy": self.ws_slow_policy,
                "events_enqueued": self._events_enqueued,
                "events_dropped": dict(self._dropped),
            }
        out_ws["events_rendered"] = events_rendered_count()
        return {
            "ws": out_ws,
            "cache": (self.cache.stats() if self.cache is not None
                      else {"enabled": False}),
        }

    # -- dispatch ------------------------------------------------------

    def call(self, method: str, params: dict) -> dict:
        fn = self.routes.get(method)
        if fn is None:
            raise RPCError(jsonrpc.ERR_METHOD_NOT_FOUND,
                           f"method {method!r} not found")
        return fn(self.env, params)

    def call_bytes(self, method: str, params: dict) -> bytes:
        """One RPC call, returning the RESULT as serialized JSON bytes.
        Cache-eligible calls ([rpc] cache_bytes > 0) are served from —
        and fill — the response cache; a hit never runs the handler or
        the JSON encoder. Raises exactly like call()."""
        cache = self.cache
        if cache is None or not cache.enabled:
            return jsonrpc.dumps(self.call(method, params))
        plan = cache_plan(self.env, method, params)
        if plan is None:
            return jsonrpc.dumps(self.call(method, params))
        key, generational = plan
        raw = cache.get(method, key)
        if raw is not None:
            return raw
        gen0 = cache.generation  # observed BEFORE the handler runs
        raw = jsonrpc.dumps(self.call(method, params))
        cache.put(method, key, raw, generational=generational,
                  generation=gen0)
        return raw


def _make_handler(server: RPCServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route to our logger
            LOG.debug("http %s", fmt % args)

        # ---- plain HTTP ---------------------------------------------

        def _send_body(self, body: bytes, status: int = 200) -> None:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, obj, status: int = 200) -> None:
            self._send_body(jsonrpc.dumps(obj), status=status)

        def do_POST(self):
            try:
                length = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                length = -1
            if not 0 <= length <= MAX_BODY_BYTES:
                # unread body bytes would desync this keep-alive stream
                self.close_connection = True
                return self._send_json(
                    jsonrpc.error_response(
                        None, jsonrpc.ERR_INVALID_REQUEST,
                        f"request body exceeds {MAX_BODY_BYTES} bytes"),
                    status=413)
            raw = self.rfile.read(length)
            try:
                req = jsonrpc.loads(raw)
            except RPCError as e:
                return self._send_json(
                    jsonrpc.error_response(None, e.code, e.message))
            if isinstance(req, list):  # batch
                return self._send_body(
                    b"[" + b",".join(self._handle_one(r) for r in req)
                    + b"]")
            self._send_body(self._handle_one(req))

        def _handle_one(self, req) -> bytes:
            if not isinstance(req, dict) or "method" not in req:
                return jsonrpc.dumps(jsonrpc.error_response(
                    None, jsonrpc.ERR_INVALID_REQUEST, "invalid request"))
            id_ = req.get("id")
            try:
                raw = server.call_bytes(req["method"],
                                        req.get("params") or {})
                return _result_frame(id_, raw)
            except RPCError as e:
                return jsonrpc.dumps(
                    jsonrpc.error_response(id_, e.code, e.message, e.data))
            except Exception as e:  # noqa: BLE001 - handler crash → 32603
                LOG.exception("rpc %s failed", req.get("method"))
                return jsonrpc.dumps(jsonrpc.error_response(
                    id_, jsonrpc.ERR_INTERNAL, str(e)))

        def do_GET(self):
            parsed = urlparse(self.path)
            path = parsed.path.strip("/")
            if path == "websocket":
                return self._upgrade_websocket()
            if not path:  # route listing (handlers.go writeListOfEndpoints)
                listing = "".join(
                    f"<a href=\"/{m}\">/{m}</a><br>"
                    for m in sorted(server.routes)
                )
                body = f"<html><body>{listing}</body></html>".encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            # latin-1 round-trips every percent-decoded byte 1:1 (like
            # Go's string-of-bytes), so binary payloads in quoted params
            # survive; utf-8 would fold invalid sequences into U+FFFD
            params = dict(parse_qsl(parsed.query, encoding="latin-1"))
            # quoted URI values are RAW strings (reference handlers.go);
            # keep the marker so byte-typed params skip base64/hex
            params = {
                k: (jsonrpc.QuotedStr(v[1:-1])
                    if len(v) >= 2 and v[0] == v[-1] == '"' else v)
                for k, v in params.items()
            }
            try:
                raw = server.call_bytes(path, params)
                self._send_body(_result_frame("", raw))
            except RPCError as e:
                self._send_json(
                    jsonrpc.error_response("", e.code, e.message, e.data))
            except Exception as e:  # noqa: BLE001
                LOG.exception("rpc %s failed", path)
                self._send_json(
                    jsonrpc.error_response("", jsonrpc.ERR_INTERNAL, str(e)))

        # ---- websocket (rpc/lib/server/handlers.go wsConnection) ----

        def _upgrade_websocket(self):
            key = self.headers.get("Sec-WebSocket-Key")
            if not key or "upgrade" not in self.headers.get(
                    "Connection", "").lower():
                self.send_error(400, "not a websocket handshake")
                return
            accept = base64.b64encode(
                hashlib.sha1((key + WS_GUID).encode()).digest()
            ).decode()
            self.send_response(101, "Switching Protocols")
            self.send_header("Upgrade", "websocket")
            self.send_header("Connection", "Upgrade")
            self.send_header("Sec-WebSocket-Accept", accept)
            self.end_headers()
            self.close_connection = True
            conn = WSConn(self.connection, server)
            server._ws_register(conn)
            try:
                conn.serve()  # blocks for the life of the ws conn
            finally:
                server._ws_unregister(conn)

    return Handler


class WSConn:
    """One websocket client: JSON-RPC dispatch + event subscriptions
    (reference wsConnection + wsSubscribe in rpc/core/events.go).

    Event notifications go through a bounded send queue drained by a
    dedicated writer thread — a client that stops reading backs up its
    own queue only, and the configured slow policy (drop/disconnect)
    applies there. Direct RPC responses and pongs bypass the queue (a
    slow client stalls only its own request thread)."""

    def __init__(self, sock: socket.socket, server: RPCServer):
        self.sock = sock
        self.server = server
        self.env = server.env
        self._send_lock = threading.Lock()
        self._subscriber = f"ws-{id(self):x}-{time.monotonic_ns()}"
        self._subs: Dict[str, object] = {}  # query str -> Subscription
        self._pumps = []
        self._closed = threading.Event()
        # bounded event send queue + its writer
        self._q: collections.deque = collections.deque()
        self._q_cap = server.ws_send_queue
        self._q_cond = threading.Condition()
        self._q_hwm = 0
        self.events_sent = 0
        self.events_dropped = 0
        self._writer: Optional[threading.Thread] = None

    # -- frame IO ------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf += chunk
        return buf

    def recv_frame(self) -> Optional[bytes]:
        """Returns a full text/binary message, None on close frame.
        Fragmented messages are reassembled; ping answered inline.
        Frames (and reassembled messages) over MAX_BODY_BYTES tear the
        connection down — the extended length field is wire input and
        must never size an allocation unchecked."""
        message = b""
        while True:
            hdr = self._recv_exact(2)
            fin = hdr[0] & 0x80
            opcode = hdr[0] & 0x0F
            masked = hdr[1] & 0x80
            ln = hdr[1] & 0x7F
            if ln == 126:
                ln = struct.unpack(">H", self._recv_exact(2))[0]
            elif ln == 127:
                ln = struct.unpack(">Q", self._recv_exact(8))[0]
            if ln + len(message) > MAX_BODY_BYTES:
                raise ConnectionError(
                    f"ws frame exceeds {MAX_BODY_BYTES} bytes")
            mask = self._recv_exact(4) if masked else b""
            payload = self._recv_exact(ln)
            if masked:
                payload = bytes(
                    b ^ mask[i % 4] for i, b in enumerate(payload))
            if opcode == 0x8:  # close
                return None
            if opcode == 0x9:  # ping → pong
                self.send_frame(payload, opcode=0xA)
                continue
            if opcode == 0xA:  # pong
                continue
            message += payload
            if fin:
                return message

    def send_frame(self, payload: bytes, opcode: int = 0x1) -> None:
        with self._send_lock:
            header = bytes([0x80 | opcode])
            ln = len(payload)
            if ln < 126:
                header += bytes([ln])
            elif ln < (1 << 16):
                header += bytes([126]) + struct.pack(">H", ln)
            else:
                header += bytes([127]) + struct.pack(">Q", ln)
            self.sock.sendall(header + payload)

    def send_json(self, obj: dict) -> None:
        self.send_bytes(jsonrpc.dumps(obj))

    def send_bytes(self, payload: bytes) -> None:
        try:
            self.send_frame(payload)
        except OSError:
            self._closed.set()

    # -- event send queue ----------------------------------------------

    def queue_depth(self) -> int:
        with self._q_cond:
            return len(self._q)

    def enqueue_event(self, frame: bytes) -> bool:
        """Queue one pre-rendered event frame for the writer. Applies
        the slow-client policy when the queue is full; returns False if
        the frame was shed (or the connection is closing)."""
        if self._closed.is_set():
            return False
        disconnect = False
        with self._q_cond:
            if len(self._q) >= self._q_cap:
                self.events_dropped += 1
                policy = self.server.ws_slow_policy
                self.server._note_dropped(policy)
                disconnect = policy == "disconnect"
            else:
                self._q.append(frame)
                self._q_hwm = max(self._q_hwm, len(self._q))
                self._q_cond.notify()
                self.server._note_enqueued()
                return True
        if disconnect:
            LOG.info("ws client too slow (queue %d full); disconnecting",
                     self._q_cap)
            self.close()
        return False

    # frames appended per enqueue_events lock hold: amortizes the queue
    # lock while still releasing it between chunks, so the writer
    # thread can interleave pops — a burst sheds only what the writer
    # genuinely can't drain (the per-frame enqueue_event behavior),
    # not deterministically everything past the cap
    ENQUEUE_CHUNK = 32

    def enqueue_events(self, frames) -> int:
        """Queue a drained batch of pre-rendered frames in chunked lock
        holds. Per-frame semantics match enqueue_event: each frame past
        capacity is counted dropped INDIVIDUALLY (a burst shedding k
        frames bumps the counters by k), the writer can drain between
        chunks, and the disconnect policy trips on the first overflow.
        Returns the number queued."""
        if self._closed.is_set() or not frames:
            return 0
        disconnect = False
        accepted = 0
        dropped = 0
        for start in range(0, len(frames), self.ENQUEUE_CHUNK):
            chunk = frames[start:start + self.ENQUEUE_CHUNK]
            with self._q_cond:
                chunk_accepted = 0
                for frame in chunk:
                    if len(self._q) >= self._q_cap:
                        dropped += 1
                        self.events_dropped += 1
                        if self.server.ws_slow_policy == "disconnect":
                            disconnect = True
                            break
                    else:
                        self._q.append(frame)
                        chunk_accepted += 1
                if chunk_accepted:
                    self._q_hwm = max(self._q_hwm, len(self._q))
                    self._q_cond.notify()
                    accepted += chunk_accepted
            if disconnect:
                break
        if dropped:
            self.server._note_dropped(self.server.ws_slow_policy, dropped)
        if accepted:
            self.server._note_enqueued(accepted)
        if disconnect:
            LOG.info("ws client too slow (queue %d full); disconnecting",
                     self._q_cap)
            self.close()
        return accepted

    def _writer_loop(self) -> None:
        while True:
            with self._q_cond:
                while not self._q and not self._closed.is_set():
                    self._q_cond.wait(timeout=0.5)
                if self._closed.is_set() and not self._q:
                    return
                frame = self._q.popleft()
            try:
                self.send_frame(frame)
                self.events_sent += 1
            except OSError:
                self._closed.set()
                with self._q_cond:
                    self._q.clear()
                    self._q_cond.notify_all()
                return

    # -- serve loop ----------------------------------------------------

    def serve(self) -> None:
        self._writer = threading.Thread(
            target=self._writer_loop, daemon=True,
            name=f"ws-writer-{id(self):x}")
        self._writer.start()
        try:
            while not self._closed.is_set():
                msg = self.recv_frame()
                if msg is None:
                    break
                try:
                    req = jsonrpc.loads(msg)
                except RPCError as e:
                    self.send_json(
                        jsonrpc.error_response(None, e.code, e.message))
                    continue
                self._dispatch(req)
        except (ConnectionError, OSError):
            pass
        finally:
            self._closed.set()
            with self._q_cond:
                self._q_cond.notify_all()
            self.env.event_bus.unsubscribe_all(self._subscriber)
            self.server._note_subs(-len(self._subs))
            self._subs.clear()
            try:
                self.sock.close()
            except OSError:
                pass

    def close(self) -> None:
        """Tear the connection down from outside (server stop, slow-
        client disconnect): a FIN reaches the client so its read loop
        exits promptly."""
        self._closed.set()
        with self._q_cond:
            self._q_cond.notify_all()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def _dispatch(self, req: dict) -> None:
        if not isinstance(req, dict) or "method" not in req:
            return self.send_json(jsonrpc.error_response(
                None, jsonrpc.ERR_INVALID_REQUEST, "invalid request"))
        id_ = req.get("id")
        method = req["method"]
        params = req.get("params") or {}
        try:
            if method == "subscribe":
                self.send_json(jsonrpc.ok_response(
                    id_, self._subscribe(params)))
            elif method == "unsubscribe":
                self.send_json(jsonrpc.ok_response(
                    id_, self._unsubscribe(params)))
            elif method == "unsubscribe_all":
                self.env.event_bus.unsubscribe_all(self._subscriber)
                self.server._note_subs(-len(self._subs))
                self._subs.clear()
                self.send_json(jsonrpc.ok_response(id_, {}))
            else:
                raw = self.server.call_bytes(method, params)
                self.send_bytes(_result_frame(id_, raw))
        except RPCError as e:
            self.send_json(jsonrpc.error_response(id_, e.code, e.message))
        except Exception as e:  # noqa: BLE001
            LOG.exception("ws rpc %s failed", method)
            self.send_json(
                jsonrpc.error_response(id_, jsonrpc.ERR_INTERNAL, str(e)))

    # -- subscriptions (rpc/core/events.go Subscribe) ------------------

    def _subscribe(self, params: dict) -> dict:
        qs = params.get("query")
        if not qs:
            raise RPCError(jsonrpc.ERR_INVALID_PARAMS, "missing query")
        if qs in self._subs:
            raise RPCError(jsonrpc.ERR_SERVER, "already subscribed")
        sub = self.env.event_bus.subscribe(self._subscriber, Query(qs), 128)
        self._subs[qs] = sub
        self.server._note_subs(1)
        t = threading.Thread(
            target=self._pump, args=(qs, sub), daemon=True,
            name=f"ws-sub-{len(self._subs)}",
        )
        t.start()
        self._pumps.append(t)
        return {}

    def _unsubscribe(self, params: dict) -> dict:
        qs = params.get("query")
        if not qs or qs not in self._subs:
            raise RPCError(jsonrpc.ERR_SERVER, "subscription not found")
        self.env.event_bus.unsubscribe(self._subscriber, Query(qs))
        if self._subs.pop(qs, None) is not None:
            self.server._note_subs(-1)
        return {}

    def _pump(self, qs: str, sub) -> None:
        """Move matching events from the bus subscription into this
        client's send queue, a drained batch at a time: payloads are
        rendered ONCE per event process-wide (render_event_frames
        memoizes data+tags on the Message, taking the render lock once
        per batch instead of once per tx); this pump only splices the
        query string and enqueues the batch under one queue-lock
        acquisition."""
        from .core import render_event_frames

        while not self._closed.is_set() and not sub.cancelled:
            msgs = sub.get_batch(256, timeout=0.5)
            if not msgs:
                continue
            self.enqueue_events(render_event_frames(msgs, qs))
