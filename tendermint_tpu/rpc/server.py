"""JSON-RPC server: HTTP POST + GET-URI + websocket on one port
(reference rpc/lib/server/handlers.go + http_server.go).

- POST /            JSON-RPC 2.0 body
- GET  /<method>?a=b   URI route (params from query string)
- GET  /websocket   RFC6455 upgrade; JSON-RPC frames; subscribe/
                    unsubscribe stream events to the client
- GET  /            route listing (handlers.go writes the same)

The websocket side is hand-rolled (accept-key handshake + masked
client frames) so one threaded server owns both transports, matching
the reference's single listener.
"""

from __future__ import annotations

import base64
import hashlib
import logging
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qsl, urlparse

from ..libs.events import Query
from . import jsonrpc
from .core import ROUTES, UNSAFE_ROUTES, RPCEnvironment
from .jsonrpc import RPCError

LOG = logging.getLogger("rpc.server")

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# cap on POST bodies: the RPC port is public, and Content-Length is
# attacker-controlled (same spirit as the remote-signer MAX_FRAME)
MAX_BODY_BYTES = 1 << 20


class RPCServer:
    def __init__(self, env: RPCEnvironment, host: str, port: int,
                 unsafe: bool = False, max_open_connections: int = 0):
        self.env = env
        self.unsafe = unsafe
        self.routes = dict(ROUTES)
        if unsafe:
            self.routes.update(UNSAFE_ROUTES)
        handler = _make_handler(self)

        outer = self

        class _LimitedHTTPServer(ThreadingHTTPServer):
            """Connection-capped server (reference
            rpc/lib/server/http_server.go StartHTTPServer →
            netutil.LimitListener): beyond max_open_connections,
            new connections are closed immediately instead of
            accumulating unbounded handler threads."""

            def process_request(self, request, client_address):
                if (outer.max_open_connections > 0
                        and outer._open_conns_add() is False):
                    try:
                        request.close()
                    except OSError:
                        pass
                    return
                try:
                    super().process_request(request, client_address)
                except BaseException:
                    # thread failed to start (fd/thread exhaustion):
                    # process_request_thread never runs, so release the
                    # slot here or it leaks forever
                    if outer.max_open_connections > 0:
                        outer._open_conns_done()
                    raise

            def process_request_thread(self, request, client_address):
                try:
                    super().process_request_thread(request, client_address)
                finally:
                    if outer.max_open_connections > 0:
                        outer._open_conns_done()

        self.max_open_connections = max_open_connections
        self._open_conns = 0
        self._open_lock = threading.Lock()
        self._httpd = _LimitedHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None
        # live websocket connections: ThreadingHTTPServer.shutdown()
        # only stops the accept loop — established websockets would keep
        # being served (answering pings!) by their daemon threads, so a
        # "stopped" node would look alive to subscribed clients and
        # their auto-reconnect would never fire
        self._ws_conns: set = set()
        self._ws_lock = threading.Lock()

    @property
    def listen_addr(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rpc-http", daemon=True
        )
        self._thread.start()
        LOG.info("RPC server listening on %s", self.listen_addr)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        with self._ws_lock:
            conns = list(self._ws_conns)
        for c in conns:
            c.close()

    def _open_conns_add(self) -> bool:
        with self._open_lock:
            if self._open_conns >= self.max_open_connections:
                return False
            self._open_conns += 1
            return True

    def _open_conns_done(self) -> None:
        with self._open_lock:
            self._open_conns -= 1

    def _ws_register(self, conn) -> None:
        with self._ws_lock:
            self._ws_conns.add(conn)

    def _ws_unregister(self, conn) -> None:
        with self._ws_lock:
            self._ws_conns.discard(conn)

    # -- dispatch ------------------------------------------------------

    def call(self, method: str, params: dict) -> dict:
        fn = self.routes.get(method)
        if fn is None:
            raise RPCError(jsonrpc.ERR_METHOD_NOT_FOUND,
                           f"method {method!r} not found")
        return fn(self.env, params)


def _make_handler(server: RPCServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route to our logger
            LOG.debug("http %s", fmt % args)

        # ---- plain HTTP ---------------------------------------------

        def _send_json(self, obj: dict, status: int = 200) -> None:
            body = jsonrpc.dumps(obj)
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            try:
                length = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                length = -1
            if not 0 <= length <= MAX_BODY_BYTES:
                # unread body bytes would desync this keep-alive stream
                self.close_connection = True
                return self._send_json(
                    jsonrpc.error_response(
                        None, jsonrpc.ERR_INVALID_REQUEST,
                        f"request body exceeds {MAX_BODY_BYTES} bytes"),
                    status=413)
            raw = self.rfile.read(length)
            try:
                req = jsonrpc.loads(raw)
            except RPCError as e:
                return self._send_json(
                    jsonrpc.error_response(None, e.code, e.message))
            if isinstance(req, list):  # batch
                return self._send_json(
                    [self._handle_one(r) for r in req])
            self._send_json(self._handle_one(req))

        def _handle_one(self, req) -> dict:
            if not isinstance(req, dict) or "method" not in req:
                return jsonrpc.error_response(
                    None, jsonrpc.ERR_INVALID_REQUEST, "invalid request")
            id_ = req.get("id")
            try:
                result = server.call(req["method"], req.get("params") or {})
                return jsonrpc.ok_response(id_, result)
            except RPCError as e:
                return jsonrpc.error_response(id_, e.code, e.message, e.data)
            except Exception as e:  # noqa: BLE001 - handler crash → 32603
                LOG.exception("rpc %s failed", req.get("method"))
                return jsonrpc.error_response(
                    id_, jsonrpc.ERR_INTERNAL, str(e))

        def do_GET(self):
            parsed = urlparse(self.path)
            path = parsed.path.strip("/")
            if path == "websocket":
                return self._upgrade_websocket()
            if not path:  # route listing (handlers.go writeListOfEndpoints)
                listing = "".join(
                    f"<a href=\"/{m}\">/{m}</a><br>"
                    for m in sorted(server.routes)
                )
                body = f"<html><body>{listing}</body></html>".encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            # latin-1 round-trips every percent-decoded byte 1:1 (like
            # Go's string-of-bytes), so binary payloads in quoted params
            # survive; utf-8 would fold invalid sequences into U+FFFD
            params = dict(parse_qsl(parsed.query, encoding="latin-1"))
            # quoted URI values are RAW strings (reference handlers.go);
            # keep the marker so byte-typed params skip base64/hex
            params = {
                k: (jsonrpc.QuotedStr(v[1:-1])
                    if len(v) >= 2 and v[0] == v[-1] == '"' else v)
                for k, v in params.items()
            }
            try:
                result = server.call(path, params)
                self._send_json(jsonrpc.ok_response("", result))
            except RPCError as e:
                self._send_json(
                    jsonrpc.error_response("", e.code, e.message, e.data))
            except Exception as e:  # noqa: BLE001
                LOG.exception("rpc %s failed", path)
                self._send_json(
                    jsonrpc.error_response("", jsonrpc.ERR_INTERNAL, str(e)))

        # ---- websocket (rpc/lib/server/handlers.go wsConnection) ----

        def _upgrade_websocket(self):
            key = self.headers.get("Sec-WebSocket-Key")
            if not key or "upgrade" not in self.headers.get(
                    "Connection", "").lower():
                self.send_error(400, "not a websocket handshake")
                return
            accept = base64.b64encode(
                hashlib.sha1((key + WS_GUID).encode()).digest()
            ).decode()
            self.send_response(101, "Switching Protocols")
            self.send_header("Upgrade", "websocket")
            self.send_header("Connection", "Upgrade")
            self.send_header("Sec-WebSocket-Accept", accept)
            self.end_headers()
            self.close_connection = True
            conn = WSConn(self.connection, server)
            server._ws_register(conn)
            try:
                conn.serve()  # blocks for the life of the ws conn
            finally:
                server._ws_unregister(conn)

    return Handler


class WSConn:
    """One websocket client: JSON-RPC dispatch + event subscriptions
    (reference wsConnection + wsSubscribe in rpc/core/events.go)."""

    def __init__(self, sock: socket.socket, server: RPCServer):
        self.sock = sock
        self.server = server
        self.env = server.env
        self._send_lock = threading.Lock()
        self._subscriber = f"ws-{id(self):x}-{time.monotonic_ns()}"
        self._subs: Dict[str, object] = {}  # query str -> Subscription
        self._pumps = []
        self._closed = threading.Event()

    # -- frame IO ------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf += chunk
        return buf

    def recv_frame(self) -> Optional[bytes]:
        """Returns a full text/binary message, None on close frame.
        Fragmented messages are reassembled; ping answered inline."""
        message = b""
        while True:
            hdr = self._recv_exact(2)
            fin = hdr[0] & 0x80
            opcode = hdr[0] & 0x0F
            masked = hdr[1] & 0x80
            ln = hdr[1] & 0x7F
            if ln == 126:
                ln = struct.unpack(">H", self._recv_exact(2))[0]
            elif ln == 127:
                ln = struct.unpack(">Q", self._recv_exact(8))[0]
            mask = self._recv_exact(4) if masked else b""
            payload = self._recv_exact(ln)
            if masked:
                payload = bytes(
                    b ^ mask[i % 4] for i, b in enumerate(payload))
            if opcode == 0x8:  # close
                return None
            if opcode == 0x9:  # ping → pong
                self.send_frame(payload, opcode=0xA)
                continue
            if opcode == 0xA:  # pong
                continue
            message += payload
            if fin:
                return message

    def send_frame(self, payload: bytes, opcode: int = 0x1) -> None:
        with self._send_lock:
            header = bytes([0x80 | opcode])
            ln = len(payload)
            if ln < 126:
                header += bytes([ln])
            elif ln < (1 << 16):
                header += bytes([126]) + struct.pack(">H", ln)
            else:
                header += bytes([127]) + struct.pack(">Q", ln)
            self.sock.sendall(header + payload)

    def send_json(self, obj: dict) -> None:
        try:
            self.send_frame(jsonrpc.dumps(obj))
        except OSError:
            self._closed.set()

    # -- serve loop ----------------------------------------------------

    def serve(self) -> None:
        try:
            while not self._closed.is_set():
                msg = self.recv_frame()
                if msg is None:
                    break
                try:
                    req = jsonrpc.loads(msg)
                except RPCError as e:
                    self.send_json(
                        jsonrpc.error_response(None, e.code, e.message))
                    continue
                self._dispatch(req)
        except (ConnectionError, OSError):
            pass
        finally:
            self._closed.set()
            self.env.event_bus.unsubscribe_all(self._subscriber)
            try:
                self.sock.close()
            except OSError:
                pass

    def close(self) -> None:
        """Tear the connection down from outside (server stop): a FIN
        reaches the client so its read loop exits promptly."""
        self._closed.set()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def _dispatch(self, req: dict) -> None:
        if not isinstance(req, dict) or "method" not in req:
            return self.send_json(jsonrpc.error_response(
                None, jsonrpc.ERR_INVALID_REQUEST, "invalid request"))
        id_ = req.get("id")
        method = req["method"]
        params = req.get("params") or {}
        try:
            if method == "subscribe":
                result = self._subscribe(params)
            elif method == "unsubscribe":
                result = self._unsubscribe(params)
            elif method == "unsubscribe_all":
                self.env.event_bus.unsubscribe_all(self._subscriber)
                self._subs.clear()
                result = {}
            else:
                result = self.server.call(method, params)
            self.send_json(jsonrpc.ok_response(id_, result))
        except RPCError as e:
            self.send_json(jsonrpc.error_response(id_, e.code, e.message))
        except Exception as e:  # noqa: BLE001
            LOG.exception("ws rpc %s failed", method)
            self.send_json(
                jsonrpc.error_response(id_, jsonrpc.ERR_INTERNAL, str(e)))

    # -- subscriptions (rpc/core/events.go Subscribe) ------------------

    def _subscribe(self, params: dict) -> dict:
        qs = params.get("query")
        if not qs:
            raise RPCError(jsonrpc.ERR_INVALID_PARAMS, "missing query")
        if qs in self._subs:
            raise RPCError(jsonrpc.ERR_SERVER, "already subscribed")
        sub = self.env.event_bus.subscribe(self._subscriber, Query(qs), 128)
        self._subs[qs] = sub
        t = threading.Thread(
            target=self._pump, args=(qs, sub), daemon=True,
            name=f"ws-sub-{len(self._subs)}",
        )
        t.start()
        self._pumps.append(t)
        return {}

    def _unsubscribe(self, params: dict) -> dict:
        qs = params.get("query")
        if not qs or qs not in self._subs:
            raise RPCError(jsonrpc.ERR_SERVER, "subscription not found")
        self.env.event_bus.unsubscribe(self._subscriber, Query(qs))
        self._subs.pop(qs, None)
        return {}

    def _pump(self, qs: str, sub) -> None:
        """Stream matching events to the client as JSON-RPC
        notifications with id '#event' (reference events.go:73-90)."""
        from .core import _event_data_json

        while not self._closed.is_set() and not sub.cancelled:
            msg = sub.get(timeout=0.5)
            if msg is None:
                continue
            self.send_json({
                "jsonrpc": "2.0",
                "id": "#event",
                "result": {
                    "query": qs,
                    "data": _event_data_json(msg),
                    "tags": msg.tags,
                },
            })
