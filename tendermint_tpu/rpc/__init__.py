"""rpc — JSON-RPC 2.0 API over HTTP + websocket (reference rpc/).

rpc/lib equivalent: jsonrpc.py (framing) + server.py (HTTP POST, GET
URI, and websocket handlers on one port). rpc/core equivalent: core.py
(the route table + handlers, env-injected like rpc/core/pipe.go).
Clients in client.py.
"""

from .client import HTTPClient  # noqa: F401
from .core import RPCEnvironment, ROUTES  # noqa: F401
from .server import RPCServer  # noqa: F401
