"""RPC clients (reference rpc/client/httpclient.go + lib/client/).

HTTPClient: JSON-RPC over HTTP POST via urllib (stdlib; zero deps).
WSClient: thread-driven websocket client for subscriptions — the
transport tm-bench/tm-monitor equivalents use.
"""

from __future__ import annotations

import base64
import hashlib
import os
import queue
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional
from urllib.request import Request, urlopen

from . import jsonrpc
from .jsonrpc import RPCError
from .server import WS_GUID


class HTTPClient:
    """JSON-RPC over HTTP POST (rpc/lib/client/httpclient.go)."""

    def __init__(self, addr: str, timeout: float = 10.0):
        # accept "host:port", "tcp://host:port" or full http URL
        if addr.startswith("tcp://"):
            addr = addr[len("tcp://"):]
        if not addr.startswith("http"):
            addr = "http://" + addr
        self.url = addr
        self.timeout = timeout
        self._id = 0
        self._lock = threading.Lock()

    def call(self, method: str, params: Optional[dict] = None) -> Any:
        with self._lock:
            self._id += 1
            id_ = self._id
        body = jsonrpc.dumps(jsonrpc.request(id_, method, params))
        req = Request(self.url, data=body,
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=self.timeout) as resp:
            out = jsonrpc.loads(resp.read())
        if "error" in out and out["error"]:
            e = out["error"]
            raise RPCError(e.get("code", -1), e.get("message", ""),
                           e.get("data"))
        return out.get("result")

    # -- convenience wrappers (rpc/client/httpclient.go methods) -------

    def status(self):
        return self.call("status")

    def net_info(self):
        return self.call("net_info")

    def genesis(self):
        return self.call("genesis")

    def health(self):
        return self.call("health")

    def block(self, height: Optional[int] = None):
        return self.call("block", {"height": height} if height else {})

    def block_results(self, height: Optional[int] = None):
        return self.call("block_results",
                         {"height": height} if height else {})

    def blockchain(self, min_height: int = 0, max_height: int = 0):
        return self.call("blockchain", {"minHeight": min_height,
                                        "maxHeight": max_height})

    def commit(self, height: Optional[int] = None):
        return self.call("commit", {"height": height} if height else {})

    def validators(self, height: Optional[int] = None):
        return self.call("validators", {"height": height} if height else {})

    def abci_info(self):
        return self.call("abci_info")

    def abci_query(self, path: str, data: bytes, height: int = 0,
                   prove: bool = False):
        return self.call("abci_query", {
            "path": path, "data": data.hex(), "height": height,
            "prove": prove,
        })

    def broadcast_tx_async(self, tx: bytes):
        return self.call("broadcast_tx_async",
                         {"tx": base64.b64encode(tx).decode()})

    def broadcast_tx_sync(self, tx: bytes):
        return self.call("broadcast_tx_sync",
                         {"tx": base64.b64encode(tx).decode()})

    def broadcast_tx_commit(self, tx: bytes):
        return self.call("broadcast_tx_commit",
                         {"tx": base64.b64encode(tx).decode()})

    def tx(self, hash_: bytes):
        return self.call("tx", {"hash": hash_.hex()})

    def tx_search(self, query: str, page: int = 1, per_page: int = 30):
        return self.call("tx_search", {"query": query, "page": page,
                                       "per_page": per_page})

    def unconfirmed_txs(self, limit: int = 30):
        return self.call("unconfirmed_txs", {"limit": limit})

    def num_unconfirmed_txs(self):
        return self.call("num_unconfirmed_txs")

    def consensus_state(self):
        return self.call("consensus_state")

    def dump_consensus_state(self):
        return self.call("dump_consensus_state")


class WSClient:
    """Minimal websocket JSON-RPC client (rpc/lib/client/ws_client.go).

    Responses and event notifications are delivered on an internal
    queue (or a callback); the caller drives subscribe()/call()."""

    def __init__(self, addr: str,
                 on_event: Optional[Callable[[dict], None]] = None):
        if addr.startswith("tcp://"):
            addr = addr[len("tcp://"):]
        if addr.startswith("http://"):
            addr = addr[len("http://"):]
        host, _, port = addr.rpartition(":")
        self.host, self.port = host, int(port)
        self.on_event = on_event
        self.events: "queue.Queue[dict]" = queue.Queue()
        self.responses: "queue.Queue[dict]" = queue.Queue()
        self._sock: Optional[socket.socket] = None
        self._id = 0
        self._send_lock = threading.Lock()
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_rx = time.time()

    def connect(self, timeout: float = 10.0) -> None:
        self._sock = socket.create_connection((self.host, self.port),
                                              timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        req = (
            f"GET /websocket HTTP/1.1\r\nHost: {self.host}:{self.port}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
        )
        self._sock.sendall(req.encode())
        # read the 101 response headers
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionError("ws handshake failed")
            buf += chunk
        status = buf.split(b"\r\n", 1)[0]
        if b"101" not in status:
            raise ConnectionError(f"ws handshake rejected: {status!r}")
        expect = base64.b64encode(
            hashlib.sha1((key + WS_GUID).encode()).digest())
        if expect not in buf:
            raise ConnectionError("bad Sec-WebSocket-Accept")
        self._sock.settimeout(None)
        self._thread = threading.Thread(target=self._read_loop,
                                        name="ws-client", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._closed.set()
        if self._sock is not None:
            # shutdown BEFORE close: close() alone does not wake a
            # read loop blocked in recv (Linux keeps the in-flight
            # syscall blocked on the open file description), so no FIN
            # would reach the server and its connection state — pumps,
            # subscription counts — would linger until the next event
            # happened to flow
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass

    # -- frame IO (client frames are masked per RFC6455) ---------------

    def _send_frame(self, payload: bytes, opcode: int = 0x1) -> None:
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        header = bytes([0x80 | opcode])
        ln = len(payload)
        if ln < 126:
            header += bytes([0x80 | ln])
        elif ln < (1 << 16):
            header += bytes([0x80 | 126]) + struct.pack(">H", ln)
        else:
            header += bytes([0x80 | 127]) + struct.pack(">Q", ln)
        with self._send_lock:
            self._sock.sendall(header + mask + masked)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf += chunk
        return buf

    def _read_loop(self) -> None:
        try:
            message = b""
            while not self._closed.is_set():
                hdr = self._recv_exact(2)
                self._last_rx = time.time()
                fin = hdr[0] & 0x80
                opcode = hdr[0] & 0x0F
                ln = hdr[1] & 0x7F
                if ln == 126:
                    ln = struct.unpack(">H", self._recv_exact(2))[0]
                elif ln == 127:
                    ln = struct.unpack(">Q", self._recv_exact(8))[0]
                payload = self._recv_exact(ln)  # server frames unmasked
                if opcode == 0x8:
                    break
                if opcode == 0x9:
                    self._send_frame(payload, opcode=0xA)
                    continue
                if opcode == 0xA:
                    continue
                message += payload
                if not fin:
                    continue
                self._handle(message)
                message = b""
        except (ConnectionError, OSError):
            pass
        finally:
            self._closed.set()

    def _handle(self, raw: bytes) -> None:
        try:
            obj = jsonrpc.loads(raw)
        except RPCError:
            return
        if obj.get("id") == "#event":
            if self.on_event is not None:
                self.on_event(obj.get("result") or {})
            else:
                self.events.put(obj.get("result") or {})
        else:
            self.responses.put(obj)

    # -- calls ---------------------------------------------------------

    def call(self, method: str, params: Optional[dict] = None,
             timeout: float = 10.0) -> Any:
        self._id += 1
        self._send_frame(
            jsonrpc.dumps(jsonrpc.request(self._id, method, params)))
        resp = self.responses.get(timeout=timeout)
        if resp.get("error"):
            e = resp["error"]
            raise RPCError(e.get("code", -1), e.get("message", ""))
        return resp.get("result")

    def subscribe(self, query: str, timeout: float = 10.0) -> None:
        self.call("subscribe", {"query": query}, timeout=timeout)

    def unsubscribe(self, query: str, timeout: float = 10.0) -> None:
        self.call("unsubscribe", {"query": query}, timeout=timeout)

    def next_event(self, timeout: Optional[float] = None) -> Optional[dict]:
        try:
            return self.events.get(timeout=timeout)
        except queue.Empty:
            return None


class ReconnectingWSClient(WSClient):
    """WSClient that survives server restarts (reference
    rpc/lib/client/ws_client.go:47-62,108): when the read loop dies it
    redials with exponential backoff + jitter up to
    max_reconnect_attempts, re-issues every recorded subscription, and
    invokes on_reconnect — so long-lived consumers (tm-monitor) keep
    receiving events across node restarts without their own retry
    plumbing."""

    def __init__(self, addr: str,
                 on_event: Optional[Callable[[dict], None]] = None,
                 max_reconnect_attempts: int = 25,
                 on_reconnect: Optional[Callable[[], None]] = None,
                 ping_period: float = 5.0,
                 pong_timeout: float = 12.0,
                 backoff_scale: float = 1.0):
        super().__init__(addr, on_event)
        self.max_reconnect_attempts = max_reconnect_attempts
        self.on_reconnect = on_reconnect
        self.ping_period = ping_period
        self.pong_timeout = pong_timeout
        self.backoff_scale = backoff_scale
        self._subs: list[str] = []
        self._want_close = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        self._ping_thread: Optional[threading.Thread] = None
        self.reconnects = 0

    def connect(self, timeout: float = 10.0) -> None:
        super().connect(timeout)
        if self._monitor_thread is None:
            self._monitor_thread = threading.Thread(
                target=self._monitor_loop, name="ws-reconnect", daemon=True)
            self._monitor_thread.start()
        if self._ping_thread is None:
            self._ping_thread = threading.Thread(
                target=self._ping_loop, name="ws-keepalive", daemon=True)
            self._ping_thread.start()

    def _ping_loop(self) -> None:
        """Client-side keepalive (ws_client.go pingPeriod/pongWait): a
        half-open TCP connection — e.g. the server restarted without our
        side seeing a FIN — would otherwise never error, so the read loop
        would wait forever and reconnect would never trigger. Ping every
        ping_period; if nothing (pong or data) arrives within
        pong_timeout, kill the socket so the read loop dies and the
        reconnect monitor takes over."""
        while not self._want_close.wait(self.ping_period):
            if self._closed.is_set():
                continue  # reconnect monitor is on it
            try:
                self._send_frame(b"", opcode=0x9)
            except Exception:  # noqa: BLE001 - send failure = dead conn
                pass
            if time.time() - self._last_rx > self.pong_timeout:
                sock = self._sock
                if sock is not None:
                    # shutdown first: close() alone cannot wake the
                    # read loop out of a blocked recv (see close()),
                    # and waking it is this kill's entire purpose
                    try:
                        sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        sock.close()
                    except OSError:
                        pass

    def subscribe(self, query: str, timeout: float = 10.0) -> None:
        super().subscribe(query, timeout)
        if query not in self._subs:
            self._subs.append(query)

    def unsubscribe(self, query: str, timeout: float = 10.0) -> None:
        super().unsubscribe(query, timeout)
        if query in self._subs:
            self._subs.remove(query)

    def close(self) -> None:
        self._want_close.set()
        super().close()

    def is_connected(self) -> bool:
        return not self._closed.is_set()

    # -- reconnect machinery -------------------------------------------

    def _monitor_loop(self) -> None:
        import random

        while not self._want_close.is_set():
            self._closed.wait()
            if self._want_close.is_set():
                return
            redialed = False
            for attempt in range(self.max_reconnect_attempts):
                # 1<<attempt seconds with jitter, capped at 10s AFTER
                # scaling (ws_client.go:108); backoff_scale lets latency-
                # sensitive consumers (monitors, tests) redial faster
                delay = min(
                    (1 << min(attempt, 30)) * (0.5 + random.random() * 0.5)
                    * self.backoff_scale,
                    10.0,
                )
                if self._want_close.wait(delay):
                    return
                try:
                    self._redial()
                    redialed = True
                    break
                except Exception:  # noqa: BLE001 - keep backing off
                    continue
            if not redialed:
                return  # attempts exhausted; stays closed
            self.reconnects += 1
            try:
                for q in list(self._subs):
                    super().subscribe(q)
                if self.on_reconnect is not None:
                    self.on_reconnect()
            except Exception:  # noqa: BLE001 - next death triggers retry
                continue

    def _redial(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # drop stale responses so post-reconnect calls pair correctly
        while True:
            try:
                self.responses.get_nowait()
            except queue.Empty:
                break
        self._closed.clear()
        self._last_rx = time.time()
        WSClient.connect(self, timeout=5.0)
