"""Profiling endpoint — pprof-equivalent (reference node/node.go:468-474
mounts net/http/pprof when ProfListenAddress is set).

Serves:
- /debug/pprof/            index
- /debug/pprof/goroutine   all thread stacks (goroutine-dump analogue)
- /debug/pprof/heap        tracemalloc snapshot (top allocations)
- /debug/pprof/profile?seconds=N  statistical CPU profile via cProfile
- /debug/trace[?clear=1]   chrome://tracing JSON of the span ring buffer
                           (libs/tracing.py; no reference equivalent)
- /debug/timeline?height=N block-lifecycle record for one height
                           (libs/timeline.py marks stitched with the
                           tracer spans tagged height=N)
- /debug/clock             wall + monotonic timestamps and the node's
                           identity — the echo half of fleettrace's
                           NTP-style RTT-symmetric offset probe
                           (tools/fleettrace.py)
- plus any `providers` routes the node mounts: /debug/consensus (the
  stall watchdog's diagnostic bundle), /debug/statesync (snapshot
  inventory, chunk counters, and live restore progress), /debug/abci
  (per-connection ResilientClient state: health, reconnects, last
  error) and /debug/lockdep (libs/lockdep.py acquisition graph,
  lock-order-inversion witnesses, and per-site hold stats when
  [instrumentation] lockdep is on)
"""

from __future__ import annotations

import cProfile
import io
import json
import pstats
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qsl, urlparse

from ..libs import timeline as timeline_mod
from ..libs import tracing


class ProfServer:
    def __init__(self, host: str, port: int,
                 tracer: Optional[tracing.Tracer] = None,
                 timeline: Optional[timeline_mod.Timeline] = None,
                 providers: Optional[Dict[str, Callable]] = None,
                 identity: Optional[dict] = None,
                 clock_skew_s: float = 0.0):
        """`timeline` is the node's per-instance lifecycle recorder
        (falls back to the process-global one for standalone servers);
        `providers` maps a path (e.g. "/debug/consensus") to a
        callable(query_params: dict) -> JSON-able object. `identity`
        (node_id/moniker) is echoed at /debug/clock so fleettrace can
        map scrape endpoints to p2p peer ids; `clock_skew_s` offsets the
        wall timestamp there — a test/chaos knob matching
        Timeline.set_skew, so in-process localnets present genuinely
        skewed clocks for offset-recovery to find."""
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        # the handler reaches the tracer through the server instance
        self._httpd.tracer = tracer if tracer is not None else tracing.get_tracer()
        self._httpd.timeline = (timeline if timeline is not None
                                else timeline_mod.get_timeline())
        self._httpd.providers = dict(providers or {})
        self._httpd.identity = dict(identity or {})
        self._httpd.clock_skew_s = float(clock_skew_s)
        self._thread: Optional[threading.Thread] = None

    @property
    def listen_addr(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="prof-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def _thread_dump() -> str:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in frames.items():
        out.append(f"thread {names.get(tid, '?')} (id={tid}):")
        out.extend(l.rstrip() for l in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def _heap_dump() -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return "tracemalloc just started; re-request for a snapshot"
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:50]
    return "\n".join(str(s) for s in stats)


# cProfile hooks the process-global interpreter profile slot: two
# overlapping Profile.enable() calls corrupt each other's state (and the
# second enable() raises on some versions). One profile at a time.
_profile_lock = threading.Lock()


def _cpu_profile(seconds: float) -> str:
    prof = cProfile.Profile()
    prof.enable()
    threading.Event().wait(seconds)
    prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats("cumulative").print_stats(60)
    return buf.getvalue()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _text(self, body: str, status: int = 200,
              content_type: str = "text/plain; charset=utf-8") -> None:
        raw = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def do_GET(self):
        parsed = urlparse(self.path)
        path = parsed.path.rstrip("/")
        if path in ("", "/debug/pprof"):
            extra = "".join(f" {p.rsplit('/', 1)[-1]}"
                            for p in sorted(self.server.providers))
            self._text(
                f"profiles: goroutine heap profile trace timeline"
                f" clock{extra}\n")
        elif path == "/debug/pprof/goroutine":
            self._text(_thread_dump())
        elif path == "/debug/pprof/heap":
            self._text(_heap_dump())
        elif path == "/debug/pprof/profile":
            q = dict(parse_qsl(parsed.query))
            secs = min(float(q.get("seconds", 5)), 60.0)
            if not _profile_lock.acquire(blocking=False):
                self._text("a CPU profile is already running\n", status=429)
                return
            try:
                body = _cpu_profile(secs)
            finally:
                _profile_lock.release()
            self._text(body)
        elif path == "/debug/trace":
            tracer: tracing.Tracer = self.server.tracer
            body = tracer.chrome_trace_json()
            if dict(parse_qsl(parsed.query)).get("clear"):
                tracer.clear()
            self._text(body, content_type="application/json")
        elif path == "/debug/timeline":
            self._serve_timeline(dict(parse_qsl(parsed.query)))
        elif path == "/debug/clock":
            # the echo half of the fleettrace offset probe: the caller
            # brackets this request with its own monotonic clock and
            # treats wall_s as sampled at the request midpoint (NTP
            # midpoint estimate); mono_ns lets it detect server-side
            # wall-clock steps between probes
            self._json({
                "wall_s": time.time() + self.server.clock_skew_s,
                "mono_ns": time.monotonic_ns(),
                "identity": self.server.identity,
            })
        elif path in self.server.providers:
            q = dict(parse_qsl(parsed.query))
            try:
                obj = self.server.providers[path](q)
            except Exception as e:  # noqa: BLE001 - surface, don't kill
                self._json({"error": str(e)}, status=500)
                return
            self._json(obj)
        else:
            self._text("not found", status=404)

    def _json(self, obj, status: int = 200) -> None:
        self._text(json.dumps(obj, separators=(",", ":"), default=str),
                   status=status, content_type="application/json")

    def _serve_timeline(self, q: dict) -> None:
        """One height's lifecycle record, stitched with the tracer spans
        tagged with that height; ?list=1 enumerates recorded heights
        (the fleettrace collector's common-height discovery)."""
        tl: timeline_mod.Timeline = self.server.timeline
        if q.get("list"):
            self._json({"heights": tl.heights(),
                        "latest": tl.latest_height()})
            return
        try:
            height = int(q.get("height", 0))
        except ValueError:
            self._json({"error": f"bad height {q.get('height')!r}"},
                       status=400)
            return
        if height <= 0:
            height = tl.latest_height()
        rec = tl.record(height)
        if rec is None:
            self._json(
                {"error": f"no timeline for height {height}",
                 "heights": tl.heights()},
                status=404)
            return
        tracer: tracing.Tracer = self.server.tracer
        rec["spans"] = tracer.spans_where(height=height)
        self._json(rec)
