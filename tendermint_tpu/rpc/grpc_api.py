"""gRPC BroadcastAPI (reference rpc/grpc/: Ping + BroadcastTx).

The reference exposes a minimal gRPC service next to JSON-RPC
(rpc/grpc/api.go). We register the same two methods as generic gRPC
handlers with JSON-encoded request/response bodies — real gRPC over
HTTP/2 (grpcio), without a .proto codegen step.
"""

from __future__ import annotations

import json
from typing import Optional

import grpc
from concurrent import futures

SERVICE = "core_grpc.BroadcastAPI"


def _ser(obj) -> bytes:
    return json.dumps(obj).encode()


def _deser(raw: bytes):
    return json.loads(raw) if raw else {}


class BroadcastAPIServer:
    """rpc/grpc/api.go broadcastAPI over generic handlers."""

    def __init__(self, env, host: str, port: int):
        self.env = env
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        handlers = {
            "Ping": grpc.unary_unary_rpc_method_handler(
                self._ping, request_deserializer=_deser,
                response_serializer=_ser),
            "BroadcastTx": grpc.unary_unary_rpc_method_handler(
                self._broadcast_tx, request_deserializer=_deser,
                response_serializer=_ser),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")

    @property
    def listen_addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)

    # -- methods (rpc/grpc/api.go:15-36) -------------------------------

    def _ping(self, request, context):
        return {}

    def _broadcast_tx(self, request, context):
        from .core import broadcast_tx_commit

        res = broadcast_tx_commit(self.env, {"tx": request.get("tx", "")})
        return {
            "check_tx": res["check_tx"],
            "deliver_tx": res["deliver_tx"],
            "hash": res["hash"],
            "height": res["height"],
        }


class BroadcastAPIClient:
    """gRPC client for the BroadcastAPI (rpc/grpc/client_server.go)."""

    def __init__(self, addr: str):
        self._channel = grpc.insecure_channel(addr)
        self._ping = self._channel.unary_unary(
            f"/{SERVICE}/Ping", request_serializer=_ser,
            response_deserializer=_deser)
        self._btx = self._channel.unary_unary(
            f"/{SERVICE}/BroadcastTx", request_serializer=_ser,
            response_deserializer=_deser)

    def ping(self) -> dict:
        return self._ping({})

    def broadcast_tx(self, tx: bytes) -> dict:
        import base64

        return self._btx({"tx": base64.b64encode(tx).decode()})

    def close(self) -> None:
        self._channel.close()
