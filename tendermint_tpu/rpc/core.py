"""RPC core — the route table + handlers (reference rpc/core/).

Route parity with rpc/core/routes.go:11-52. Handlers receive their
dependencies through RPCEnvironment (the setter-injected globals of
rpc/core/pipe.go become one explicit env object around the Node).
Heights/ints are rendered as strings like the reference's amino-JSON.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..abci import types as abci
from ..libs.events import Query
from ..state import load_abci_responses, load_validators
from ..types.block import tx_hash as compute_tx_hash
from ..types.event_bus import (
    EVENT_TX,
    TX_HASH_KEY,
    query_for_event,
)
from . import encoding as enc
from .jsonrpc import ERR_INVALID_PARAMS, ERR_SERVER, QuotedStr, RPCError

SUBSCRIBE_TIMEOUT = 10.0  # reference rpc/core/events.go subscribeTimeout


class RPCEnvironment:
    """All node internals the handlers need (rpc/core/pipe.go).

    ``consensus_state`` is None on a read replica ([base] mode =
    replica): the node tails blocks through the fast-sync reactor and
    never runs consensus, so the latest State lives on the blockchain
    reactor instead."""

    def __init__(self, node):
        self.node = node
        self.config = node.config
        self.block_store = node.block_store
        self.state_db = node.state_db
        self.mempool = node.mempool
        self.evidence_pool = node.evidence_pool
        self.consensus_state = getattr(node, "consensus_state", None)
        self.p2p_switch = node.sw
        self.event_bus = node.event_bus
        self.tx_indexer = node.tx_indexer
        self.genesis_doc = node.genesis_doc
        self.proxy_app_query = node.proxy_app.query
        self.pub_key = (
            node.priv_validator.get_pub_key() if node.priv_validator else None
        )

    def latest_state(self):
        if self.consensus_state is not None:
            return self.consensus_state.state
        return self.node.blockchain_reactor.state


# --- helpers ----------------------------------------------------------


def _int(params: dict, key: str, default=None) -> Optional[int]:
    v = params.get(key, None)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except (TypeError, ValueError):
        raise RPCError(ERR_INVALID_PARAMS, f"bad int param {key}={v!r}")


def _tx_param(params: dict) -> bytes:
    tx = params.get("tx")
    if tx is None:
        raise RPCError(ERR_INVALID_PARAMS, "missing tx param")
    if isinstance(tx, QuotedStr):
        return tx.raw_bytes()  # quoted URI value = raw bytes (handlers.go)
    if isinstance(tx, str):
        return enc.unb64(tx)
    return bytes(tx)


def _bool(params: dict, key: str, default: bool) -> bool:
    """URI booleans arrive as strings: 'false'/'0'/'' must be False
    (the reference's reflection-based URI parser parses bool args)."""
    v = params.get(key, default)
    if isinstance(v, str):
        return v.strip().lower() in ("true", "1", "t")
    return bool(v)


def _hash_param(params: dict, key: str = "hash") -> bytes:
    h = params.get(key)
    if h is None:
        raise RPCError(ERR_INVALID_PARAMS, f"missing {key} param")
    if isinstance(h, QuotedStr):
        return h.raw_bytes()  # quoted URI value = raw bytes
    if isinstance(h, str):
        return bytes.fromhex(h)
    return bytes(h)


def _load_height(env: RPCEnvironment, params: dict) -> int:
    """Height param defaulting to the store tip (rpc/core/blocks.go
    getHeight)."""
    store_h = env.block_store.height()
    h = _int(params, "height", None)
    if h is None or h == 0:
        return store_h
    if h <= 0:
        raise RPCError(ERR_INVALID_PARAMS, "height must be greater than 0")
    if h > store_h:
        raise RPCError(
            ERR_SERVER, f"height {h} must be less than or equal to the "
            f"current blockchain height {store_h}"
        )
    return h


# --- response-cache planning (rpc/cache.py) ---------------------------
#
# Which calls may serve pre-rendered bytes, and under what key. A plan
# is (key, generational): immutable entries (height <= tip) live until
# evicted; generational entries expire when the EventBus NewBlock hook
# bumps the cache generation. None = not cacheable (including any
# malformed params — the handler still runs to produce the right error).

CACHEABLE_METHODS = frozenset((
    "status", "genesis", "block", "block_results", "commit",
    "validators", "blockchain", "tx_search",
))


def cache_plan(env: RPCEnvironment, method: str, params: dict):
    if method not in CACHEABLE_METHODS:
        return None
    try:
        if method == "status":
            return ((), True)
        if method == "genesis":
            return ((), False)
        store_h = env.block_store.height()
        if method in ("block", "block_results", "commit"):
            h = _int(params, "height", None)
            if h is None or h == 0:
                # latest-height variant: tip-dependent, expire per block
                return (("latest",), True)
            if not 1 <= h <= store_h:
                return None
            if method == "commit" and h == store_h:
                # the tip's commit is the mutable seen-commit until the
                # next block makes it canonical (rpc/core/blocks.go)
                return ((h,), True)
            return ((h,), False)
        if method == "validators":
            h = _int(params, "height", None)
            if h is None or h == 0:
                return (("latest",), True)  # next-height set, from State
            return ((h,), False) if h >= 1 else None
        if method == "blockchain":
            # the response embeds last_height = the MOVING tip, so no
            # blockchain range is ever immutable — every variant is
            # generational (and negative/omitted maxHeight resolves to
            # the tip anyway)
            min_p = _int(params, "minHeight", None)
            max_p = _int(params, "maxHeight", None)
            return ((min_p, max_p), True)
        if method == "tx_search":
            # indexer queries: keyed by the index GENERATION (a per-tx
            # ingest counter — the result is a pure function of the
            # index contents, which change exactly when it advances;
            # the ROADMAP's "last uncached hot read"). Keying by
            # indexed HEIGHT would be wrong: it bumps on a block's
            # first tx, so a result computed mid-ingest would keep
            # serving after the rest of the block landed. Still
            # generational as a belt: TTL bounds any unforeseen
            # staleness on a stalled chain.
            qs = params.get("query")
            if not qs:
                return None  # handler produces the real error
            page = max(_int(params, "page", 1) or 1, 1)
            per_page = min(max(_int(params, "per_page", 30) or 30, 1), 100)
            return ((str(qs), page, per_page,
                     env.tx_indexer.index_generation()), True)
    except RPCError:
        return None
    return None


# --- info routes (rpc/core/routes.go:14-27) ---------------------------


def health(env: RPCEnvironment, params: dict) -> dict:
    return {}


def status(env: RPCEnvironment, params: dict) -> dict:
    """rpc/core/status.go Status"""
    node_info = env.p2p_switch.node_info()
    latest_height = env.block_store.height()
    latest_meta = (
        env.block_store.load_block_meta(latest_height) if latest_height else None
    )
    latest_hash = latest_meta.block_id.hash if latest_meta else b""
    latest_app_hash = latest_meta.header.app_hash if latest_meta else b""
    latest_time = latest_meta.header.time if latest_meta else 0
    voting_power = 0
    if env.pub_key is not None:
        state = env.latest_state()
        addr = env.pub_key.address()
        if state.validators.has_address(addr):
            voting_power = state.validators.get_by_address(addr)[1].voting_power
    bcr = env.node.blockchain_reactor
    # replicas fast-sync forever; "catching up" means actually behind
    # the best peer height, not merely running the tail loop
    catching_up = getattr(bcr, "catching_up", None)
    if catching_up is None:
        catching_up = getattr(bcr, "fast_sync", False)
    sync_info = {
        "latest_block_hash": enc.hexu(latest_hash),
        "latest_app_hash": enc.hexu(latest_app_hash),
        "latest_block_height": str(latest_height),
        "latest_block_time": str(latest_time),
        # lowest height with a full block on disk: > 1 on pruned or
        # state-synced nodes (reference v0.34 earliest_* fields)
        "earliest_block_height": str(env.block_store.base()),
        "catching_up": catching_up,
    }
    tree = getattr(env.node, "replica_tree", None)
    if tree is not None:
        # fan-out tree position (replicas only; generational cache
        # keeps these at most one block generation stale, same as
        # latest_block_height)
        ts = tree.status()
        sync_info["replica_parent"] = ts["parent"]
        sync_info["replica_tree_depth"] = ts["depth"]
        sync_info["replica_lag_blocks"] = ts["lag_blocks"]
    return {
        "node_info": {
            "id": node_info.id,
            "listen_addr": node_info.listen_addr,
            "network": node_info.network,
            "version": node_info.version,
            "channels": node_info.channels.hex(),
            "moniker": node_info.moniker,
            "protocol_version": {
                "p2p": str(node_info.protocol_version.p2p),
                "block": str(node_info.protocol_version.block),
                "app": str(node_info.protocol_version.app),
            },
        },
        "sync_info": sync_info,
        "validator_info": {
            "address": enc.hexu(env.pub_key.address()) if env.pub_key else "",
            "pub_key": (
                {"type": "ed25519", "value": enc.b64(env.pub_key.bytes())}
                if env.pub_key
                else None
            ),
            "voting_power": str(voting_power),
        },
    }


def net_info(env: RPCEnvironment, params: dict) -> dict:
    """rpc/core/net.go NetInfo; each peer carries its live
    p2p.ConnectionStatus (flowrate monitors + per-channel queue depths,
    reference rpc/core/types/responses.go Peer.ConnectionStatus)."""
    peers = []
    for p in env.p2p_switch.peers.list():
        try:
            conn_status = p.status()
        except Exception:  # noqa: BLE001 - peer may be tearing down
            conn_status = None
        peers.append({
            "node_info": {
                "id": p.node_info.id,
                "listen_addr": p.node_info.listen_addr,
                "network": p.node_info.network,
                "moniker": p.node_info.moniker,
            },
            "is_outbound": p.outbound,
            "connection_status": conn_status,
            "remote_ip": p.socket_addr,
        })
    return {
        "listening": True,
        "listeners": [env.p2p_switch.transport.listen_addr],
        "n_peers": str(len(peers)),
        "peers": peers,
    }


def genesis(env: RPCEnvironment, params: dict) -> dict:
    import json

    return {"genesis": json.loads(env.genesis_doc.to_json())}


def blockchain(env: RPCEnvironment, params: dict) -> dict:
    """rpc/core/blocks.go BlockchainInfo: metas for [min,max], newest
    first, max 20 per page."""
    store_h = env.block_store.height()
    min_h = _int(params, "minHeight", 1) or 1
    max_h = _int(params, "maxHeight", store_h) or store_h
    max_h = min(max_h, store_h) if max_h > 0 else store_h
    min_h = max(min_h, 1)
    min_h = max(min_h, max_h - 20 + 1)
    if min_h > max_h:
        raise RPCError(ERR_SERVER, f"min height {min_h} > max height {max_h}")
    metas = []
    for h in range(max_h, min_h - 1, -1):
        m = env.block_store.load_block_meta(h)
        if m is not None:
            metas.append(enc.block_meta_json(m))
    return {"last_height": str(store_h), "block_metas": metas}


def block(env: RPCEnvironment, params: dict) -> dict:
    h = _load_height(env, params)
    meta = env.block_store.load_block_meta(h)
    blk = env.block_store.load_block(h)
    if blk is None:
        raise RPCError(ERR_SERVER, f"no block at height {h}")
    return {
        "block_meta": enc.block_meta_json(meta) if meta else None,
        "block": enc.block_json(blk),
    }


def block_results(env: RPCEnvironment, params: dict) -> dict:
    h = _load_height(env, params)
    res = load_abci_responses(env.state_db, h)
    if res is None:
        raise RPCError(ERR_SERVER, f"no results for height {h}")
    eb = res.end_block
    return {
        "height": str(h),
        "results": {
            "DeliverTx": [enc.tx_response_json(r) for r in res.deliver_tx],
            "EndBlock": {
                "validator_updates": [
                    _validator_update_json(u)
                    for u in (eb.validator_updates if eb else [])
                ],
                "consensus_param_updates": (
                    _param_updates_json(eb.consensus_param_updates)
                    if eb is not None else None
                ),
            },
        },
    }


def _validator_update_json(u) -> dict:
    """abci.ValidatorUpdate (type-tagged pubkey bytes + power). The
    reference marshals abci.PubKey with json tag "data", not "value"."""
    from ..crypto import pubkey_from_bytes
    from ..crypto.keys import PubKeyEd25519

    pk = pubkey_from_bytes(u.pub_key)
    typ = "ed25519" if isinstance(pk, PubKeyEd25519) else "secp256k1"
    return {
        "pub_key": {"type": typ, "data": enc.b64(pk.bytes())},
        "power": str(u.power),
    }


def _param_updates_json(pu) -> Optional[dict]:
    """abci.ConsensusParamUpdates: only the sections the app set."""
    if pu is None:
        return None
    out: dict = {}
    if pu.block_size is not None:
        out["block_size"] = {
            "max_bytes": str(pu.block_size.max_bytes),
            "max_gas": str(pu.block_size.max_gas),
        }
    if pu.evidence is not None:
        out["evidence"] = {"max_age": str(pu.evidence.max_age)}
    return out


def commit(env: RPCEnvironment, params: dict) -> dict:
    """rpc/core/blocks.go Commit: header + commit; canonical unless the
    commit is the tip's seen-commit."""
    h = _load_height(env, params)
    meta = env.block_store.load_block_meta(h)
    if meta is None:
        raise RPCError(ERR_SERVER, f"no header at height {h}")
    if h == env.block_store.height():
        com = env.block_store.load_seen_commit(h)
        canonical = False
    else:
        com = env.block_store.load_block_commit(h)
        canonical = True
    return {
        "signed_header": {
            "header": enc.header_json(meta.header),
            "commit": enc.commit_json(com),
        },
        "canonical": canonical,
    }


def validators(env: RPCEnvironment, params: dict) -> dict:
    store_h = env.block_store.height()
    h = _int(params, "height", None)
    if h is None or h == 0:
        h = store_h + 1  # current validators are for next height
        vals = env.latest_state().validators
    else:
        vals = load_validators(env.state_db, h)
        if vals is None:
            raise RPCError(ERR_SERVER, f"no validators at height {h}")
    return {
        "block_height": str(h),
        "validators": [enc.validator_json(v) for v in vals.validators],
    }


def _require_consensus(env: RPCEnvironment):
    if env.consensus_state is None:
        raise RPCError(
            ERR_SERVER, "consensus is not running on this node "
            "([base] mode = replica serves reads only)")
    return env.consensus_state


def dump_consensus_state(env: RPCEnvironment, params: dict) -> dict:
    # stamped snapshot, not a live .rs reference: this runs on an RPC
    # worker thread. Diagnostics tolerate a torn read, but report the
    # stamp so an operator (or test) can tell.
    rs = _require_consensus(env).get_round_state()
    peers = []
    for p in env.p2p_switch.peers.list():
        ps = p.get("consensus_peer_state")
        prs = ps.get_round_state() if ps is not None else None
        peers.append({
            "node_address": f"{p.node_info.id}@{p.socket_addr}",
            "peer_state": (
                {
                    "height": str(prs.height),
                    "round": str(prs.round),
                    "step": prs.step,
                }
                if prs is not None
                else None
            ),
        })
    return {"round_state": _round_state_json(rs, full=True),
            "snapshot_gen": getattr(rs, "snapshot_gen", None),
            "snapshot_consistent": getattr(rs, "snapshot_consistent", True),
            "peers": peers}


def consensus_state(env: RPCEnvironment, params: dict) -> dict:
    rs = _require_consensus(env).get_round_state()
    return {"round_state": _round_state_json(rs, full=False),
            "snapshot_consistent": getattr(rs, "snapshot_consistent", True)}


def _round_state_json(rs, full: bool) -> dict:
    from ..consensus.cstypes import RoundStepType

    out = {
        "height": str(rs.height),
        "round": str(rs.round),
        "step": RoundStepType.name(rs.step),
        "height/round/step": f"{rs.height}/{rs.round}/{rs.step}",
        "start_time": str(rs.start_time),
        "proposal_block_hash": enc.hexu(
            rs.proposal_block.hash() if rs.proposal_block else b""
        ),
        "locked_block_hash": enc.hexu(
            rs.locked_block.hash() if rs.locked_block else b""
        ),
        "valid_block_hash": enc.hexu(
            rs.valid_block.hash() if rs.valid_block else b""
        ),
    }
    if full and rs.votes is not None:
        out["height_vote_set"] = str(rs.votes)
    return out


def consensus_params(env: RPCEnvironment, params: dict) -> dict:
    """rpc/core/consensus.go:319-330 ConsensusParams: the historical
    consensus params in effect at `height` (default: the params for the
    next block, LastBlockHeight+1 — they are stored ahead of execution)."""
    from ..state import load_consensus_params
    from ..state.store import NoConsensusParamsForHeightError

    latest = env.latest_state().last_block_height + 1
    h = _int(params, "height", None)
    if h is None:
        h = latest
    elif h <= 0:
        # an EXPLICITLY supplied height=0 is invalid (reference
        # getHeight); only an omitted height defaults to latest
        raise RPCError(ERR_INVALID_PARAMS, "height must be greater than 0")
    elif h > latest:
        # params are stored through the NEXT block's height
        raise RPCError(
            ERR_SERVER, f"height {h} must be less than or equal to the "
            f"next block height {latest}"
        )
    try:
        cp = load_consensus_params(env.state_db, h)
    except NoConsensusParamsForHeightError:
        raise RPCError(ERR_SERVER, f"no consensus params for height {h}")
    return {
        "block_height": str(h),
        "consensus_params": _consensus_params_json(cp),
    }


def _consensus_params_json(cp) -> dict:
    """types/params.go JSON shape (block_size/evidence sections)."""
    return {
        "block_size": {
            "max_bytes": str(cp.block_size.max_bytes),
            "max_gas": str(cp.block_size.max_gas),
        },
        "evidence": {"max_age": str(cp.evidence.max_age)},
    }


def unconfirmed_txs(env: RPCEnvironment, params: dict) -> dict:
    limit = _int(params, "limit", 30) or 30
    txs = env.mempool.reap_max_txs(limit)
    return {
        "n_txs": str(len(txs)),
        "txs": [enc.b64(tx) for tx in txs],
    }


def num_unconfirmed_txs(env: RPCEnvironment, params: dict) -> dict:
    """Pool pressure without reaping: count AND resident bytes, so load
    tooling can watch saturation (reference ResultUnconfirmedTxs carries
    total_bytes too)."""
    return {
        "n_txs": str(env.mempool.size()),
        "total_bytes": str(env.mempool.tx_bytes()),
        "txs": None,
    }


# --- tx routes (rpc/core/mempool.go, tx.go) ---------------------------


_async_pool = None
_async_pool_lock = threading.Lock()


def _async_executor():
    """Shared small worker pool for fire-and-forget CheckTx — mempool
    admission is serialized behind its own lock anyway, so per-tx
    threads would be pure churn."""
    global _async_pool
    if _async_pool is None:
        from concurrent.futures import ThreadPoolExecutor

        with _async_pool_lock:
            if _async_pool is None:
                _async_pool = ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="rpc-tx-async")
    return _async_pool


def broadcast_tx_async(env: RPCEnvironment, params: dict) -> dict:
    """CheckTx in the background; return immediately (mempool.go:26).
    With batched pre-verification on, the tx goes straight into the
    mempool's ingest queue (sharing a signature batch with concurrent
    submissions) instead of through the worker pool."""
    tx = _tx_param(params)
    if env.mempool.check_tx_nowait(tx) is None:
        # batching off: today's small worker pool runs CheckTx inline
        _async_executor().submit(_checked_check_tx, env, tx)
    return {"code": 0, "data": "", "log": "",
            "hash": enc.hexu(compute_tx_hash(tx))}


def _checked_check_tx(env, tx):
    try:
        env.mempool.check_tx(tx)
    except Exception:  # noqa: BLE001 - async fire-and-forget
        pass


def broadcast_tx_sync(env: RPCEnvironment, params: dict) -> dict:
    """CheckTx and return its result (mempool.go:76)."""
    tx = _tx_param(params)
    try:
        res = env.mempool.check_tx(tx)
    except Exception as e:  # mempool full / cache errors
        raise RPCError(ERR_SERVER, str(e))
    return {
        "code": res.code,
        "data": enc.b64(res.data) if res.data else "",
        "log": res.log,
        "hash": enc.hexu(compute_tx_hash(tx)),
    }


def broadcast_tx_commit(env: RPCEnvironment, params: dict) -> dict:
    """Subscribe to the tx's DeliverTx event, CheckTx, wait for commit
    (reference rpc/core/mempool.go:168-230). The wait is bounded by
    [rpc] timeout_broadcast_tx_commit (default: the reference's 10s);
    a CheckTx rejection tears the subscription down immediately — it
    must never linger for the commit timeout."""
    tx = _tx_param(params)
    txh = compute_tx_hash(tx)
    q = Query(f"{TX_HASH_KEY} = '{txh.hex().upper()}'")
    subscriber = f"rpc-btc-{txh.hex()[:16]}-{time.monotonic_ns()}"
    timeout = getattr(env.config.rpc, "timeout_broadcast_tx_commit",
                      SUBSCRIBE_TIMEOUT) or SUBSCRIBE_TIMEOUT
    sub = env.event_bus.subscribe(subscriber, q, 4)
    try:
        try:
            check_res = env.mempool.check_tx(tx)
        except Exception as e:
            raise RPCError(ERR_SERVER, str(e))
        if check_res.code != abci.CODE_TYPE_OK:
            # early-return path: drop the subscription NOW (the finally
            # below also runs, but being explicit keeps the invariant
            # obvious — a rejected tx never holds event-bus state)
            env.event_bus.unsubscribe_all(subscriber)
            return {
                "check_tx": enc.tx_response_json(check_res),
                "deliver_tx": enc.tx_response_json(abci.ResponseDeliverTx()),
                "hash": enc.hexu(txh),
                "height": "0",
            }
        msg = sub.get(timeout=timeout)
        if msg is None:
            raise RPCError(ERR_SERVER, "timed out waiting for tx to be "
                           "included in a block")
        data = msg.data
        return {
            "check_tx": enc.tx_response_json(check_res),
            "deliver_tx": enc.tx_response_json(data["result"]),
            "hash": enc.hexu(txh),
            "height": str(data["height"]),
        }
    finally:
        env.event_bus.unsubscribe_all(subscriber)


def tx(env: RPCEnvironment, params: dict) -> dict:
    """rpc/core/tx.go Tx: look up one tx by hash in the indexer."""
    h = _hash_param(params)
    r = env.tx_indexer.get(h)
    if r is None:
        raise RPCError(ERR_SERVER, f"tx {h.hex().upper()} not found")
    return _tx_result_json(r, h)


def tx_search(env: RPCEnvironment, params: dict) -> dict:
    """rpc/core/tx.go TxSearch with page/per_page."""
    qs = params.get("query")
    if not qs:
        raise RPCError(ERR_INVALID_PARAMS, "missing query param")
    page = max(_int(params, "page", 1) or 1, 1)
    per_page = min(max(_int(params, "per_page", 30) or 30, 1), 100)
    results = env.tx_indexer.search(Query(qs))
    total = len(results)
    start = (page - 1) * per_page
    chunk = results[start : start + per_page]
    return {
        "txs": [_tx_result_json(r, compute_tx_hash(r.tx)) for r in chunk],
        "total_count": str(total),
    }


def _tx_result_json(r, h: bytes) -> dict:
    return {
        "hash": enc.hexu(h),
        "height": str(r.height),
        "index": r.index,
        "tx_result": enc.tx_response_json(r.result),
        "tx": enc.b64(r.tx),
    }


# --- abci routes (rpc/core/abci.go) -----------------------------------


def abci_query(env: RPCEnvironment, params: dict) -> dict:
    data = params.get("data", "")
    if isinstance(data, QuotedStr):
        data = data.raw_bytes()  # quoted URI value = raw bytes
    elif isinstance(data, str):
        data = bytes.fromhex(data) if data else b""
    res = env.proxy_app_query.query(
        abci.RequestQuery(
            data=data,
            path=params.get("path", ""),
            height=_int(params, "height", 0) or 0,
            prove=_bool(params, "prove", False),
        )
    )
    return {
        "response": {
            "code": res.code,
            "log": res.log,
            "info": res.info,
            "index": str(res.index),
            "key": enc.b64(res.key) if res.key else "",
            "value": enc.b64(res.value) if res.value else "",
            "height": str(res.height),
        }
    }


def abci_info(env: RPCEnvironment, params: dict) -> dict:
    res = env.proxy_app_query.info(abci.RequestInfo(version="rpc"))
    return {
        "response": {
            "data": res.data,
            "version": res.version,
            "last_block_height": str(res.last_block_height),
            "last_block_app_hash": enc.b64(res.last_block_app_hash),
        }
    }


# --- unsafe routes (rpc/core/routes.go:44-52, net.go) -----------------


def dial_seeds(env: RPCEnvironment, params: dict) -> dict:
    seeds = params.get("seeds") or []
    if not seeds:
        raise RPCError(ERR_INVALID_PARAMS, "no seeds provided")
    for s in seeds:
        from ..p2p.pex import parse_net_address

        nid, addr = parse_net_address(str(s))
        threading.Thread(
            target=env.p2p_switch.dial_peer, args=(addr,),
            kwargs={"expect_id": nid}, daemon=True,
        ).start()
    return {"log": "Dialing seeds in progress. See /net_info for details"}


def dial_peers(env: RPCEnvironment, params: dict) -> dict:
    peers = params.get("peers") or []
    persistent = bool(params.get("persistent", False))
    if not peers:
        raise RPCError(ERR_INVALID_PARAMS, "no peers provided")
    for s in peers:
        from ..p2p.pex import parse_net_address

        nid, addr = parse_net_address(str(s))
        threading.Thread(
            target=env.p2p_switch.dial_peer, args=(addr,),
            kwargs={"expect_id": nid, "persistent": persistent}, daemon=True,
        ).start()
    return {"log": "Dialing peers in progress. See /net_info for details"}


# --- event rendering for websocket subscribers ------------------------

# render-once fan-out: the heavy part of an event notification (the
# amino-JSON data union + tags) is identical for every subscriber, so
# it is rendered to wire bytes ONCE per Message and memoized on the
# message object; per-subscriber work shrinks to splicing the (tiny)
# query string into the frame. _render_lock serializes the first
# render so N pumps racing one fresh event still cost one render.
_render_lock = threading.Lock()
_events_rendered = 0  # process-wide funnel counter (tests/bench assert)
_rpc_metrics = None  # RPCMetrics sink, wired by the node like crypto's


def events_rendered_count() -> int:
    return _events_rendered


def set_metrics(m) -> None:
    """Install (or clear, with None) the process-wide RPCMetrics sink
    the event renderer reports to."""
    global _rpc_metrics
    _rpc_metrics = m


def get_metrics():
    return _rpc_metrics


def _render_payload_locked(msg) -> bytes:
    """Render + memoize one message's payload. Caller holds
    _render_lock and has checked the cache."""
    global _events_rendered
    _events_rendered += 1
    if _rpc_metrics is not None:
        _rpc_metrics.events_rendered.inc()
    from . import jsonrpc as _jsonrpc

    body = _jsonrpc.dumps(
        {"data": _event_data_json(msg), "tags": msg.tags})
    cached = body[1:-1]  # strip the object braces for splicing
    msg._rpc_wire_payload = cached
    return cached


def render_event_payload(msg) -> bytes:
    """`"data":<...>,"tags":<...>` as JSON bytes (no surrounding
    braces), rendered once per EventBus Message and cached on it."""
    cached = getattr(msg, "_rpc_wire_payload", None)
    if cached is not None:
        return cached
    with _render_lock:
        cached = getattr(msg, "_rpc_wire_payload", None)
        if cached is None:
            cached = _render_payload_locked(msg)
    return cached


def render_event_frame(msg, query_str: str) -> bytes:
    """The full JSON-RPC notification frame for one subscriber: only
    the query string is per-subscriber; data+tags come pre-rendered."""
    from . import jsonrpc as _jsonrpc

    return (b'{"jsonrpc":"2.0","id":"#event","result":{"query":'
            + _jsonrpc.dumps(query_str) + b","
            + render_event_payload(msg) + b"}}")


def render_event_frames(msgs, query_str: str) -> List[bytes]:
    """Frames for a whole drained batch: any still-unrendered payloads
    are rendered under ONE _render_lock acquisition (instead of
    re-acquiring per tx), then each frame is a pure byte splice. The
    render-once guarantee is unchanged — a payload another pump already
    rendered is reused, and racing pumps still cost one render per
    event process-wide."""
    from . import jsonrpc as _jsonrpc

    if any(getattr(m, "_rpc_wire_payload", None) is None for m in msgs):
        with _render_lock:
            for m in msgs:
                if getattr(m, "_rpc_wire_payload", None) is None:
                    _render_payload_locked(m)
    prefix = (b'{"jsonrpc":"2.0","id":"#event","result":{"query":'
              + _jsonrpc.dumps(query_str) + b",")
    return [prefix + m._rpc_wire_payload + b"}}" for m in msgs]


def _event_data_json(msg) -> dict:
    """Render an EventBus message for a websocket subscriber (reference
    amino-JSON EventData* union, rpc/core/types/responses.go:190)."""
    event_type = msg.tags.get("tm.event", "")
    data = msg.data
    out: dict = {"type": event_type}
    if not isinstance(data, dict):
        out["value"] = str(data)
        return out
    value: dict = {}
    for k, v in data.items():
        if v is None:
            value[k] = None
        elif k == "block":
            value[k] = enc.block_json(v)
        elif k == "header":
            value[k] = enc.header_json(v)
        elif k == "vote":
            value[k] = enc.vote_json(v)
        elif k == "result" and hasattr(v, "code"):
            value[k] = enc.tx_response_json(v)
        elif isinstance(v, bytes):
            value[k] = enc.b64(v)
        elif isinstance(v, (int, float, str, bool)):
            value[k] = v
        else:
            value[k] = str(v)
    out["value"] = value
    return out


# --- route table (rpc/core/routes.go:11-52) ---------------------------

ROUTES: Dict[str, Callable[[RPCEnvironment, dict], dict]] = {
    "health": health,
    "status": status,
    "net_info": net_info,
    "genesis": genesis,
    "blockchain": blockchain,
    "block": block,
    "block_results": block_results,
    "commit": commit,
    "validators": validators,
    "dump_consensus_state": dump_consensus_state,
    "consensus_state": consensus_state,
    "consensus_params": consensus_params,
    "unconfirmed_txs": unconfirmed_txs,
    "num_unconfirmed_txs": num_unconfirmed_txs,
    "broadcast_tx_commit": broadcast_tx_commit,
    "broadcast_tx_sync": broadcast_tx_sync,
    "broadcast_tx_async": broadcast_tx_async,
    "tx": tx,
    "tx_search": tx_search,
    "abci_query": abci_query,
    "abci_info": abci_info,
}

def unsafe_flush_mempool(env: RPCEnvironment, params: dict) -> dict:
    """rpc/core/dev.go UnsafeFlushMempool."""
    env.mempool.flush()
    return {}


UNSAFE_ROUTES: Dict[str, Callable[[RPCEnvironment, dict], dict]] = {
    "dial_seeds": dial_seeds,
    "dial_peers": dial_peers,
    "unsafe_flush_mempool": unsafe_flush_mempool,
}
