"""Height-keyed RPC response cache — serialized JSON bytes for the hot
read endpoints (no reference equivalent; the reference re-marshals
every response).

Two entry classes share one LRU with a byte budget ([rpc] cache_bytes):

- **immutable** entries, keyed ``(method, height-ish key)``: a block,
  commit, block-results or validator set at a height at-or-below the
  store tip never changes once written, so its rendered JSON bytes are
  valid forever (eviction is purely a memory decision).
- **generational** entries, for tip-dependent responses (``/status``,
  latest-height variants, tip commits): stamped with the cache
  generation at fill time. A single EventBus ``NewBlock`` subscription
  bumps the generation, so a stale tip response is never served past
  one generation — without enumerating or locking per-method state on
  the commit path.

Values are the serialized JSON bytes of the RPC *result* (not the
response envelope): a hit is spliced into the JSON-RPC frame by byte
concatenation (rpc/server.py), skipping both the handler and the
re-encode entirely.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

# bookkeeping bytes charged per entry on top of the payload, so a flood
# of tiny entries can't blow the budget through dict/key overhead
ENTRY_OVERHEAD = 256

# wall-clock ceiling on generational entries: generations only advance
# on LOCAL NewBlock, so a node whose block flow stalls would otherwise
# serve its last healthy-looking /status forever — after this many
# seconds a generational entry expires even with no bump, and the live
# handler (whose catching_up/height now tell the truth) runs again.
# Immutable entries are unaffected (a stored block did not change).
GEN_TTL_S = 10.0


class RPCCache:
    """LRU over rendered result bytes with a hard byte budget.

    Thread-safe; every operation is a dict hit under one lock. A
    ``max_bytes`` of 0 disables the cache (every get misses, puts are
    dropped) — the configured default, preserving current behavior.
    """

    def __init__(self, max_bytes: int = 0, metrics=None,
                 gen_ttl_s: float = GEN_TTL_S):
        self.max_bytes = max(0, int(max_bytes))
        self.metrics = metrics  # RPCMetrics or None
        self.gen_ttl_s = gen_ttl_s
        self._lock = threading.Lock()
        # (method, key) -> (raw bytes, generation or None for
        # immutable, monotonic fill time)
        self._lru: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        self.generation = 0
        # counters (also mirrored into metrics when wired)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    # -- read/write ----------------------------------------------------

    def get(self, method: str, key: tuple) -> Optional[bytes]:
        """Serve cached result bytes, or None. A generational entry
        stamped with an older generation — or older than gen_ttl_s of
        wall clock, covering a node whose block flow (and therefore
        generation counter) has stalled — is dropped and misses."""
        if not self.enabled:
            return None
        k = (method, key)
        with self._lock:
            ent = self._lru.get(k)
            if ent is not None:
                raw, gen, stamp = ent
                if gen is None or (
                        gen == self.generation
                        and time.monotonic() - stamp <= self.gen_ttl_s):
                    self._lru.move_to_end(k)
                    self.hits += 1
                    if self.metrics is not None:
                        self.metrics.cache_hits.inc()
                    return raw
                # stale generation/TTL: drop eagerly, free the budget
                del self._lru[k]
                self._bytes -= len(raw) + ENTRY_OVERHEAD
                if self.metrics is not None:
                    self.metrics.cache_bytes.set(self._bytes)
            self.misses += 1
        if self.metrics is not None:
            self.metrics.cache_misses.inc()
        return None

    def put(self, method: str, key: tuple, raw: bytes,
            generational: bool = False,
            generation: Optional[int] = None) -> None:
        """Store result bytes. Generational callers should pass the
        generation they observed BEFORE computing the result: if a
        block landed while the handler ran, the entry is then already
        stale and dies on first lookup, instead of serving pre-bump
        data for the whole next generation."""
        if not self.enabled:
            return
        cost = len(raw) + ENTRY_OVERHEAD
        if cost > self.max_bytes:
            return  # larger than the whole budget: never cacheable
        k = (method, key)
        with self._lock:
            old = self._lru.pop(k, None)
            if old is not None:
                self._bytes -= len(old[0]) + ENTRY_OVERHEAD
            gen = None
            if generational:
                gen = self.generation if generation is None else generation
            self._lru[k] = (raw, gen, time.monotonic())
            self._bytes += cost
            while self._bytes > self.max_bytes and self._lru:
                _, (oraw, _, _) = self._lru.popitem(last=False)
                self._bytes -= len(oraw) + ENTRY_OVERHEAD
                self.evictions += 1
            if self.metrics is not None:
                self.metrics.cache_bytes.set(self._bytes)

    # -- invalidation --------------------------------------------------

    def on_new_block(self) -> None:
        """The EventBus NewBlock hook: one integer bump expires every
        generational entry at once. Immutable entries survive — blocks
        already on disk did not change."""
        with self._lock:
            self.generation += 1
            self.invalidations += 1

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._bytes = 0
            if self.metrics is not None:
                self.metrics.cache_bytes.set(0)

    # -- introspection -------------------------------------------------

    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        # one consistent snapshot: hits/misses/evictions/generation are
        # all written under the lock by the serving threads, so reading
        # them bare here could pair a fresh hit count with a stale total
        # (checker finding CC-GUARD:rpc/cache.py:RPCCache.*)
        with self._lock:
            n = len(self._lru)
            b = self._bytes
            hits, misses = self.hits, self.misses
            generation, evictions = self.generation, self.evictions
        total = hits + misses
        return {
            "enabled": self.enabled,
            "max_bytes": self.max_bytes,
            "bytes": b,
            "entries": n,
            "generation": generation,
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / total, 4) if total else 0.0,
            "evictions": evictions,
        }
