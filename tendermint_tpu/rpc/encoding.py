"""JSON views of core types for RPC responses (reference renders these
via amino-JSON; we use plain JSON with hex hashes and base64 txs, the
same field names as rpc/core/types/responses.go).
"""

from __future__ import annotations

import base64
from typing import Optional


def b64(data: bytes) -> str:
    return base64.b64encode(data).decode()


def unb64(s: str) -> bytes:
    return base64.b64decode(s)


def hexu(data: bytes) -> str:
    return data.hex().upper()


def part_set_header_json(psh) -> dict:
    return {"total": psh.total, "hash": hexu(psh.hash)}


def block_id_json(bid) -> dict:
    return {"hash": hexu(bid.hash),
            "parts": part_set_header_json(bid.parts_header)}


def header_json(h) -> dict:
    return {
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": str(h.time),
        "num_txs": str(h.num_txs),
        "total_txs": str(h.total_txs),
        "last_block_id": block_id_json(h.last_block_id),
        "last_commit_hash": hexu(h.last_commit_hash),
        "data_hash": hexu(h.data_hash),
        "validators_hash": hexu(h.validators_hash),
        "next_validators_hash": hexu(h.next_validators_hash),
        "consensus_hash": hexu(h.consensus_hash),
        "app_hash": hexu(h.app_hash),
        "last_results_hash": hexu(h.last_results_hash),
        "evidence_hash": hexu(h.evidence_hash),
        "proposer_address": hexu(h.proposer_address),
    }


def vote_json(v) -> Optional[dict]:
    if v is None:
        return None
    return {
        "validator_address": hexu(v.validator_address),
        "validator_index": str(v.validator_index),
        "height": str(v.height),
        "round": str(v.round),
        "timestamp": str(v.timestamp),
        "type": v.type,
        "block_id": block_id_json(v.block_id),
        "signature": b64(v.signature),
    }


def commit_json(c) -> Optional[dict]:
    if c is None:
        return None
    from ..types.block import AggregateCommit

    if isinstance(c, AggregateCommit):
        return {
            "block_id": block_id_json(c.block_id),
            "height": str(c.agg_height),
            "round": str(c.agg_round),
            "signers": b64(c.signers.to_bytes()),
            "signers_bits": c.signers.size(),
            "aggregate_signature": b64(c.agg_sig),
        }
    return {
        "block_id": block_id_json(c.block_id),
        "precommits": [vote_json(v) for v in c.precommits],
    }


def block_json(b) -> dict:
    return {
        "header": header_json(b.header),
        "data": {"txs": [b64(tx) for tx in b.data.txs]},
        "evidence": {"evidence": []},
        "last_commit": commit_json(b.last_commit),
    }


def block_meta_json(m) -> dict:
    return {"block_id": block_id_json(m.block_id),
            "header": header_json(m.header)}


def validator_json(v) -> dict:
    # type tag matches the [crypto] key_type registry names; our own
    # decoder sniffs key length, but external consumers trust the tag
    key_type = "bls12381" if len(v.pub_key.bytes()) == 48 else "ed25519"
    o = {
        "address": hexu(v.address),
        "pub_key": {"type": key_type, "value": b64(v.pub_key.bytes())},
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }
    # BLS proof of possession rides along (optional key, Ed25519 wire
    # shape unchanged): lite clients rebuilding valsets from RPC need
    # it to prove possession of signers outside their trusted set
    if v.pop:
        o["pop"] = b64(v.pop)
    return o


# --- decoders (inverse views, used by the lite client and RPC-driven
# tools to rebuild typed objects from responses) -----------------------


def part_set_header_from_json(o) -> "PartSetHeader":
    from ..types.basic import PartSetHeader

    return PartSetHeader(total=int(o["total"]), hash=bytes.fromhex(o["hash"]))


def block_id_from_json(o) -> "BlockID":
    from ..types.basic import BlockID

    return BlockID(hash=bytes.fromhex(o["hash"]),
                   parts_header=part_set_header_from_json(o["parts"]))


def header_from_json(o) -> "Header":
    from ..types.block import Header

    return Header(
        chain_id=o["chain_id"],
        height=int(o["height"]),
        time=int(o["time"]),
        num_txs=int(o["num_txs"]),
        total_txs=int(o["total_txs"]),
        last_block_id=block_id_from_json(o["last_block_id"]),
        last_commit_hash=bytes.fromhex(o["last_commit_hash"]),
        data_hash=bytes.fromhex(o["data_hash"]),
        validators_hash=bytes.fromhex(o["validators_hash"]),
        next_validators_hash=bytes.fromhex(o["next_validators_hash"]),
        consensus_hash=bytes.fromhex(o["consensus_hash"]),
        app_hash=bytes.fromhex(o["app_hash"]),
        last_results_hash=bytes.fromhex(o["last_results_hash"]),
        evidence_hash=bytes.fromhex(o["evidence_hash"]),
        proposer_address=bytes.fromhex(o["proposer_address"]),
    )


def vote_from_json(o) -> Optional["Vote"]:
    from ..types.basic import Vote

    if o is None:
        return None
    return Vote(
        validator_address=bytes.fromhex(o["validator_address"]),
        validator_index=int(o["validator_index"]),
        height=int(o["height"]),
        round=int(o["round"]),
        timestamp=int(o["timestamp"]),
        type=int(o["type"]),
        block_id=block_id_from_json(o["block_id"]),
        signature=unb64(o["signature"]),
    )


def commit_from_json(o):
    from ..types.block import Commit

    if o is None:
        return None
    if "aggregate_signature" in o:
        from ..libs.bit_array import BitArray
        from ..types.block import AggregateCommit

        return AggregateCommit(
            block_id=block_id_from_json(o["block_id"]),
            agg_height=int(o["height"]),
            agg_round=int(o["round"]),
            signers=BitArray.from_bytes_size(unb64(o["signers"]),
                                             int(o["signers_bits"])),
            agg_sig=unb64(o["aggregate_signature"]),
        )
    return Commit(
        block_id=block_id_from_json(o["block_id"]),
        precommits=[vote_from_json(v) for v in o["precommits"]],
    )


def validator_from_json(o) -> "Validator":
    from ..crypto.keys import PubKeyEd25519
    from ..types.validator_set import Validator

    raw = unb64(o["pub_key"]["value"])
    if len(raw) == 48:
        from ..crypto.bls import PubKeyBLS12381

        pub = PubKeyBLS12381(raw)
    else:
        pub = PubKeyEd25519(raw)
    v = Validator.new(pub, int(o["voting_power"]),
                      pop=unb64(o["pop"]) if o.get("pop") else b"")
    v.proposer_priority = int(o.get("proposer_priority", 0))
    return v


def validator_set_from_json(vals: list) -> "ValidatorSet":
    from ..types.validator_set import ValidatorSet

    return ValidatorSet([validator_from_json(o) for o in vals])


def tx_response_json(res) -> dict:
    """ResponseCheckTx / ResponseDeliverTx → JSON."""
    return {
        "code": res.code,
        "data": b64(res.data) if res.data else "",
        "log": res.log,
        "info": res.info,
        "gas_wanted": str(res.gas_wanted),
        "gas_used": str(res.gas_used),
        "tags": [
            {"key": b64(kv.key), "value": b64(kv.value)} for kv in res.tags
        ],
    }
