"""JSON-RPC 2.0 framing (reference rpc/lib/types/types.go).

Requests: {"jsonrpc":"2.0","id":...,"method":...,"params":{...}}.
Responses carry either "result" or "error":{code,message,data}.
"""

from __future__ import annotations

import json
from typing import Any, Optional

# reference rpc/lib/types/types.go error codes (JSON-RPC 2.0 standard)
ERR_PARSE = -32700
ERR_INVALID_REQUEST = -32600
ERR_METHOD_NOT_FOUND = -32601
ERR_INVALID_PARAMS = -32602
ERR_INTERNAL = -32603
ERR_SERVER = -32000


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: Optional[str] = None):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


def request(id_: Any, method: str, params: Optional[dict] = None) -> dict:
    return {"jsonrpc": "2.0", "id": id_, "method": method,
            "params": params or {}}


def ok_response(id_: Any, result: Any) -> dict:
    return {"jsonrpc": "2.0", "id": id_, "result": result}


def error_response(id_: Any, code: int, message: str,
                   data: Optional[str] = None) -> dict:
    err = {"code": code, "message": message}
    if data:
        err["data"] = data
    return {"jsonrpc": "2.0", "id": id_, "error": err}


def dumps(obj: Any) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode()


def loads(raw: bytes) -> Any:
    try:
        return json.loads(raw)
    except (ValueError, UnicodeDecodeError) as e:
        raise RPCError(ERR_PARSE, f"parse error: {e}")


class QuotedStr(str):
    """A URI parameter that arrived double-quoted. The reference's URI
    parser (rpc/lib/server/handlers.go) treats quoted values as RAW
    strings for []byte arguments, while JSON-RPC bodies carry base64 —
    byte-typed param handlers use this marker to tell them apart.
    The server decodes the query string as latin-1, so raw_bytes()
    recovers the exact percent-decoded bytes."""

    def raw_bytes(self) -> bytes:
        return self.encode("latin-1")
