"""PartSet — block chunking for gossip (reference types/part_set.go).

A block's deterministic encoding is split into fixed-size parts; the
PartSetHeader (total, merkle root) identifies the set, and each Part
carries a merkle proof so peers can verify chunks independently.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional

from ..crypto import merkle
from ..libs.bit_array import BitArray
from .basic import PartSetHeader

BLOCK_PART_SIZE = 65536


@dataclass
class Part:
    index: int
    bytes: bytes
    proof: merkle.SimpleProof

    def validate(self, header: PartSetHeader) -> bool:
        return (
            self.proof.index == self.index
            and self.proof.total == header.total
            and self.proof.verify(header.hash, self.bytes)
        )


class PartSet:
    def __init__(self, header: PartSetHeader):
        self._header = header
        self._parts: List[Optional[Part]] = [None] * header.total
        self._bit_array = BitArray(header.total)
        self._count = 0
        self._lock = threading.Lock()

    @classmethod
    def from_data(cls, data: bytes, part_size: int = BLOCK_PART_SIZE) -> "PartSet":
        chunks = [data[i : i + part_size] for i in range(0, len(data), part_size)] or [b""]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(total=len(chunks), hash=root))
        for i, (chunk, proof) in enumerate(zip(chunks, proofs)):
            ps._parts[i] = Part(index=i, bytes=chunk, proof=proof)
        ps._bit_array = BitArray.from_bools([True] * len(chunks))
        ps._count = len(chunks)
        return ps

    def header(self) -> PartSetHeader:
        return self._header

    def has_header(self, header: PartSetHeader) -> bool:
        return self._header == header

    def bit_array(self) -> BitArray:
        with self._lock:
            return self._bit_array.copy()

    def total(self) -> int:
        return self._header.total

    def count(self) -> int:
        with self._lock:
            return self._count

    def is_complete(self) -> bool:
        with self._lock:
            return self._count == self._header.total

    def add_part(self, part: Part) -> bool:
        """Returns True if added; raises ValueError on invalid proof."""
        with self._lock:
            if part.index >= self._header.total:
                raise ValueError("part index out of range")
            if self._parts[part.index] is not None:
                return False
            if not part.validate(self._header):
                raise ValueError("invalid part proof")
            self._parts[part.index] = part
            self._bit_array.set_index(part.index, True)
            self._count += 1
            return True

    def get_part(self, index: int) -> Optional[Part]:
        with self._lock:
            if 0 <= index < len(self._parts):
                return self._parts[index]
            return None

    def assemble(self) -> bytes:
        if not self.is_complete():
            raise ValueError("part set incomplete")
        with self._lock:
            return b"".join(p.bytes for p in self._parts)
