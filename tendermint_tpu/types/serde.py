"""Structural serialization for storage / WAL / p2p (msgpack, list-shaped).

Deterministic: every type encodes as a fixed-order list (never a map), so
identical values yield identical bytes — required because the block's
part-set hash commits to these bytes. Distinct from the codec module,
which produces the minimal canonical encodings used for sign-bytes and
merkle leaves only.
"""

from __future__ import annotations

from typing import Optional

import msgpack

from ..crypto import merkle, pubkey_from_bytes, pubkey_to_bytes
from .basic import BlockID, PartSetHeader, Proposal, Vote
from .block import Block, Commit, Data, EvidenceData, Header
from .part_set import Part
from .validator_set import Validator, ValidatorSet


def pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes):
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


# --- to_obj / from_obj -----------------------------------------------------


def psh_obj(p: PartSetHeader):
    return [p.total, p.hash]


def psh_from(o) -> PartSetHeader:
    return PartSetHeader(total=o[0], hash=o[1])


def block_id_obj(b: BlockID):
    return [b.hash, psh_obj(b.parts_header)]


def block_id_from(o) -> BlockID:
    return BlockID(hash=o[0], parts_header=psh_from(o[1]))


def vote_obj(v: Optional[Vote]):
    if v is None:
        return None
    return [
        v.validator_address,
        v.validator_index,
        v.height,
        v.round,
        v.timestamp,
        v.type,
        block_id_obj(v.block_id),
        v.signature,
    ]


def vote_from(o) -> Optional[Vote]:
    if o is None:
        return None
    return Vote(
        validator_address=o[0],
        validator_index=o[1],
        height=o[2],
        round=o[3],
        timestamp=o[4],
        type=o[5],
        block_id=block_id_from(o[6]),
        signature=o[7],
    )


def proposal_obj(p: Proposal):
    return [
        p.height,
        p.round,
        psh_obj(p.block_parts_header),
        p.pol_round,
        block_id_obj(p.pol_block_id),
        p.timestamp,
        p.signature,
    ]


def proposal_from(o) -> Proposal:
    return Proposal(
        height=o[0],
        round=o[1],
        block_parts_header=psh_from(o[2]),
        pol_round=o[3],
        pol_block_id=block_id_from(o[4]),
        timestamp=o[5],
        signature=o[6],
    )


def commit_obj(c):
    if c is None:
        return None
    from .block import AggregateCommit

    if isinstance(c, AggregateCommit):
        # tagged form: a plain Commit's first element is a block-id obj
        # (a list), so the string tag is unambiguous on decode
        return ["AGG", block_id_obj(c.block_id), c.agg_height, c.agg_round,
                c.signers.size(), c.signers.to_bytes(), c.agg_sig]
    return [block_id_obj(c.block_id), [vote_obj(v) for v in c.precommits]]


def commit_from(o):
    if o is None:
        return None
    if isinstance(o[0], str) and o[0] == "AGG":
        from ..libs.bit_array import BitArray
        from .block import AggregateCommit

        return AggregateCommit(
            block_id=block_id_from(o[1]), agg_height=o[2], agg_round=o[3],
            signers=BitArray.from_bytes_size(o[5], o[4]), agg_sig=o[6],
        )
    return Commit(block_id=block_id_from(o[0]), precommits=[vote_from(v) for v in o[1]])


def header_obj(h: Header):
    return [
        h.chain_id,
        h.height,
        h.time,
        h.num_txs,
        h.total_txs,
        block_id_obj(h.last_block_id),
        h.last_commit_hash,
        h.data_hash,
        h.validators_hash,
        h.next_validators_hash,
        h.consensus_hash,
        h.app_hash,
        h.last_results_hash,
        h.evidence_hash,
        h.proposer_address,
    ]


def header_from(o) -> Header:
    return Header(
        chain_id=o[0],
        height=o[1],
        time=o[2],
        num_txs=o[3],
        total_txs=o[4],
        last_block_id=block_id_from(o[5]),
        last_commit_hash=o[6],
        data_hash=o[7],
        validators_hash=o[8],
        next_validators_hash=o[9],
        consensus_hash=o[10],
        app_hash=o[11],
        last_results_hash=o[12],
        evidence_hash=o[13],
        proposer_address=o[14],
    )


def evidence_obj(e):
    from .evidence import evidence_to_obj

    return evidence_to_obj(e)


def block_obj(b: Block):
    return [
        header_obj(b.header),
        [bytes(t) for t in b.data.txs],
        [evidence_obj(e) for e in b.evidence.evidence],
        commit_obj(b.last_commit),
    ]


def block_from(o) -> Block:
    from .evidence import evidence_from_obj

    return Block(
        header=header_from(o[0]),
        data=Data(txs=list(o[1])),
        evidence=EvidenceData(evidence=[evidence_from_obj(e) for e in o[2]]),
        last_commit=commit_from(o[3]),
    )


def encode_block(b: Block) -> bytes:
    return pack(block_obj(b))


def decode_block(data: bytes) -> Block:
    return block_from(unpack(data))


def encode_vote(v: Vote) -> bytes:
    return pack(vote_obj(v))


def decode_vote(data: bytes) -> Vote:
    return vote_from(unpack(data))


def encode_commit(c: Commit) -> bytes:
    return pack(commit_obj(c))


def decode_commit(data: bytes) -> Commit:
    return commit_from(unpack(data))


def validator_obj(v: Validator):
    # element 4 (proof of possession) is optional on the wire: older
    # peers / previously persisted valsets serialized 4-element lists
    return [v.address, pubkey_to_bytes(v.pub_key), v.voting_power,
            v.proposer_priority, v.pop]


def validator_from(o) -> Validator:
    return Validator(
        address=o[0],
        pub_key=pubkey_from_bytes(o[1]),
        voting_power=o[2],
        proposer_priority=o[3],
        pop=bytes(o[4]) if len(o) > 4 and o[4] else b"",
    )


def valset_obj(vs: ValidatorSet):
    prop = vs.proposer.address if vs.proposer else b""
    return [[validator_obj(v) for v in vs.validators], prop]


def valset_from(o) -> ValidatorSet:
    vs = ValidatorSet.__new__(ValidatorSet)
    vs.validators = [validator_from(v) for v in o[0]]
    # __new__ skips __init__'s sort/rotation on purpose (persisted sets
    # carry their exact order + priorities) but its duplicate-address
    # check must still hold: statesync feeds wire bytes through here,
    # and a repeated entry would double-count that validator's power in
    # every tally downstream (lite aggregate trusting path included)
    addrs = [v.address for v in vs.validators]
    if len(set(addrs)) != len(addrs):
        raise ValueError("duplicate validator address")
    vs._total = None
    vs.proposer = None
    for v in vs.validators:
        if v.address == o[1]:
            vs.proposer = v
    return vs


def proof_obj(p: merkle.SimpleProof):
    return [p.total, p.index, p.leaf_hash, list(p.aunts)]


def proof_from(o) -> merkle.SimpleProof:
    return merkle.SimpleProof(total=o[0], index=o[1], leaf_hash=o[2], aunts=list(o[3]))


def part_obj(p: Part):
    return [p.index, p.bytes, proof_obj(p.proof)]


def part_from(o) -> Part:
    return Part(index=o[0], bytes=o[1], proof=proof_from(o[2]))
