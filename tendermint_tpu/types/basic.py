"""BlockID, PartSetHeader, vote types, canonical sign-bytes.

Reference parity: types/block.go (BlockID :480), types/part_set.go
(PartSetHeader), types/vote.go (Vote :51-60, SignBytes :62-68),
types/canonical.go (CanonicalVote/CanonicalProposal :35-73). Timestamps
are integer unix nanoseconds everywhere (deterministic; the reference's
RFC3339Nano canonical-time rule collapses to the same total order).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field as dc_field
from typing import Optional

from .. import codec
from ..crypto import tmhash

# vote types (reference types/vote.go VoteTypePrevote/Precommit)
VOTE_TYPE_PREVOTE = 1
VOTE_TYPE_PRECOMMIT = 2

MAX_VOTE_BYTES = 256  # conservative analogue of types/vote.go:15 (223)


def now_ns() -> int:
    return _time.time_ns()


class ErrVoteConflictingVotes(Exception):
    def __init__(self, vote_a: "Vote", vote_b: "Vote"):
        super().__init__("conflicting votes from validator")
        self.vote_a = vote_a
        self.vote_b = vote_b


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def encode(self) -> bytes:
        return codec.t_uvarint(1, self.total) + codec.t_bytes(2, self.hash)

    def __str__(self):
        return f"{self.total}:{self.hash.hex()[:12]}"


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    parts_header: PartSetHeader = dc_field(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        return not self.hash and self.parts_header.is_zero()

    def encode(self) -> bytes:
        return codec.t_bytes(1, self.hash) + codec.t_message(
            2, self.parts_header.encode()
        )

    def key(self) -> bytes:
        # length-prefixed: without separation, (hash, psh.hash) pairs that
        # concatenate identically would collide into one vote-tally bucket
        return (
            codec.uvarint(len(self.hash))
            + self.hash
            + codec.uvarint(len(self.parts_header.hash))
            + self.parts_header.hash
            + codec.uvarint(self.parts_header.total)
        )

    def __str__(self):
        return f"{self.hash.hex()[:12]}:{self.parts_header}"


ZERO_BLOCK_ID = BlockID()


def canonical_vote_sign_bytes(
    chain_id: str,
    vote_type: int,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """Deterministic sign-bytes (replaces amino CanonicalVote,
    types/canonical.go:35-42). Height/round are fixed64 like the
    reference's binary:fixed64 annotations."""
    return (
        codec.t_uvarint(1, vote_type)
        + codec.t_fixed64(2, height)
        + codec.t_fixed64(3, round_)
        + codec.t_message(4, block_id.encode())
        + codec.t_fixed64(5, timestamp_ns)
        + codec.t_string(6, chain_id)
    )


def canonical_proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    parts_header: PartSetHeader,
    pol_round: int,
    pol_block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """Sign-bytes for proposals (types/canonical.go CanonicalProposal)."""
    return (
        codec.t_uvarint(1, 32)  # message kind discriminator: proposal
        + codec.t_fixed64(2, height)
        + codec.t_fixed64(3, round_)
        + codec.t_message(4, parts_header.encode())
        + codec.t_fixed64(5, pol_round + 1)  # -1 (no POL) encodes as 0
        + codec.t_message(6, pol_block_id.encode())
        + codec.t_fixed64(7, timestamp_ns)
        + codec.t_string(8, chain_id)
    )


@dataclass
class Vote:
    """A signed prevote or precommit (reference types/vote.go:51-60)."""

    validator_address: bytes
    validator_index: int
    height: int
    round: int
    timestamp: int  # unix ns
    type: int
    block_id: BlockID
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_vote_sign_bytes(
            chain_id, self.type, self.height, self.round, self.block_id, self.timestamp
        )

    def verify(self, chain_id: str, pub_key) -> bool:
        """Single-vote verify (reference types/vote.go:102-111). The bulk
        path goes through ValidatorSet.verify_commit / VoteSet batching."""
        if pub_key.address() != self.validator_address:
            return False
        return pub_key.verify_bytes(self.sign_bytes(chain_id), self.signature)

    def is_prevote(self) -> bool:
        return self.type == VOTE_TYPE_PREVOTE

    def copy(self) -> "Vote":
        return Vote(
            self.validator_address,
            self.validator_index,
            self.height,
            self.round,
            self.timestamp,
            self.type,
            self.block_id,
            self.signature,
        )

    def encode(self) -> bytes:
        return (
            codec.t_bytes(1, self.validator_address)
            + codec.t_uvarint(2, self.validator_index + 1)
            + codec.t_fixed64(3, self.height)
            + codec.t_fixed64(4, self.round)
            + codec.t_fixed64(5, self.timestamp)
            + codec.t_uvarint(6, self.type)
            + codec.t_message(7, self.block_id.encode())
            + codec.t_bytes(8, self.signature)
        )

    def hash(self) -> bytes:
        return tmhash.sum(self.encode())

    def __str__(self):
        t = "prevote" if self.type == VOTE_TYPE_PREVOTE else "precommit"
        return (
            f"Vote{{{self.validator_index}:{self.validator_address.hex()[:8]} "
            f"{self.height}/{self.round} {t} {self.block_id}}}"
        )


@dataclass
class Proposal:
    """Block proposal (reference types/proposal.go)."""

    height: int
    round: int
    block_parts_header: PartSetHeader
    pol_round: int  # -1 when no proof-of-lock
    pol_block_id: BlockID
    timestamp: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_proposal_sign_bytes(
            chain_id,
            self.height,
            self.round,
            self.block_parts_header,
            self.pol_round,
            self.pol_block_id,
            self.timestamp,
        )

    def __str__(self):
        return f"Proposal{{{self.height}/{self.round} {self.block_parts_header} pol={self.pol_round}}}"
