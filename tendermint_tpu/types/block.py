"""Block, Header, Data, Commit (reference types/block.go).

Hashes: header hash is a merkle tree over the encoded fields (reference
Header.Hash :403-426 uses a simple map hasher; we use an ordered field
list — deterministic and proof-friendly); data/evidence/commit hashes are
merkle roots over item encodings.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import List, Optional

from .. import codec
from ..crypto import merkle, tmhash
from .basic import VOTE_TYPE_PRECOMMIT, BlockID, PartSetHeader, Vote

MAX_BLOCK_SIZE_BYTES = 104857600  # reference types/params.go MaxBlockSizeBytes


@dataclass
class Header:
    chain_id: str = ""
    height: int = 0
    time: int = 0  # unix ns
    num_txs: int = 0
    total_txs: int = 0
    last_block_id: BlockID = dc_field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    def hash(self) -> Optional[bytes]:
        """Merkle root over encoded fields; None until validators_hash is
        populated (reference Header.Hash returns nil likewise)."""
        if not self.validators_hash:
            return None
        fields = [
            codec.t_string(1, self.chain_id),
            codec.t_fixed64(1, self.height),
            codec.t_fixed64(1, self.time),
            codec.t_fixed64(1, self.num_txs),
            codec.t_fixed64(1, self.total_txs),
            self.last_block_id.encode(),
            self.last_commit_hash,
            self.data_hash,
            self.validators_hash,
            self.next_validators_hash,
            self.consensus_hash,
            self.app_hash,
            self.last_results_hash,
            self.evidence_hash,
            self.proposer_address,
        ]
        return merkle.hash_from_byte_slices(fields)

    def __str__(self):
        return f"Header{{{self.chain_id}/{self.height} t:{self.time}}}"


@dataclass
class Data:
    txs: List[bytes] = dc_field(default_factory=list)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices(self.txs)


def tx_hash(tx: bytes) -> bytes:
    return tmhash.sum(tx)


@dataclass
class Commit:
    """+2/3 precommits for a block (reference types/block.go:480-490).
    precommits[i] corresponds to validator i of the set; None = absent."""

    block_id: BlockID
    precommits: List[Optional[Vote]]

    def height(self) -> int:
        for v in self.precommits:
            if v is not None:
                return v.height
        return 0

    def round(self) -> int:
        for v in self.precommits:
            if v is not None:
                return v.round
        return 0

    def size(self) -> int:
        return len(self.precommits)

    def is_commit(self) -> bool:
        return len(self.precommits) > 0

    def bit_array(self):
        from ..libs.bit_array import BitArray

        return BitArray.from_bools([v is not None for v in self.precommits])

    def validate_basic(self) -> None:
        if self.block_id.is_zero():
            raise ValueError("commit has zero block id")
        if not self.precommits:
            raise ValueError("commit has no precommits")
        h, r = self.height(), self.round()
        for v in self.precommits:
            if v is None:
                continue
            if v.type != VOTE_TYPE_PRECOMMIT:
                raise ValueError("commit contains non-precommit vote")
            if v.height != h or v.round != r:
                raise ValueError("commit contains vote from wrong height/round")

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices(
            [v.encode() if v is not None else b"" for v in self.precommits]
        )

    def __str__(self):
        n = sum(1 for v in self.precommits if v is not None)
        return f"Commit{{{self.height()}/{self.round()} {n}/{len(self.precommits)} {self.block_id}}}"


@dataclass
class AggregateCommit:
    """O(1) commit certificate for BLS12-381-keyed validator sets: the
    signer bitmap plus ONE 96-byte aggregate signature (no reference
    equivalent; the aggregate-signature fast lane's wire/store form).

    Every signer's precommit for (height, round, block_id) covers
    identical sign-bytes — BLS-lane votes carry timestamp 0 (see
    MIGRATION.md) — so the certificate verifies with one
    fast_aggregate_verify over the bitmap-selected pubkeys, replacing
    N per-vote signature checks AND N×64 wire bytes with
    ceil(N/8) + 96. Duck-types the Commit query surface (height/round/
    size/bit_array/validate_basic/hash) used by stores, gossip, and
    verification; it deliberately has NO .precommits — every consumer
    branches explicitly so the plain per-vote path stays byte-for-byte
    untouched."""

    block_id: BlockID
    agg_height: int
    agg_round: int
    signers: "object"  # libs.bit_array.BitArray
    agg_sig: bytes  # 96-byte compressed G2 aggregate

    def height(self) -> int:
        return self.agg_height

    def round(self) -> int:
        return self.agg_round

    def size(self) -> int:
        return self.signers.size()

    def is_commit(self) -> bool:
        return self.signers.num_true() > 0

    def bit_array(self):
        return self.signers.copy()

    def num_signers(self) -> int:
        return self.signers.num_true()

    def num_absent(self) -> int:
        return self.signers.size() - self.signers.num_true()

    def sign_bytes(self, chain_id: str) -> bytes:
        """The single message every signer covered (precommit canonical
        sign-bytes with timestamp 0)."""
        from .basic import canonical_vote_sign_bytes

        return canonical_vote_sign_bytes(
            chain_id, VOTE_TYPE_PRECOMMIT, self.agg_height, self.agg_round,
            self.block_id, 0,
        )

    def validate_basic(self) -> None:
        if self.block_id.is_zero():
            raise ValueError("aggregate commit has zero block id")
        if self.signers.size() == 0 or self.signers.num_true() == 0:
            raise ValueError("aggregate commit has no signers")
        if len(self.agg_sig) != 96:
            raise ValueError("aggregate commit signature must be 96 bytes")
        if self.agg_height <= 0:
            raise ValueError("aggregate commit height must be positive")
        if self.agg_round < 0:
            raise ValueError("aggregate commit round must be non-negative")

    def encode(self) -> bytes:
        return (
            codec.t_message(1, self.block_id.encode())
            + codec.t_fixed64(2, self.agg_height)
            + codec.t_fixed64(3, self.agg_round)
            + codec.t_uvarint(4, self.signers.size())
            + codec.t_bytes(5, self.signers.to_bytes())
            + codec.t_bytes(6, self.agg_sig)
        )

    def size_bytes(self) -> int:
        """Certificate wire size — the constant-vs-64×N story the
        agg_commit_size_bytes gauge reports."""
        return len(self.encode())

    def hash(self) -> bytes:
        return tmhash.sum(self.encode())

    def __str__(self):
        return (
            f"AggregateCommit{{{self.agg_height}/{self.agg_round} "
            f"{self.num_signers()}/{self.size()} {self.block_id}}}"
        )


@dataclass
class EvidenceData:
    evidence: list = dc_field(default_factory=list)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices([e.encode() for e in self.evidence])


@dataclass
class Block:
    header: Header
    data: Data
    evidence: EvidenceData
    last_commit: Optional[Commit]

    @classmethod
    def make(
        cls,
        height: int,
        txs: List[bytes],
        last_commit: Optional[Commit],
        evidence: list,
    ) -> "Block":
        """Reference types/block.go MakeBlock — header is only partially
        filled; fill_header + the proposer complete it."""
        block = cls(
            header=Header(height=height, num_txs=len(txs)),
            data=Data(txs=list(txs)),
            evidence=EvidenceData(evidence=list(evidence)),
            last_commit=last_commit,
        )
        block.fill_header()
        return block

    def fill_header(self) -> None:
        h = self.header
        if not h.last_commit_hash and self.last_commit is not None:
            h.last_commit_hash = self.last_commit.hash()
        if not h.data_hash:
            h.data_hash = self.data.hash()
        if not h.evidence_hash:
            h.evidence_hash = self.evidence.hash()

    def hash(self) -> Optional[bytes]:
        if self.header is None or self.last_commit is None and self.header.height != 1:
            return None
        self.fill_header()
        return self.header.hash()

    def validate_basic(self) -> None:
        if self.header.height < 1:
            raise ValueError(f"invalid block height {self.header.height}")
        if self.header.height > 1:
            if self.last_commit is None:
                raise ValueError("nil last_commit for height > 1")
            self.last_commit.validate_basic()
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError("last_commit_hash mismatch")
        if self.header.num_txs != len(self.data.txs):
            raise ValueError("num_txs mismatch")
        if self.header.data_hash != self.data.hash():
            raise ValueError("data_hash mismatch")
        if self.header.evidence_hash != self.evidence.hash():
            raise ValueError("evidence_hash mismatch")

    def encode(self) -> bytes:
        """Deterministic encoding for PartSet chunking / storage."""
        from . import serde

        return serde.encode_block(self)

    def __str__(self):
        return f"Block{{{self.header} txs:{len(self.data.txs)}}}"


@dataclass
class BlockMeta:
    """Header + BlockID summary stored per height (reference
    types/block_meta.go)."""

    block_id: BlockID
    header: Header

    @classmethod
    def from_block(cls, block: Block, part_set) -> "BlockMeta":
        return cls(
            block_id=BlockID(block.hash(), part_set.header()),
            header=block.header,
        )


def make_part_set(block: Block, part_size: int = 65536):
    from .part_set import PartSet

    return PartSet.from_data(block.encode(), part_size)
