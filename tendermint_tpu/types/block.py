"""Block, Header, Data, Commit (reference types/block.go).

Hashes: header hash is a merkle tree over the encoded fields (reference
Header.Hash :403-426 uses a simple map hasher; we use an ordered field
list — deterministic and proof-friendly); data/evidence/commit hashes are
merkle roots over item encodings.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import List, Optional

from .. import codec
from ..crypto import merkle, tmhash
from .basic import VOTE_TYPE_PRECOMMIT, BlockID, PartSetHeader, Vote

MAX_BLOCK_SIZE_BYTES = 104857600  # reference types/params.go MaxBlockSizeBytes


@dataclass
class Header:
    chain_id: str = ""
    height: int = 0
    time: int = 0  # unix ns
    num_txs: int = 0
    total_txs: int = 0
    last_block_id: BlockID = dc_field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    def hash(self) -> Optional[bytes]:
        """Merkle root over encoded fields; None until validators_hash is
        populated (reference Header.Hash returns nil likewise)."""
        if not self.validators_hash:
            return None
        fields = [
            codec.t_string(1, self.chain_id),
            codec.t_fixed64(1, self.height),
            codec.t_fixed64(1, self.time),
            codec.t_fixed64(1, self.num_txs),
            codec.t_fixed64(1, self.total_txs),
            self.last_block_id.encode(),
            self.last_commit_hash,
            self.data_hash,
            self.validators_hash,
            self.next_validators_hash,
            self.consensus_hash,
            self.app_hash,
            self.last_results_hash,
            self.evidence_hash,
            self.proposer_address,
        ]
        return merkle.hash_from_byte_slices(fields)

    def __str__(self):
        return f"Header{{{self.chain_id}/{self.height} t:{self.time}}}"


@dataclass
class Data:
    txs: List[bytes] = dc_field(default_factory=list)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices(self.txs)


def tx_hash(tx: bytes) -> bytes:
    return tmhash.sum(tx)


@dataclass
class Commit:
    """+2/3 precommits for a block (reference types/block.go:480-490).
    precommits[i] corresponds to validator i of the set; None = absent."""

    block_id: BlockID
    precommits: List[Optional[Vote]]

    def height(self) -> int:
        for v in self.precommits:
            if v is not None:
                return v.height
        return 0

    def round(self) -> int:
        for v in self.precommits:
            if v is not None:
                return v.round
        return 0

    def size(self) -> int:
        return len(self.precommits)

    def is_commit(self) -> bool:
        return len(self.precommits) > 0

    def bit_array(self):
        from ..libs.bit_array import BitArray

        return BitArray.from_bools([v is not None for v in self.precommits])

    def validate_basic(self) -> None:
        if self.block_id.is_zero():
            raise ValueError("commit has zero block id")
        if not self.precommits:
            raise ValueError("commit has no precommits")
        h, r = self.height(), self.round()
        for v in self.precommits:
            if v is None:
                continue
            if v.type != VOTE_TYPE_PRECOMMIT:
                raise ValueError("commit contains non-precommit vote")
            if v.height != h or v.round != r:
                raise ValueError("commit contains vote from wrong height/round")

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices(
            [v.encode() if v is not None else b"" for v in self.precommits]
        )

    def __str__(self):
        n = sum(1 for v in self.precommits if v is not None)
        return f"Commit{{{self.height()}/{self.round()} {n}/{len(self.precommits)} {self.block_id}}}"


@dataclass
class EvidenceData:
    evidence: list = dc_field(default_factory=list)

    def hash(self) -> bytes:
        return merkle.hash_from_byte_slices([e.encode() for e in self.evidence])


@dataclass
class Block:
    header: Header
    data: Data
    evidence: EvidenceData
    last_commit: Optional[Commit]

    @classmethod
    def make(
        cls,
        height: int,
        txs: List[bytes],
        last_commit: Optional[Commit],
        evidence: list,
    ) -> "Block":
        """Reference types/block.go MakeBlock — header is only partially
        filled; fill_header + the proposer complete it."""
        block = cls(
            header=Header(height=height, num_txs=len(txs)),
            data=Data(txs=list(txs)),
            evidence=EvidenceData(evidence=list(evidence)),
            last_commit=last_commit,
        )
        block.fill_header()
        return block

    def fill_header(self) -> None:
        h = self.header
        if not h.last_commit_hash and self.last_commit is not None:
            h.last_commit_hash = self.last_commit.hash()
        if not h.data_hash:
            h.data_hash = self.data.hash()
        if not h.evidence_hash:
            h.evidence_hash = self.evidence.hash()

    def hash(self) -> Optional[bytes]:
        if self.header is None or self.last_commit is None and self.header.height != 1:
            return None
        self.fill_header()
        return self.header.hash()

    def validate_basic(self) -> None:
        if self.header.height < 1:
            raise ValueError(f"invalid block height {self.header.height}")
        if self.header.height > 1:
            if self.last_commit is None:
                raise ValueError("nil last_commit for height > 1")
            self.last_commit.validate_basic()
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError("last_commit_hash mismatch")
        if self.header.num_txs != len(self.data.txs):
            raise ValueError("num_txs mismatch")
        if self.header.data_hash != self.data.hash():
            raise ValueError("data_hash mismatch")
        if self.header.evidence_hash != self.evidence.hash():
            raise ValueError("evidence_hash mismatch")

    def encode(self) -> bytes:
        """Deterministic encoding for PartSet chunking / storage."""
        from . import serde

        return serde.encode_block(self)

    def __str__(self):
        return f"Block{{{self.header} txs:{len(self.data.txs)}}}"


@dataclass
class BlockMeta:
    """Header + BlockID summary stored per height (reference
    types/block_meta.go)."""

    block_id: BlockID
    header: Header

    @classmethod
    def from_block(cls, block: Block, part_set) -> "BlockMeta":
        return cls(
            block_id=BlockID(block.hash(), part_set.header()),
            header=block.header,
        )


def make_part_set(block: Block, part_size: int = 65536):
    from .part_set import PartSet

    return PartSet.from_data(block.encode(), part_size)
