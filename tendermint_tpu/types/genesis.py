"""Genesis document + consensus params (reference types/genesis.go,
types/params.go)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field as dc_field
from typing import List, Optional

from ..crypto import PubKey, pubkey_from_bytes, pubkey_to_bytes, tmhash
from .basic import now_ns
from .validator_set import Validator

MAX_CHAIN_ID_LEN = 50


@dataclass
class BlockSizeParams:
    max_bytes: int = 22020096  # 21MB (reference types/params.go:18)
    max_gas: int = -1


@dataclass
class EvidenceParams:
    max_age: int = 100000


@dataclass
class ConsensusParams:
    block_size: BlockSizeParams = dc_field(default_factory=BlockSizeParams)
    evidence: EvidenceParams = dc_field(default_factory=EvidenceParams)

    def validate(self) -> None:
        if self.block_size.max_bytes <= 0 or self.block_size.max_bytes > 104857600:
            raise ValueError(f"invalid max_bytes {self.block_size.max_bytes}")
        if self.evidence.max_age <= 0:
            raise ValueError("evidence max_age must be positive")

    def hash(self) -> bytes:
        return tmhash.sum(
            json.dumps(
                {
                    "block_size": [self.block_size.max_bytes, self.block_size.max_gas],
                    "evidence": [self.evidence.max_age],
                },
                sort_keys=True,
            ).encode()
        )

    def update(self, abci_params) -> "ConsensusParams":
        """Apply ABCI EndBlock param updates (None fields keep current)."""
        res = ConsensusParams(
            BlockSizeParams(self.block_size.max_bytes, self.block_size.max_gas),
            EvidenceParams(self.evidence.max_age),
        )
        if abci_params is None:
            return res
        if abci_params.block_size is not None:
            res.block_size.max_bytes = abci_params.block_size.max_bytes
            res.block_size.max_gas = abci_params.block_size.max_gas
        if abci_params.evidence is not None:
            res.evidence.max_age = abci_params.evidence.max_age
        return res


@dataclass
class GenesisValidator:
    pub_key: PubKey
    power: int
    name: str = ""
    # BLS12-381 keys must prove possession (rogue-key defense for the
    # aggregate fast lane): 96-byte PoP signature over the pubkey bytes,
    # verified + registered by validate_and_complete. Empty for Ed25519.
    pop: bytes = b""


def genesis_validator_for(priv_key, power: int, name: str = "") -> "GenesisValidator":
    """Build a GenesisValidator from a private key, attaching the proof
    of possession BLS keys require (no-op for other key types)."""
    gv = GenesisValidator(priv_key.pub_key(), power, name)
    from ..crypto import bls

    if isinstance(priv_key, bls.PrivKeyBLS12381):
        gv.pop = bls.pop_prove(priv_key)
    return gv


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time: int = dc_field(default_factory=now_ns)
    consensus_params: ConsensusParams = dc_field(default_factory=ConsensusParams)
    validators: List[GenesisValidator] = dc_field(default_factory=list)
    app_hash: bytes = b""
    app_state: Optional[dict] = None

    def validate_and_complete(self) -> None:
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError(f"chain_id length must be <= {MAX_CHAIN_ID_LEN}")
        self.consensus_params.validate()
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise ValueError(f"genesis validator {i} has zero voting power")
        self._validate_key_types()

    def _validate_key_types(self) -> None:
        """The aggregate fast lane is all-or-nothing per chain: a valset
        mixing BLS and non-BLS keys cannot form one certificate, so
        mixed genesis docs are rejected outright (MIGRATION.md). BLS
        validators must additionally carry a verifying proof of
        possession, which is registered process-wide here."""
        if not self.validators:
            return
        from ..crypto import bls

        kinds = {isinstance(v.pub_key, bls.PubKeyBLS12381)
                 for v in self.validators}
        if kinds == {True, False}:
            raise ValueError(
                "genesis validator set mixes bls12381 and non-BLS key "
                "types; the aggregate-signature lane is per-chain — use "
                "one key type for every validator (see MIGRATION.md "
                "[crypto] key_type)")
        if kinds == {True}:
            for i, v in enumerate(self.validators):
                if not v.pop:
                    raise ValueError(
                        f"genesis validator {i} has a bls12381 key but no "
                        "proof of possession (pop); aggregate verification "
                        "would be rogue-key-attackable without it")
                if not bls.register_proof_of_possession(v.pub_key.bytes(),
                                                        v.pop):
                    raise ValueError(
                        f"genesis validator {i} proof of possession does "
                        "not verify")

    def validator_set_validators(self) -> List[Validator]:
        # the PoP rides along so valsets served to lite clients /
        # statesync peers carry their possession proofs (the lite
        # aggregate path requires them for keys outside its trusted set)
        return [Validator.new(v.pub_key, v.power, pop=v.pop)
                for v in self.validators]

    def to_json(self) -> str:
        return json.dumps(
            {
                "chain_id": self.chain_id,
                "genesis_time": self.genesis_time,
                "consensus_params": {
                    "block_size": {
                        "max_bytes": self.consensus_params.block_size.max_bytes,
                        "max_gas": self.consensus_params.block_size.max_gas,
                    },
                    "evidence": {"max_age": self.consensus_params.evidence.max_age},
                },
                "validators": [
                    {
                        "pub_key": pubkey_to_bytes(v.pub_key).hex(),
                        "power": v.power,
                        "name": v.name,
                        **({"pop": v.pop.hex()} if v.pop else {}),
                    }
                    for v in self.validators
                ],
                "app_hash": self.app_hash.hex(),
                "app_state": self.app_state,
            },
            indent=2,
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, data: str) -> "GenesisDoc":
        o = json.loads(data)
        doc = cls(
            chain_id=o["chain_id"],
            genesis_time=o.get("genesis_time", 0),
            consensus_params=ConsensusParams(
                BlockSizeParams(
                    o["consensus_params"]["block_size"]["max_bytes"],
                    o["consensus_params"]["block_size"]["max_gas"],
                ),
                EvidenceParams(o["consensus_params"]["evidence"]["max_age"]),
            ),
            validators=[
                GenesisValidator(
                    pub_key=pubkey_from_bytes(bytes.fromhex(v["pub_key"])),
                    power=v["power"],
                    name=v.get("name", ""),
                    pop=bytes.fromhex(v.get("pop", "")),
                )
                for v in o.get("validators", [])
            ],
            app_hash=bytes.fromhex(o.get("app_hash", "")),
            app_state=o.get("app_state"),
        )
        doc.validate_and_complete()
        return doc

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())
