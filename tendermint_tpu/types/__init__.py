"""Core data model: blocks, votes, validators, commits, evidence, genesis."""

from .basic import (  # noqa: F401
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    BlockID,
    ErrVoteConflictingVotes,
    PartSetHeader,
    Proposal,
    Vote,
    ZERO_BLOCK_ID,
    canonical_proposal_sign_bytes,
    canonical_vote_sign_bytes,
    now_ns,
)
from .block import Block, Commit, Data, EvidenceData, Header  # noqa: F401
from .evidence import DuplicateVoteEvidence, ErrEvidenceInvalid  # noqa: F401
from .genesis import ConsensusParams, GenesisDoc, GenesisValidator  # noqa: F401
from .part_set import Part, PartSet  # noqa: F401
from .validator_set import (  # noqa: F401
    ErrInvalidCommit,
    ErrInvalidCommitSignatures,
    ErrNotEnoughVotingPower,
    Validator,
    ValidatorSet,
    random_validator_set,
)
from .vote_set import ErrVoteInvalid, VoteSet  # noqa: F401
