"""Validator, ValidatorSet, and the BATCHED commit verification.

Reference parity: types/validator_set.go. The crucial departure:
verify_commit (reference :330-378 — a serial per-precommit signature loop)
assembles all (sign-bytes, signature, pubkey) triples and issues ONE
BatchVerifier call, which on the jax backend is a single TPU program over
the whole commit. This is north-star call site #1.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field as dc_field
from typing import List, Optional

from .. import codec
from ..crypto import PubKey, batch, tmhash
from .basic import VOTE_TYPE_PRECOMMIT, BlockID

LOG = logging.getLogger("types.validator_set")

MAX_TOTAL_VOTING_POWER = 2**63 // 8  # overflow guard (reference :19)


class ErrInvalidCommit(Exception):
    pass


class ErrInvalidCommitSignatures(ErrInvalidCommit):
    pass


class ErrNotEnoughVotingPower(ErrInvalidCommit):
    pass


class PendingCommitVerify:
    """Handle for an in-flight begin_verify_commit. result() blocks on
    the dispatched signature batch, finishes the tally, and raises
    exactly what verify_commit would have raised. Idempotent: the
    outcome is computed once and replayed on repeat calls."""

    __slots__ = ("_finish", "_exc", "_done")

    def __init__(self, finish=None, exc=None):
        self._finish = finish
        self._exc = exc
        self._done = finish is None

    def result(self) -> None:
        if not self._done:
            self._done = True
            finish, self._finish = self._finish, None
            try:
                finish()
            except Exception as e:  # noqa: BLE001 - replayed to every caller
                self._exc = e
        if self._exc is not None:
            raise self._exc


@dataclass
class Validator:
    address: bytes
    pub_key: PubKey
    voting_power: int
    proposer_priority: int = 0
    # BLS12-381 proof of possession (96-byte signature over the pubkey
    # bytes under the POP DST; empty for Ed25519). Travels with the
    # validator on the wire so lite clients / statesync — which never
    # see the genesis doc — can prove possession of keys outside their
    # trusted set before an aggregate check (rogue-key defense).
    # Deliberately EXCLUDED from encode()/hash_bytes(): the valset hash
    # must stay identical whether or not the PoP rides along.
    pop: bytes = b""

    @classmethod
    def new(cls, pub_key: PubKey, power: int, pop: bytes = b"") -> "Validator":
        return cls(pub_key.address(), pub_key, power, 0, pop)

    def copy(self) -> "Validator":
        return Validator(self.address, self.pub_key, self.voting_power,
                         self.proposer_priority, self.pop)

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher priority wins; ties break by lower address (reference
        validator.go CompareProposerPriority)."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        return self if self.address < other.address else other

    def encode(self) -> bytes:
        from ..crypto import pubkey_to_bytes

        return (
            codec.t_bytes(1, self.address)
            + codec.t_bytes(2, pubkey_to_bytes(self.pub_key))
            + codec.t_fixed64(3, self.voting_power)
        )

    def hash_bytes(self) -> bytes:
        """Bytes contributing to ValidatorSet.hash (no priority — it
        changes every round)."""
        return self.encode()

    def __str__(self):
        return f"Val{{{self.address.hex()[:8]} pow:{self.voting_power} pri:{self.proposer_priority}}}"


class ValidatorSet:
    """Sorted-by-address validator set with proposer rotation
    (reference types/validator_set.go:33-117)."""

    def __init__(self, validators: List[Validator]):
        vals = sorted((v.copy() for v in validators), key=lambda v: v.address)
        addrs = [v.address for v in vals]
        if len(set(addrs)) != len(addrs):
            raise ValueError("duplicate validator address")
        self.validators = vals
        self._total: Optional[int] = None
        self.proposer: Optional[Validator] = None
        if vals:
            self.increment_proposer_priority(1)

    def __len__(self):
        return len(self.validators)

    def copy(self) -> "ValidatorSet":
        vs = ValidatorSet.__new__(ValidatorSet)
        vs.validators = [v.copy() for v in self.validators]
        vs._total = self._total
        vs.proposer = None
        if self.proposer is not None:
            for v in vs.validators:
                if v.address == self.proposer.address:
                    vs.proposer = v
        return vs

    def total_voting_power(self) -> int:
        if self._total is None:
            t = sum(v.voting_power for v in self.validators)
            if t > MAX_TOTAL_VOTING_POWER:
                raise ValueError("total voting power exceeds maximum")
            self._total = t
        return self._total

    def has_address(self, address: bytes) -> bool:
        return any(v.address == address for v in self.validators)

    def get_by_address(self, address: bytes):
        """-> (index, Validator) or (-1, None)."""
        for i, v in enumerate(self.validators):
            if v.address == address:
                return i, v
        return -1, None

    def get_by_index(self, index: int):
        if 0 <= index < len(self.validators):
            v = self.validators[index]
            return v.address, v
        return None, None

    def increment_proposer_priority(self, times: int) -> None:
        """Advance proposer rotation `times` rounds (reference :76-117).

        Deliberate redesign vs the reference: priorities are unbounded
        Python ints, so the int64-overflow clamps of
        types/validator_set.go:547-585 are unnecessary for safety — but
        the reference's *behavioral* bounds are kept so proposer
        selection matches across implementations: before incrementing,
        priorities are centered on their average and the spread is
        clipped to 2*total_voting_power (same window factor, same
        truncated-division semantics as Go). The per-round loop itself is
        O(times*n) exactly like the reference; `times` is the round/height
        delta, which state transitions keep small (capped here as a
        guard against pathological callers)."""
        if not self.validators:
            return
        if times > 100_000:
            raise ValueError(f"increment_proposer_priority: times {times} too large")
        total = self.total_voting_power()
        self._rescale_priorities(2 * total)
        self._shift_by_avg_priority()
        for _ in range(times):
            mx = None
            for v in self.validators:
                v.proposer_priority += v.voting_power
                mx = v if mx is None else mx.compare_proposer_priority(v)
            mx.proposer_priority -= total
            self.proposer = mx

    @staticmethod
    def _trunc_div(a: int, b: int) -> int:
        """Go's integer division truncates toward zero; Python's floors."""
        q = abs(a) // b
        return -q if a < 0 else q

    def _rescale_priorities(self, diff_max: int) -> None:
        """Clip the priority spread to diff_max (reference
        types/validator_set.go:547-585 RescalePriorities)."""
        if diff_max <= 0:
            return
        prios = [v.proposer_priority for v in self.validators]
        dist = max(prios) - min(prios)
        if dist > diff_max:
            ratio = (dist + diff_max - 1) // diff_max
            for v in self.validators:
                v.proposer_priority = self._trunc_div(v.proposer_priority, ratio)

    def _shift_by_avg_priority(self) -> None:
        """Center priorities on their average (reference
        shiftByAvgProposerPriority). The reference computes the average
        with big.Int.Div — Euclidean division, which for a positive
        divisor equals Python's floor `//` (NOT Go's truncating `/`)."""
        n = len(self.validators)
        avg = sum(v.proposer_priority for v in self.validators) // n
        for v in self.validators:
            v.proposer_priority -= avg

    def get_proposer(self) -> Validator:
        if self.proposer is None:
            self.increment_proposer_priority(1)
        return self.proposer

    def hash(self) -> bytes:
        from ..crypto import merkle

        return merkle.hash_from_byte_slices([v.hash_bytes() for v in self.validators])

    def is_bls(self) -> bool:
        """True when every validator key is BLS12-381 — the aggregate
        fast lane's opt-in switch (mixed sets are rejected at genesis).
        Cached: hot paths (gossip ticks, vote signing, VoteSet
        construction) query this per call, and at mega-committee sizes
        an O(N) isinstance scan per query is real interpreter time.
        getattr-with-default keeps instances built via __new__ (copy,
        serde) safe; update_with_changes invalidates."""
        cached = getattr(self, "_is_bls_cache", None)
        if cached is not None:
            return cached
        if not self.validators:
            return False  # not cached: an empty set may still be grown
        from ..crypto.bls import PubKeyBLS12381

        result = all(isinstance(v.pub_key, PubKeyBLS12381)
                     for v in self.validators)
        self._is_bls_cache = result
        return result

    # --- commit verification (north-star call site #1) ---------------------

    def verify_commit(self, chain_id: str, block_id: BlockID, height: int, commit) -> None:
        """Verify +2/3 precommits for block_id at height. Raises
        ErrInvalidCommit subclasses on failure.

        Reference types/validator_set.go:330-378, except the per-signature
        loop becomes one BatchVerifier call (TPU-batched). An
        AggregateCommit certificate (BLS fast lane) instead routes to
        verify_commit_aggregate: ONE pairing check regardless of
        committee size.
        """
        from .block import AggregateCommit

        if isinstance(commit, AggregateCommit):
            self.verify_commit_aggregate(chain_id, block_id, height, commit)
            return
        bv, entries = self._prepare_commit_verify(chain_id, block_id, height, commit)
        mask, psum_tally = self._run_batch_verify(bv, entries, block_id)
        self._finish_commit_verify(mask, psum_tally, entries, block_id)

    def _gate_commit_aggregate(self, chain_id: str, block_id: BlockID,
                               height: int, commit):
        """Crypto-free front of aggregate-commit verification: structural
        checks and the voting-power tally over the signer bitmap. Returns
        (pubkeys, sign_bytes) ready for the pairing check; raises
        ErrInvalidCommit subclasses on any gate failure — an
        under-powered or malformed certificate must not cost a
        pairing."""
        if commit.signers.size() != len(self.validators):
            raise ErrInvalidCommit(
                f"invalid aggregate commit: {commit.signers.size()} signer "
                f"bits for {len(self.validators)} validators")
        if height != commit.height():
            raise ErrInvalidCommit(
                f"invalid aggregate commit height {commit.height()} != {height}")
        if commit.block_id != block_id:
            raise ErrInvalidCommit(
                f"invalid aggregate commit block id {commit.block_id} != {block_id}")
        pubkeys = []
        tallied = 0
        for idx in range(len(self.validators)):
            if commit.signers.get_index(idx):
                val = self.validators[idx]
                pubkeys.append(val.pub_key.bytes())
                tallied += val.voting_power
        if 3 * tallied <= 2 * self.total_voting_power():
            raise ErrNotEnoughVotingPower(
                f"invalid aggregate commit: tallied {tallied} <= 2/3 of "
                f"{self.total_voting_power()}")
        return pubkeys, commit.sign_bytes(chain_id)

    def verify_commit_aggregate(self, chain_id: str, block_id: BlockID,
                                height: int, commit) -> None:
        """Verify an AggregateCommit: structural checks, the voting-power
        tally over the signer bitmap, then ONE fast_aggregate_verify
        (bitmap->aggregate-pubkey MSM + a 2-pairing product check)
        instead of N signature checks.

        PoP note: rogue-key safety for the aggregate check rests on
        proof-of-possession at key REGISTRATION time (genesis validation
        / the app's validator updates); a valset reaching this method is
        hash-chained from that trust root, so the per-call registry
        check is skipped (require_pop=False)."""
        from ..crypto import batch as crypto_batch
        from ..crypto import bls

        pubkeys, msg = self._gate_commit_aggregate(
            chain_id, block_id, height, commit)
        if not bls.fast_aggregate_verify(pubkeys, msg, commit.agg_sig,
                                         require_pop=False):
            raise ErrInvalidCommitSignatures(
                f"invalid aggregate signature over {len(pubkeys)} signers")
        m = crypto_batch.get_metrics()
        if m is not None:
            m.agg_commit_size_bytes.set(commit.size_bytes())

    def verify_commits_aggregate_many(self, chain_id: str, checks):
        """Batched aggregate-commit verification: checks =
        [(block_id, height, commit), ...], every certificate against
        THIS validator set. The per-certificate structural/power gates
        are exactly verify_commit_aggregate's; the k certificates that
        survive them collapse into ONE bls.verify_aggregates_many
        multi-pair product check instead of k sequential 2-pairing
        checks. Returns one Optional[Exception] per check (None =
        verified) — the replica catch-up and statesync bisection
        callers want per-height verdicts, not a first-failure raise."""
        from ..crypto import bls

        results = [None] * len(checks)
        idxs = []
        items = []
        for i, (block_id, height, commit) in enumerate(checks):
            try:
                pubkeys, msg = self._gate_commit_aggregate(
                    chain_id, block_id, height, commit)
            except ErrInvalidCommit as e:
                results[i] = e
                continue
            idxs.append(i)
            items.append((pubkeys, msg, commit.agg_sig))
        if items:
            verdicts = bls.verify_aggregates_many(items)
            for i, ok in zip(idxs, verdicts):
                if not ok:
                    results[i] = ErrInvalidCommitSignatures(
                        "invalid aggregate signature over "
                        f"{checks[i][2].signers.num_true()} signers")
        return results

    def begin_verify_commit(
        self, chain_id: str, block_id: BlockID, height: int, commit
    ) -> "PendingCommitVerify":
        """verify_commit with the signature batch dispatched ASYNC
        (BatchVerifier.verify_async): structural pre-checks run — and
        raise — here; .result() blocks on the device batch, completes
        the tally, and raises exactly what verify_commit would have.
        The fast-sync pipeline uses this to verify block k+1's commit
        on-device while block k applies on the host. When async dispatch
        is disabled the whole verification runs synchronously here and
        .result() just replays the outcome. (The multi-device psum tally
        path is sync-only; the host tally is authoritative either way.)

        AggregateCommit certificates verify synchronously (one pairing —
        there is no batch to overlap); the pending handle just replays
        the outcome."""
        from .block import AggregateCommit

        if isinstance(commit, AggregateCommit):
            try:
                self.verify_commit_aggregate(chain_id, block_id, height, commit)
            except ErrInvalidCommit as e:
                return PendingCommitVerify(exc=e)
            return PendingCommitVerify()
        bv, entries = self._prepare_commit_verify(chain_id, block_id, height, commit)
        if entries and batch.async_enabled():
            fut = bv.verify_async()
            return PendingCommitVerify(
                lambda: self._finish_commit_verify(
                    fut.result(), None, entries, block_id)
            )
        try:
            mask, psum_tally = self._run_batch_verify(bv, entries, block_id)
            self._finish_commit_verify(mask, psum_tally, entries, block_id)
        except ErrInvalidCommit as e:
            return PendingCommitVerify(exc=e)
        return PendingCommitVerify()

    def _prepare_commit_verify(self, chain_id: str, block_id: BlockID,
                               height: int, commit):
        """Structural pre-checks + batch assembly (raises ErrInvalidCommit
        on malformed commits). Returns (bv, entries) with entries =
        [(index, precommit, validator)] aligned to the batch."""
        if len(self.validators) != len(commit.precommits):
            raise ErrInvalidCommit(
                f"invalid commit: {len(commit.precommits)} precommits for {len(self.validators)} validators"
            )
        if height != commit.height():
            raise ErrInvalidCommit(f"invalid commit height {commit.height()} != {height}")
        round_ = commit.round()

        bv = batch.new_batch_verifier()
        entries = []  # (index, precommit, validator)
        for idx, precommit in enumerate(commit.precommits):
            if precommit is None:
                continue
            if precommit.height != height:
                raise ErrInvalidCommit(f"invalid commit precommit height {precommit.height}")
            if precommit.round != round_:
                raise ErrInvalidCommit(f"invalid commit precommit round {precommit.round}")
            if precommit.type != VOTE_TYPE_PRECOMMIT:
                raise ErrInvalidCommit("invalid commit vote type")
            _, val = self.get_by_index(idx)
            bv.add(precommit.sign_bytes(chain_id), precommit.signature, val.pub_key.bytes())
            entries.append((idx, precommit, val))
        return bv, entries

    def _finish_commit_verify(self, mask, psum_tally, entries,
                              block_id: BlockID) -> None:
        """Tally the verified mask and enforce the +2/3 threshold."""
        tallied = 0
        for ok, (idx, precommit, val) in zip(mask, entries):
            if not ok:
                raise ErrInvalidCommitSignatures(
                    f"invalid commit signature from validator {idx} ({val.address.hex()[:12]})"
                )
            if precommit.block_id == block_id:
                tallied += val.voting_power

        if psum_tally is not None and psum_tally != tallied:
            # the host loop above is authoritative; a differing on-device
            # psum tally can only mean a kernel defect — surface it loudly
            LOG.error(
                "sharded psum tally %d != host tally %d (using host)",
                psum_tally, tallied,
            )

        if 3 * tallied <= 2 * self.total_voting_power():
            raise ErrNotEnoughVotingPower(
                f"invalid commit: tallied {tallied} <= 2/3 of {self.total_voting_power()}"
            )

    @staticmethod
    def _run_batch_verify(bv, entries, block_id):
        """Run the accumulated signature batch. With more than one device
        visible and the jax backend active, the batch shards across the
        'dp' mesh and the 2/3 tally happens on-device via psum
        (crypto/jaxed25519/verify.sharded_commit_verify); the host tally
        in verify_commit stays authoritative. Returns (mask, psum_tally
        or None)."""
        if entries:
            try:
                # Backend and batch-size checks come FIRST: importing jax /
                # calling jax.devices() initializes the TPU backend, which
                # must never happen inside the consensus path when the host
                # OpenSSL backend is selected or the batch is tiny.
                backend = batch.default_backend_name()
                min_batch = (batch.effective_batch_min()
                             if backend == "adaptive" else 1)
                if (backend in ("jax", "adaptive")
                        and len(entries) >= min_batch
                        # the fused psum path reads the raw batch and
                        # would bypass the verified-signature cache; with
                        # a cache installed, bv.verify() below serves
                        # hits and device-dispatches only the misses
                        # (host tally is authoritative either way)
                        and batch.get_sig_cache() is None
                        and all(0 <= v.voting_power < 2**31
                                for _, _, v in entries)):
                    import jax

                    from ..crypto.jaxed25519 import verify as jv

                    if len(jax.devices()) > 1:
                        msgs, sigs, pks = zip(*bv._items)
                        powers = [v.voting_power for _, _, v in entries]
                        for_block = [int(p.block_id == block_id)
                                     for _, p, _ in entries]
                        return jv.sharded_commit_verify(
                            list(msgs), list(sigs), list(pks), powers,
                            for_block)
            except ImportError:
                pass
            except Exception as e:  # noqa: BLE001 - host path is authoritative
                # any device-side failure (compile error, OOM, topology
                # change) must not abort commit verification: the host
                # batch path below verifies identically
                LOG.warning("sharded commit verify failed, host fallback: %s", e)
        return bv.verify(), None

    # --- updates (reference :411-472 via state.updateState) ---------------

    def update_with_changes(self, changes: List[Validator]) -> None:
        """Apply validator updates (power 0 removes). Reference
        validator_set.go Update/Add/Remove semantics."""
        by_addr = {v.address: v for v in self.validators}
        for c in changes:
            if c.voting_power < 0:
                raise ValueError("negative voting power")
            if c.voting_power == 0:
                if c.address not in by_addr:
                    raise ValueError("removing unknown validator")
                del by_addr[c.address]
            else:
                prev = by_addr.get(c.address)
                nv = c.copy()
                nv.proposer_priority = prev.proposer_priority if prev else 0
                by_addr[c.address] = nv
        self.validators = sorted(by_addr.values(), key=lambda v: v.address)
        self._total = None
        self._is_bls_cache = None
        if self.proposer is not None and self.proposer.address not in by_addr:
            self.proposer = None
        self.total_voting_power()

    def __str__(self):
        prop = self.proposer.address.hex()[:8] if self.proposer else "none"
        return f"ValidatorSet{{n:{len(self.validators)} proposer:{prop}}}"


def random_validator_set(n: int, power: int = 10):
    """Test fixture (reference types/validator_set.go:531 RandValidatorSet).
    Returns (ValidatorSet, [PrivKeyEd25519] sorted to match)."""
    from ..crypto import PrivKeyEd25519

    keys = [PrivKeyEd25519.generate() for _ in range(n)]
    vals = [Validator.new(k.pub_key(), power) for k in keys]
    vs = ValidatorSet(vals)
    keys_sorted = sorted(keys, key=lambda k: k.pub_key().address())
    return vs, keys_sorted


def random_bls_validator_set(n: int, power: int = 10, seed: bytes = b"bls"):
    """BLS-keyed fixture for the aggregate fast lane: deterministic keys
    (pairing-grade keygen is ~10ms/key, so fixtures stay cheap and
    cacheable). Returns (ValidatorSet, [PrivKeyBLS12381] sorted to
    match)."""
    from ..crypto.bls import PrivKeyBLS12381

    from ..crypto import bls

    keys = [PrivKeyBLS12381.gen_from_secret(seed + b"-%d" % i)
            for i in range(n)]
    vals = [Validator.new(k.pub_key(), power, pop=bls.pop_prove(k))
            for k in keys]
    vs = ValidatorSet(vals)
    keys_sorted = sorted(keys, key=lambda k: k.pub_key().address())
    return vs, keys_sorted
