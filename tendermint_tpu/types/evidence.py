"""Byzantine-fault evidence (reference types/evidence.go).

DuplicateVoteEvidence: two distinct votes by one validator for the same
height/round/type — proof of equivocation, slashable via ABCI.
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import codec
from ..crypto import PubKey, pubkey_from_bytes, pubkey_to_bytes, tmhash
from .basic import Vote

MAX_EVIDENCE_AGE = 100000  # heights (reference state/validation.go maxEvidenceAge analogue)


class ErrEvidenceInvalid(Exception):
    pass


@dataclass
class DuplicateVoteEvidence:
    pub_key: PubKey
    vote_a: Vote
    vote_b: Vote

    def height(self) -> int:
        return self.vote_a.height

    def address(self) -> bytes:
        return self.pub_key.address()

    def index(self) -> int:
        return self.vote_a.validator_index

    def encode(self) -> bytes:
        return (
            codec.t_bytes(1, pubkey_to_bytes(self.pub_key))
            + codec.t_message(2, self.vote_a.encode())
            + codec.t_message(3, self.vote_b.encode())
        )

    def hash(self) -> bytes:
        return tmhash.sum(self.encode())

    def verify(self, chain_id: str) -> None:
        """Raises ErrEvidenceInvalid unless this is genuine equivocation
        (reference types/evidence.go DuplicateVoteEvidence.Verify)."""
        a, b = self.vote_a, self.vote_b
        if a.height != b.height or a.round != b.round or a.type != b.type:
            raise ErrEvidenceInvalid("votes from different height/round/type")
        if a.validator_address != b.validator_address:
            raise ErrEvidenceInvalid("votes from different validators")
        if a.validator_address != self.pub_key.address():
            raise ErrEvidenceInvalid("address does not match pubkey")
        if a.block_id == b.block_id:
            raise ErrEvidenceInvalid("votes are for the same block — not equivocation")
        for v in (a, b):
            if not v.verify(chain_id, self.pub_key):
                raise ErrEvidenceInvalid("invalid signature on evidence vote")

    def equal(self, other) -> bool:
        return isinstance(other, DuplicateVoteEvidence) and self.encode() == other.encode()

    def __str__(self):
        return f"DuplicateVoteEvidence{{{self.address().hex()[:8]} h:{self.height()}}}"


def evidence_to_obj(e):
    from .serde import vote_obj

    if isinstance(e, DuplicateVoteEvidence):
        return ["duplicate_vote", pubkey_to_bytes(e.pub_key), vote_obj(e.vote_a), vote_obj(e.vote_b)]
    raise TypeError(f"unknown evidence type {type(e)}")


def evidence_from_obj(o):
    from .serde import vote_from

    if o[0] == "duplicate_vote":
        return DuplicateVoteEvidence(
            pub_key=pubkey_from_bytes(o[1]), vote_a=vote_from(o[2]), vote_b=vote_from(o[3])
        )
    raise ValueError(f"unknown evidence kind {o[0]!r}")
