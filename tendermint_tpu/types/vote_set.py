"""VoteSet — 2/3-quorum vote tallying (reference types/vote_set.go).

North-star call site #2: votes arrive one-per-message on the live path
(add_vote, latency-shaped — single CPU verify), but bulk ingestion
(add_votes: reactor catch-up, WAL replay, fast-sync) verifies the whole
batch in ONE BatchVerifier call before tallying — the TPU path.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..crypto import batch
from ..libs.bit_array import BitArray
from .basic import BlockID, ErrVoteConflictingVotes, Vote
from .validator_set import ValidatorSet


class ErrVoteInvalid(Exception):
    pass


class VoteSet:
    def __init__(self, chain_id: str, height: int, round_: int, type_: int, val_set: ValidatorSet):
        if height == 0:
            raise ValueError("VoteSet height cannot be 0")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type = type_
        self.val_set = val_set
        self._lock = threading.RLock()
        n = len(val_set)
        self.votes_bit_array = BitArray(n)
        self.votes: List[Optional[Vote]] = [None] * n
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        # per-block tallies; block key -> (votes bit array, power sum)
        self._votes_by_block: Dict[bytes, "_BlockVotes"] = {}
        # peer id -> block key they claim has 2/3 (reference peerMaj23s)
        self._peer_maj23s: Dict[str, bytes] = {}

    def size(self) -> int:
        return len(self.val_set)

    # --- add ---------------------------------------------------------------

    def add_vote(self, vote: Vote, verified: bool = False) -> bool:
        """Verify + add one vote. Returns True if it was added (False =
        benign duplicate). Raises ErrVoteInvalid / ErrVoteConflictingVotes.

        verified=True means the signature was ALREADY checked against
        this VoteSet's (chain_id, valset) by a batched pre-verification —
        only internal callers that ran the BatchVerifier themselves may
        set it (the live batched vote path in consensus/state.py)."""
        with self._lock:
            self._precheck(vote)
            _, val = self.val_set.get_by_index(vote.validator_index)
            conflict = self._conflict_check(vote)
            if conflict == "dup":
                return False
            if not verified and not vote.verify(self.chain_id, val.pub_key):
                raise ErrVoteInvalid(f"invalid signature on {vote}")
            if conflict is not None:
                raise ErrVoteConflictingVotes(conflict, vote)
            self._add_verified(vote, val.voting_power)
            return True

    def add_votes(self, votes: List[Vote]) -> List[bool]:
        """Bulk-add: one batched signature verification for all votes
        (TPU path), then tally with PER-ITEM acceptance — every vote whose
        signature is valid is applied even when the batch also contains
        invalid ones, so a peer-supplied batch with one bad signature
        cannot suppress the valid votes riding with it (the kernel
        returns per-item masks; use them). After the good votes are
        applied, the first bad signature raises ErrVoteInvalid and the
        first conflict raises ErrVoteConflictingVotes (evidence)."""
        with self._lock:
            to_verify = []
            for vote in votes:
                self._precheck(vote)
                _, val = self.val_set.get_by_index(vote.validator_index)
                to_verify.append((vote, val))
            bv = batch.new_batch_verifier()
            for vote, val in to_verify:
                bv.add(vote.sign_bytes(self.chain_id), vote.signature, val.pub_key.bytes())
            mask = bv.verify()
            added = []
            first_invalid: Optional[Vote] = None
            first_conflict = None
            for ok, (vote, val) in zip(mask, to_verify):
                if not ok:
                    if first_invalid is None:
                        first_invalid = vote
                    added.append(False)
                    continue
                conflict = self._conflict_check(vote)
                if conflict == "dup":
                    added.append(False)
                    continue
                if conflict is not None:
                    if first_conflict is None:
                        first_conflict = (conflict, vote)
                    added.append(False)
                    continue
                self._add_verified(vote, val.voting_power)
                added.append(True)
            if first_conflict is not None:
                raise ErrVoteConflictingVotes(first_conflict[0], first_conflict[1])
            if first_invalid is not None:
                raise ErrVoteInvalid(f"invalid signature on {first_invalid}")
            return added

    def _precheck(self, vote: Optional[Vote]) -> None:
        if vote is None:
            raise ErrVoteInvalid("nil vote")
        if (vote.height, vote.round, vote.type) != (self.height, self.round, self.type):
            raise ErrVoteInvalid(
                f"vote {vote.height}/{vote.round}/{vote.type} does not match "
                f"VoteSet {self.height}/{self.round}/{self.type}"
            )
        idx = vote.validator_index
        if not 0 <= idx < len(self.val_set):
            raise ErrVoteInvalid(f"validator index {idx} out of range")
        addr, _ = self.val_set.get_by_index(idx)
        if addr != vote.validator_address:
            raise ErrVoteInvalid("validator address does not match index")
        if len(vote.signature) != 64:
            raise ErrVoteInvalid("malformed signature")

    def _conflict_check(self, vote: Vote):
        """Returns None (new), "dup" (same again), or the existing
        conflicting Vote."""
        existing = self.votes[vote.validator_index]
        if existing is None:
            # also check block-keyed duplicates (maj23 rollback paths)
            return None
        if existing.block_id == vote.block_id:
            return "dup"
        return existing

    def _add_verified(self, vote: Vote, power: int) -> None:
        idx = vote.validator_index
        self.votes[idx] = vote
        self.votes_bit_array.set_index(idx, True)
        self.sum += power
        key = vote.block_id.key()
        bv = self._votes_by_block.get(key)
        if bv is None:
            bv = _BlockVotes(len(self.val_set))
            self._votes_by_block[key] = bv
        bv.add(idx, power)
        if (
            self.maj23 is None
            and 3 * bv.sum > 2 * self.val_set.total_voting_power()
        ):
            self.maj23 = vote.block_id

    # --- queries -----------------------------------------------------------

    def get_by_index(self, idx: int) -> Optional[Vote]:
        with self._lock:
            return self.votes[idx] if 0 <= idx < len(self.votes) else None

    def get_by_address(self, addr: bytes) -> Optional[Vote]:
        with self._lock:
            idx, _ = self.val_set.get_by_address(addr)
            return self.votes[idx] if idx >= 0 else None

    def has_two_thirds_majority(self) -> bool:
        with self._lock:
            return self.maj23 is not None

    def two_thirds_majority(self) -> Optional[BlockID]:
        with self._lock:
            return self.maj23

    def has_two_thirds_any(self) -> bool:
        with self._lock:
            return 3 * self.sum > 2 * self.val_set.total_voting_power()

    def has_all(self) -> bool:
        with self._lock:
            return self.sum == self.val_set.total_voting_power()

    def bit_array(self) -> BitArray:
        with self._lock:
            return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> Optional[BitArray]:
        with self._lock:
            bv = self._votes_by_block.get(block_id.key())
            return bv.bit_array.copy() if bv else None

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """Record a peer's claim that block_id has 2/3 (drives vote-bitmap
        gossip; reference vote_set.go SetPeerMaj23)."""
        with self._lock:
            self._peer_maj23s.setdefault(peer_id, block_id.key())

    def make_commit(self):
        from .block import Commit

        with self._lock:
            from .basic import VOTE_TYPE_PRECOMMIT

            if self.type != VOTE_TYPE_PRECOMMIT:
                raise ValueError("cannot make commit from non-precommit VoteSet")
            if self.maj23 is None:
                raise ValueError("cannot make commit: no 2/3 majority")
            precommits = [
                v.copy() if v is not None and v.block_id == self.maj23 else None
                for v in self.votes
            ]
            return Commit(block_id=self.maj23, precommits=precommits)

    def __str__(self):
        return (
            f"VoteSet{{h:{self.height}/{self.round}/{self.type} "
            f"{self.votes_bit_array.num_true()}/{len(self.val_set)} sum:{self.sum} maj23:{self.maj23}}}"
        )


class _BlockVotes:
    __slots__ = ("bit_array", "sum")

    def __init__(self, n: int):
        self.bit_array = BitArray(n)
        self.sum = 0

    def add(self, idx: int, power: int) -> None:
        if not self.bit_array.get_index(idx):
            self.bit_array.set_index(idx, True)
            self.sum += power
