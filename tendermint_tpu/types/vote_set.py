"""VoteSet — 2/3-quorum vote tallying (reference types/vote_set.go).

North-star call site #2: votes arrive one-per-message on the live path
(add_vote, latency-shaped — single CPU verify), but bulk ingestion
(add_votes: reactor catch-up, WAL replay, fast-sync) verifies the whole
batch in ONE BatchVerifier call before tallying — the TPU path.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..crypto import batch
from ..libs.bit_array import BitArray
from .basic import BlockID, ErrVoteConflictingVotes, Vote
from .validator_set import ValidatorSet


class ErrVoteInvalid(Exception):
    pass


# Aggregate-certificate DoS bounds (Handel-lite lane): a certificate
# claiming fewer signers than this rides the per-vote path instead of
# paying a pairing; a peer whose certificates fail verification this
# many times in one VoteSet (height, round) is ignored thereafter; the
# failed-certificate memo holds this many digests, FIFO-evicted.
_AGG_MIN_CERT_SIGNERS = 2
_AGG_CERT_FAIL_BUDGET = 8
_AGG_REJECT_MEMO_MAX = 512


class VoteSet:
    def __init__(self, chain_id: str, height: int, round_: int, type_: int, val_set: ValidatorSet):
        if height == 0:
            raise ValueError("VoteSet height cannot be 0")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type = type_
        self.val_set = val_set
        self._lock = threading.RLock()
        n = len(val_set)
        self.votes_bit_array = BitArray(n)
        self.votes: List[Optional[Vote]] = [None] * n
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        # per-block tallies; block key -> (votes bit array, power sum)
        self._votes_by_block: Dict[bytes, "_BlockVotes"] = {}
        # peer id -> block key they claim has 2/3 (reference peerMaj23s)
        self._peer_maj23s: Dict[str, bytes] = {}
        # BLS aggregate lane (precommit sets over BLS valsets only):
        # block key -> running (signer bits, aggregate G2 point, power),
        # grown incrementally from individual votes and absorbed gossip
        # certificates so make_commit / gossip compose in O(1)
        from .basic import VOTE_TYPE_PRECOMMIT as _PC

        self._agg_enabled = type_ == _PC and n > 0 and val_set.is_bls()
        self._agg: Dict[bytes, "_AggState"] = {}
        # failed-certificate memo: a certificate that failed its pairing
        # check is remembered (FIFO-bounded) so a replaying peer costs a
        # sha256 per repeat instead of ~90ms of pairing — the cert-lane
        # analogue of the verified-signature cache. Unique garbage is
        # bounded separately: each peer gets _AGG_CERT_FAIL_BUDGET
        # failed verifications per (height, round), then its
        # certificates are ignored (per-vote gossip still progresses).
        self._agg_rejects: Dict[bytes, bool] = {}
        self._agg_cert_fails: Dict[str, int] = {}

    def size(self) -> int:
        return len(self.val_set)

    # --- add ---------------------------------------------------------------

    def add_vote(self, vote: Vote, verified: bool = False) -> bool:
        """Verify + add one vote. Returns True if it was added (False =
        benign duplicate). Raises ErrVoteInvalid / ErrVoteConflictingVotes.

        verified=True means the signature was ALREADY checked against
        this VoteSet's (chain_id, valset) by a batched pre-verification —
        only internal callers that ran the BatchVerifier themselves may
        set it (the live batched vote path in consensus/state.py)."""
        with self._lock:
            self._precheck(vote)
            _, val = self.val_set.get_by_index(vote.validator_index)
            conflict = self._conflict_check_locked(vote)
            if conflict == "dup":
                return False
            if not verified and not vote.verify(self.chain_id, val.pub_key):
                raise ErrVoteInvalid(f"invalid signature on {vote}")
            if conflict is not None:
                raise ErrVoteConflictingVotes(conflict, vote)
            self._add_verified_locked(vote, val.voting_power)
            return True

    def add_votes(self, votes: List[Vote]) -> List[bool]:
        """Bulk-add: one batched signature verification for all votes
        (TPU path), then tally with PER-ITEM acceptance — every vote whose
        signature is valid is applied even when the batch also contains
        invalid ones, so a peer-supplied batch with one bad signature
        cannot suppress the valid votes riding with it (the kernel
        returns per-item masks; use them). After the good votes are
        applied, the first bad signature raises ErrVoteInvalid and the
        first conflict raises ErrVoteConflictingVotes (evidence)."""
        with self._lock:
            to_verify = []
            for vote in votes:
                self._precheck(vote)
                _, val = self.val_set.get_by_index(vote.validator_index)
                to_verify.append((vote, val))
            bv = batch.new_batch_verifier()
            for vote, val in to_verify:
                bv.add(vote.sign_bytes(self.chain_id), vote.signature, val.pub_key.bytes())
            mask = bv.verify()
            added = []
            first_invalid: Optional[Vote] = None
            first_conflict = None
            for ok, (vote, val) in zip(mask, to_verify):
                if not ok:
                    if first_invalid is None:
                        first_invalid = vote
                    added.append(False)
                    continue
                conflict = self._conflict_check_locked(vote)
                if conflict == "dup":
                    added.append(False)
                    continue
                if conflict is not None:
                    if first_conflict is None:
                        first_conflict = (conflict, vote)
                    added.append(False)
                    continue
                self._add_verified_locked(vote, val.voting_power)
                added.append(True)
            if first_conflict is not None:
                raise ErrVoteConflictingVotes(first_conflict[0], first_conflict[1])
            if first_invalid is not None:
                raise ErrVoteInvalid(f"invalid signature on {first_invalid}")
            return added

    def _precheck(self, vote: Optional[Vote]) -> None:
        if vote is None:
            raise ErrVoteInvalid("nil vote")
        if (vote.height, vote.round, vote.type) != (self.height, self.round, self.type):
            raise ErrVoteInvalid(
                f"vote {vote.height}/{vote.round}/{vote.type} does not match "
                f"VoteSet {self.height}/{self.round}/{self.type}"
            )
        idx = vote.validator_index
        if not 0 <= idx < len(self.val_set):
            raise ErrVoteInvalid(f"validator index {idx} out of range")
        addr, _ = self.val_set.get_by_index(idx)
        if addr != vote.validator_address:
            raise ErrVoteInvalid("validator address does not match index")
        if len(vote.signature) not in (64, 96):  # ed25519 | bls12381
            raise ErrVoteInvalid("malformed signature")
        if self._agg_enabled and vote.timestamp != 0:
            # BLS-lane precommits MUST sign timestamp 0: aggregation
            # composes votes into one certificate whose sign-bytes
            # assume it. A vote with any other timestamp verifies
            # individually (it signs its own bytes) but would poison
            # the running aggregate — make_commit would emit a
            # certificate that fails verification chain-wide
            raise ErrVoteInvalid(
                f"BLS-lane precommit carries timestamp {vote.timestamp} "
                "!= 0 (aggregate sign-bytes invariant)")

    def _conflict_check_locked(self, vote: Vote):
        """Returns None (new), "dup" (same again), or the existing
        conflicting Vote."""
        existing = self.votes[vote.validator_index]
        if existing is None:
            # also check block-keyed duplicates (maj23 rollback paths)
            return None
        if existing.block_id == vote.block_id:
            return "dup"
        return existing

    def _add_verified_locked(self, vote: Vote, power: int) -> None:
        idx = vote.validator_index
        self.votes[idx] = vote
        # a certificate may already have claimed this bit (aggregate
        # lane); the global power sum counts each validator once
        if not self.votes_bit_array.get_index(idx):
            self.votes_bit_array.set_index(idx, True)
            self.sum += power
        key = vote.block_id.key()
        bv = self._votes_by_block.get(key)
        if bv is None:
            bv = _BlockVotes(len(self.val_set))
            self._votes_by_block[key] = bv
        bv.add(idx, power)
        if self._agg_enabled:
            self._agg_fold_vote(vote, power)
        if (
            self.maj23 is None
            and 3 * bv.sum > 2 * self.val_set.total_voting_power()
        ):
            self.maj23 = vote.block_id

    # --- BLS aggregate lane -------------------------------------------------

    def _agg_state(self, key: bytes, block_id: BlockID) -> "_AggState":
        st = self._agg.get(key)
        if st is None:
            st = _AggState(block_id)
            self._agg[key] = st
        return st

    def _agg_fold_vote(self, vote: Vote, power: int) -> None:
        """Fold one verified BLS precommit into its block's running
        aggregate (decompression is cached process-wide in crypto.bls)."""
        from ..crypto import bls
        from ..crypto.bls.curve import g2_add

        if vote.timestamp != 0:
            # enforced by _precheck; defensive — a non-zero timestamp
            # vote signs different bytes and must never fold into the
            # timestamp-0 aggregate
            return
        st = self._agg_state(vote.block_id.key(), vote.block_id)
        idx = vote.validator_index
        if idx in st.bits:
            return
        pt = bls._parse_signature_point(vote.signature)
        if pt is None:  # verified upstream; defensive
            return
        st.point = g2_add(st.point, pt)
        st.bits.add(idx)
        st.power += power

    def _agg_cert_composable(self, key: bytes, bits: set) -> bool:
        """Would merging `bits` advance the running aggregate for this
        block? (lock held by caller)"""
        st = self._agg.get(key)
        have = st.bits if st is not None else set()
        if bits <= have:
            return False  # nothing new
        if have and not (bits.isdisjoint(have) or bits >= have):
            return False  # non-composable overlap; keep what we have
        return True

    def absorb_certificate(self, cert, peer_id: str = "") -> bool:
        """Absorb a gossiped (bitmap, aggregate-signature) precommit
        certificate (Handel-lite lane). The certificate's aggregate
        signature is verified over exactly its bitmap, then merged into
        the running aggregate when composable (disjoint, or a superset
        that replaces it); newly covered validators join the power
        tallies. Returns True when the certificate advanced our
        aggregate, False otherwise (bad certificates and non-composable
        overlaps are just ignored — per-vote gossip still makes
        progress).

        DoS posture: the pairing (~hundreds of ms pure-Python) runs
        OUTSIDE the VoteSet lock so certificate verification never
        stalls vote processing; composability is re-checked after
        reacquiring. A certificate only pays a pairing when it claims
        at least _AGG_MIN_CERT_SIGNERS signers (singletons ride the
        per-vote path) and would advance our aggregate, and each peer
        gets _AGG_CERT_FAIL_BUDGET failed verifications per VoteSet
        before its certificates are dropped unexamined. Both admission
        gates apply to gossip input only: local call sites (stored
        seen-commit reconstruction, self-composed certificates) pass an
        empty peer_id and skip them — a whale chain's legitimate
        1-signer certificate must still reconstruct on restart."""
        import hashlib as _hashlib

        from ..crypto import bls
        from ..crypto.bls.curve import g2_add
        from .block import AggregateCommit

        if not self._agg_enabled or not isinstance(cert, AggregateCommit):
            return False
        with self._lock:
            n = len(self.val_set)
            if (cert.agg_height != self.height or cert.agg_round != self.round
                    or cert.signers.size() != n):
                return False
            bits = set(cert.signers.true_indices())
            if not bits:
                return False
            # DoS admission gates apply to REMOTE input only (non-empty
            # peer_id, i.e. the gossip lane). Local call sites — the
            # stored seen-commit on restart, self-composed certificates
            # — must not be bounced: a whale chain can legitimately
            # persist a 1-signer certificate, and rejecting it at
            # reconstruction would crash-loop the node.
            if peer_id:
                if len(bits) < min(_AGG_MIN_CERT_SIGNERS, n):
                    return False
                if self._agg_cert_fails.get(peer_id, 0) >= \
                        _AGG_CERT_FAIL_BUDGET:
                    return False
            if not self._agg_cert_composable(cert.block_id.key(), bits):
                return False
            reject_key = _hashlib.sha256(
                cert.block_id.key() + cert.signers.to_bytes() + cert.agg_sig
            ).digest()
            if reject_key in self._agg_rejects:
                return False
            pubkeys = [self.val_set.validators[i].pub_key.bytes()
                       for i in sorted(bits)]
            msg = cert.sign_bytes(self.chain_id)
        # pairing outside the lock — votes keep flowing while we verify
        ok = bls.fast_aggregate_verify(pubkeys, msg, cert.agg_sig,
                                       require_pop=False)
        with self._lock:
            if not ok:
                if len(self._agg_rejects) >= _AGG_REJECT_MEMO_MAX:
                    # FIFO eviction (insertion-ordered dict), not a
                    # wholesale clear a flooder could exploit to force
                    # re-verification of replayed garbage
                    self._agg_rejects.pop(next(iter(self._agg_rejects)))
                self._agg_rejects[reject_key] = True
                if peer_id:  # gossip lane only — local calls aren't peers
                    self._agg_cert_fails[peer_id] = \
                        self._agg_cert_fails.get(peer_id, 0) + 1
                return False
            # the set may have advanced while the pairing ran
            if not self._agg_cert_composable(cert.block_id.key(), bits):
                return False
            pt = bls._parse_signature_point(cert.agg_sig)
            if pt is None:
                return False
            power_of = {}
            for i in bits:
                _, val = self.val_set.get_by_index(i)
                power_of[i] = val.voting_power
            st = self._agg_state(cert.block_id.key(), cert.block_id)
            if bits >= st.bits:
                st.bits = set(bits)
                st.point = pt
                st.power = sum(power_of[i] for i in bits)
            else:  # disjoint merge
                st.point = g2_add(st.point, pt)
                st.bits |= bits
                st.power += sum(power_of[i] for i in bits)
            # tally newly covered validators (each counted once globally)
            bv = self._votes_by_block.get(cert.block_id.key())
            if bv is None:
                bv = _BlockVotes(n)
                self._votes_by_block[cert.block_id.key()] = bv
            for i in bits:
                if not self.votes_bit_array.get_index(i):
                    self.votes_bit_array.set_index(i, True)
                    self.sum += power_of[i]
                bv.add(i, power_of[i])
            if (self.maj23 is None
                    and 3 * bv.sum > 2 * self.val_set.total_voting_power()):
                self.maj23 = cert.block_id
            return True

    def aggregate_certificate(self, block_id: Optional[BlockID] = None):
        """Current best AggregateCommit for block_id (default: the maj23
        block, else the highest-power block) — what the reactor gossips.
        Returns None when the lane is off or nothing is aggregated."""
        from .block import AggregateCommit

        with self._lock:
            if not self._agg_enabled or not self._agg:
                return None
            if block_id is None:
                key = None
                if self.maj23 is not None:
                    key = self.maj23.key()
                if key is None or key not in self._agg:
                    key = max(self._agg, key=lambda k: self._agg[k].power)
                st = self._agg[key]
            else:
                st = self._agg.get(block_id.key())
                if st is None:
                    return None
            signers = BitArray(len(self.val_set))
            for i in st.bits:
                signers.set_index(i, True)
            from ..crypto.bls.curve import g2_compress

            return AggregateCommit(
                block_id=st.block_id, agg_height=self.height,
                agg_round=self.round, signers=signers,
                agg_sig=g2_compress(st.point),
            )

    # --- queries -----------------------------------------------------------

    def get_by_index(self, idx: int) -> Optional[Vote]:
        with self._lock:
            return self.votes[idx] if 0 <= idx < len(self.votes) else None

    def get_by_address(self, addr: bytes) -> Optional[Vote]:
        with self._lock:
            idx, _ = self.val_set.get_by_address(addr)
            return self.votes[idx] if idx >= 0 else None

    def has_two_thirds_majority(self) -> bool:
        with self._lock:
            return self.maj23 is not None

    def two_thirds_majority(self) -> Optional[BlockID]:
        with self._lock:
            return self.maj23

    def has_two_thirds_any(self) -> bool:
        with self._lock:
            return 3 * self.sum > 2 * self.val_set.total_voting_power()

    def has_all(self) -> bool:
        with self._lock:
            return self.sum == self.val_set.total_voting_power()

    def bit_array(self) -> BitArray:
        with self._lock:
            return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> Optional[BitArray]:
        with self._lock:
            bv = self._votes_by_block.get(block_id.key())
            return bv.bit_array.copy() if bv else None

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """Record a peer's claim that block_id has 2/3 (drives vote-bitmap
        gossip; reference vote_set.go SetPeerMaj23)."""
        with self._lock:
            self._peer_maj23s.setdefault(peer_id, block_id.key())

    def make_commit(self):
        from .block import Commit

        with self._lock:
            from .basic import VOTE_TYPE_PRECOMMIT

            if self.type != VOTE_TYPE_PRECOMMIT:
                raise ValueError("cannot make commit from non-precommit VoteSet")
            if self.maj23 is None:
                raise ValueError("cannot make commit: no 2/3 majority")
            if self._agg_enabled:
                # BLS fast lane: the running aggregate for the decided
                # block IS the commit — bitmap + one 96-byte signature.
                # Its power covers at least the tallied quorum (every
                # tallied bit was folded when counted).
                cert = self.aggregate_certificate(self.maj23)
                if cert is None or 3 * self._agg[self.maj23.key()].power <= \
                        2 * self.val_set.total_voting_power():
                    raise ValueError(
                        "cannot make aggregate commit: composed "
                        "certificate below 2/3")
                return cert
            precommits = [
                v.copy() if v is not None and v.block_id == self.maj23 else None
                for v in self.votes
            ]
            return Commit(block_id=self.maj23, precommits=precommits)

    def __str__(self):
        with self._lock:
            return (
                f"VoteSet{{h:{self.height}/{self.round}/{self.type} "
                f"{self.votes_bit_array.num_true()}/{len(self.val_set)} "
                f"sum:{self.sum} maj23:{self.maj23}}}"
            )


class _BlockVotes:
    __slots__ = ("bit_array", "sum")

    def __init__(self, n: int):
        self.bit_array = BitArray(n)
        self.sum = 0

    def add(self, idx: int, power: int) -> None:
        if not self.bit_array.get_index(idx):
            self.bit_array.set_index(idx, True)
            self.sum += power


class _AggState:
    """Running (signer bits, aggregate G2 point, power) for one block —
    the incremental composition behind make_commit and cert gossip."""

    __slots__ = ("block_id", "bits", "point", "power")

    def __init__(self, block_id: BlockID):
        self.block_id = block_id
        self.bits: set = set()
        self.point = None  # curve.G2Point (Jacobian); None = identity
        self.power = 0
