"""EventBus — typed event publishing over the pubsub core.

Reference parity: types/event_bus.go:23 (EventBus wraps libs/pubsub and
is the single place events get published), types/events.go (event string
constants + tag keys). Subscribers (RPC websocket clients, the tx
indexer) filter with the query language in libs/events.py.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..libs.events import PubSub, Query, Subscription
from ..libs.service import BaseService
from .block import tx_hash

# event values for the tm.event tag (reference types/events.go:17-36)
EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_TX = "Tx"
EVENT_VOTE = "Vote"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_NEW_ROUND = "NewRound"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_POLKA = "Polka"
EVENT_UNLOCK = "Unlock"
EVENT_RELOCK = "Relock"
EVENT_LOCK = "Lock"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_PROPOSAL_HEARTBEAT = "ProposalHeartbeat"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"

# tag keys (reference types/events.go:79-86)
EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"


def query_for_event(event: str) -> Query:
    return Query(f"{EVENT_TYPE_KEY} = '{event}'")


class EventBus(BaseService):
    """The node-wide event bus (reference types/event_bus.go:23-49)."""

    def __init__(self):
        super().__init__("EventBus")
        self._pubsub = PubSub()

    def subscribe(self, subscriber: str, query: Query, capacity: int = 1024) -> Subscription:
        return self._pubsub.subscribe(subscriber, query, capacity)

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        self._pubsub.unsubscribe(subscriber, query)

    def unsubscribe_all(self, subscriber: str) -> None:
        self._pubsub.unsubscribe_all(subscriber)

    def num_subscriptions(self) -> int:
        return self._pubsub.num_subscriptions()

    # --- publishing ---------------------------------------------------------

    def _publish(self, event: str, data: object, extra_tags: Optional[Dict[str, str]] = None) -> None:
        tags = {EVENT_TYPE_KEY: event}
        if extra_tags:
            # the event-type tag wins on collision (reference event_bus.go:72)
            merged = dict(extra_tags)
            merged.update(tags)
            tags = merged
        self._pubsub.publish(data, tags)

    def publish_new_block(self, block, result_begin_block=None, result_end_block=None) -> None:
        self._publish(EVENT_NEW_BLOCK, {
            "block": block,
            "result_begin_block": result_begin_block,
            "result_end_block": result_end_block,
        })

    def publish_new_block_header(self, header, result_begin_block=None, result_end_block=None) -> None:
        self._publish(EVENT_NEW_BLOCK_HEADER, {
            "header": header,
            "result_begin_block": result_begin_block,
            "result_end_block": result_end_block,
        })

    def _tx_event(self, height: int, index: int, tx: bytes, result):
        """One tx's (data, tags) pair — shared by the per-tx and the
        block-scoped publish paths so they cannot drift (reference
        event_bus.go PublishEventTx:78-108: app tags for this tx become
        query-able event tags; the event-type tag wins on collision).
        Runs once per committed tx — the hash import is hoisted."""
        tags: Dict[str, str] = {}
        res_tags = getattr(result, "tags", None) or []
        for kv in res_tags:
            try:
                tags[kv.key.decode()] = kv.value.decode()
            except (UnicodeDecodeError, AttributeError):
                continue
        tags[TX_HASH_KEY] = tx_hash(tx).hex().upper()
        tags[TX_HEIGHT_KEY] = str(height)
        tags[EVENT_TYPE_KEY] = EVENT_TX
        data = {
            "height": height,
            "index": index,
            "tx": tx,
            "result": result,
        }
        return data, tags

    def publish_tx(self, height: int, index: int, tx: bytes, result) -> None:
        """EventDataTx (reference event_bus.go PublishEventTx:78-108)."""
        data, tags = self._tx_event(height, index, tx, result)
        self._pubsub.publish(data, tags)

    def publish_txs(self, height: int, txs, results) -> None:
        """Block-scoped tx event publish: the whole block's tx events
        hit the pubsub core in ONE publish_batch call (one subscription
        snapshot, one buffer lock per subscription, query matching per
        distinct tag-shape). Subscriber-observed event sequences are
        identical to calling publish_tx per tx in index order."""
        self._pubsub.publish_batch(
            self._tx_event(height, i, tx, results[i])
            for i, tx in enumerate(txs)
        )

    def publish_vote(self, vote) -> None:
        self._publish(EVENT_VOTE, {"vote": vote})

    def publish_validator_set_updates(self, updates) -> None:
        self._publish(EVENT_VALIDATOR_SET_UPDATES, {"validator_updates": updates})

    # round-state events (consensus machine → reactor/RPC; reference
    # consensus/state.go eventBus usage + types/event_bus.go:110-150)
    def publish_new_round_step(self, rs) -> None:
        self._publish(EVENT_NEW_ROUND_STEP, rs)

    def publish_new_round(self, rs) -> None:
        self._publish(EVENT_NEW_ROUND, rs)

    def publish_complete_proposal(self, rs) -> None:
        self._publish(EVENT_COMPLETE_PROPOSAL, rs)

    def publish_polka(self, rs) -> None:
        self._publish(EVENT_POLKA, rs)

    def publish_unlock(self, rs) -> None:
        self._publish(EVENT_UNLOCK, rs)

    def publish_relock(self, rs) -> None:
        self._publish(EVENT_RELOCK, rs)

    def publish_lock(self, rs) -> None:
        self._publish(EVENT_LOCK, rs)

    def publish_timeout_propose(self, rs) -> None:
        self._publish(EVENT_TIMEOUT_PROPOSE, rs)

    def publish_timeout_wait(self, rs) -> None:
        self._publish(EVENT_TIMEOUT_WAIT, rs)


class NopEventBus:
    """Publish-to-nowhere bus for tests (reference types/nop_event_bus.go)."""

    def __getattr__(self, name):
        if name.startswith("publish"):
            return lambda *a, **k: None
        raise AttributeError(name)
