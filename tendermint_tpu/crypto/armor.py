"""ASCII armor for key material (reference crypto/armor/armor.go).

PEM-like blocks with headers (the reference uses OpenPGP armor via
golang.org/x/crypto/openpgp/armor; same shape: type line, k/v headers,
base64 body, end line), plus the encrypt-armor-privkey helpers that
pair armor with the symmetric secret-box (keys/mintkey.go pattern).
"""

from __future__ import annotations

import base64
import textwrap
from typing import Dict, Tuple

from .keys import PrivKey, privkey_from_bytes, privkey_to_bytes
from .symmetric import decrypt_symmetric, encrypt_symmetric, key_from_passphrase

BLOCK_TYPE_PRIVKEY = "TENDERMINT PRIVATE KEY"
BLOCK_TYPE_KEYINFO = "TENDERMINT KEY INFO"


def encode_armor(block_type: str, headers: Dict[str, str],
                 data: bytes) -> str:
    """armor.go EncodeArmor."""
    lines = [f"-----BEGIN {block_type}-----"]
    for k in sorted(headers):
        lines.append(f"{k}: {headers[k]}")
    lines.append("")
    body = base64.b64encode(data).decode()
    lines.extend(textwrap.wrap(body, 64))
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(armor_str: str) -> Tuple[str, Dict[str, str], bytes]:
    """armor.go DecodeArmor -> (block_type, headers, data)."""
    lines = [l.rstrip("\r") for l in armor_str.strip().split("\n")]
    if not lines or not lines[0].startswith("-----BEGIN "):
        raise ValueError("no armor begin line")
    block_type = lines[0][len("-----BEGIN "):].rstrip("-")
    end = f"-----END {block_type}-----"
    if lines[-1] != end:
        raise ValueError("no matching armor end line")
    headers: Dict[str, str] = {}
    i = 1
    while i < len(lines) - 1 and lines[i]:
        if ":" not in lines[i]:
            break  # body started without blank separator
        k, _, v = lines[i].partition(":")
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(lines) - 1 and not lines[i]:
        i += 1
    body = "".join(lines[i:-1])
    return block_type, headers, base64.b64decode(body)


def encrypt_armor_privkey(privkey: PrivKey, passphrase: str) -> str:
    """mintkey.go EncryptArmorPrivKey: scrypt(salt) + secret-box +
    armor with the salt/kdf in headers."""
    import os

    salt = os.urandom(16)
    key = key_from_passphrase(passphrase, salt)
    ct = encrypt_symmetric(privkey_to_bytes(privkey), key)
    return encode_armor(
        BLOCK_TYPE_PRIVKEY,
        {"kdf": "scrypt", "salt": salt.hex().upper()},
        ct,
    )


def unarmor_decrypt_privkey(armor_str: str, passphrase: str) -> PrivKey:
    """mintkey.go UnarmorDecryptPrivKey."""
    block_type, headers, data = decode_armor(armor_str)
    if block_type != BLOCK_TYPE_PRIVKEY:
        raise ValueError(f"unrecognized armor type {block_type!r}")
    if headers.get("kdf") != "scrypt":
        raise ValueError(f"unrecognized KDF {headers.get('kdf')!r}")
    salt = bytes.fromhex(headers["salt"])
    key = key_from_passphrase(passphrase, salt)
    return privkey_from_bytes(decrypt_symmetric(data, key))
