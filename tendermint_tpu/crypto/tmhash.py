"""SHA256 / truncated SHA256-20 hashing.

Capability parity with the reference's crypto/tmhash/hash.go: full 32-byte
SHA256 plus the 20-byte truncated variant used for addresses.
"""

import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum(data: bytes) -> bytes:  # noqa: A001 - mirrors reference naming
    return hashlib.sha256(data).digest()


def sum_truncated(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]
