"""Pure-Python Ed25519 (RFC 8032) — import-compatible fallback for the
`cryptography` package's Ed25519PrivateKey / Ed25519PublicKey.

Used only when OpenSSL bindings are absent from the environment
(crypto/keys.py gates the import). Orders of magnitude slower than
OpenSSL (~ms per op) but mathematically identical; bulk verification
still routes through crypto/batch.py, where the jax backend does the
heavy lifting. Not constant-time — acceptable for a fallback whose
alternative is no signatures at all; production deployments install
`cryptography`.
"""

from __future__ import annotations

import functools as _functools
import hashlib
import os

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P

# base point B (RFC 8032 §5.1)
_BY = (4 * pow(5, P - 2, P)) % P
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
_B = (_BX, _BY, 1, (_BX * _BY) % P)  # extended coords (X, Y, Z, T)
_IDENT = (0, 1, 1, 0)

_SQRT_M1 = pow(2, (P - 1) // 4, P)


class InvalidSignature(Exception):
    """Mirror of cryptography.exceptions.InvalidSignature."""


def _pt_add(p1, p2):
    # add-2008-hwcd-3 (complete for a=-1 twisted Edwards)
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = ((y1 - x1) * (y2 - x2)) % P
    b = ((y1 + x1) * (y2 + x2)) % P
    c = (2 * t1 * t2 * D) % P
    dd = (2 * z1 * z2) % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def _pt_dbl(p):
    # dbl-2008-hwcd (a=-1): 4M+4S, ~2x faster than the unified add
    x1, y1, z1, _ = p
    a = (x1 * x1) % P
    b = (y1 * y1) % P
    c = (2 * z1 * z1) % P
    e = ((x1 + y1) * (x1 + y1) - a - b) % P
    g = (b - a) % P  # D + B with D = -A
    f = (g - c) % P
    h = (-a - b) % P  # D - B
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def _pt_mul(s, pt):
    q = _IDENT
    while s > 0:
        if s & 1:
            q = _pt_add(q, pt)
        pt = _pt_dbl(pt)
        s >>= 1
    return q


# Window tables — table[i][j] = j * 16**i * P — turn a 256-bit scalar
# mult into a ≤64-add lookup sum with no doublings. Built lazily (≈1k
# adds, ~10 ms) for the base point on first sign/verify, and per
# public key under an LRU: consensus verifies hundreds of votes from
# the same handful of validator keys, so the build amortizes fast.


def _build_table(pt):
    table, base = [], pt
    for _ in range(64):
        row, acc = [_IDENT], _IDENT
        for _ in range(15):
            acc = _pt_add(acc, base)
            row.append(acc)
        table.append(row)
        base = _pt_add(acc, base)  # 16**(i+1) * pt
    return table


def _table_mul(table, s):
    q = _IDENT
    i = 0
    while s > 0:
        nib = s & 15
        if nib:
            q = _pt_add(q, table[i][nib])
        s >>= 4
        i += 1
    return q


_B_TABLE = None


def _fixed_base_mul(s):
    global _B_TABLE
    if _B_TABLE is None:
        _B_TABLE = _build_table(_B)
    return _table_mul(_B_TABLE, s)


@_functools.lru_cache(maxsize=64)
def _pub_key_table(pub_bytes):
    """Window table for a public key, or None if it fails to decompress.
    maxsize bounds worst-case memory at a few MB; any real validator set
    fits with room to spare."""
    a = _decompress(pub_bytes)
    return None if a is None else _build_table(a)


def _pt_equal(p1, p2):
    x1, y1, z1, _ = p1
    x2, y2, z2, _ = p2
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def _compress(pt) -> bytes:
    x, y, z, _ = pt
    zinv = pow(z, P - 2, P)
    x, y = (x * zinv) % P, (y * zinv) % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def _decompress(data: bytes):
    if len(data) != 32:
        return None
    val = int.from_bytes(data, "little")
    y = val & ((1 << 255) - 1)
    sign = val >> 255
    if y >= P:
        return None
    y2 = (y * y) % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # sqrt(u/v) per RFC 8032 §5.1.3
    x = (u * v**3 * pow(u * v**7, (P - 5) // 8, P)) % P
    vxx = (v * x * x) % P
    if vxx == u % P:
        pass
    elif vxx == (-u) % P:
        x = (x * _SQRT_M1) % P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y, 1, (x * y) % P)


def _sha512_mod_l(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little") % L


def _clamp(h32: bytes) -> int:
    a = int.from_bytes(h32, "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


class Ed25519PublicKey:
    def __init__(self, data: bytes):
        self._data = bytes(data)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "Ed25519PublicKey":
        if len(data) != 32:
            raise ValueError("ed25519 public key must be 32 bytes")
        return cls(data)

    def public_bytes_raw(self) -> bytes:
        return self._data

    def verify(self, signature: bytes, data: bytes) -> None:
        if len(signature) != 64:
            raise InvalidSignature("bad signature length")
        a_table = _pub_key_table(self._data)
        if a_table is None:
            raise InvalidSignature("malformed public key")
        r = _decompress(signature[:32])
        if r is None:
            raise InvalidSignature("malformed R point")
        s = int.from_bytes(signature[32:], "little")
        if s >= L:
            raise InvalidSignature("non-canonical S")
        k = _sha512_mod_l(signature[:32], self._data, data)
        if not _pt_equal(_fixed_base_mul(s),
                         _pt_add(r, _table_mul(a_table, k))):
            raise InvalidSignature("signature mismatch")


class Ed25519PrivateKey:
    def __init__(self, seed: bytes):
        self._seed = bytes(seed)
        h = hashlib.sha512(self._seed).digest()
        self._a = _clamp(h[:32])
        self._prefix = h[32:]
        self._pub = _compress(_fixed_base_mul(self._a))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "Ed25519PrivateKey":
        if len(data) != 32:
            raise ValueError("ed25519 private key must be 32 bytes")
        return cls(data)

    @classmethod
    def generate(cls) -> "Ed25519PrivateKey":
        return cls(os.urandom(32))

    def private_bytes_raw(self) -> bytes:
        return self._seed

    def public_key(self) -> Ed25519PublicKey:
        return Ed25519PublicKey(self._pub)

    def sign(self, data: bytes) -> bytes:
        r = _sha512_mod_l(self._prefix, data)
        r_enc = _compress(_fixed_base_mul(r))
        k = _sha512_mod_l(r_enc, self._pub, data)
        s = (r + k * self._a) % L
        return r_enc + int.to_bytes(s, 32, "little")
