"""secp256k1 ECDSA keys (reference crypto/secp256k1/secp256k1.go).

Reference semantics: 32-byte privkey, 33-byte compressed pubkey,
address = RIPEMD160(SHA256(compressed-pubkey)) (secp256k1.go:10-14,
unlike ed25519's SHA256-20). Signatures are 64-byte r||s with low-s
normalization. Backed by the `cryptography` library's EC primitives
(the host-native path; this curve never needs the TPU batch engine —
consensus keys are ed25519).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
        encode_dss_signature,
    )

    _FALLBACK = None
except ImportError:  # no OpenSSL bindings: pure-Python ECDSA fallback
    from . import _secp256k1_fallback as _FALLBACK

    class InvalidSignature(Exception):  # keeps except-clauses importable
        pass

from .keys import PrivKey, PubKey

# curve order, for low-s normalization
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141

SECP256K1_PUBKEY_SIZE = 33
SECP256K1_PRIVKEY_SIZE = 32
SECP256K1_SIG_SIZE = 64


def _ripemd160_sha256(data: bytes) -> bytes:
    h = hashlib.new("ripemd160")
    h.update(hashlib.sha256(data).digest())
    return h.digest()


@dataclass(frozen=True)
class PubKeySecp256k1(PubKey):
    data: bytes  # 33-byte compressed SEC1

    def __post_init__(self):
        if len(self.data) != SECP256K1_PUBKEY_SIZE:
            raise ValueError(
                f"secp256k1 pubkey must be {SECP256K1_PUBKEY_SIZE} bytes")

    def address(self) -> bytes:
        """RIPEMD160(SHA256(pubkey)) — secp256k1.go:117-124."""
        return _ripemd160_sha256(self.data)

    def bytes(self) -> bytes:
        return self.data

    def verify_bytes(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SECP256K1_SIG_SIZE:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if s > _N // 2:
            # reject malleable high-s signatures like the reference, which
            # parses into canonical form "to prevent Secp256k1 malleability"
            # (secp256k1.go:140-152)
            return False
        if _FALLBACK is not None:
            return _FALLBACK.ecdsa_verify(self.data, msg, r, s)
        try:
            pub = ec.EllipticCurvePublicKey.from_encoded_point(
                ec.SECP256K1(), self.data)
            pub.verify(encode_dss_signature(r, s), msg,
                       ec.ECDSA(hashes.SHA256()))
            return True
        except (InvalidSignature, ValueError):
            return False

    def equals(self, other) -> bool:
        return isinstance(other, PubKeySecp256k1) and self.data == other.data


@dataclass(frozen=True)
class PrivKeySecp256k1(PrivKey):
    data: bytes  # 32-byte big-endian scalar

    def __post_init__(self):
        if len(self.data) != SECP256K1_PRIVKEY_SIZE:
            raise ValueError(
                f"secp256k1 privkey must be {SECP256K1_PRIVKEY_SIZE} bytes")

    @classmethod
    def generate(cls) -> "PrivKeySecp256k1":
        if _FALLBACK is not None:
            return cls(_FALLBACK.gen_scalar().to_bytes(32, "big"))
        key = ec.generate_private_key(ec.SECP256K1())
        d = key.private_numbers().private_value
        return cls(d.to_bytes(32, "big"))

    @classmethod
    def gen_from_secret(cls, secret: bytes) -> "PrivKeySecp256k1":
        """secp256k1.go GenPrivKeySecp256k1: sha256(secret) used
        directly as the scalar, re-hashed until it lands in [1, n)."""
        digest = hashlib.sha256(secret).digest()
        d = int.from_bytes(digest, "big")
        while d == 0 or d >= _N:
            digest = hashlib.sha256(digest).digest()
            d = int.from_bytes(digest, "big")
        return cls(d.to_bytes(32, "big"))

    def _key(self) -> ec.EllipticCurvePrivateKey:
        return ec.derive_private_key(
            int.from_bytes(self.data, "big"), ec.SECP256K1())

    def sign(self, msg: bytes) -> bytes:
        if _FALLBACK is not None:
            r, s = _FALLBACK.ecdsa_sign(
                int.from_bytes(self.data, "big"), msg)
        else:
            der = self._key().sign(msg, ec.ECDSA(hashes.SHA256()))
            r, s = decode_dss_signature(der)
        if s > _N // 2:  # low-s, like btcec
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    def pub_key(self) -> PubKeySecp256k1:
        if _FALLBACK is not None:
            return PubKeySecp256k1(
                _FALLBACK.pub_from_scalar(int.from_bytes(self.data, "big")))
        pub = self._key().public_key()
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        return PubKeySecp256k1(
            pub.public_bytes(Encoding.X962, PublicFormat.CompressedPoint))

    def bytes(self) -> bytes:
        return self.data

    def equals(self, other) -> bool:
        return (isinstance(other, PrivKeySecp256k1)
                and self.data == other.data)
