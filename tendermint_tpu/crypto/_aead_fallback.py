"""Pure-Python X25519 / HKDF-SHA256 / ChaCha20-Poly1305 (RFC 7748,
RFC 5869, RFC 8439) — import-compatible fallback for the `cryptography`
primitives behind the SecretConnection handshake and symmetric AEAD.

Used only when OpenSSL bindings are absent from the environment
(p2p/conn/secret_connection.py and crypto/symmetric.py gate the import),
the same arrangement as crypto/_ed25519_fallback.py. Roughly three
orders of magnitude slower than OpenSSL — ~1 ms to seal a 1 KiB frame —
which is plenty for consensus-sized p2p traffic. Not constant-time;
production deployments install `cryptography`.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct

_M32 = 0xFFFFFFFF


class InvalidTag(Exception):
    """Mirror of cryptography.exceptions.InvalidTag."""


# -- HKDF-SHA256 (RFC 5869) ----------------------------------------------


class _SHA256:
    """Stand-in for cryptography.hazmat.primitives.hashes.SHA256; the
    fallback HKDF is SHA256-only, so this carries no behaviour."""

    digest_size = 32


class hashes:  # noqa: N801 — mimics the `hashes` module namespace
    SHA256 = _SHA256


class HKDF:
    def __init__(self, algorithm=None, length: int = 32, salt: bytes = None,
                 info: bytes = b""):
        self._length = length
        self._salt = salt if salt else b"\x00" * 32
        self._info = info or b""

    def derive(self, ikm: bytes) -> bytes:
        prk = hmac.new(self._salt, ikm, hashlib.sha256).digest()
        okm, t, i = b"", b"", 1
        while len(okm) < self._length:
            t = hmac.new(prk, t + self._info + bytes([i]),
                         hashlib.sha256).digest()
            okm += t
            i += 1
        return okm[: self._length]


# -- X25519 (RFC 7748 §5) ------------------------------------------------

_P = 2**255 - 19
_A24 = 121665


def _x25519(k: bytes, u: bytes) -> bytes:
    scalar = int.from_bytes(k, "little")
    scalar &= (1 << 254) - 8
    scalar |= 1 << 254
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (scalar >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = (a * a) % _P
        b = (x2 - z2) % _P
        bb = (b * b) % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = (d * a) % _P
        cb = (c * b) % _P
        x3 = ((da + cb) ** 2) % _P
        z3 = (x1 * (da - cb) ** 2) % _P
        x2 = (aa * bb) % _P
        z2 = (e * (aa + _A24 * e)) % _P
    if swap:
        x2, z2 = x3, z3
    return ((x2 * pow(z2, _P - 2, _P)) % _P).to_bytes(32, "little")


class X25519PublicKey:
    def __init__(self, data: bytes):
        self._data = bytes(data)

    @classmethod
    def from_public_bytes(cls, data: bytes) -> "X25519PublicKey":
        if len(data) != 32:
            raise ValueError("x25519 public key must be 32 bytes")
        return cls(data)

    def public_bytes_raw(self) -> bytes:
        return self._data


class X25519PrivateKey:
    _BASE = (9).to_bytes(32, "little")

    def __init__(self, data: bytes):
        self._data = bytes(data)

    @classmethod
    def generate(cls) -> "X25519PrivateKey":
        return cls(os.urandom(32))

    @classmethod
    def from_private_bytes(cls, data: bytes) -> "X25519PrivateKey":
        if len(data) != 32:
            raise ValueError("x25519 private key must be 32 bytes")
        return cls(data)

    def private_bytes_raw(self) -> bytes:
        return self._data

    def public_key(self) -> X25519PublicKey:
        return X25519PublicKey(_x25519(self._data, self._BASE))

    def exchange(self, peer_public_key: X25519PublicKey) -> bytes:
        out = _x25519(self._data, peer_public_key.public_bytes_raw())
        if out == b"\x00" * 32:
            raise ValueError("x25519 produced all-zero shared secret")
        return out


# -- ChaCha20-Poly1305 AEAD (RFC 8439) -----------------------------------

_CHACHA_CONSTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


# The block function is exec-generated with all 16 state words as
# locals and the 80 quarter-rounds unrolled: ~5x over an indexed-list
# loop in CPython, which matters because every 1 KiB p2p frame costs 17
# blocks. The generator emits the RFC 8439 §2.3 schedule verbatim.


def _gen_chacha20_block():
    qr = []
    for a, b, c, d in ((0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14),
                       (3, 7, 11, 15), (0, 5, 10, 15), (1, 6, 11, 12),
                       (2, 7, 8, 13), (3, 4, 9, 14)):
        qr.append(f"""
        x{a} = (x{a} + x{b}) & M; x{d} ^= x{a}; x{d} = ((x{d} << 16) | (x{d} >> 16)) & M
        x{c} = (x{c} + x{d}) & M; x{b} ^= x{c}; x{b} = ((x{b} << 12) | (x{b} >> 20)) & M
        x{a} = (x{a} + x{b}) & M; x{d} ^= x{a}; x{d} = ((x{d} << 8) | (x{d} >> 24)) & M
        x{c} = (x{c} + x{d}) & M; x{b} ^= x{c}; x{b} = ((x{b} << 7) | (x{b} >> 25)) & M""")
    rounds = "".join(qr)
    src = f"""
def _chacha20_block(key_words, counter, nonce_words, _pack=struct.pack, M={_M32}):
    s4, s5, s6, s7, s8, s9, s10, s11 = key_words
    s12 = counter & M
    s13, s14, s15 = nonce_words
    x0, x1, x2, x3 = {_CHACHA_CONSTS}
    x4, x5, x6, x7, x8, x9, x10, x11 = key_words
    x12, x13, x14, x15 = s12, s13, s14, s15
    for _ in range(10):{rounds}
    return _pack(
        "<16I",
        (x0 + {_CHACHA_CONSTS[0]}) & M, (x1 + {_CHACHA_CONSTS[1]}) & M,
        (x2 + {_CHACHA_CONSTS[2]}) & M, (x3 + {_CHACHA_CONSTS[3]}) & M,
        (x4 + s4) & M, (x5 + s5) & M, (x6 + s6) & M, (x7 + s7) & M,
        (x8 + s8) & M, (x9 + s9) & M, (x10 + s10) & M, (x11 + s11) & M,
        (x12 + s12) & M, (x13 + s13) & M, (x14 + s14) & M, (x15 + s15) & M)
"""
    ns = {"struct": struct}
    exec(src, ns)
    return ns["_chacha20_block"]


_chacha20_block = _gen_chacha20_block()


def _chacha20_xor(key_words, nonce_words, counter: int, data: bytes) -> bytes:
    n = len(data)
    ks = b"".join(
        _chacha20_block(key_words, counter + i, nonce_words)
        for i in range((n + 63) // 64)
    )
    return (
        int.from_bytes(data, "little") ^ int.from_bytes(ks[:n], "little")
    ).to_bytes(n, "little")


def _poly1305(otk: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(otk[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(otk[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        acc = ((acc + int.from_bytes(block, "little")
                + (1 << (8 * len(block)))) * r) % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    return b"\x00" * (-len(data) % 16)


class ChaCha20Poly1305:
    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("chacha20poly1305 key must be 32 bytes")
        self._key_words = struct.unpack("<8I", key)

    def _mac(self, nonce_words, aad: bytes, ct: bytes) -> bytes:
        otk = _chacha20_block(self._key_words, 0, nonce_words)[:32]
        mac_data = (aad + _pad16(aad) + ct + _pad16(ct)
                    + struct.pack("<QQ", len(aad), len(ct)))
        return _poly1305(otk, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        nonce_words = struct.unpack("<3I", nonce)
        ct = _chacha20_xor(self._key_words, nonce_words, 1, data)
        return ct + self._mac(nonce_words, associated_data or b"", ct)

    def decrypt(self, nonce: bytes, data: bytes, associated_data) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than poly1305 tag")
        nonce_words = struct.unpack("<3I", nonce)
        ct, tag = data[:-16], data[-16:]
        expect = self._mac(nonce_words, associated_data or b"", ct)
        if not hmac.compare_digest(expect, tag):
            raise InvalidTag("poly1305 tag mismatch")
        return _chacha20_xor(self._key_words, nonce_words, 1, ct)
