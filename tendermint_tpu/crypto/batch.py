"""BatchVerifier — the pluggable bulk-verification engine (the north star).

The reference verifies every vote/commit signature serially
(types/validator_set.go:345-371, types/vote_set.go:189 →
crypto/ed25519/ed25519.go:151-157). Here every bulk call site —
ValidatorSet.verify_commit, fast-sync block validation, VoteSet batching —
routes through this registry instead, and per-item validity masks come back
(mixed valid/invalid batches are first-class; no all-or-nothing batch
equations).

Backends:
  "cpu"      — per-signature verify via OpenSSL (always available; baseline)
  "jax"      — vectorized Ed25519 verify (decompress → SHA-512 → double
               scalar mult) under vmap/jit; shards across every visible
               device with shard_map when more than one is present.
  "adaptive" — (default when jax is importable) routes batches below
               TM_TPU_BATCH_MIN to "cpu" and the rest to "jax": the
               latency-shaped live vote path stays serial when traffic is
               light and rides the device exactly when batching pays.

Select with set_default_backend() or the TM_TPU_CRYPTO_BACKEND env var.

Two cross-cutting layers sit in front of every backend:

- Verified-signature cache (sigcache.SigCache, installed process-wide
  via set_sig_cache / configure): verify() consults it first and only
  the cache-miss subset reaches the backend; the per-item mask is
  re-interleaved in add order. Duplicate triples within one batch are
  dispatched once.
- Async dispatch: verify_async() runs the exact verify() pipeline on a
  dedicated per-backend dispatch thread and returns a VerifyFuture, so
  callers overlap verification with other work (fast-sync applies block
  k while block k+1's commit verifies; the consensus receive loop WALs
  a vote run while its batch is on the device). Backend exceptions
  surface at .result(), never in the dispatch thread.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from ..libs import tracing

Triple = Tuple[bytes, bytes, bytes]  # (message, signature, pubkey)

# Process-wide CryptoMetrics sink (tendermint_tpu.metrics.CryptoMetrics).
# None (the default) costs one load+is-check per verify() call; a Node
# with instrumentation on wires its live metric set here so EVERY call
# site — VoteSet, ValidatorSet.verify_commit, fast-sync, lite client —
# is measured without plumbing a metrics object through each of them.
_metrics = None
_metrics_lock = threading.Lock()


def set_metrics(metrics) -> None:
    """Install (or, with None, remove) the process-wide CryptoMetrics."""
    global _metrics
    with _metrics_lock:
        _metrics = metrics


def get_metrics():
    return _metrics


def record_device_split(transfer_s: float, compute_s: float) -> None:
    """Called by the jax backend with the last batch's host->device
    pack+transfer time vs on-device compute/wait time."""
    m = _metrics
    if m is not None:
        m.device_transfer_seconds.set(transfer_s)
        m.device_compute_seconds.set(compute_s)


# --- process-wide [crypto] configuration (sig cache + async flag) ------
#
# Like the metrics sink above, these are process-global so every call
# site — VoteSet, ValidatorSet.verify_commit, fast-sync, consensus —
# picks them up without plumbing. node.Node wires them from the
# config.py [crypto] section; library users call the setters directly.

_sig_cache = None  # sigcache.SigCache or None (cache disabled)
_async_enabled = True  # gates the PIPELINED call sites, not verify_async


def set_sig_cache(cache) -> None:
    """Install (or, with None, remove) the process-wide verified-
    signature cache consulted by every BatchVerifier.verify()."""
    global _sig_cache
    _sig_cache = cache


def get_sig_cache():
    return _sig_cache


def set_async_enabled(on: bool) -> None:
    global _async_enabled
    _async_enabled = bool(on)


def async_enabled() -> bool:
    """Whether pipelined call sites (fast-sync verify/apply overlap, the
    consensus WAL/dispatch overlap) should use verify_async. The
    verify_async API itself always works regardless."""
    return _async_enabled


def configure(async_dispatch: Optional[bool] = None,
              sig_cache_size: Optional[int] = None,
              coalesce_window_ms: Optional[float] = None,
              coalesce_max_batch: Optional[int] = None) -> None:
    """Apply the [crypto] config section (config.CryptoConfig)."""
    if async_dispatch is not None:
        set_async_enabled(async_dispatch)
    if sig_cache_size is not None:
        if sig_cache_size > 0:
            from .sigcache import SigCache

            set_sig_cache(SigCache(sig_cache_size))
        else:
            set_sig_cache(None)
    if coalesce_window_ms is not None or coalesce_max_batch is not None:
        set_coalesce(coalesce_window_ms, coalesce_max_batch)


# --- async dispatch ----------------------------------------------------


class VerifyFuture:
    """Handle for one verify_async() call. result() returns exactly what
    verify() would have (per-item mask in add order) or re-raises the
    backend exception — errors never die in the dispatch thread."""

    __slots__ = ("_event", "_mask", "_exc", "_t_submit", "_t_done",
                 "_overlap_recorded")

    def __init__(self):
        self._event = threading.Event()
        self._mask: Optional[List[bool]] = None
        self._exc: Optional[BaseException] = None
        self._t_submit = time.perf_counter()
        self._t_done: Optional[float] = None
        self._overlap_recorded = False

    def done(self) -> bool:
        return self._event.is_set()

    def _set_result(self, mask) -> None:
        self._t_done = time.perf_counter()
        self._mask = mask
        self._event.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._t_done = time.perf_counter()
        self._exc = exc
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> List[bool]:
        t_ask = time.perf_counter()
        if not self._event.wait(timeout):
            raise TimeoutError("verify_async result not ready")
        if not self._overlap_recorded:
            # pipeline overlap = wall time the caller spent elsewhere
            # while the batch was in flight: submit -> first result()
            # call, capped at completion (waiting inside result() is not
            # overlap). One sample per future.
            self._overlap_recorded = True
            m = _metrics
            if m is not None:
                overlap = max(0.0, min(t_ask, self._t_done) - self._t_submit)
                m.pipeline_overlap_seconds.observe(overlap)
        if self._exc is not None:
            raise self._exc
        return self._mask


# live async-batch count, readable without a metrics registry — the
# consensus stall watchdog includes it in /debug/consensus bundles (a
# stall with batches in flight points at the device, not the network)
_inflight = 0
_inflight_lock = threading.Lock()


def _inflight_add(d: int) -> None:
    global _inflight
    with _inflight_lock:
        _inflight += d


def inflight_count() -> int:
    """Async verify batches dispatched and not yet completed."""
    return _inflight


class _Dispatcher:
    """One daemon thread draining verify jobs for one backend name.
    stop() enqueues a sentinel, so queued jobs complete (their futures
    always resolve) before the thread exits."""

    def __init__(self, name: str):
        self.name = name
        self._q: "_queue.Queue" = _queue.Queue()
        # guards the stopping flag so a submit racing stop() can never
        # land behind the sentinel (its future would never resolve and
        # result() callers block forever) — it runs inline instead
        self._stop_lock = threading.Lock()
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run, name=f"crypto-dispatch-{name}", daemon=True
        )
        self._thread.start()

    def submit(self, fn: Callable[[], List[bool]]) -> VerifyFuture:
        fut = VerifyFuture()
        # capture the metrics sink ONCE: increment and decrement must hit
        # the same gauge even if set_metrics re-wires the process-wide
        # sink while this batch is in flight
        m = _metrics
        _inflight_add(1)
        if m is not None:
            m.inflight_batches.add(1)
        with self._stop_lock:
            if not self._stopping:
                self._q.put((fn, fut, m))
                return fut
        self._execute(fn, fut, m)  # stopping: run inline, future resolves
        return fut

    @staticmethod
    def _execute(fn, fut: VerifyFuture, m) -> None:
        try:
            fut._set_result(fn())
        except BaseException as e:  # noqa: BLE001 - surfaces at result()
            fut._set_exception(e)
        finally:
            _inflight_add(-1)
            if m is not None:
                m.inflight_batches.add(-1)

    def _run(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                return
            self._execute(*task)

    def stop(self, timeout: float = 10.0) -> None:
        with self._stop_lock:
            if not self._stopping:
                self._stopping = True
                self._q.put(None)
        self._thread.join(timeout)

    def alive(self) -> bool:
        return self._thread.is_alive()


_dispatchers: dict = {}
_dispatchers_lock = threading.Lock()


def _dispatcher(name: str) -> _Dispatcher:
    with _dispatchers_lock:
        d = _dispatchers.get(name)
        if d is None or not d.alive():
            d = _Dispatcher(name)
            _dispatchers[name] = d
        return d


def shutdown_dispatchers(timeout: float = 10.0) -> None:
    """Stop every dispatch thread after draining its queue: in-flight
    futures complete, then the threads join. Called by Node.stop; a
    verify_async() issued afterwards lazily spawns a fresh dispatcher,
    so concurrent nodes in one process stay correct (at worst a thread
    respawn)."""
    with _coalescers_lock:
        cs = list(_coalescers.values())
        _coalescers.clear()
    for c in cs:
        c.stop(timeout)
    with _dispatchers_lock:
        ds = list(_dispatchers.values())
        _dispatchers.clear()
    for d in ds:
        d.stop(timeout)


# --- cross-height verify scheduler (coalescing verify_async) -----------
#
# With many verification streams in flight at once — pipelined fast
# sync, live votes, statesync bisection — each caller's verify_async
# issues its own (often half-full) device dispatch, and every dispatch
# pays the fixed kernel-launch cost. When [crypto] coalesce_window_ms
# is > 0, verify_async calls for the same backend arriving within that
# window are merged into ONE backend dispatch (up to coalesce_max_batch
# signatures); each caller's future still resolves with exactly its own
# slice of the merged mask, in add order, so verdicts are identical to
# sequential dispatch (property-tested). Defaults keep the scheduler
# off: 0ms window = the plain per-call dispatcher path, untouched.

_coalesce_window_s = 0.0
_coalesce_max = 8192
_coalescers: dict = {}  # (backend, class, instance key) -> _Coalescer
_coalescers_lock = threading.Lock()


def set_coalesce(window_ms: Optional[float] = None,
                 max_batch: Optional[int] = None) -> None:
    """Configure the verify_async coalescing scheduler. window_ms <= 0
    disables it (every call dispatches immediately, the pre-PR-8
    behavior). Takes effect for subsequent verify_async calls; already
    pending entries flush under the window they were submitted with."""
    global _coalesce_window_s, _coalesce_max
    if window_ms is not None:
        _coalesce_window_s = max(0.0, float(window_ms) / 1e3)
    if max_batch is not None:
        _coalesce_max = max(1, int(max_batch))


def coalesce_window_ms() -> float:
    return _coalesce_window_s * 1e3


def coalesce_status() -> dict:
    """Bundle for /debug/crypto: scheduler config + live pending size."""
    with _coalescers_lock:
        pending = sum(c.pending_items() for c in _coalescers.values())
    return {
        "window_ms": _coalesce_window_s * 1e3,
        "max_batch": _coalesce_max,
        "pending_items": pending,
    }


class _Coalescer:
    """One daemon thread merging verify_async calls for one (backend,
    verifier class) pair. Entries are (verifier, items, future); at
    flush the first entry's verifier runs verify() over the merged item
    list (the same _items-swap trick BatchVerifier.verify uses for the
    sigcache miss subset), and each future resolves with its own slice.
    A backend exception fans out to every future in the merged dispatch
    — it still surfaces at result(), never in this thread."""

    def __init__(self, name: str):
        self.name = name
        self._cv = threading.Condition()
        self._pending: list = []  # (verifier, items, future, metrics)
        self._count = 0
        self._deadline: Optional[float] = None
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run, name=f"crypto-coalesce-{name}", daemon=True
        )
        self._thread.start()

    def pending_items(self) -> int:
        return self._count

    def submit(self, verifier: "BatchVerifier") -> Optional[VerifyFuture]:
        """Queue this verifier's items for the next merged dispatch.
        Returns None when stopping — the caller falls back to the plain
        dispatcher path (its future then resolves there)."""
        with self._cv:
            if self._stopping:
                return None
            fut = VerifyFuture()
            m = _metrics
            _inflight_add(1)
            if m is not None:
                m.inflight_batches.add(1)
            if not self._pending:
                self._deadline = time.perf_counter() + _coalesce_window_s
            self._pending.append((verifier, list(verifier._items), fut, m))
            self._count += len(verifier._items)
            self._cv.notify()
            return fut

    def _take(self) -> list:
        """Pop the next merged group (caller holds the lock): entries in
        submission order until max_batch is covered; anything past the
        cap stays pending with an immediate deadline, so an oversize
        burst drains as back-to-back full dispatches."""
        taken, total = [], 0
        while self._pending:
            n = len(self._pending[0][1])
            if taken and total + n > _coalesce_max:
                break
            taken.append(self._pending.pop(0))
            total += n
        self._count -= total
        self._deadline = time.perf_counter() if self._pending else None
        return taken

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stopping:
                    self._cv.wait()
                if not self._pending:
                    return  # stopping with nothing queued
                # sit out the rest of the window unless the cap is hit
                # or stop() needs the queue drained
                while (self._count < _coalesce_max and not self._stopping):
                    now = time.perf_counter()
                    if self._deadline is None or now >= self._deadline:
                        break
                    self._cv.wait(self._deadline - now)
                entries = self._take()
            self._execute(entries)

    @staticmethod
    def _execute(entries: list) -> None:
        host = entries[0][0]
        merged = [t for _, items, _, _ in entries for t in items]
        mask = None
        exc: Optional[BaseException] = None
        try:
            saved = host._items
            host._items = merged
            try:
                mask = host.verify()
            finally:
                host._items = saved
        except BaseException as e:  # noqa: BLE001 - surfaces at result()
            exc = e
        if len(entries) > 1:
            m0 = entries[0][3]
            if m0 is not None:
                m0.coalesced_calls.inc(len(entries) - 1)
        off = 0
        for _, items, fut, m in entries:
            try:
                if exc is not None:
                    fut._set_exception(exc)
                else:
                    fut._set_result(mask[off:off + len(items)])
            finally:
                off += len(items)
                _inflight_add(-1)
                if m is not None:
                    m.inflight_batches.add(-1)

    def stop(self, timeout: float = 10.0) -> None:
        with self._cv:
            if not self._stopping:
                self._stopping = True
                self._cv.notify_all()
        self._thread.join(timeout)

    def alive(self) -> bool:
        return self._thread.is_alive()


def _coalescer(verifier: "BatchVerifier") -> _Coalescer:
    cls = type(verifier)
    key = (verifier.BACKEND, cls, verifier._coalesce_key())
    with _coalescers_lock:
        c = _coalescers.get(key)
        if c is None or not c.alive():
            c = _Coalescer(f"{verifier.BACKEND}-{cls.__name__}")
            _coalescers[key] = c
        return c


class BatchVerifier:
    """Accumulate (msg, sig, pubkey) triples, then verify all at once.

    Backends implement _verify(); the public verify() wraps it with
    latency/batch-size/validity telemetry (no-op until set_metrics) and
    a tracing span. Subclasses may still override verify() wholesale
    (test fakes do) — they just opt out of the built-in telemetry."""

    BACKEND = "unknown"

    def __init__(self):
        self._items: List[Triple] = []

    def add(self, msg: bytes, sig: bytes, pubkey: bytes) -> None:
        self._items.append((msg, sig, pubkey))

    def __len__(self) -> int:
        return len(self._items)

    def _verify(self) -> List[bool]:
        raise NotImplementedError

    def _coalesce_key(self) -> tuple:
        """Extra (hashable) key material for the coalescing scheduler:
        only verifiers with equal (BACKEND, class, this key) merge into
        one dispatch. Subclasses carrying per-instance dispatch policy
        must include it here, so a merged batch never runs under
        another caller's configuration."""
        return ()

    def verify(self) -> List[bool]:
        """Returns one validity flag per added triple, in add order.

        Consults the process-wide verified-signature cache first: cached
        triples never reach the backend, duplicate triples within the
        batch are dispatched once, and only the cache-miss subset runs
        _verify(); the mask is re-interleaved in add order."""
        cache = _sig_cache
        if cache is None or not self._items:
            return self._verify_instrumented()
        items = self._items
        keys = [cache.key(msg, sig, pk) for msg, sig, pk in items]
        verdicts: List[Optional[bool]] = [None] * len(items)
        miss_pos: dict = {}  # key -> index into miss_idx (in-batch dedup)
        miss_idx: List[int] = []
        hits = 0
        for i, k in enumerate(keys):
            if k in miss_pos:
                continue  # duplicate of an in-batch miss: filled below
            v = cache.get(k)
            if v is None:
                miss_pos[k] = len(miss_idx)
                miss_idx.append(i)
            else:
                verdicts[i] = v
                hits += 1
        m = _metrics
        if m is not None:
            if hits:
                m.sig_cache_hits.inc(hits)
            if miss_idx:
                m.sig_cache_misses.inc(len(miss_idx))
        if miss_idx:
            # _verify() reads self._items; narrow it to the miss subset
            # for the dispatch (single-caller contract, like add/verify)
            self._items = [items[i] for i in miss_idx]
            try:
                submask = self._verify_instrumented()
            finally:
                self._items = items
            for pos, i in enumerate(miss_idx):
                ok = bool(submask[pos])
                verdicts[i] = ok
                cache.put(keys[i], ok)
        for i, k in enumerate(keys):
            if verdicts[i] is None:  # in-batch duplicate of a miss
                verdicts[i] = verdicts[miss_idx[miss_pos[k]]]
        return verdicts

    def _verify_instrumented(self) -> List[bool]:
        """_verify() wrapped with latency/size/validity telemetry."""
        m = _metrics
        tracer = tracing.get_tracer()
        if m is None and not tracer.enabled:
            return self._verify()
        n = len(self._items)
        with tracer.span("crypto.batchVerify", cat="crypto",
                         backend=self.BACKEND, n=n):
            t0 = time.perf_counter()
            mask = self._verify()
            dt = time.perf_counter() - t0
        if m is not None:
            m.batch_verify_seconds.with_labels(self.BACKEND).observe(dt)
            m.batch_size.observe(n)
            ok = sum(1 for b in mask if b)
            if ok:
                m.signatures_verified.inc(ok)
            if n - ok:
                m.signatures_invalid.inc(n - ok)
        return mask

    def verify_async(self) -> VerifyFuture:
        """Dispatch verify() of the CURRENT items on this backend's
        dedicated dispatch thread. The caller must not add() to this
        verifier while the future is in flight; result() returns the
        per-item mask (add order) or re-raises the backend error.

        With [crypto] coalesce_window_ms > 0, calls landing within the
        window are merged into one backend dispatch (same class AND
        same per-instance _coalesce_key only, so backend semantics are
        exact); the future still resolves with this call's own mask
        slice."""
        if _coalesce_window_s > 0 and self._items:
            fut = _coalescer(self).submit(self)
            if fut is not None:
                return fut
        return _dispatcher(self.BACKEND).submit(self.verify)

    def verify_all(self) -> bool:
        return all(self.verify())


class CPUBatchVerifier(BatchVerifier):
    """Serial per-signature verification — the reference semantics.

    Key type is dispatched on pubkey length: 32 bytes = Ed25519,
    48 bytes = BLS12-381 (the aggregate fast lane's INDIVIDUAL votes —
    live gossip still delivers one precommit at a time; the O(1)
    certificate path is ValidatorSet.verify_commit_aggregate)."""

    BACKEND = "cpu"

    def _verify(self) -> List[bool]:
        from .keys import PubKeyEd25519

        out = []
        for msg, sig, pk in self._items:
            try:
                if len(pk) == 48:
                    from .bls import PubKeyBLS12381

                    out.append(PubKeyBLS12381(pk).verify_bytes(msg, sig))
                else:
                    out.append(PubKeyEd25519(pk).verify_bytes(msg, sig))
            except ValueError:
                out.append(False)
        return out


class AdaptiveBatchVerifier(BatchVerifier):
    """Latency-shaped dispatch: device batch verification pays a fixed
    dispatch cost per call, so tiny batches (the live add_vote path when
    traffic is light) run the serial CPU path and only batches of
    >= min_device_batch ride the device kernel. The threshold is the
    crossover point between per-sig CPU cost (~100µs) and device
    dispatch overhead; tune with TM_TPU_BATCH_MIN."""

    BACKEND = "adaptive"

    def __init__(self, device_factory: Callable[[], BatchVerifier],
                 min_device_batch: int | None = None):
        super().__init__()
        self._device_factory = device_factory
        if min_device_batch is None:
            min_device_batch = effective_batch_min()
        self._min = min_device_batch

    def _coalesce_key(self) -> tuple:
        # routing policy is per-instance: two nodes in one process may
        # configure different factories/thresholds, and a merged batch
        # runs entirely on the FIRST caller's instance
        return (self._device_factory, self._min)

    def verify(self) -> List[bool]:
        # overrides verify() (not _verify) on purpose: the inner
        # verifier's own verify() records the latency/size telemetry
        # under its leaf backend label — a template here would double
        # count every batch. Adaptive only adds the routing decision.
        n = len(self._items)
        if any(len(pk) != 32 for _, _, pk in self._items):
            # non-Ed25519 triples (BLS fast lane): the jax kernel is
            # Ed25519-specific — route straight to the CPU dispatcher
            inner = CPUBatchVerifier()
            for msg, sig, pk in self._items:
                inner.add(msg, sig, pk)
            return inner.verify()
        cache = _sig_cache
        if cache is not None and n:
            # route on the CACHE-MISS count (stats-neutral peek): the
            # leaf verifier will only dispatch the misses, so a mostly-
            # cached batch must not pay the fixed device dispatch for a
            # handful of stragglers
            n = sum(1 for msg, sig, pk in self._items
                    if cache.peek(cache.key(msg, sig, pk)) is None)
        use_device = n >= self._min
        m = _metrics
        if m is not None:
            m.routing_decisions.with_labels(
                "device" if use_device else "cpu").inc()
        inner = self._device_factory() if use_device else CPUBatchVerifier()
        for msg, sig, pk in self._items:
            inner.add(msg, sig, pk)
        return inner.verify()


_registry: dict[str, Callable[[], BatchVerifier]] = {}
_default_lock = threading.Lock()
_default_name: str | None = None
_calibrated_min: int | None = None


def set_calibrated_batch_min(n: int) -> None:
    """Record the MEASURED device break-even (verify.warmup calibrates:
    one compiled-dispatch round trip vs the serial per-signature cost on
    the hardware actually attached). Consulted whenever TM_TPU_BATCH_MIN
    is not explicitly set, so the device is only used where it wins —
    e.g. a remote-tunnel TPU with ~64ms round trips calibrates to
    hundreds, while direct-attached hardware calibrates to ~tens."""
    global _calibrated_min
    with _default_lock:
        _calibrated_min = max(1, int(n))


def calibrated_batch_min() -> int | None:
    with _default_lock:
        return _calibrated_min


def effective_batch_min(default: int = 16) -> int:
    """The adaptive cutoff: explicit TM_TPU_BATCH_MIN wins, then the
    warmup-measured calibration, then the static default."""
    env = os.environ.get("TM_TPU_BATCH_MIN")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass  # malformed env must never take down verification
    with _default_lock:
        if _calibrated_min is not None:
            return _calibrated_min
    return default


def register_backend(name: str, factory: Callable[[], BatchVerifier]) -> None:
    _registry[name] = factory


def backends() -> List[str]:
    return sorted(_registry)


def set_default_backend(name: str) -> None:
    global _default_name
    if name not in _registry:
        raise KeyError(f"unknown batch-verify backend {name!r}; have {backends()}")
    with _default_lock:
        _default_name = name


def default_backend_name() -> str:
    global _default_name
    with _default_lock:
        if _default_name is None:
            env = os.environ.get("TM_TPU_CRYPTO_BACKEND")
            if env and env in _registry:
                _default_name = env
            elif "adaptive" in _registry:
                _default_name = "adaptive"
            elif "jax" in _registry:
                _default_name = "jax"
            else:
                _default_name = "cpu"
        return _default_name


def new_batch_verifier(name: str | None = None) -> BatchVerifier:
    if name is None:
        name = default_backend_name()
    try:
        factory = _registry[name]
    except KeyError:
        raise KeyError(f"unknown batch-verify backend {name!r}; have {backends()}")
    return factory()


def batch_verify(
    triples: Sequence[Triple], backend: str | None = None
) -> List[bool]:
    bv = new_batch_verifier(backend)
    for msg, sig, pk in triples:
        bv.add(msg, sig, pk)
    return bv.verify()


register_backend("cpu", CPUBatchVerifier)


def _register_jax_backend():
    """Deferred so importing tendermint_tpu.crypto never forces jax init."""
    try:
        from .jaxed25519.verify import JAXBatchVerifier
    except ImportError as e:
        import logging

        logging.getLogger(__name__).warning(
            "jax batch-verify backend unavailable, falling back to cpu: %s", e
        )
        return
    register_backend("jax", JAXBatchVerifier)
    register_backend(
        "adaptive", lambda: AdaptiveBatchVerifier(JAXBatchVerifier)
    )


_register_jax_backend()
