"""BatchVerifier — the pluggable bulk-verification engine (the north star).

The reference verifies every vote/commit signature serially
(types/validator_set.go:345-371, types/vote_set.go:189 →
crypto/ed25519/ed25519.go:151-157). Here every bulk call site —
ValidatorSet.verify_commit, fast-sync block validation, VoteSet batching —
routes through this registry instead, and per-item validity masks come back
(mixed valid/invalid batches are first-class; no all-or-nothing batch
equations).

Backends:
  "cpu"      — per-signature verify via OpenSSL (always available; baseline)
  "jax"      — vectorized Ed25519 verify (decompress → SHA-512 → double
               scalar mult) under vmap/jit; shards across every visible
               device with shard_map when more than one is present.
  "adaptive" — (default when jax is importable) routes batches below
               TM_TPU_BATCH_MIN to "cpu" and the rest to "jax": the
               latency-shaped live vote path stays serial when traffic is
               light and rides the device exactly when batching pays.

Select with set_default_backend() or the TM_TPU_CRYPTO_BACKEND env var.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Sequence, Tuple

from ..libs import tracing

Triple = Tuple[bytes, bytes, bytes]  # (message, signature, pubkey)

# Process-wide CryptoMetrics sink (tendermint_tpu.metrics.CryptoMetrics).
# None (the default) costs one load+is-check per verify() call; a Node
# with instrumentation on wires its live metric set here so EVERY call
# site — VoteSet, ValidatorSet.verify_commit, fast-sync, lite client —
# is measured without plumbing a metrics object through each of them.
_metrics = None
_metrics_lock = threading.Lock()


def set_metrics(metrics) -> None:
    """Install (or, with None, remove) the process-wide CryptoMetrics."""
    global _metrics
    with _metrics_lock:
        _metrics = metrics


def get_metrics():
    return _metrics


def record_device_split(transfer_s: float, compute_s: float) -> None:
    """Called by the jax backend with the last batch's host->device
    pack+transfer time vs on-device compute/wait time."""
    m = _metrics
    if m is not None:
        m.device_transfer_seconds.set(transfer_s)
        m.device_compute_seconds.set(compute_s)


class BatchVerifier:
    """Accumulate (msg, sig, pubkey) triples, then verify all at once.

    Backends implement _verify(); the public verify() wraps it with
    latency/batch-size/validity telemetry (no-op until set_metrics) and
    a tracing span. Subclasses may still override verify() wholesale
    (test fakes do) — they just opt out of the built-in telemetry."""

    BACKEND = "unknown"

    def __init__(self):
        self._items: List[Triple] = []

    def add(self, msg: bytes, sig: bytes, pubkey: bytes) -> None:
        self._items.append((msg, sig, pubkey))

    def __len__(self) -> int:
        return len(self._items)

    def _verify(self) -> List[bool]:
        raise NotImplementedError

    def verify(self) -> List[bool]:
        """Returns one validity flag per added triple, in add order."""
        m = _metrics
        tracer = tracing.get_tracer()
        if m is None and not tracer.enabled:
            return self._verify()
        n = len(self._items)
        with tracer.span("crypto.batchVerify", cat="crypto",
                         backend=self.BACKEND, n=n):
            t0 = time.perf_counter()
            mask = self._verify()
            dt = time.perf_counter() - t0
        if m is not None:
            m.batch_verify_seconds.with_labels(self.BACKEND).observe(dt)
            m.batch_size.observe(n)
            ok = sum(1 for b in mask if b)
            if ok:
                m.signatures_verified.inc(ok)
            if n - ok:
                m.signatures_invalid.inc(n - ok)
        return mask

    def verify_all(self) -> bool:
        return all(self.verify())


class CPUBatchVerifier(BatchVerifier):
    """Serial per-signature verification — the reference semantics."""

    BACKEND = "cpu"

    def _verify(self) -> List[bool]:
        from .keys import PubKeyEd25519

        out = []
        for msg, sig, pk in self._items:
            try:
                out.append(PubKeyEd25519(pk).verify_bytes(msg, sig))
            except ValueError:
                out.append(False)
        return out


class AdaptiveBatchVerifier(BatchVerifier):
    """Latency-shaped dispatch: device batch verification pays a fixed
    dispatch cost per call, so tiny batches (the live add_vote path when
    traffic is light) run the serial CPU path and only batches of
    >= min_device_batch ride the device kernel. The threshold is the
    crossover point between per-sig CPU cost (~100µs) and device
    dispatch overhead; tune with TM_TPU_BATCH_MIN."""

    BACKEND = "adaptive"

    def __init__(self, device_factory: Callable[[], BatchVerifier],
                 min_device_batch: int | None = None):
        super().__init__()
        self._device_factory = device_factory
        if min_device_batch is None:
            min_device_batch = effective_batch_min()
        self._min = min_device_batch

    def verify(self) -> List[bool]:
        # overrides verify() (not _verify) on purpose: the inner
        # verifier's own verify() records the latency/size telemetry
        # under its leaf backend label — a template here would double
        # count every batch. Adaptive only adds the routing decision.
        use_device = len(self._items) >= self._min
        m = _metrics
        if m is not None:
            m.routing_decisions.with_labels(
                "device" if use_device else "cpu").inc()
        inner = self._device_factory() if use_device else CPUBatchVerifier()
        for msg, sig, pk in self._items:
            inner.add(msg, sig, pk)
        return inner.verify()


_registry: dict[str, Callable[[], BatchVerifier]] = {}
_default_lock = threading.Lock()
_default_name: str | None = None
_calibrated_min: int | None = None


def set_calibrated_batch_min(n: int) -> None:
    """Record the MEASURED device break-even (verify.warmup calibrates:
    one compiled-dispatch round trip vs the serial per-signature cost on
    the hardware actually attached). Consulted whenever TM_TPU_BATCH_MIN
    is not explicitly set, so the device is only used where it wins —
    e.g. a remote-tunnel TPU with ~64ms round trips calibrates to
    hundreds, while direct-attached hardware calibrates to ~tens."""
    global _calibrated_min
    with _default_lock:
        _calibrated_min = max(1, int(n))


def calibrated_batch_min() -> int | None:
    with _default_lock:
        return _calibrated_min


def effective_batch_min(default: int = 16) -> int:
    """The adaptive cutoff: explicit TM_TPU_BATCH_MIN wins, then the
    warmup-measured calibration, then the static default."""
    env = os.environ.get("TM_TPU_BATCH_MIN")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass  # malformed env must never take down verification
    with _default_lock:
        if _calibrated_min is not None:
            return _calibrated_min
    return default


def register_backend(name: str, factory: Callable[[], BatchVerifier]) -> None:
    _registry[name] = factory


def backends() -> List[str]:
    return sorted(_registry)


def set_default_backend(name: str) -> None:
    global _default_name
    if name not in _registry:
        raise KeyError(f"unknown batch-verify backend {name!r}; have {backends()}")
    with _default_lock:
        _default_name = name


def default_backend_name() -> str:
    global _default_name
    with _default_lock:
        if _default_name is None:
            env = os.environ.get("TM_TPU_CRYPTO_BACKEND")
            if env and env in _registry:
                _default_name = env
            elif "adaptive" in _registry:
                _default_name = "adaptive"
            elif "jax" in _registry:
                _default_name = "jax"
            else:
                _default_name = "cpu"
        return _default_name


def new_batch_verifier(name: str | None = None) -> BatchVerifier:
    if name is None:
        name = default_backend_name()
    try:
        factory = _registry[name]
    except KeyError:
        raise KeyError(f"unknown batch-verify backend {name!r}; have {backends()}")
    return factory()


def batch_verify(
    triples: Sequence[Triple], backend: str | None = None
) -> List[bool]:
    bv = new_batch_verifier(backend)
    for msg, sig, pk in triples:
        bv.add(msg, sig, pk)
    return bv.verify()


register_backend("cpu", CPUBatchVerifier)


def _register_jax_backend():
    """Deferred so importing tendermint_tpu.crypto never forces jax init."""
    try:
        from .jaxed25519.verify import JAXBatchVerifier
    except ImportError as e:
        import logging

        logging.getLogger(__name__).warning(
            "jax batch-verify backend unavailable, falling back to cpu: %s", e
        )
        return
    register_backend("jax", JAXBatchVerifier)
    register_backend(
        "adaptive", lambda: AdaptiveBatchVerifier(JAXBatchVerifier)
    )


_register_jax_backend()
