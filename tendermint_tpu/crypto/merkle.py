"""Merkle trees and inclusion proofs (RFC-6962 style).

Capability parity with the reference's crypto/merkle/simple_tree.go:23
(SimpleHashFromByteSlices), simple_proof.go:70 (SimpleProof.Verify), and
proof.go (ProofOperators for ABCI query proofs). We use domain-separated
leaf/inner hashing (0x00 / 0x01 prefixes) and the same largest-power-of-two
split rule, so proofs are position-binding.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Sequence

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Root hash of the simple tree over items. Empty tree hashes to
    SHA256 of the empty string, matching an unambiguous fixed value."""
    n = len(items)
    if n == 0:
        return _sha256(b"")
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:]))


def hash_from_map(m: Dict[str, bytes]) -> bytes:
    """Deterministic root over a str->bytes map (sorted by key), used for
    header app-level maps (reference types/block.go Header.Hash uses a
    simple map hasher)."""
    kvs = []
    for key in sorted(m):
        kvs.append(leaf_hash(key.encode()) + leaf_hash(m[key]))
    return hash_from_byte_slices(kvs)


@dataclass
class SimpleProof:
    """Inclusion proof for item `index` of `total` leaves.

    aunts are sibling hashes from leaf level up to the root.
    """

    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def compute_root(self) -> bytes:
        return _compute_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total <= 0 or not (0 <= self.index < self.total):
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = self.compute_root()
        return computed is not None and computed == root


def _compute_from_aunts(index, total, leaf, aunts):
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        return leaf if not aunts else None
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]):
    """Returns (root, [SimpleProof per item])."""
    trails, root_node = _trails_from_byte_slices(list(items))
    root = root_node.hash if root_node else _sha256(b"")
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(
            SimpleProof(
                total=len(items),
                index=i,
                leaf_hash=trail.hash,
                aunts=trail.flatten_aunts(),
            )
        )
    return root, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h):
        self.hash = h
        self.parent = None
        self.left = None  # sibling on the left
        self.right = None  # sibling on the right

    def flatten_aunts(self):
        aunts = []
        node = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            elif node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_byte_slices(items):
    n = len(items)
    if n == 0:
        return [], None
    if n == 1:
        node = _Node(leaf_hash(items[0]))
        return [node], node
    k = _split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root


# --- proof operators (ABCI query proof chaining) ---------------------------


class ProofOp:
    """One verification step: takes child value(s), returns parent value."""

    type: str = ""

    def run(self, values: List[bytes]) -> List[bytes]:
        raise NotImplementedError

    def get_key(self) -> bytes:
        return b""


@dataclass
class SimpleValueOp(ProofOp):
    """Proves value at key is included in a simple tree with given root."""

    key: bytes
    proof: SimpleProof
    type: str = "simple:v"

    def run(self, values: List[bytes]) -> List[bytes]:
        if len(values) != 1:
            raise ValueError("SimpleValueOp expects one value")
        vhash = _sha256(values[0])
        # leaf is encoded as key/value-hash pair
        kv = _encode_lenprefixed(self.key) + _encode_lenprefixed(vhash)
        if leaf_hash(kv) != self.proof.leaf_hash:
            raise ValueError("leaf hash mismatch")
        root = self.proof.compute_root()
        if root is None:
            raise ValueError("bad proof")
        return [root]

    def get_key(self) -> bytes:
        return self.key


def _encode_lenprefixed(b: bytes) -> bytes:
    out = bytearray()
    n = len(b)
    while True:
        bb = n & 0x7F
        n >>= 7
        if n:
            out.append(bb | 0x80)
        else:
            out.append(bb)
            break
    return bytes(out) + b


class ProofOperators(list):
    def verify_value(self, root: bytes, keypath: List[bytes], value: bytes) -> bool:
        return self.verify(root, keypath, [value])

    def verify(self, root: bytes, keypath: List[bytes], args: List[bytes]) -> bool:
        keys = list(keypath)
        for op in self:
            key = op.get_key()
            if key:
                if not keys or keys[-1] != key:
                    return False
                keys = keys[:-1]
            try:
                args = op.run(args)
            except ValueError:
                return False
        return bool(args) and args[0] == root and not keys
