"""Key interfaces and the Ed25519 implementation.

Capability parity with the reference's crypto/crypto.go:22-34 (PubKey /
PrivKey interfaces) and crypto/ed25519/ed25519.go (64-byte privkey =
seed || pubkey; SHA256-20 addresses). Single-signature sign/verify runs on
CPU via the `cryptography` package (OpenSSL); bulk verification routes
through crypto.batch.BatchVerifier, whose TPU backend is the framework's
north-star kernel (see crypto/jaxed25519/).
"""

from __future__ import annotations

import hmac
from dataclasses import dataclass

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
except ImportError:  # no OpenSSL bindings: pure-Python RFC 8032 fallback
    from ._ed25519_fallback import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
        InvalidSignature,
    )

from . import tmhash

ED25519_PUBKEY_SIZE = 32
ED25519_PRIVKEY_SIZE = 64  # seed (32) || pubkey (32), as in the reference
ED25519_SIGNATURE_SIZE = 64
ADDRESS_SIZE = tmhash.TRUNCATED_SIZE


class PubKey:
    """Interface: Address() Bytes() VerifyBytes(msg, sig) Equals()."""

    def address(self) -> bytes:
        raise NotImplementedError

    def bytes(self) -> bytes:
        raise NotImplementedError

    def verify_bytes(self, msg: bytes, sig: bytes) -> bool:
        raise NotImplementedError

    def __eq__(self, other) -> bool:
        return isinstance(other, PubKey) and self.bytes() == other.bytes()

    def __hash__(self):
        return hash(self.bytes())


class PrivKey:
    """Interface: Bytes() Sign(msg) PubKey() Equals()."""

    def bytes(self) -> bytes:
        raise NotImplementedError

    def sign(self, msg: bytes) -> bytes:
        raise NotImplementedError

    def pub_key(self) -> PubKey:
        raise NotImplementedError

    def __eq__(self, other) -> bool:
        return isinstance(other, PrivKey) and hmac.compare_digest(
            self.bytes(), other.bytes()
        )

    def __hash__(self):
        return hash(self.bytes())


@dataclass(frozen=True)
class PubKeyEd25519(PubKey):
    data: bytes  # 32 raw bytes

    def __post_init__(self):
        if len(self.data) != ED25519_PUBKEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be {ED25519_PUBKEY_SIZE} bytes")

    def address(self) -> bytes:
        return tmhash.sum_truncated(self.data)

    def bytes(self) -> bytes:
        return self.data

    def verify_bytes(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != ED25519_SIGNATURE_SIZE:
            return False
        try:
            Ed25519PublicKey.from_public_bytes(self.data).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False

    def __eq__(self, other):
        return PubKey.__eq__(self, other)

    def __hash__(self):
        return PubKey.__hash__(self)


@dataclass(frozen=True)
class PrivKeyEd25519(PrivKey):
    data: bytes  # 64 bytes: seed || pubkey

    def __post_init__(self):
        if len(self.data) != ED25519_PRIVKEY_SIZE:
            raise ValueError(f"ed25519 privkey must be {ED25519_PRIVKEY_SIZE} bytes")
        derived = (
            Ed25519PrivateKey.from_private_bytes(self.data[:32])
            .public_key()
            .public_bytes_raw()
        )
        if derived != self.data[32:]:
            raise ValueError("ed25519 privkey pubkey half does not match seed")

    @staticmethod
    def generate() -> "PrivKeyEd25519":
        sk = Ed25519PrivateKey.generate()
        seed = sk.private_bytes_raw()
        pub = sk.public_key().public_bytes_raw()
        return PrivKeyEd25519(seed + pub)

    @staticmethod
    def from_seed(seed: bytes) -> "PrivKeyEd25519":
        sk = Ed25519PrivateKey.from_private_bytes(seed)
        pub = sk.public_key().public_bytes_raw()
        return PrivKeyEd25519(seed + pub)

    @staticmethod
    def gen_from_secret(secret: bytes) -> "PrivKeyEd25519":
        """Deterministic key from a secret (test fixtures; reference
        crypto/ed25519/ed25519.go GenPrivKeyFromSecret)."""
        return PrivKeyEd25519.from_seed(tmhash.sum(secret))

    def bytes(self) -> bytes:
        return self.data

    def seed(self) -> bytes:
        return self.data[:32]

    def sign(self, msg: bytes) -> bytes:
        return Ed25519PrivateKey.from_private_bytes(self.data[:32]).sign(msg)

    def pub_key(self) -> PubKeyEd25519:
        return PubKeyEd25519(self.data[32:])

    def __eq__(self, other):
        return PrivKey.__eq__(self, other)

    def __hash__(self):
        return PrivKey.__hash__(self)


# --- key (de)serialization -------------------------------------------------
# The reference uses amino type-prefixed bytes; we use a 1-byte type tag.

TYPE_ED25519 = 0x01
TYPE_SECP256K1 = 0x02
TYPE_MULTISIG = 0x03
TYPE_BLS12381 = 0x04

# key-type names accepted by genesis / priv_validator / [crypto] config
KEY_TYPE_ED25519 = "ed25519"
KEY_TYPE_BLS12381 = "bls12381"


def generate_priv_key(key_type: str = KEY_TYPE_ED25519) -> PrivKey:
    """Key-type registry entry point for config/CLI plumbing."""
    if key_type == KEY_TYPE_ED25519:
        return PrivKeyEd25519.generate()
    if key_type == KEY_TYPE_BLS12381:
        from .bls import PrivKeyBLS12381

        return PrivKeyBLS12381.generate()
    raise ValueError(
        f"unknown key type {key_type!r}; have "
        f"{KEY_TYPE_ED25519!r}, {KEY_TYPE_BLS12381!r}")


def key_type_of(pk) -> str:
    """Canonical key-type name of a PubKey or PrivKey instance."""
    from .bls import PrivKeyBLS12381, PubKeyBLS12381

    if isinstance(pk, (PubKeyBLS12381, PrivKeyBLS12381)):
        return KEY_TYPE_BLS12381
    return KEY_TYPE_ED25519


def pubkey_to_bytes(pk: PubKey) -> bytes:
    from .bls import PubKeyBLS12381
    from .multisig import PubKeyMultisigThreshold
    from .secp256k1 import PubKeySecp256k1

    if isinstance(pk, PubKeyEd25519):
        return bytes([TYPE_ED25519]) + pk.data
    if isinstance(pk, PubKeySecp256k1):
        return bytes([TYPE_SECP256K1]) + pk.data
    if isinstance(pk, PubKeyMultisigThreshold):
        return bytes([TYPE_MULTISIG]) + pk.bytes()
    if isinstance(pk, PubKeyBLS12381):
        return bytes([TYPE_BLS12381]) + pk.data
    raise TypeError(f"unknown pubkey type {type(pk)}")


def pubkey_from_bytes(data: bytes) -> PubKey:
    if not data:
        raise ValueError("empty pubkey bytes")
    if data[0] == TYPE_ED25519:
        return PubKeyEd25519(data[1:])
    if data[0] == TYPE_SECP256K1:
        from .secp256k1 import PubKeySecp256k1

        return PubKeySecp256k1(data[1:])
    if data[0] == TYPE_MULTISIG:
        from .multisig import PubKeyMultisigThreshold

        return PubKeyMultisigThreshold.from_bytes(data[1:])
    if data[0] == TYPE_BLS12381:
        from .bls import PubKeyBLS12381

        return PubKeyBLS12381(data[1:])
    raise ValueError(f"unknown pubkey type tag {data[0]:#x}")


def privkey_to_bytes(sk: PrivKey) -> bytes:
    from .bls import PrivKeyBLS12381
    from .secp256k1 import PrivKeySecp256k1

    if isinstance(sk, PrivKeyEd25519):
        return bytes([TYPE_ED25519]) + sk.data
    if isinstance(sk, PrivKeySecp256k1):
        return bytes([TYPE_SECP256K1]) + sk.data
    if isinstance(sk, PrivKeyBLS12381):
        return bytes([TYPE_BLS12381]) + sk.data
    raise TypeError(f"unknown privkey type {type(sk)}")


def privkey_from_bytes(data: bytes) -> PrivKey:
    if not data:
        raise ValueError("empty privkey bytes")
    if data[0] == TYPE_ED25519:
        return PrivKeyEd25519(data[1:])
    if data[0] == TYPE_SECP256K1:
        from .secp256k1 import PrivKeySecp256k1

        return PrivKeySecp256k1(data[1:])
    if data[0] == TYPE_BLS12381:
        from .bls import PrivKeyBLS12381

        return PrivKeyBLS12381(data[1:])
    raise ValueError(f"unknown privkey type tag {data[0]:#x}")
