"""Threshold multisig pubkeys + compact bit arrays (reference
crypto/multisig/threshold_pubkey.go + bitarray/compact_bit_array.go).

A K-of-N pubkey: verification succeeds when the multisignature carries
≥K valid signatures from distinct member keys, positions flagged in a
compact bit array.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List

from ..types import serde
from . import tmhash
from .keys import PubKey, pubkey_from_bytes, pubkey_to_bytes


class CompactBitArray:
    """bitarray/compact_bit_array.go: bits packed into bytes, MSB
    first, with the true size carried separately."""

    def __init__(self, size: int):
        if size < 0:
            raise ValueError("negative size")
        self.size = size
        self.elems = bytearray((size + 7) // 8)

    def get_index(self, i: int) -> bool:
        if not 0 <= i < self.size:
            return False
        return bool(self.elems[i >> 3] & (1 << (7 - (i & 7))))

    def set_index(self, i: int, v: bool) -> bool:
        if not 0 <= i < self.size:
            return False
        if v:
            self.elems[i >> 3] |= 1 << (7 - (i & 7))
        else:
            self.elems[i >> 3] &= ~(1 << (7 - (i & 7)))
        return True

    def num_true_bits_before(self, index: int) -> int:
        """compact_bit_array.go NumTrueBitsBefore — the signature slot
        for member `index`."""
        return sum(1 for i in range(index) if self.get_index(i))

    def count_true(self) -> int:
        return self.num_true_bits_before(self.size)

    def to_bytes(self) -> bytes:
        return serde.pack([self.size, bytes(self.elems)])

    @classmethod
    def from_bytes(cls, raw: bytes) -> "CompactBitArray":
        size, elems = serde.unpack(raw)
        ba = cls(size)
        if len(elems) != len(ba.elems):
            raise ValueError(
                f"bit array size {size} needs {len(ba.elems)} bytes, "
                f"got {len(elems)}")
        ba.elems = bytearray(elems)
        return ba

    def __eq__(self, other):
        return (isinstance(other, CompactBitArray)
                and self.size == other.size and self.elems == other.elems)


@dataclass
class Multisignature:
    """multisig/multisignature.go: bit array + ordered sub-signatures."""

    bit_array: CompactBitArray
    sigs: List[bytes] = field(default_factory=list)

    def add_signature_from_pubkey(self, sig: bytes, pubkey: PubKey,
                                  keys: List[PubKey]) -> None:
        index = next(
            (i for i, k in enumerate(keys) if k.bytes() == pubkey.bytes()),
            -1)
        if index < 0:
            raise ValueError("pubkey not in multisig key list")
        slot = self.bit_array.num_true_bits_before(index)
        if self.bit_array.get_index(index):
            self.sigs[slot] = sig  # replace
            return
        self.bit_array.set_index(index, True)
        self.sigs.insert(slot, sig)

    def marshal(self) -> bytes:
        return serde.pack([self.bit_array.to_bytes(), list(self.sigs)])

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Multisignature":
        ba_raw, sigs = serde.unpack(raw)
        return cls(bit_array=CompactBitArray.from_bytes(ba_raw),
                   sigs=[bytes(s) for s in sigs])


@dataclass(frozen=True)
class PubKeyMultisigThreshold(PubKey):
    """threshold_pubkey.go:10-60: K-of-N."""

    k: int
    pubkeys: tuple  # tuple[PubKey, ...]

    def __post_init__(self):
        if self.k < 1:
            raise ValueError("threshold k must be > 0")
        if len(self.pubkeys) < self.k:
            raise ValueError("len(pubkeys) < k")

    def bytes(self) -> bytes:
        return serde.pack(
            ["multisig", self.k,
             [pubkey_to_bytes(pk) for pk in self.pubkeys]])

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PubKeyMultisigThreshold":
        tag, k, pks = serde.unpack(raw)
        if tag != "multisig":
            raise ValueError("not a multisig pubkey")
        return cls(k=k, pubkeys=tuple(pubkey_from_bytes(bytes(b))
                                      for b in pks))

    def address(self) -> bytes:
        return tmhash.sum_truncated(self.bytes())

    def verify_bytes(self, msg: bytes, sig: bytes) -> bool:
        """threshold_pubkey.go VerifyBytes:24-57."""
        try:
            ms = Multisignature.unmarshal(sig)
        except Exception:  # noqa: BLE001 - malformed multisig blob
            return False
        size = ms.bit_array.size
        if len(self.pubkeys) != size:
            return False
        if len(ms.sigs) < self.k or ms.bit_array.count_true() != len(ms.sigs):
            return False
        sig_index = 0
        for i in range(size):
            if not ms.bit_array.get_index(i):
                continue
            if not self.pubkeys[i].verify_bytes(msg, ms.sigs[sig_index]):
                return False
            sig_index += 1
        return sig_index >= self.k

    def equals(self, other) -> bool:
        return (isinstance(other, PubKeyMultisigThreshold)
                and self.k == other.k
                and len(self.pubkeys) == len(other.pubkeys)
                and all(a.bytes() == b.bytes()
                        for a, b in zip(self.pubkeys, other.pubkeys)))
