"""Verified-signature cache — sharded, bounded LRU over verify verdicts.

The same (msg, sig, pubkey) triple is verified up to three times in a
vote's lifetime — live pre-verification in the consensus receive loop,
commit reconstruction via VoteSet.add_votes, and
ValidatorSet.verify_commit — plus once more per duplicate gossip
delivery. Ed25519 verification is a pure function of the triple, so the
verdict can be memoized: BatchVerifier.verify() consults this cache and
only dispatches the cache-miss subset to the backend (arXiv:2302.00418
measures exactly this redundant re-verification as a first-order cost
in committee consensus).

Design notes:
- Keyed by sha256(msg ‖ sig ‖ pubkey). sig (64B) and pubkey (32B) are
  fixed length and form the suffix, so the concatenation is injective
  even though msg is variable length. Storing the 32-byte digest rather
  than the triple bounds memory at ~100B/entry regardless of message
  size.
- BOTH verdicts are cached. A False verdict is as deterministic as a
  True one, and caching it means a replayed bad signature costs one
  dict lookup instead of one device dispatch (cheap DoS resistance).
  An invalid signature can therefore never be cached as valid — the
  stored verdict is exactly what the backend returned for that triple.
- Sharded: the key's first byte picks a shard, each with its own lock
  and LRU (OrderedDict), so the consensus receive loop, fast-sync pool
  thread, and async dispatch threads don't serialize on one mutex.
- Bounded: per-shard capacity = capacity // shards; least-recently-used
  entries are evicted on insert. Hit/miss counters are maintained under
  the shard locks (exact, cheap) for bench/metrics reporting.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import List, Optional

DEFAULT_SHARDS = 8


class SigCache:
    def __init__(self, capacity: int, shards: int = DEFAULT_SHARDS):
        if capacity < 1:
            raise ValueError("SigCache capacity must be >= 1")
        shards = max(1, min(int(shards), int(capacity)))
        self._per_shard_cap = max(1, int(capacity) // shards)
        self._shards: List[OrderedDict] = [OrderedDict() for _ in range(shards)]
        self._locks = [threading.Lock() for _ in range(shards)]
        self._hits = [0] * shards
        self._misses = [0] * shards

    @property
    def capacity(self) -> int:
        return self._per_shard_cap * len(self._shards)

    @staticmethod
    def key(msg: bytes, sig: bytes, pk: bytes) -> bytes:
        """Digest of the triple. sig+pk are a fixed-length (96B) suffix,
        so msg ‖ sig ‖ pk is an injective encoding."""
        return hashlib.sha256(msg + sig + pk).digest()

    def _idx(self, key: bytes) -> int:
        return key[0] % len(self._shards)

    def get(self, key: bytes) -> Optional[bool]:
        """Cached verdict for `key`, or None on miss. A hit refreshes
        the entry's LRU position."""
        i = self._idx(key)
        with self._locks[i]:
            shard = self._shards[i]
            v = shard.get(key)
            if v is None:
                self._misses[i] += 1
                return None
            shard.move_to_end(key)
            self._hits[i] += 1
            return v

    def peek(self, key: bytes) -> Optional[bool]:
        """Like get(), but stats-neutral: no hit/miss counting and no
        LRU refresh. For callers that only need to KNOW whether a triple
        is cached (e.g. the adaptive router sizing the miss subset)
        without double-counting the lookup the verify template will do."""
        i = self._idx(key)
        with self._locks[i]:
            return self._shards[i].get(key)

    def put(self, key: bytes, verdict: bool) -> None:
        i = self._idx(key)
        with self._locks[i]:
            shard = self._shards[i]
            shard[key] = bool(verdict)
            shard.move_to_end(key)
            while len(shard) > self._per_shard_cap:
                shard.popitem(last=False)

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    @property
    def hits(self) -> int:
        return sum(self._hits)

    @property
    def misses(self) -> int:
        return sum(self._misses)

    def clear(self) -> None:
        for i, lock in enumerate(self._locks):
            with lock:
                self._shards[i].clear()
