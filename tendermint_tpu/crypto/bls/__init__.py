"""BLS12-381 min-pubkey signatures (48-byte G1 pubkeys, 96-byte G2
signatures) — the aggregate-signature fast lane.

Scheme layout follows draft-irtf-cfrg-bls-signature (min-pubkey-size,
proof-of-possession scheme): sign(sk, m) = [sk] H(m) with H = hash-to-G2
(hash_to_curve.py; RFC 9380 structure, SvdW map — see the deviation note
there), verify via the 2-pairing product check, aggregation = one G2
point addition per signature, and fast_aggregate_verify (same-message
aggregate: exactly the commit-certificate shape) = ONE pubkey MSM + ONE
2-pairing check regardless of committee size. Rogue-key attacks are
blocked by proof-of-possession registration: aggregate verification is
only sound over keys whose PoP was checked, so the registry refuses
unproven keys and ValidatorSet construction enforces registration for
BLS validator sets.

Point parsing is cached process-wide (decompression + subgroup check
are the per-object costs; gossip re-delivery then costs a dict hit).
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence

from .. import tmhash
from ..keys import PrivKey, PubKey
from . import msm
from .curve import (
    G1_GEN,
    G2Point,
    g1_compress,
    g1_decompress,
    g1_in_subgroup,
    g1_neg,
    g1_mul,
    g1_to_affine,
    g2_add,
    g2_compress,
    g2_decompress,
    g2_in_subgroup,
    g2_mul,
)
from .fields import R_ORDER
from .hash_to_curve import hash_to_g2
from .pairing import pairing_product_is_one

BLS_PUBKEY_SIZE = 48
BLS_PRIVKEY_SIZE = 32
BLS_SIGNATURE_SIZE = 96

# ciphersuite DSTs (names kept from the Eth2 / draft-irtf ciphersuite;
# the curve map deviation is documented in hash_to_curve.py)
DST_SIG = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

_NEG_G1_GEN = g1_neg(G1_GEN)


class _PointCache:
    """Tiny thread-safe LRU: compressed bytes -> (point, in_subgroup)."""

    def __init__(self, maxsize: int = 16384):
        self._d: OrderedDict = OrderedDict()
        self._max = maxsize
        self._lock = threading.Lock()

    def get(self, key: bytes):
        with self._lock:
            v = self._d.get(key)
            if v is not None:
                self._d.move_to_end(key)
            return v

    def put(self, key: bytes, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self._max:
                self._d.popitem(last=False)


_g1_cache = _PointCache()
_g2_cache = _PointCache()


def _parse_pubkey_point(data: bytes):
    """48 compressed bytes -> affine (x, y) in the G1 subgroup, or None
    for invalid/infinity/out-of-subgroup encodings."""
    hit = _g1_cache.get(data)
    if hit is not None:
        return hit[0] if hit[1] else None
    try:
        pt = g1_decompress(data)
    except ValueError:
        _g1_cache.put(data, (None, False))
        return None
    if pt is None or not g1_in_subgroup(pt):
        _g1_cache.put(data, (None, False))
        return None
    aff = g1_to_affine(pt)
    _g1_cache.put(data, (aff, True))
    return aff


def _parse_signature_point(data: bytes) -> Optional[G2Point]:
    hit = _g2_cache.get(data)
    if hit is not None:
        return hit[0] if hit[1] else None
    try:
        pt = g2_decompress(data)
    except ValueError:
        _g2_cache.put(data, (None, False))
        return None
    if pt is None or not g2_in_subgroup(pt):
        _g2_cache.put(data, (None, False))
        return None
    _g2_cache.put(data, (pt, True))
    return pt


# --- proof-of-possession registry -------------------------------------
# fast_aggregate_verify is only rogue-key-safe over keys that proved
# possession. Registration verifies the PoP once; the valset layer
# refuses BLS keys that never registered.

_pop_registry: set = set()
_pop_lock = threading.Lock()


def pop_prove(priv: "PrivKeyBLS12381") -> bytes:
    """PoP = sign the pubkey bytes under the POP DST."""
    pk = priv.pub_key().data
    sk = int.from_bytes(priv.data, "big") % R_ORDER
    return g2_compress(g2_mul(hash_to_g2(pk, DST_POP), sk))


def pop_verify(pubkey: bytes, proof: bytes) -> bool:
    pk_pt = _parse_pubkey_point(pubkey)
    sig_pt = _parse_signature_point(proof)
    if pk_pt is None or sig_pt is None:
        return False
    hm = hash_to_g2(pubkey, DST_POP)
    return pairing_product_is_one(
        [((pk_pt[0], pk_pt[1], 1), hm), (_NEG_G1_GEN, sig_pt)]
    )


def register_proof_of_possession(pubkey: bytes, proof: bytes) -> bool:
    """Verify + record a key's PoP; aggregate paths only trust
    registered keys. Returns False (and records nothing) on a bad
    proof."""
    with _pop_lock:
        if pubkey in _pop_registry:
            return True
    if not pop_verify(pubkey, proof):
        return False
    with _pop_lock:
        _pop_registry.add(pubkey)
    return True


def pop_registered(pubkey: bytes) -> bool:
    with _pop_lock:
        return pubkey in _pop_registry


def register_pop_trusted(pubkey: bytes) -> None:
    """Harness-only: record a key as possession-proven WITHOUT checking
    a proof. Scenario fixtures with thousands of phantom validators use
    this to skip ~2 pairings per key at genesis load; the phantoms never
    sign, so nothing downstream ever relies on their proofs. Never call
    this for keys that arrived on the wire."""
    _register_pop_unchecked(pubkey)


_pop_verify_cache = _PointCache(4096)


def pop_verify_cached(pubkey: bytes, proof: bytes) -> bool:
    """pop_verify behind a bounded LRU memo, for proofs arriving on the
    wire (lite / statesync valsets). Unlike register_proof_of_possession
    this adds NOTHING to the process-wide registry: an untrusted source
    streaming valsets of fresh keys with valid PoPs must not grow
    process memory without bound, and each (key, proof) pair costs at
    most one pairing before the memo answers replays."""
    # length-gate BEFORE caching: the key embeds the wire-supplied
    # proof, so an oversized proof would occupy oversized memo entries
    # (4096 × attacker-chosen bytes); real encodings have fixed sizes
    if len(pubkey) != BLS_PUBKEY_SIZE or len(proof) != BLS_SIGNATURE_SIZE:
        return False
    key = pubkey + proof
    hit = _pop_verify_cache.get(key)
    if hit is not None:
        return hit
    ok = pop_verify(pubkey, proof)
    _pop_verify_cache.put(key, ok)
    return ok


def _register_pop_unchecked(pubkey: bytes) -> None:
    """Key generated locally from its secret — possession is intrinsic
    (used by PrivKeyBLS12381.pub_key so self-generated keys can always
    participate)."""
    with _pop_lock:
        _pop_registry.add(pubkey)


# --- key types (crypto.keys interface) --------------------------------


@dataclass(frozen=True)
class PubKeyBLS12381(PubKey):
    data: bytes  # 48 compressed G1 bytes

    def __post_init__(self):
        if len(self.data) != BLS_PUBKEY_SIZE:
            raise ValueError(f"bls12381 pubkey must be {BLS_PUBKEY_SIZE} bytes")

    def address(self) -> bytes:
        return tmhash.sum_truncated(self.data)

    def bytes(self) -> bytes:
        return self.data

    def verify_bytes(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != BLS_SIGNATURE_SIZE:
            return False
        pk_pt = _parse_pubkey_point(self.data)
        sig_pt = _parse_signature_point(sig)
        if pk_pt is None or sig_pt is None:
            return False
        hm = hash_to_g2(msg, DST_SIG)
        return pairing_product_is_one(
            [((pk_pt[0], pk_pt[1], 1), hm), (_NEG_G1_GEN, sig_pt)]
        )

    def __eq__(self, other):
        return PubKey.__eq__(self, other)

    def __hash__(self):
        return PubKey.__hash__(self)


@dataclass(frozen=True)
class PrivKeyBLS12381(PrivKey):
    data: bytes  # 32-byte big-endian scalar in [1, r)

    def __post_init__(self):
        if len(self.data) != BLS_PRIVKEY_SIZE:
            raise ValueError(f"bls12381 privkey must be {BLS_PRIVKEY_SIZE} bytes")
        if int.from_bytes(self.data, "big") % R_ORDER == 0:
            raise ValueError("bls12381 privkey scalar is zero mod r")

    @staticmethod
    def generate() -> "PrivKeyBLS12381":
        import secrets

        while True:
            sk = secrets.randbits(380) % R_ORDER
            if sk:
                return PrivKeyBLS12381(sk.to_bytes(32, "big"))

    @staticmethod
    def gen_from_secret(secret: bytes) -> "PrivKeyBLS12381":
        """Deterministic key from a secret (test fixtures; mirrors
        PrivKeyEd25519.gen_from_secret)."""
        seed = hashlib.sha512(b"bls12381-keygen" + secret).digest()
        sk = int.from_bytes(seed, "big") % R_ORDER
        if sk == 0:  # pragma: no cover - probability 2^-255
            sk = 1
        return PrivKeyBLS12381(sk.to_bytes(32, "big"))

    def bytes(self) -> bytes:
        return self.data

    def sign(self, msg: bytes) -> bytes:
        sk = int.from_bytes(self.data, "big") % R_ORDER
        return g2_compress(g2_mul(hash_to_g2(msg, DST_SIG), sk))

    def pub_key(self) -> PubKeyBLS12381:
        sk = int.from_bytes(self.data, "big") % R_ORDER
        pk = g1_compress(g1_mul(G1_GEN, sk))
        _register_pop_unchecked(pk)
        return PubKeyBLS12381(pk)

    def pop_prove(self) -> bytes:
        return pop_prove(self)

    def __eq__(self, other):
        return PrivKey.__eq__(self, other)

    def __hash__(self):
        return PrivKey.__hash__(self)


# --- aggregation -------------------------------------------------------


def aggregate_signatures(sigs: Sequence[bytes]) -> bytes:
    """Sum the G2 signature points; raises on malformed input (callers
    aggregate only signatures they individually accepted)."""
    if not sigs:
        raise ValueError("cannot aggregate zero signatures")
    acc: G2Point = None
    for s in sigs:
        pt = _parse_signature_point(s)
        if pt is None:
            raise ValueError("cannot aggregate invalid signature")
        acc = g2_add(acc, pt)
    return g2_compress(acc)


def aggregate_pubkeys(pubkeys: Sequence[bytes], backend: Optional[str] = None):
    """Bitmap-selected pubkey aggregation: the MSM kernel input. Returns
    a Jacobian G1 point or None; invalid keys raise."""
    pts = []
    for pk in pubkeys:
        aff = _parse_pubkey_point(pk)
        if aff is None:
            raise ValueError("cannot aggregate invalid pubkey")
        pts.append(aff)
    return msm.aggregate_points(pts, backend=backend)


def fast_aggregate_verify(
    pubkeys: Sequence[bytes], msg: bytes, signature: bytes,
    backend: Optional[str] = None, require_pop: bool = True,
) -> bool:
    """Same-message aggregate verification: one pubkey MSM + one
    2-pairing product check — O(1) pairings for any committee size.

    require_pop (default) refuses the check unless every key registered
    a proof of possession: fast aggregate verification without PoP is
    exactly the rogue-key attack surface."""
    if not pubkeys:
        return False
    if len(signature) != BLS_SIGNATURE_SIZE:
        return False
    if require_pop and not all(pop_registered(pk) for pk in pubkeys):
        return False
    sig_pt = _parse_signature_point(signature)
    if sig_pt is None:
        return False
    t0 = time.perf_counter()
    try:
        agg_pk = aggregate_pubkeys(pubkeys, backend=backend)
    except ValueError:
        return False
    if agg_pk is None:  # keys summed to infinity (attack-shaped input)
        return False
    hm = hash_to_g2(msg, DST_SIG)
    ok = pairing_product_is_one([(agg_pk, hm), (_NEG_G1_GEN, sig_pt)])
    _record_agg_metrics(time.perf_counter() - t0, len(pubkeys))
    return ok


def verify_aggregates_many(
    items: Sequence[tuple], backend: Optional[str] = None,
    require_pop: bool = False,
) -> List[bool]:
    """Verify k same-message aggregate certificates in ONE multi-pair
    product check (2k pairs through a single shared-squaring Miller
    loop + one final exponentiation) instead of k sequential 2-pairing
    checks. items = [(pubkeys, msg, signature), ...]; returns one
    verdict per item, order-aligned.

    Soundness rides a random linear combination: each certificate i is
    scaled by an independent 128-bit scalar r_i and the combined check
    prod_i e(r_i*agg_pk_i, H(m_i)) * e(r_i*(-G1), sig_i) == 1 holds iff
    every per-certificate relation holds, except with probability
    ~2^-128 over the scalars. Scalars come from a Fiat-Shamir sha256
    transcript of every batched input — deterministic and replayable,
    no RNG in the verify path — and ride the G1 side only (two cheap
    G1 muls per certificate; the G2 points are untouched). r_0 is
    pinned to 1 so the first certificate's muls are free. If the
    combined check fails, each batched item is re-verified alone so
    callers still get exact per-certificate verdicts (the slow path
    only runs when something IS invalid).

    require_pop defaults False here (unlike fast_aggregate_verify):
    every call site — statesync anchor commits, replica catch-up
    certificates, Handel level contributions — verifies against a
    hash-chained valset whose keys passed proof-of-possession at
    registration time."""
    items = list(items)
    if not items:
        return []
    if len(items) == 1:
        pks, msg, sig = items[0]
        return [fast_aggregate_verify(pks, msg, sig, backend=backend,
                                      require_pop=require_pop)]
    t0 = time.perf_counter()
    verdicts: List[Optional[bool]] = [None] * len(items)
    parsed = []  # (item index, agg_pk, H(m), sig point)
    hm_memo = {}  # distinct messages hash once per call
    for i, (pks, msg, sig) in enumerate(items):
        if not pks or len(sig) != BLS_SIGNATURE_SIZE:
            verdicts[i] = False
            continue
        if require_pop and not all(pop_registered(pk) for pk in pks):
            verdicts[i] = False
            continue
        sig_pt = _parse_signature_point(sig)
        if sig_pt is None:
            verdicts[i] = False
            continue
        try:
            agg_pk = aggregate_pubkeys(pks, backend=backend)
        except ValueError:
            verdicts[i] = False
            continue
        if agg_pk is None:  # keys summed to infinity (attack-shaped)
            verdicts[i] = False
            continue
        hm = hm_memo.get(msg)
        if hm is None:
            hm = hash_to_g2(msg, DST_SIG)
            hm_memo[msg] = hm
        parsed.append((i, agg_pk, hm, sig_pt))
    if parsed:
        tr = hashlib.sha256()
        for i, _, _, _ in parsed:
            pks, msg, sig = items[i]
            tr.update(len(pks).to_bytes(4, "big"))
            for pk in pks:
                tr.update(pk)
            tr.update(len(msg).to_bytes(4, "big"))
            tr.update(msg)
            tr.update(sig)
        seed = tr.digest()
        pairs = []
        total_signers = 0
        for k, (i, agg_pk, hm, sig_pt) in enumerate(parsed):
            total_signers += len(items[i][0])
            if k == 0:
                r = 1
            else:
                r = int.from_bytes(
                    hashlib.sha256(seed + k.to_bytes(4, "big")).digest()[:16],
                    "big") or 1
            if r == 1:
                pairs.append((agg_pk, hm))
                pairs.append((_NEG_G1_GEN, sig_pt))
            else:
                pairs.append((g1_mul(agg_pk, r), hm))
                pairs.append((g1_mul(_NEG_G1_GEN, r), sig_pt))
        if pairing_product_is_one(pairs):
            for i, _, _, _ in parsed:
                verdicts[i] = True
        else:
            for i, _, _, _ in parsed:
                pks, msg, sig = items[i]
                verdicts[i] = fast_aggregate_verify(
                    pks, msg, sig, backend=backend, require_pop=require_pop)
        _record_agg_metrics(time.perf_counter() - t0, total_signers)
    return [bool(v) for v in verdicts]


def _record_agg_metrics(dt: float, signers: int) -> None:
    from .. import batch

    m = batch.get_metrics()
    if m is not None:
        m.agg_verify_seconds.observe(dt)
        m.agg_signers.observe(signers)
