"""BLS12-381 field tower: Fp, Fp2, Fp12 (house pure-Python style).

Layout mirrors the other pure-Python crypto fallbacks (RFC-pinned,
zero-dependency): Fp elements are plain ints mod P; Fp2 elements are
(c0, c1) tuples meaning c0 + c1*u with u^2 = -1; Fp12 elements are
6-tuples of Fp2 coefficients over w with w^6 = XI = 1 + u (the sextic
non-residue). The "sextic over quadratic" representation keeps
Frobenius maps coefficient-wise: (sum c_i w^i)^(p^k) needs only an Fp2
conjugation (k odd) and a precomputed twist constant per coefficient —
all constants are DERIVED at import from P and XI, never transcribed.

Every derived constant that has a checkable algebraic property is
asserted in tests/test_bls.py (tower consistency, Frobenius == repeated
multiplication, inverse round-trips).
"""

from __future__ import annotations

from typing import Optional, Tuple

# --- curve family constants (verified in tests against the defining
# relations r = x^4 - x^2 + 1 and p = (x-1)^2 r / 3 + x) ----------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_PARAM = -0xD201000000010000  # the BLS12-381 curve parameter (negative)

Fp2 = Tuple[int, int]
Fp12 = Tuple[Fp2, Fp2, Fp2, Fp2, Fp2, Fp2]

F2_ZERO: Fp2 = (0, 0)
F2_ONE: Fp2 = (1, 0)
XI: Fp2 = (1, 1)  # the sextic non-residue 1 + u; w^6 = XI

# --- Fp ----------------------------------------------------------------


def fp_inv(a: int) -> int:
    return pow(a, P - 2, P)


def fp_sqrt(a: int) -> Optional[int]:
    """sqrt mod P (P = 3 mod 4), or None if a is a non-residue."""
    s = pow(a, (P + 1) // 4, P)
    return s if s * s % P == a % P else None


def fp_is_square(a: int) -> bool:
    return a % P == 0 or pow(a, (P - 1) // 2, P) == 1


# --- Fp2 ---------------------------------------------------------------


def f2_add(a: Fp2, b: Fp2) -> Fp2:
    return (a[0] + b[0]) % P, (a[1] + b[1]) % P


def f2_sub(a: Fp2, b: Fp2) -> Fp2:
    return (a[0] - b[0]) % P, (a[1] - b[1]) % P


def f2_neg(a: Fp2) -> Fp2:
    return (-a[0]) % P, (-a[1]) % P


def f2_mul(a: Fp2, b: Fp2) -> Fp2:
    # Karatsuba: 3 big multiplications
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    return (t0 - t1) % P, ((a[0] + a[1]) * (b[0] + b[1]) - t0 - t1) % P


def f2_sqr(a: Fp2) -> Fp2:
    # (c0+c1 u)^2 = (c0+c1)(c0-c1) + 2 c0 c1 u
    t = a[0] * a[1]
    return (a[0] + a[1]) * (a[0] - a[1]) % P, (t + t) % P


def f2_mul_fp(a: Fp2, s: int) -> Fp2:
    return a[0] * s % P, a[1] * s % P


def f2_mul_xi(a: Fp2) -> Fp2:
    # (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u
    return (a[0] - a[1]) % P, (a[0] + a[1]) % P


def f2_conj(a: Fp2) -> Fp2:
    """Frobenius a^p on Fp2 = conjugation."""
    return a[0], (-a[1]) % P


def f2_inv(a: Fp2) -> Fp2:
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    ni = fp_inv(norm)
    return a[0] * ni % P, (-a[1]) * ni % P


def f2_pow(a: Fp2, e: int) -> Fp2:
    out = F2_ONE
    base = a
    while e:
        if e & 1:
            out = f2_mul(out, base)
        base = f2_sqr(base)
        e >>= 1
    return out


def f2_is_square(a: Fp2) -> bool:
    """a is a square in Fp2 iff its norm is a square in Fp."""
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    return fp_is_square(norm)


def f2_sqrt(a: Fp2) -> Optional[Fp2]:
    """Square root in Fp2 via the complex method (P = 3 mod 4); returns
    None for non-squares. Output is verified by squaring before return,
    so a wrong branch can never leak an invalid root."""
    if a == F2_ZERO:
        return F2_ZERO
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        s = fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        s = fp_sqrt((-a0) % P)  # (s u)^2 = -s^2 = a0
        return (0, s) if s is not None else None
    alpha = (a0 * a0 + a1 * a1) % P
    n = fp_sqrt(alpha)
    if n is None:
        return None
    inv2 = (P + 1) // 2
    delta = (a0 + n) * inv2 % P
    s = fp_sqrt(delta)
    if s is None:
        delta = (a0 - n) * inv2 % P
        s = fp_sqrt(delta)
        if s is None:
            return None
    c0 = s
    c1 = a1 * fp_inv(2 * s % P) % P
    cand = (c0, c1)
    return cand if f2_sqr(cand) == (a0, a1) else None


def f2_sgn0(a: Fp2) -> int:
    """RFC 9380 sgn0 for m=2: parity of c0, falling back to c1's parity
    when c0 == 0."""
    s0 = a[0] % 2
    if a[0] % P != 0:
        return s0
    return a[1] % 2


def f2_batch_inv(xs):
    """Montgomery batch inversion: one fp_inv for the whole list. All
    inputs must be nonzero."""
    n = len(xs)
    if n == 0:
        return []
    prefix = [None] * n
    acc = F2_ONE
    for i, x in enumerate(xs):
        prefix[i] = acc
        acc = f2_mul(acc, x)
    inv = f2_inv(acc)
    out = [None] * n
    for i in range(n - 1, -1, -1):
        out[i] = f2_mul(inv, prefix[i])
        inv = f2_mul(inv, xs[i])
    return out


# --- Fp12 as Fp2[w] / (w^6 - XI) --------------------------------------

F12_ONE: Fp12 = (F2_ONE, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO)


def f12_mul(a: Fp12, b: Fp12) -> Fp12:
    out = [F2_ZERO] * 6
    for i in range(6):
        ai = a[i]
        if ai == F2_ZERO:
            continue
        for j in range(6):
            bj = b[j]
            if bj == F2_ZERO:
                continue
            t = f2_mul(ai, bj)
            k = i + j
            if k >= 6:
                k -= 6
                t = f2_mul_xi(t)
            out[k] = f2_add(out[k], t)
    return tuple(out)


def f12_sqr(a: Fp12) -> Fp12:
    out = [F2_ZERO] * 6
    for i in range(6):
        ai = a[i]
        if ai == F2_ZERO:
            continue
        t = f2_sqr(ai)
        k = 2 * i
        if k >= 6:
            k -= 6
            t = f2_mul_xi(t)
        out[k] = f2_add(out[k], t)
        for j in range(i + 1, 6):
            aj = a[j]
            if aj == F2_ZERO:
                continue
            t = f2_mul(ai, aj)
            t = f2_add(t, t)
            k = i + j
            if k >= 6:
                k -= 6
                t = f2_mul_xi(t)
            out[k] = f2_add(out[k], t)
    return tuple(out)


def f12_mul_sparse(a: Fp12, c0: Fp2, c3: Fp2, c5: Fp2) -> Fp12:
    """Multiply by the sparse line element c0 + c3 w^3 + c5 w^5 (the
    shape every Miller-loop line evaluation produces)."""
    out = [F2_ZERO] * 6
    for j, cj in ((0, c0), (3, c3), (5, c5)):
        if cj == F2_ZERO:
            continue
        for i in range(6):
            ai = a[i]
            if ai == F2_ZERO:
                continue
            t = f2_mul(ai, cj)
            k = i + j
            if k >= 6:
                k -= 6
                t = f2_mul_xi(t)
            out[k] = f2_add(out[k], t)
    return tuple(out)


def _poly_xgcd_inverse(a: Fp12) -> Fp12:
    """Invert a as a polynomial in Fp2[x] modulo x^6 - XI (extended
    Euclid). Only used once per final exponentiation — correctness over
    speed."""
    mod = [f2_neg(XI), F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ONE]

    def deg(p):
        for i in range(len(p) - 1, -1, -1):
            if p[i] != F2_ZERO:
                return i
        return -1

    def trim(p):
        d = deg(p)
        return list(p[: d + 1]) if d >= 0 else []

    r0, r1 = trim(mod), trim(list(a))
    s0, s1 = [], [F2_ONE]
    while r1:
        d0, d1 = deg(r0), deg(r1)
        if d0 < d1:
            r0, r1, s0, s1 = r1, r0, s1, s0
            continue
        lead = f2_mul(r0[d0], f2_inv(r1[d1]))
        shift = d0 - d1
        nr = list(r0)
        for i, c in enumerate(r1):
            nr[i + shift] = f2_sub(nr[i + shift], f2_mul(lead, c))
        ns = list(s0) + [F2_ZERO] * max(0, d1 + shift + 1 - len(s0))
        for i, c in enumerate(s1):
            if i + shift < len(ns):
                ns[i + shift] = f2_sub(ns[i + shift], f2_mul(lead, c))
            else:
                ns.append(f2_neg(f2_mul(lead, c)))
        r0, s0 = trim(nr), ns
        if deg(r0) < deg(r1):
            r0, r1, s0, s1 = r1, r0, s1, s0
    # r0 is the gcd (a nonzero constant for invertible a)
    if deg(r0) != 0:
        raise ZeroDivisionError("Fp12 element is not invertible")
    c = f2_inv(r0[0])
    out = [f2_mul(c, s) for s in s0[:6]]
    out += [F2_ZERO] * (6 - len(out))
    return tuple(out)


def f12_inv(a: Fp12) -> Fp12:
    return _poly_xgcd_inverse(a)


def f12_pow(a: Fp12, e: int) -> Fp12:
    if e < 0:
        raise ValueError("negative exponent")
    out = F12_ONE
    base = a
    while e:
        if e & 1:
            out = f12_mul(out, base)
        base = f12_sqr(base)
        e >>= 1
    return out


# --- Frobenius maps ----------------------------------------------------
# (sum c_i w^i)^(p^k) = sum c_i^(p^k) * GAMMA_k[i] * w^i with
# GAMMA_k[i] = XI^(i * (p^k - 1) / 6); c^(p^k) is an Fp2 conjugation for
# odd k and the identity for even k. All tables derived at import.


def _gamma(k: int):
    e = (P**k - 1) // 6
    return tuple(f2_pow(XI, (i * e) % (P * P - 1)) for i in range(6))


_G1 = _gamma(1)
_G2 = _gamma(2)
_G3 = _gamma(3)
_G6 = _gamma(6)


def f12_frob1(a: Fp12) -> Fp12:
    return tuple(f2_mul(f2_conj(a[i]), _G1[i]) for i in range(6))


def f12_frob2(a: Fp12) -> Fp12:
    return tuple(f2_mul(a[i], _G2[i]) for i in range(6))


def f12_frob3(a: Fp12) -> Fp12:
    return tuple(f2_mul(f2_conj(a[i]), _G3[i]) for i in range(6))


def f12_conj6(a: Fp12) -> Fp12:
    """a^(p^6). For elements of the cyclotomic subgroup (every
    post-easy-part value) this is the multiplicative INVERSE, which is
    what makes negative-x exponentiation cheap."""
    return tuple(f2_mul(a[i], _G6[i]) for i in range(6))
