"""Hash-to-G2 for BLS12-381 (RFC 9380 structure).

expand_message_xmd (SHA-256) and hash_to_field follow RFC 9380 §5
exactly and are pinned to the RFC's published expander test vectors in
tests/test_bls.py. The curve mapping is the RFC's Shallue–van de
Woestijne map (§6.6.1) applied directly to the twist — NOT the
BLS12381G2 ciphersuite's SSWU + 3-isogeny, whose isogeny constant
tables are not reproducible from first principles in this repo's
no-transcription style. Consequence: hash outputs are valid, uniform,
constant-DST points of G2 but are not byte-compatible with Eth2
signatures (documented in PARITY_DEVIATIONS.md). The SvdW constants are
DERIVED from the curve at import via the RFC's find_z_svdw criteria.

Cofactor clearing uses the psi-endomorphism method (curve.py,
Budroni–Pintore); tests pin [r]·hash(msg) == O and hash distinctness
across messages and DSTs.
"""

from __future__ import annotations

import hashlib
import struct
from typing import List, Tuple

from .curve import B_G2, G2Point, g2_add, g2_clear_cofactor
from .fields import (
    F2_ONE,
    F2_ZERO,
    P,
    Fp2,
    f2_add,
    f2_inv,
    f2_is_square,
    f2_mul,
    f2_mul_fp,
    f2_neg,
    f2_sgn0,
    f2_sqr,
    f2_sqrt,
    f2_sub,
)

_B_IN_BYTES = 32  # SHA-256 output size
_S_IN_BYTES = 64  # SHA-256 block size
_L = 64  # per-element expansion length for 128-bit security margin


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 with SHA-256."""
    if len(dst) > 255:
        raise ValueError("DST longer than 255 bytes")
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255:
        raise ValueError("requested expansion too long")
    dst_prime = dst + struct.pack("B", len(dst))
    z_pad = b"\x00" * _S_IN_BYTES
    l_i_b_str = struct.pack(">H", len_in_bytes)
    b0 = hashlib.sha256(
        z_pad + msg + l_i_b_str + b"\x00" + dst_prime
    ).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    bs = [b1]
    for i in range(2, ell + 1):
        prev = bs[-1]
        mixed = bytes(a ^ b for a, b in zip(b0, prev))
        bs.append(hashlib.sha256(mixed + struct.pack("B", i) + dst_prime).digest())
    return b"".join(bs)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, dst: bytes, count: int) -> List[Fp2]:
    """RFC 9380 §5.2 for m=2, L=64."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = _L * (j + i * 2)
            coords.append(int.from_bytes(uniform[off : off + _L], "big") % P)
        out.append((coords[0], coords[1]))
    return out


# --- Shallue–van de Woestijne map on the twist ------------------------


def _g(x: Fp2) -> Fp2:
    return f2_add(f2_mul(f2_sqr(x), x), B_G2)


def _find_z_svdw() -> Fp2:
    """RFC 9380 Appendix H.3 criteria, searched over a fixed small
    candidate order (a + b*u for growing |a|, |b|)."""
    candidates = []
    for mag in range(1, 8):
        for a in range(-mag, mag + 1):
            for b in range(-mag, mag + 1):
                if max(abs(a), abs(b)) == mag:
                    candidates.append((a % P, b % P))
    inv2 = (P + 1) // 2
    for z in candidates:
        gz = _g(z)
        if gz == F2_ZERO:
            continue
        t = f2_mul_fp(f2_sqr(z), 3)  # 3Z^2 (A = 0)
        if t == F2_ZERO:
            continue
        h = f2_mul(f2_neg(t), f2_inv(f2_mul_fp(gz, 4)))
        if h == F2_ZERO or not f2_is_square(h):
            continue
        neg_half_z = f2_mul_fp(f2_neg(z), inv2)
        if f2_is_square(gz) or f2_is_square(_g(neg_half_z)):
            return z
    raise RuntimeError("no SvdW Z found")  # pragma: no cover


_Z = _find_z_svdw()
_GZ = _g(_Z)
_3Z2 = f2_mul_fp(f2_sqr(_Z), 3)
_TV4_C = f2_sqrt(f2_mul(f2_neg(_GZ), _3Z2))
if _TV4_C is None:  # pragma: no cover - guaranteed by the Z criteria
    raise RuntimeError("SvdW constant sqrt(-g(Z)(3Z^2)) does not exist")
if f2_sgn0(_TV4_C) == 1:
    _TV4_C = f2_neg(_TV4_C)
_TV6_C = f2_mul(f2_mul_fp(_GZ, 4), f2_inv(f2_neg(_3Z2)))  # -4g(Z)/(3Z^2)
_NEG_HALF_Z = f2_mul_fp(f2_neg(_Z), (P + 1) // 2)


def map_to_curve_svdw(u: Fp2) -> Tuple[Fp2, Fp2]:
    """RFC 9380 §6.6.1 straight-line map; returns an affine twist point."""
    tv1 = f2_mul(f2_sqr(u), _GZ)
    tv2 = f2_add(F2_ONE, tv1)
    tv1 = f2_sub(F2_ONE, tv1)
    prod = f2_mul(tv1, tv2)
    tv3 = f2_inv(prod) if prod != F2_ZERO else F2_ZERO  # inv0
    tv5 = f2_mul(f2_mul(f2_mul(u, tv1), tv3), _TV4_C)
    x1 = f2_sub(_NEG_HALF_Z, tv5)
    x2 = f2_add(_NEG_HALF_Z, tv5)
    x3 = f2_add(_Z, f2_mul(_TV6_C, f2_sqr(f2_mul(f2_sqr(tv2), tv3))))
    for x in (x1, x2, x3):
        gx = _g(x)
        y = f2_sqrt(gx)
        if y is not None:
            break
    else:  # pragma: no cover - SvdW guarantees one of the three maps
        raise RuntimeError("SvdW produced no curve point")
    if f2_sgn0(u) != f2_sgn0(y):
        y = f2_neg(y)
    return x, y


def hash_to_g2(msg: bytes, dst: bytes) -> G2Point:
    """Full hash_to_curve: two field elements, two map applications,
    add, clear cofactor. Returns a Jacobian point of G2 (r-torsion)."""
    u0, u1 = hash_to_field_fp2(msg, dst, 2)
    x0, y0 = map_to_curve_svdw(u0)
    x1, y1 = map_to_curve_svdw(u1)
    q = g2_add((x0, y0, F2_ONE), (x1, y1, F2_ONE))
    return g2_clear_cofactor(q)
