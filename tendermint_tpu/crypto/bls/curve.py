"""BLS12-381 group arithmetic: G1 over Fp, G2 over Fp2 (twist), and the
ZCash-style compressed point encodings (48-byte G1, 96-byte G2).

Points are Jacobian triples (X, Y, Z); None is the point at infinity.
G1 coordinates are ints, G2 coordinates are fields.Fp2 tuples. The
formulas are the standard a=0 Jacobian ones; every deserialization
verifies the curve equation, and subgroup membership is checked with an
explicit [r]P == O multiply (cached by callers — the scheme layer
parses each key/signature once).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .fields import (
    F2_ONE,
    F2_ZERO,
    P,
    R_ORDER,
    X_PARAM,
    XI,
    f2_add,
    f2_batch_inv,
    f2_conj,
    f2_inv,
    f2_mul,
    f2_mul_fp,
    f2_neg,
    f2_pow,
    f2_sqr,
    f2_sqrt,
    f2_sub,
    fp_inv,
    fp_sqrt,
)

B_G1 = 4  # E1: y^2 = x^3 + 4
B_G2 = (4, 4)  # E2' (the twist): y^2 = x^3 + 4(1 + u)

# generators (standard constants; tests assert on-curve + order r)
G1_GEN_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_GEN_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_GEN_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_GEN_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

G1Point = Optional[Tuple[int, int, int]]
G2Point = Optional[Tuple]

G1_GEN: G1Point = (G1_GEN_X, G1_GEN_Y, 1)
G2_GEN: G2Point = (G2_GEN_X, G2_GEN_Y, F2_ONE)

# --- G1 (Jacobian over Fp) --------------------------------------------


def g1_dbl(p: G1Point) -> G1Point:
    if p is None:
        return None
    X, Y, Z = p
    if Y == 0:
        return None
    A = X * X % P
    Bv = Y * Y % P
    C = Bv * Bv % P
    D = 2 * ((X + Bv) * (X + Bv) - A - C) % P
    E = 3 * A % P
    F = E * E % P
    X3 = (F - 2 * D) % P
    Y3 = (E * (D - X3) - 8 * C) % P
    Z3 = 2 * Y * Z % P
    return (X3, Y3, Z3)


def g1_add(p: G1Point, q: G1Point) -> G1Point:
    if p is None:
        return q
    if q is None:
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    if U1 == U2:
        if S1 != S2:
            return None
        return g1_dbl(p)
    H = (U2 - U1) % P
    I = 4 * H * H % P
    J = H * I % P
    rr = 2 * (S2 - S1) % P
    V = U1 * I % P
    X3 = (rr * rr - J - 2 * V) % P
    Y3 = (rr * (V - X3) - 2 * S1 * J) % P
    Z3 = ((Z1 + Z2) * (Z1 + Z2) - Z1Z1 - Z2Z2) * H % P
    return (X3, Y3, Z3)


def g1_neg(p: G1Point) -> G1Point:
    if p is None:
        return None
    return (p[0], (-p[1]) % P, p[2])


def g1_mul(p: G1Point, k: int) -> G1Point:
    # NO reduction mod R_ORDER here (mirror g2_mul): g1_in_subgroup's
    # [r]P == O test relies on multiplying by the FULL group order — a
    # reduced scalar would turn it into [0]P and vacuously accept every
    # on-curve point, disabling pubkey subgroup validation
    if k < 0:
        return g1_neg(g1_mul(p, -k))
    out: G1Point = None
    add = p
    while k:
        if k & 1:
            out = g1_add(out, add)
        add = g1_dbl(add)
        k >>= 1
    return out


def g1_to_affine(p: G1Point) -> Optional[Tuple[int, int]]:
    if p is None:
        return None
    X, Y, Z = p
    zi = fp_inv(Z)
    zi2 = zi * zi % P
    return X * zi2 % P, Y * zi2 * zi % P


def g1_eq(p: G1Point, q: G1Point) -> bool:
    if p is None or q is None:
        return p is q or (p is None and q is None)
    # cross-multiplied Jacobian equality
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    return (
        X1 * Z2Z2 % P == X2 * Z1Z1 % P
        and Y1 * Z2 * Z2Z2 % P == Y2 * Z1 * Z1Z1 % P
    )


def g1_on_curve(p: G1Point) -> bool:
    if p is None:
        return True
    x, y = g1_to_affine(p)
    return (y * y - x * x * x - B_G1) % P == 0


def g1_in_subgroup(p: G1Point) -> bool:
    return g1_on_curve(p) and g1_mul(p, R_ORDER) is None


def g1_sum(points: List[G1Point]) -> G1Point:
    """Plain sequential Jacobian accumulation — the host-side reference
    the JAX MSM kernel (msm.py) is property-tested against."""
    acc: G1Point = None
    for p in points:
        acc = g1_add(acc, p)
    return acc


# --- G2 (Jacobian over Fp2, on the twist) -----------------------------


def g2_dbl(p: G2Point) -> G2Point:
    if p is None:
        return None
    X, Y, Z = p
    if Y == F2_ZERO:
        return None
    A = f2_sqr(X)
    Bv = f2_sqr(Y)
    C = f2_sqr(Bv)
    t = f2_sqr(f2_add(X, Bv))
    D = f2_sub(t, f2_add(A, C))
    D = f2_add(D, D)
    E = f2_add(f2_add(A, A), A)
    F = f2_sqr(E)
    X3 = f2_sub(F, f2_add(D, D))
    Y3 = f2_sub(f2_mul(E, f2_sub(D, X3)), _f2_x8(C))
    Z3 = f2_mul(f2_add(Y, Y), Z)
    return (X3, Y3, Z3)


def _f2_x8(a):
    return a[0] * 8 % P, a[1] * 8 % P


def g2_add(p: G2Point, q: G2Point) -> G2Point:
    if p is None:
        return q
    if q is None:
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = f2_sqr(Z1)
    Z2Z2 = f2_sqr(Z2)
    U1 = f2_mul(X1, Z2Z2)
    U2 = f2_mul(X2, Z1Z1)
    S1 = f2_mul(f2_mul(Y1, Z2), Z2Z2)
    S2 = f2_mul(f2_mul(Y2, Z1), Z1Z1)
    if U1 == U2:
        if S1 != S2:
            return None
        return g2_dbl(p)
    H = f2_sub(U2, U1)
    I = f2_sqr(f2_add(H, H))
    J = f2_mul(H, I)
    rr = f2_sub(S2, S1)
    rr = f2_add(rr, rr)
    V = f2_mul(U1, I)
    X3 = f2_sub(f2_sub(f2_sqr(rr), J), f2_add(V, V))
    S1J = f2_mul(S1, J)
    Y3 = f2_sub(f2_mul(rr, f2_sub(V, X3)), f2_add(S1J, S1J))
    Z3 = f2_mul(f2_sub(f2_sub(f2_sqr(f2_add(Z1, Z2)), Z1Z1), Z2Z2), H)
    return (X3, Y3, Z3)


def g2_neg(p: G2Point) -> G2Point:
    if p is None:
        return None
    return (p[0], f2_neg(p[1]), p[2])


def g2_mul(p: G2Point, k: int) -> G2Point:
    if k < 0:
        return g2_neg(g2_mul(p, -k))
    out: G2Point = None
    add = p
    while k:
        if k & 1:
            out = g2_add(out, add)
        add = g2_dbl(add)
        k >>= 1
    return out


def g2_to_affine(p: G2Point) -> Optional[Tuple]:
    if p is None:
        return None
    X, Y, Z = p
    zi = f2_inv(Z)
    zi2 = f2_sqr(zi)
    return f2_mul(X, zi2), f2_mul(Y, f2_mul(zi2, zi))


def g2_batch_to_affine(points: List[G2Point]) -> List[Optional[Tuple]]:
    """Normalize many Jacobian points with ONE field inversion."""
    zs = [p[2] for p in points if p is not None]
    invs = iter(f2_batch_inv(zs))
    out = []
    for p in points:
        if p is None:
            out.append(None)
            continue
        zi = next(invs)
        zi2 = f2_sqr(zi)
        out.append((f2_mul(p[0], zi2), f2_mul(p[1], f2_mul(zi2, zi))))
    return out


def g2_eq(p: G2Point, q: G2Point) -> bool:
    if p is None or q is None:
        return p is None and q is None
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = f2_sqr(Z1)
    Z2Z2 = f2_sqr(Z2)
    return f2_mul(X1, Z2Z2) == f2_mul(X2, Z1Z1) and f2_mul(
        f2_mul(Y1, Z2), Z2Z2
    ) == f2_mul(f2_mul(Y2, Z1), Z1Z1)


def g2_on_curve(p: G2Point) -> bool:
    if p is None:
        return True
    x, y = g2_to_affine(p)
    return f2_sqr(y) == f2_add(f2_mul(f2_sqr(x), x), B_G2)


def g2_in_subgroup(p: G2Point) -> bool:
    return g2_on_curve(p) and g2_mul(p, R_ORDER) is None


# --- psi (untwist-Frobenius-twist endomorphism on the twist) -----------
# psi(x, y) = (cx * conj(x), cy * conj(y)) with cx = XI^((1-p)/3) and
# cy = XI^((1-p)/2) — derived from untwist x/w^2, y/w^3 with w^6 = XI.

_PSI_CX = f2_inv(f2_pow(XI, (P - 1) // 3))
_PSI_CY = f2_inv(f2_pow(XI, (P - 1) // 2))


def g2_psi(p: G2Point) -> G2Point:
    if p is None:
        return None
    x, y = g2_to_affine(p)
    return (f2_mul(_PSI_CX, f2_conj(x)), f2_mul(_PSI_CY, f2_conj(y)), F2_ONE)


def g2_clear_cofactor(p: G2Point) -> G2Point:
    """Budroni–Pintore efficient cofactor clearing for BLS12 G2:
    [h_eff]P = [x^2 - x - 1]P + [x - 1]psi(P) + psi^2([2]P).
    Output is in the r-torsion (property-tested: [r]out == O)."""
    if p is None:
        return None
    xP = g2_mul(p, X_PARAM)  # [x]P
    x2P = g2_mul(xP, X_PARAM)  # [x^2]P
    out = g2_add(x2P, g2_neg(xP))  # [x^2 - x]P
    out = g2_add(out, g2_neg(p))  # [x^2 - x - 1]P
    psiP = g2_psi(p)
    t = g2_add(g2_mul(psiP, X_PARAM), g2_neg(psiP))  # [x - 1]psi(P)
    out = g2_add(out, t)
    out = g2_add(out, g2_psi(g2_psi(g2_dbl(p))))
    return out


# --- compressed serialization (ZCash flags) ---------------------------
# byte 0 high bits: 0x80 compressed (always set), 0x40 infinity,
# 0x20 sign (y is the lexicographically larger of {y, -y}).


def _fp_is_larger(y: int) -> bool:
    return y > (P - 1) // 2


def _f2_is_larger(y: Tuple[int, int]) -> bool:
    """Fp2 ordering used by the flag bit: compare as y1 * p + y0."""
    if y[1] != 0:
        return _fp_is_larger(y[1])
    return _fp_is_larger(y[0])


def g1_compress(p: G1Point) -> bytes:
    if p is None:
        return bytes([0xC0]) + b"\x00" * 47
    x, y = g1_to_affine(p)
    flags = 0x80 | (0x20 if _fp_is_larger(y) else 0)
    raw = bytearray(x.to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def g1_decompress(data: bytes) -> G1Point:
    if len(data) != 48:
        raise ValueError("G1 point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("malformed G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x coordinate out of range")
    y = fp_sqrt((x * x * x + B_G1) % P)
    if y is None:
        raise ValueError("G1 x is not on the curve")
    if _fp_is_larger(y) != bool(flags & 0x20):
        y = (-y) % P
    return (x, y, 1)


def g2_compress(p: G2Point) -> bytes:
    if p is None:
        return bytes([0xC0]) + b"\x00" * 95
    x, y = g2_to_affine(p)
    flags = 0x80 | (0x20 if _f2_is_larger(y) else 0)
    raw = bytearray(x[1].to_bytes(48, "big") + x[0].to_bytes(48, "big"))
    raw[0] |= flags
    return bytes(raw)


def g2_decompress(data: bytes) -> G2Point:
    if len(data) != 96:
        raise ValueError("G2 point must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("malformed G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x coordinate out of range")
    x = (x0, x1)
    y = f2_sqrt(f2_add(f2_mul(f2_sqr(x), x), B_G2))
    if y is None:
        raise ValueError("G2 x is not on the curve")
    if _f2_is_larger(y) != bool(flags & 0x20):
        y = f2_neg(y)
    return (x, y, F2_ONE)
