"""Optimal-ate pairing for BLS12-381 (multi-pair Miller loop + final
exponentiation).

The Miller loop walks |x| = 0xd201000000010000 (64 bits, weight 6) over
the TWISTED G2 point — all point arithmetic stays in Fp2; only the line
evaluations enter Fp12, as the sparse element

    l = XI*yP + (lam*x1 - y1) w^3 - lam*xP w^5

derived from the untwist (x, y) -> (x/w^2, y/w^3) with the whole line
scaled by XI in Fp2 (subfield scaling — erased by the final
exponentiation). Slopes come from a two-pass schedule: pass 1 records
the Jacobian chain, pass 2 batch-normalizes it and batch-inverts every
slope denominator (two field inversions per pairing instead of one per
step).

The final exponentiation uses the verified BLS12 identity

    (x-1)^2 (x+p) (x^2+p^2-1) + 3 == 3 * (p^4 - p^2 + 1) / r

so the computed value is e(P,Q)^3 — a fixed exponent coprime to r,
which preserves bilinearity, non-degeneracy, and every product==1
check this package performs (tests pin all three properties).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from .curve import (
    G1Point,
    G2Point,
    g1_to_affine,
    g2_dbl,
    g2_add,
    g2_batch_to_affine,
)
from .fields import (
    F12_ONE,
    F2_ZERO,
    P,
    X_PARAM,
    f12_conj6,
    f12_frob1,
    f12_frob2,
    f12_inv,
    f12_mul,
    f12_mul_sparse,
    f12_sqr,
    f2_add,
    f2_batch_inv,
    f2_mul,
    f2_mul_fp,
    f2_sqr,
    f2_sub,
    Fp12,
)

_ABS_X = -X_PARAM
_X_BITS = bin(_ABS_X)[3:]  # MSB-first, leading bit dropped


def _miller_schedule(q_affine) -> List[Tuple[bool, tuple, tuple]]:
    """Precompute the per-step data for one G2 point: a list of
    (is_dbl, (x1, y1), lam) with all points affine and every slope
    computed through two batch inversions."""
    qx, qy = q_affine
    # pass 1: record the Jacobian point entering each step
    jac_pts = []
    kinds = []
    R = (qx, qy, (1, 0))
    for b in _X_BITS:
        jac_pts.append(R)
        kinds.append(True)
        R = g2_dbl(R)
        if b == "1":
            jac_pts.append(R)
            kinds.append(False)
            R = g2_add(R, (qx, qy, (1, 0)))
    affine = g2_batch_to_affine(jac_pts)
    # pass 2: slope denominators (2*y1 for doubles, x2-x1 for adds)
    dens = []
    for is_dbl, pt in zip(kinds, affine):
        if pt is None:
            raise ValueError("pairing input hit the point at infinity")
        x1, y1 = pt
        dens.append(f2_add(y1, y1) if is_dbl else f2_sub(qx, x1))
    for d in dens:
        if d == F2_ZERO:
            raise ValueError("degenerate line in Miller loop")
    invs = f2_batch_inv(dens)
    steps = []
    for is_dbl, pt, di in zip(kinds, affine, invs):
        x1, y1 = pt
        if is_dbl:
            lam = f2_mul(f2_mul_fp(f2_sqr(x1), 3), di)
        else:
            lam = f2_mul(f2_sub(qy, y1), di)
        steps.append((is_dbl, (x1, y1), lam))
    return steps


def miller_loop(pairs: Sequence[Tuple[G1Point, G2Point]]) -> Fp12:
    """Product of Miller-loop values over (P in G1, Q on the twist)
    pairs, sharing one squaring chain — the multi-pairing every
    aggregate verification uses (2 pairs -> ~1.5x one pairing)."""
    prepared = []
    for p1, q2 in pairs:
        if p1 is None or q2 is None:
            raise ValueError("cannot pair the point at infinity")
        xp, yp = g1_to_affine(p1)
        steps = _miller_schedule(_affine_g2(q2))
        prepared.append((xp, yp, iter(steps), steps))
    f = F12_ONE
    for b in _X_BITS:
        f = f12_sqr(f)
        for xp, yp, it, _ in prepared:
            is_dbl, (x1, y1), lam = next(it)
            assert is_dbl
            f = _mul_line(f, xp, yp, x1, y1, lam)
        if b == "1":
            for xp, yp, it, _ in prepared:
                is_dbl, (x1, y1), lam = next(it)
                assert not is_dbl
                f = _mul_line(f, xp, yp, x1, y1, lam)
    return f


def _affine_g2(q: G2Point):
    from .curve import g2_to_affine

    return g2_to_affine(q)


def _mul_line(f: Fp12, xp: int, yp: int, x1, y1, lam) -> Fp12:
    # l = XI*yP + (lam*x1 - y1) w^3 + (-lam*xP) w^5, XI*yP = (yP, yP)
    c0 = (yp, yp)
    c3 = f2_sub(f2_mul(lam, x1), y1)
    c5 = f2_mul_fp(lam, (-xp) % P)
    return f12_mul_sparse(f, c0, c3, c5)


def _pow_abs_x(f: Fp12) -> Fp12:
    """f^|x| by plain square-and-multiply (64 bits, weight 6)."""
    out = f
    for b in _X_BITS:
        out = f12_sqr(out)
        if b == "1":
            out = f12_mul(out, f)
    return out


def _exp_x(f: Fp12) -> Fp12:
    """f^x for the (negative) curve parameter; valid for cyclotomic f
    where inversion is conjugation."""
    return f12_conj6(_pow_abs_x(f))


def final_exponentiation(f: Fp12) -> Fp12:
    # easy part: f^((p^6 - 1)(p^2 + 1))
    m = f12_mul(f12_conj6(f), f12_inv(f))
    m = f12_mul(f12_frob2(m), m)
    # hard part (verified chain, see module docstring): exponent
    # (x-1)^2 (x+p) (x^2+p^2-1) + 3
    a = f12_conj6(f12_mul(_pow_abs_x(m), m))  # m^(x-1)
    a = f12_conj6(f12_mul(_pow_abs_x(a), a))  # m^((x-1)^2)
    b = f12_mul(_exp_x(a), f12_frob1(a))  # a^(x+p)
    c = f12_mul(
        f12_mul(_exp_x(_exp_x(b)), f12_frob2(b)), f12_conj6(b)
    )  # b^(x^2+p^2-1)
    return f12_mul(f12_mul(c, f12_sqr(m)), m)


def pairing(p1: G1Point, q2: G2Point) -> Fp12:
    """e(P, Q)^3 (fixed cube of the ate pairing; see module docstring)."""
    return final_exponentiation(miller_loop([(p1, q2)]))


def pairing_product_is_one(pairs: Sequence[Tuple[G1Point, G2Point]]) -> bool:
    """prod e(P_i, Q_i) == 1 — the only predicate signature verification
    needs, immune to the fixed-cube convention."""
    return final_exponentiation(miller_loop(pairs)) == F12_ONE
