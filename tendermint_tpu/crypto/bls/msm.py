"""Bitmap -> aggregate-pubkey G1 summation kernels.

The aggregate-verification hot path reduces a signer bitmap over the
validator set's G1 pubkeys to ONE aggregate public key. That is a
multi-scalar multiplication with every scalar equal to 1 — the
degenerate (single-bucket) case of a windowed/Pippenger MSM — so the
kernel is a masked Jacobian tree reduction.

Two registered backends, mirroring crypto/batch's registry idiom
(select with TM_TPU_BLS_MSM or set_default_msm_backend):

  "python" — sequential Jacobian accumulation (curve.g1_sum); the
             reference implementation and the default.
  "jax"    — vectorized tree reduction: field elements are (26, B)
             int64 arrays of 15-bit limbs (the jaxed25519 layout scaled
             to 381 bits), one jitted level-step reused across all
             log2(n) levels via roll-based pairing, so the kernel
             compiles once per batch shape. Guarded: any jax failure
             falls back to the python path (the two are property-tested
             identical in tests/test_bls.py).

The kernels consume AFFINE point tuples ((x, y) ints, None = infinity)
and return a Jacobian curve.G1Point.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .curve import G1Point, g1_add, g1_sum
from .fields import P

LOG = logging.getLogger("crypto.bls.msm")

AffinePoint = Optional[Tuple[int, int]]

_registry: Dict[str, Callable[[List[AffinePoint]], G1Point]] = {}
_default_lock = threading.Lock()
_default_name: Optional[str] = None


def register_msm_backend(name: str, fn) -> None:
    _registry[name] = fn


def msm_backends() -> List[str]:
    return sorted(_registry)


def set_default_msm_backend(name: str) -> None:
    global _default_name
    if name not in _registry:
        raise KeyError(f"unknown BLS MSM backend {name!r}; have {msm_backends()}")
    with _default_lock:
        _default_name = name


def default_msm_backend() -> str:
    global _default_name
    with _default_lock:
        if _default_name is None:
            env = os.environ.get("TM_TPU_BLS_MSM")
            _default_name = env if env in _registry else "python"
        return _default_name


def aggregate_points(points: List[AffinePoint], backend: Optional[str] = None) -> G1Point:
    """Sum the given affine G1 points (the bitmap-selected pubkeys)."""
    name = backend or default_msm_backend()
    fn = _registry.get(name)
    if fn is None:
        raise KeyError(f"unknown BLS MSM backend {name!r}; have {msm_backends()}")
    if name != "python":
        try:
            return fn(points)
        except Exception as e:  # noqa: BLE001 - host path is authoritative
            LOG.warning("BLS MSM backend %s failed, python fallback: %s",
                        name, e)
            return _python_sum(points)
    return fn(points)


def _python_sum(points: List[AffinePoint]) -> G1Point:
    return g1_sum([(x, y, 1) for x, y in (p for p in points if p is not None)])


register_msm_backend("python", _python_sum)


# --- jax kernel --------------------------------------------------------
#
# Field layout: 26 limbs of 15 bits, limb-major (26, B) int64. A full
# 381x381 product is a 51-coefficient convolution (partial products
# <= 2^30, at most 26 summed -> < 2^35, safely inside int64); the high
# 25 coefficients fold back through a precomputed (25, 26) table of
# 2^(15*(i+26)) mod p in limb form, then parallel carry rounds restore
# the 15-bit invariant. Comparisons (the add formula's doubling /
# negation cases) are exact because operands are frozen (canonical,
# < p) after every operation.

_NLIMB = 26
_BITS = 15
_MASK = (1 << _BITS) - 1


def _int_to_limbs_py(v: int) -> List[int]:
    return [(v >> (_BITS * i)) & _MASK for i in range(_NLIMB)]


def _limbs_to_int_py(ls) -> int:
    return sum(int(l) << (_BITS * i) for i, l in enumerate(ls))


def _build_jax():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import enable_x64

    # int64 limbs need the x64 trace context; scoping it here (instead
    # of flipping jax_enable_x64 globally) keeps the jaxed25519 kernels'
    # int32 world untouched
    _x64 = enable_x64

    # FOLD[i] = limbs(2^(15*(26+i)) mod p): positional fold table for
    # conv coefficients 26..51 (numpy so the x64 trace keeps int64)
    FOLD = np.array(
        [_int_to_limbs_py(pow(2, _BITS * (i + _NLIMB), P))
         for i in range(_NLIMB)], dtype=np.int64)
    P_LIMBS = np.array(_int_to_limbs_py(P), dtype=np.int64)
    # Barrett-lite estimator: qhat = ((V >> 380) * C20) >> 20 with
    # C20 = floor(2^400 / p) underestimates floor(V/p) by at most a few,
    # so one multiply-subtract leaves V' < 4p for the conditional
    # subtract freeze
    C20 = (1 << 400) // P

    def _carry_rounds(v, rounds):
        """Parallel carry rounds over 26 limbs; the (small) top carry
        folds back through FOLD as a two-limb decomposition so limb
        magnitudes strictly shrink toward canonical."""
        for _ in range(rounds):
            r = v >> _BITS
            v = (v & _MASK).at[1:].add(r[:-1])
            t = r[-1]
            t0 = t & _MASK
            t1 = t >> _BITS
            v = v + t0 * FOLD[0][:, None] + t1 * FOLD[1][:, None]
        return v

    def _reduce_full(v):
        """Canonicalize limbs (possibly up to ~2^40 each) to the exact
        residue: carries -> Barrett-lite quotient subtract -> freeze."""
        v = _carry_rounds(v, 6)
        # limbs now canonical up to +-1 ulp (value < 2^390 + eps);
        # estimate the quotient from the top 11 bits. qhat can be off by
        # a couple in either direction, so add one p back before the
        # subtract and let the freeze pass absorb the slack (< 5p).
        hi = v[-1] >> 5  # V >> 380 (lower limbs contribute < 2^380)
        qhat = (hi * C20) >> 20
        v = v + P_LIMBS[:, None] - qhat[None, :] * P_LIMBS[:, None]
        # signed carries (arithmetic shift handles borrows)
        for _ in range(3):
            r = v >> _BITS
            v = (v & _MASK).at[1:].add(r[:-1])
        return _freeze(v)

    def _modmul(a, b):
        # a, b canonical (26, B) -> canonical (26, B)
        prod = jnp.zeros((2 * _NLIMB - 1,) + a.shape[1:], dtype=jnp.int64)
        for i in range(_NLIMB):
            prod = prod.at[i : i + _NLIMB].add(a[i][None, :] * b)
        # one positional carry round so fold inputs are ~2^20
        r = prod >> _BITS
        m = prod & _MASK
        pad = [(0, 0)] * (prod.ndim - 1)
        ext = jnp.pad(m, [(0, 1)] + pad) + jnp.pad(r, [(1, 0)] + pad)
        v = ext[:_NLIMB] + jnp.tensordot(
            jnp.asarray(FOLD), ext[_NLIMB:], axes=([0], [0]))
        return _reduce_full(v)

    # borrow-safe 2p: value == 2p, every limb >= MASK, so (a + B2P - b)
    # has non-negative limbs for canonical a, b (no borrow chains)
    _b2p = [2 * int(x) for x in P_LIMBS]
    for _i in range(_NLIMB - 1):
        _b2p[_i] += 1 << _BITS
        _b2p[_i + 1] -= 1
    B2P = np.array(_b2p, dtype=np.int64)

    def _modsub(a, b):
        v = a + B2P[:, None] - b
        v = _carry_rounds(v, 2)
        return _freeze(v)

    def _modadd(a, b):
        v = _carry_rounds(a + b, 2)
        return _freeze(v)

    def _geq_p(v):
        # lexicographic v >= p over limbs (both canonical-ish, < 2^15)
        gt = v > P_LIMBS[:, None]
        eq = v == P_LIMBS[:, None]
        res = jnp.ones(v.shape[1:], dtype=bool)  # running "equal so far"
        out = jnp.zeros(v.shape[1:], dtype=bool)
        for i in range(_NLIMB - 1, -1, -1):
            out = out | (res & gt[i])
            res = res & eq[i]
        return out | res  # equal counts as >=

    def _sub_p(v):
        borrow = jnp.zeros(v.shape[1:], dtype=jnp.int64)
        out = jnp.zeros_like(v)
        for i in range(_NLIMB):
            d = v[i] - P_LIMBS[i] - borrow
            borrow = (d < 0).astype(jnp.int64)
            out = out.at[i].set(d + borrow * (1 << _BITS))
        return out

    def _freeze(v):
        # conditional subtracts; callers guarantee v < 5p
        for _ in range(4):
            m = _geq_p(v)
            v = jnp.where(m[None, :], _sub_p(v), v)
        return v

    def _is_zero(v):
        return jnp.all(v == 0, axis=0)

    def _pt_add(ax, ay, az, bx, by, bz):
        """Full Jacobian add with infinity (z == 0), doubling, and
        negation masks, vectorized over the batch axis."""
        a_inf = _is_zero(az)
        b_inf = _is_zero(bz)
        z1z1 = _modmul(az, az)
        z2z2 = _modmul(bz, bz)
        u1 = _modmul(ax, z2z2)
        u2 = _modmul(bx, z1z1)
        s1 = _modmul(_modmul(ay, bz), z2z2)
        s2 = _modmul(_modmul(by, az), z1z1)
        x_eq = _is_zero(_modsub(u1, u2))
        y_eq = _is_zero(_modsub(s1, s2))
        # generic add
        h = _modsub(u2, u1)
        two_h = _modadd(h, h)
        i = _modmul(two_h, two_h)
        j = _modmul(h, i)
        rr = _modsub(s2, s1)
        rr = _modadd(rr, rr)
        v = _modmul(u1, i)
        x3 = _modsub(_modsub(_modmul(rr, rr), j), _modadd(v, v))
        s1j = _modmul(s1, j)
        y3 = _modsub(_modmul(rr, _modsub(v, x3)), _modadd(s1j, s1j))
        zz = _modsub(_modsub(_modmul(_modadd(az, bz), _modadd(az, bz)), z1z1), z2z2)
        z3 = _modmul(zz, h)
        # doubling branch (a == b)
        da = _modmul(ax, ax)
        db = _modmul(ay, ay)
        dc = _modmul(db, db)
        t = _modadd(ax, db)
        d = _modsub(_modsub(_modmul(t, t), da), dc)
        d = _modadd(d, d)
        e = _modadd(_modadd(da, da), da)
        f = _modmul(e, e)
        dx3 = _modsub(f, _modadd(d, d))
        c8 = _modadd(_modadd(dc, dc), _modadd(dc, dc))
        c8 = _modadd(c8, c8)
        dy3 = _modsub(_modmul(e, _modsub(d, dx3)), c8)
        dz3 = _modmul(_modadd(ay, ay), az)
        dbl_m = (x_eq & y_eq)[None, :]
        x3 = jnp.where(dbl_m, dx3, x3)
        y3 = jnp.where(dbl_m, dy3, y3)
        z3 = jnp.where(dbl_m, dz3, z3)
        # negation (x equal, y differing) -> infinity (z = 0)
        inf_m = (x_eq & ~y_eq)[None, :]
        z3 = jnp.where(inf_m, jnp.zeros_like(z3), z3)
        # infinity absorbers
        x3 = jnp.where(a_inf[None, :], bx, jnp.where(b_inf[None, :], ax, x3))
        y3 = jnp.where(a_inf[None, :], by, jnp.where(b_inf[None, :], ay, y3))
        z3 = jnp.where(a_inf[None, :], bz, jnp.where(b_inf[None, :], az, z3))
        return x3, y3, z3

    def _level_impl(xs, ys, zs, shift):
        """One tree level: lane i (i % (2*shift) == 0) absorbs lane
        i+shift; other lanes are zeroed to infinity."""
        n = xs.shape[1]
        bx = jnp.roll(xs, -shift, axis=1)
        by = jnp.roll(ys, -shift, axis=1)
        bz = jnp.roll(zs, -shift, axis=1)
        x3, y3, z3 = _pt_add(xs, ys, zs, bx, by, bz)
        lane = jnp.arange(n)
        keep = (lane % (2 * shift)) == 0
        x3 = jnp.where(keep[None, :], x3, jnp.zeros_like(x3))
        y3 = jnp.where(keep[None, :], y3, jnp.zeros_like(y3))
        z3 = jnp.where(keep[None, :], z3, jnp.zeros_like(z3))
        return x3, y3, z3

    # compile-once: the level step costs ~minutes of XLA compile (the
    # Jacobian add formula is a huge graph), which is why the backend
    # is opt-in — the AOT store turns that into once per MACHINE.
    # `shift` is a runtime scalar, so ONE executable per batch width
    # serves every tree level.
    from .. import kernel_cache

    _level = kernel_cache.aot_wrap("bls_msm_level", (),
                                   jax.jit(_level_impl))

    def jax_sum(points: List[AffinePoint]) -> G1Point:
        live = [p for p in points if p is not None]
        if not live:
            return None
        if len(live) == 1:
            return (live[0][0], live[0][1], 1)
        n = 1
        while n < len(live):
            n <<= 1
        xs = np.zeros((_NLIMB, n), dtype=np.int64)
        ys = np.zeros((_NLIMB, n), dtype=np.int64)
        zs = np.zeros((_NLIMB, n), dtype=np.int64)
        for i, (x, y) in enumerate(live):
            xs[:, i] = _int_to_limbs_py(x)
            ys[:, i] = _int_to_limbs_py(y)
            zs[0, i] = 1
        with _x64():
            jx, jy, jz = jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs)
            shift = 1
            while shift < n:
                jx, jy, jz = _level(jx, jy, jz, shift)
                shift <<= 1
            out = (np.asarray(jx[:, 0]), np.asarray(jy[:, 0]),
                   np.asarray(jz[:, 0]))
        X = _limbs_to_int_py(out[0])
        Y = _limbs_to_int_py(out[1])
        Z = _limbs_to_int_py(out[2])
        if Z == 0:
            return None
        return (X, Y, Z)

    return jax_sum


_jax_fn = None
_jax_lock = threading.Lock()


def _jax_sum(points: List[AffinePoint]) -> G1Point:
    global _jax_fn
    with _jax_lock:
        if _jax_fn is None:
            _jax_fn = _build_jax()
        fn = _jax_fn
    return fn(points)


def _register_jax_backend() -> None:
    """Deferred like crypto/batch: importing this module never forces a
    jax init; the kernel builds on first use."""
    try:
        import jax  # noqa: F401
    except ImportError:
        LOG.info("jax unavailable; BLS MSM runs on the python backend")
        return
    register_msm_backend("jax", _jax_sum)


_register_jax_backend()
