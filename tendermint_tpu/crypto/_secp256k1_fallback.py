"""Pure-Python secp256k1 ECDSA (SEC 2 curve, RFC 6979 nonces) —
fallback backend for crypto/secp256k1.py when the `cryptography`
package's OpenSSL bindings are absent, the same arrangement as
_ed25519_fallback.py / _aead_fallback.py.

Deterministic RFC 6979 signing (OpenSSL's random-k path isn't
reproducible anyway, and a misbehaving RNG here would leak the key).
Affine double-and-add, ~10 ms per scalar mult — secp256k1 keys are an
account-key convenience in this codebase, never the consensus hot path.
Not constant-time; production deployments install `cryptography`.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Optional, Tuple

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
_G = (_GX, _GY)

_Point = Optional[Tuple[int, int]]  # None is the point at infinity


def _pt_add(p1: _Point, p2: _Point) -> _Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        m = (3 * x1 * x1) * pow(2 * y1, P - 2, P) % P
    else:
        m = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (m * m - x1 - x2) % P
    return (x3, (m * (x1 - x3) - y1) % P)


def _pt_mul(k: int, pt: _Point) -> _Point:
    acc: _Point = None
    while k > 0:
        if k & 1:
            acc = _pt_add(acc, pt)
        pt = _pt_add(pt, pt)
        k >>= 1
    return acc


def _compress(pt: Tuple[int, int]) -> bytes:
    x, y = pt
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


def _decompress(data: bytes) -> _Point:
    if len(data) != 33 or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= P:
        return None
    y2 = (x * x * x + 7) % P
    y = pow(y2, (P + 1) // 4, P)  # P ≡ 3 (mod 4)
    if (y * y) % P != y2:
        return None  # x is not on the curve
    if y & 1 != data[0] & 1:
        y = P - y
    return (x, y)


def _rfc6979_k(d: int, e: int):
    """RFC 6979 §3.2 deterministic nonce stream, HMAC-SHA256,
    qlen = 256. Yields candidate nonces; the caller pulls another on a
    vanishing r or s (§3.2 step h.3)."""
    h1 = e.to_bytes(32, "big")
    x = d.to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            yield cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def gen_scalar() -> int:
    while True:
        d = int.from_bytes(os.urandom(32), "big")
        if 1 <= d < N:
            return d


def pub_from_scalar(d: int) -> bytes:
    """33-byte compressed SEC1 public key for the scalar d."""
    return _compress(_pt_mul(d, _G))


def ecdsa_sign(d: int, msg: bytes) -> Tuple[int, int]:
    """SHA256-ECDSA, RFC 6979 nonce. Returns raw (r, s) — the caller
    applies low-s normalization (matching the OpenSSL path's shape)."""
    e = int.from_bytes(hashlib.sha256(msg).digest(), "big")
    for k in _rfc6979_k(d, e):
        pt = _pt_mul(k, _G)
        r = pt[0] % N
        if r == 0:
            continue
        s = pow(k, N - 2, N) * (e + r * d) % N
        if s == 0:
            continue
        return r, s


def ecdsa_verify(pub33: bytes, msg: bytes, r: int, s: int) -> bool:
    if not (1 <= r < N and 1 <= s < N):
        return False
    q = _decompress(pub33)
    if q is None:
        return False
    e = int.from_bytes(hashlib.sha256(msg).digest(), "big")
    s_inv = pow(s, N - 2, N)
    pt = _pt_add(_pt_mul(e * s_inv % N, _G), _pt_mul(r * s_inv % N, q))
    return pt is not None and pt[0] % N == r
