"""Symmetric AEAD secret-box (reference crypto/xchacha20poly1305 +
crypto/xsalsa20symmetric).

Same capability surface — encrypt/decrypt with a 32-byte key, nonce
handled internally, authenticated — over ChaCha20-Poly1305 (the IETF
96-bit-nonce construction from `cryptography`; the reference's
24-byte-nonce X variants exist only to make random nonces safe, which
we keep by bounding messages per key the same way callers do: armored
key files are encrypt-once). Passphrase keys are derived with scrypt
standing in for the reference's bcrypt (armor key path,
crypto/armor + keys).
"""

from __future__ import annotations

import hashlib
import os

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ImportError:  # no OpenSSL bindings: pure-Python RFC 8439 fallback
    from ._aead_fallback import ChaCha20Poly1305, InvalidTag

NONCE_SIZE = 12
KEY_SIZE = 32


class DecryptError(Exception):
    pass


def encrypt_symmetric(plaintext: bytes, key: bytes) -> bytes:
    """xsalsa20symmetric.EncryptSymmetric equivalent:
    nonce ‖ ciphertext+tag."""
    if len(key) != KEY_SIZE:
        raise ValueError(f"key must be {KEY_SIZE} bytes")
    nonce = os.urandom(NONCE_SIZE)
    ct = ChaCha20Poly1305(key).encrypt(nonce, plaintext, b"")
    return nonce + ct

def decrypt_symmetric(ciphertext: bytes, key: bytes) -> bytes:
    if len(key) != KEY_SIZE:
        raise ValueError(f"key must be {KEY_SIZE} bytes")
    if len(ciphertext) < NONCE_SIZE + 16:
        raise DecryptError("ciphertext too short")
    nonce, ct = ciphertext[:NONCE_SIZE], ciphertext[NONCE_SIZE:]
    try:
        return ChaCha20Poly1305(key).decrypt(nonce, ct, b"")
    except InvalidTag:
        raise DecryptError("ciphertext decryption failed")


def key_from_passphrase(passphrase: str, salt: bytes) -> bytes:
    """Derive a 32-byte key (reference uses bcrypt(12) then sha256;
    scrypt n=2^15 gives comparable work)."""
    return hashlib.scrypt(passphrase.encode(), salt=salt,
                          n=1 << 15, r=8, p=1, dklen=KEY_SIZE,
                          maxmem=64 * 1024 * 1024)
