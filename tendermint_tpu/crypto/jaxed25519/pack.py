"""Host-side (numpy) packing: bytes <-> 13-bit limbs, SHA-512 padding.

The device kernel wants batch-last layouts — field elements are (20, B)
int32 limb arrays (batch rides the TPU's 128-wide lanes), SHA-512 message
words are (NB, 16, 2, B) uint32 (hi, lo) pairs. Everything here is
vectorized numpy; no per-item Python loops on the hot path.
"""

from __future__ import annotations

import numpy as np

BITS = 13
MASK = (1 << BITS) - 1
NLIMB = 20  # 260 bits >= field/scalar width


def int_to_limbs(v: int, n: int = NLIMB) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = v & MASK
        v >>= BITS
    if v:
        raise ValueError("value does not fit in limbs")
    return out


def limbs_to_int(limbs) -> int:
    v = 0
    for i, l in enumerate(np.asarray(limbs).tolist()):
        v += int(l) << (BITS * i)
    return v


def bytes_to_limbs_batch(arr: np.ndarray, nlimb: int = NLIMB) -> np.ndarray:
    """(B, nbytes) uint8 little-endian -> (nlimb, B) int32 13-bit limbs."""
    b, nbytes = arr.shape
    bits = np.unpackbits(arr, axis=1, bitorder="little")  # (B, nbytes*8)
    want = nlimb * BITS
    if bits.shape[1] < want:
        bits = np.pad(bits, ((0, 0), (0, want - bits.shape[1])))
    bits = bits[:, :want].reshape(b, nlimb, BITS)
    weights = (1 << np.arange(BITS)).astype(np.int32)
    limbs = (bits.astype(np.int32) * weights).sum(axis=2)  # (B, nlimb)
    return np.ascontiguousarray(limbs.T.astype(np.int32))


def lt_const_le_batch(arr: np.ndarray, const: int) -> np.ndarray:
    """Vectorized `little-endian-bytes < const` -> bool (B,)."""
    b, nbytes = arr.shape
    cb = np.frombuffer(const.to_bytes(nbytes, "little"), dtype=np.uint8)
    # compare from most significant byte down
    a_be = arr[:, ::-1].astype(np.int16)
    c_be = cb[::-1].astype(np.int16)
    diff = a_be - c_be  # (B, nbytes)
    neq = diff != 0
    first = np.argmax(neq, axis=1)  # first differing byte from MSB
    any_neq = neq.any(axis=1)
    picked = diff[np.arange(b), first]
    return np.where(any_neq, picked < 0, False)


def split_signatures(sigs: np.ndarray):
    """(B, 64) uint8 -> (R_y (20,B), R_sign (B,), S limbs (20,B), s_lt_l (B,))."""
    from . import ref

    r = np.ascontiguousarray(sigs[:, :32])
    s = np.ascontiguousarray(sigs[:, 32:])
    sign = (r[:, 31] >> 7).astype(np.int32)
    r_masked = r.copy()
    r_masked[:, 31] &= 0x7F
    r_y = bytes_to_limbs_batch(r_masked)
    s_limbs = bytes_to_limbs_batch(s)
    s_ok = lt_const_le_batch(s, ref.L)
    return r_y, sign, s_limbs, s_ok


def split_pubkeys(pks: np.ndarray):
    """(B, 32) uint8 -> (A_y limbs (20,B), A_sign (B,))."""
    sign = (pks[:, 31] >> 7).astype(np.int32)
    masked = pks.copy()
    masked[:, 31] &= 0x7F
    return bytes_to_limbs_batch(masked), sign


def fill_msg_bytes(out: np.ndarray, msgs: list[bytes], lens: np.ndarray,
                   col0: int = 0) -> None:
    """Write each msgs[i] into out[i, col0:col0+len(i)] — one vectorized
    scatter for ragged lengths, a plain reshape when uniform."""
    b = out.shape[0]
    if not b or not lens.max():
        return
    joined = np.frombuffer(b"".join(msgs), dtype=np.uint8)
    if (lens == lens[0]).all():
        out[:, col0 : col0 + int(lens[0])] = joined.reshape(b, int(lens[0]))
        return
    rows = np.repeat(np.arange(b), lens)
    starts = np.repeat(np.cumsum(lens) - lens, lens)
    cols = col0 + np.arange(joined.size, dtype=np.int64) - starts
    out[rows, cols] = joined


def sha512_pad_rows(prefixes: np.ndarray, msgs: list[bytes]):
    """Like sha512_pad_batch but returns (rows (B, NB*32) int32, nblocks):
    each row strip is the big-endian uint32 (hi, lo) word stream in row
    order. (The production verify path now ships raw message bytes and
    pads on device — see verify._verify_packed_core; this host padder
    serves the sharded/test path via sha512_pad_batch.) A uniform-length
    fast path skips the ragged scatter.
    """
    b = prefixes.shape[0]
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=b)
    maxlen = int(lens.max()) if b else 0
    nb = (64 + maxlen + 17 + 127) // 128
    buf = np.zeros((b, nb * 128), dtype=np.uint8)
    buf[:, :64] = prefixes
    fill_msg_bytes(buf, msgs, lens, col0=64)
    mlen = 64 + lens
    rng = np.arange(b)
    buf[rng, mlen] = 0x80
    inb = (mlen + 17 + 127) // 128
    nblocks = inb.astype(np.int32)
    bitlen = mlen * 8
    end = inb * 128
    for j in range(8):
        buf[rng, end - 8 + j] = (bitlen >> (8 * (7 - j))) & 0xFF
    # LE uint32 view + byteswap = big-endian words, already in row order
    words = buf.view("<u4").byteswap().view(np.int32)  # (B, NB*32)
    return words, nblocks


def sha512_pad_batch(prefixes: np.ndarray, msgs: list[bytes]):
    """Build padded SHA-512 input blocks for SHA512(prefix || msg) per item.

    prefixes: (B, 64) uint8 (R || A). Returns (words, nblocks):
    words (NB, 16, 2, B) uint32 (hi, lo) pairs where NB is the batch-max
    block count, and nblocks (B,) int32 — each item's own padded block
    count. The device compression loop runs NB blocks but only applies
    updates for block j < nblocks[i], so mixed message lengths hash
    correctly in one bucket. Thin layout adapter over sha512_pad_rows.
    """
    rows, nblocks = sha512_pad_rows(prefixes, msgs)
    b = rows.shape[0]
    nb = rows.shape[1] // 32
    out = rows.view(np.uint32).reshape(b, nb, 16, 2)
    return np.ascontiguousarray(out.transpose(1, 2, 3, 0)), nblocks
