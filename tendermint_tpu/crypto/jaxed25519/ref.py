"""Pure-Python Ed25519 reference (RFC 8032 math, Go-compatible verify).

Host-side big-int implementation used for (a) precomputing the fixed-base
window tables consumed by the JAX kernel, and (b) an independent test oracle
for the device implementation. Verification semantics match the reference's
forked golang.org/x/crypto/ed25519 (crypto/ed25519/ed25519.go:151-157):
reject S >= L, decompress A (mod-p interpretation of the y bytes, no
canonicity requirement), recompute R' = [S]B - [k]A and byte-compare the
canonical encoding of R' against the signature's R half.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# extended homogeneous coordinates (X, Y, Z, T) with x = X/Z, y = Y/Z, T = XY/Z
IDENTITY = (0, 1, 1, 0)


def _recover_x(y: int, sign: int):
    """x from y per RFC 8032 §5.1.3. Returns None if no square root."""
    y %= P
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P), (P - 5) // 8, P)) % P
    vxx = (v * x * x) % P
    if vxx == u:
        pass
    elif vxx == (-u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    if x == 0 and sign == 1:
        return None
    if x & 1 != sign:
        x = P - x
    return x


def decompress(data: bytes):
    """32-byte encoding -> extended point, or None. Top bit is the x sign;
    the remaining 255 bits are y interpreted mod P (Go accepts y >= P)."""
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    y %= P
    return (x, y, 1, (x * y) % P)


def compress(pt) -> bytes:
    X, Y, Z, _ = pt
    zinv = pow(Z, P - 2, P)
    x = (X * zinv) % P
    y = (Y * zinv) % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = ((Y1 - X1) * (Y2 - X2)) % P
    b = ((Y1 + X1) * (Y2 + X2)) % P
    c = (T1 * D2 * T2) % P
    d = (2 * Z1 * Z2) % P
    e, f, g, h = (b - a) % P, (d - c) % P, (d + c) % P, (b + a) % P
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def double(p):
    X1, Y1, Z1, _ = p
    a = (X1 * X1) % P
    b = (Y1 * Y1) % P
    c = (2 * Z1 * Z1) % P
    h = (a + b) % P
    e = (h - (X1 + Y1) * (X1 + Y1)) % P
    g = (a - b) % P
    f = (c + g) % P
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def negate(p):
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def scalar_mult(k: int, p):
    q = IDENTITY
    while k > 0:
        if k & 1:
            q = add(q, p)
        p = double(p)
        k >>= 1
    return q


def equal(p, q) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


@lru_cache(maxsize=1)
def base_point():
    by = (4 * pow(5, P - 2, P)) % P
    bx = _recover_x(by, 0)
    return (bx, by, 1, (bx * by) % P)


def to_affine(p):
    X, Y, Z, _ = p
    zinv = pow(Z, P - 2, P)
    return (X * zinv) % P, (Y * zinv) % P


def niels(p):
    """Affine precomputed form (y+x, y-x, 2*d*x*y) for mixed additions."""
    x, y = to_affine(p)
    return ((y + x) % P, (y - x) % P, (D2 * x * y) % P)


NIELS_IDENTITY = (1, 1, 0)


@lru_cache(maxsize=1)
def base_table():
    """table[i][j] = niels([j * 16^i]B) for i in 0..63, j in 0..15.

    Lets the device compute [S]B as 64 mixed additions with no doublings:
    S = sum(e_i * 16^i), [S]B = sum([e_i * 16^i]B).
    """
    table = []
    row_base = base_point()  # [16^i]B
    for _ in range(64):
        row = [NIELS_IDENTITY]
        acc = IDENTITY
        for _ in range(15):
            acc = add(acc, row_base)
            row.append(niels(acc))
        table.append(row)
        for _ in range(4):
            row_base = double(row_base)
    return table


def verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """Go-compatible single verify (test oracle only — the production CPU
    path is OpenSSL via crypto.keys; the production batch path is JAX)."""
    if len(sig) != 64 or len(pubkey) != 32:
        return False
    r_bytes, s_bytes = sig[:32], sig[32:]
    s = int.from_bytes(s_bytes, "little")
    if s >= L:
        return False
    a = decompress(pubkey)
    if a is None:
        return False
    k = int.from_bytes(
        hashlib.sha512(r_bytes + pubkey + msg).digest(), "little"
    ) % L
    rp = add(scalar_mult(s, base_point()), scalar_mult(k, negate(a)))
    return compress(rp) == r_bytes
