"""Batched SHA-512 on device (uint32 hi/lo pairs; no native u64 on TPU).

Computes k = SHA512(R || A || M) for every signature in the batch, entirely
on device, so the hash never bottlenecks the verify pipeline on the host.
Words are (hi, lo) uint32 pairs; 64-bit adds use an unsigned-compare carry;
rotations recombine across the pair. Message layout from pack.sha512_pad_batch:
(NB, 16, 2, B) with per-item active block counts for mixed-length batches.
"""

from __future__ import annotations

from functools import lru_cache
from math import isqrt

import jax
import jax.numpy as jnp
import numpy as np

U32 = jnp.uint32
MASK64 = (1 << 64) - 1


def _icbrt(n: int) -> int:
    x = int(round(n ** (1 / 3)))
    while x * x * x > n:
        x -= 1
    while (x + 1) ** 3 <= n:
        x += 1
    return x


@lru_cache(maxsize=1)
def _constants():
    """K[0..79] and H0[0..7] as (n, 2) uint32 numpy (hi, lo)."""
    primes = []
    c = 2
    while len(primes) < 80:
        if all(c % q for q in primes if q * q <= c):
            primes.append(c)
        c += 1
    k = [(_icbrt(p << 192) & MASK64) for p in primes]
    h0 = [(isqrt(p << 128) & MASK64) for p in primes[:8]]
    to_pairs = lambda xs: np.array(
        [[(v >> 32) & 0xFFFFFFFF, v & 0xFFFFFFFF] for v in xs], dtype=np.uint32
    )
    return to_pairs(k), to_pairs(h0)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(U32)
    hi = ah + bh + carry
    return hi, lo


def _rotr64(h, l, n):
    n %= 64
    if n == 0:
        return h, l
    if n == 32:
        return l, h
    if n < 32:
        nh = (h >> n) | (l << (32 - n))
        nl = (l >> n) | (h << (32 - n))
    else:
        m = n - 32
        nh = (l >> m) | (h << (32 - m))
        nl = (h >> m) | (l << (32 - m))
    return nh, nl


def _shr64(h, l, n):
    if n < 32:
        return h >> n, (l >> n) | (h << (32 - n))
    return jnp.zeros_like(h), h >> (n - 32)


def _small_sigma0(h, l):
    ah, al = _rotr64(h, l, 1)
    bh, bl = _rotr64(h, l, 8)
    ch, cl = _shr64(h, l, 7)
    return ah ^ bh ^ ch, al ^ bl ^ cl


def _small_sigma1(h, l):
    ah, al = _rotr64(h, l, 19)
    bh, bl = _rotr64(h, l, 61)
    ch, cl = _shr64(h, l, 6)
    return ah ^ bh ^ ch, al ^ bl ^ cl


def _big_sigma0(h, l):
    ah, al = _rotr64(h, l, 28)
    bh, bl = _rotr64(h, l, 34)
    ch, cl = _rotr64(h, l, 39)
    return ah ^ bh ^ ch, al ^ bl ^ cl


def _big_sigma1(h, l):
    ah, al = _rotr64(h, l, 14)
    bh, bl = _rotr64(h, l, 18)
    ch, cl = _rotr64(h, l, 41)
    return ah ^ bh ^ ch, al ^ bl ^ cl


def _compress_block(state, block, k_const):
    """state (8, 2, B); block (16, 2, B) -> new state."""
    bdim = block.shape[-1]
    w = jnp.zeros((80, 2, bdim), dtype=U32)
    w = w.at[:16].set(block)

    def sched(i, w):
        w2h, w2l = _small_sigma1(w[i - 2, 0], w[i - 2, 1])
        w15h, w15l = _small_sigma0(w[i - 15, 0], w[i - 15, 1])
        h, l = _add64(w[i - 16, 0], w[i - 16, 1], w2h, w2l)
        h, l = _add64(h, l, w[i - 7, 0], w[i - 7, 1])
        h, l = _add64(h, l, w15h, w15l)
        return w.at[i].set(jnp.stack([h, l]))

    w = jax.lax.fori_loop(16, 80, sched, w)

    def rnd(i, regs):
        a_h, a_l, b_h, b_l, c_h, c_l, d_h, d_l, e_h, e_l, f_h, f_l, g_h, g_l, hh, hl = regs
        s1h, s1l = _big_sigma1(e_h, e_l)
        chh = (e_h & f_h) ^ (~e_h & g_h)
        chl = (e_l & f_l) ^ (~e_l & g_l)
        t1h, t1l = _add64(hh, hl, s1h, s1l)
        t1h, t1l = _add64(t1h, t1l, chh, chl)
        t1h, t1l = _add64(t1h, t1l, k_const[i, 0], k_const[i, 1])
        t1h, t1l = _add64(t1h, t1l, w[i, 0], w[i, 1])
        s0h, s0l = _big_sigma0(a_h, a_l)
        majh = (a_h & b_h) ^ (a_h & c_h) ^ (b_h & c_h)
        majl = (a_l & b_l) ^ (a_l & c_l) ^ (b_l & c_l)
        t2h, t2l = _add64(s0h, s0l, majh, majl)
        ne_h, ne_l = _add64(d_h, d_l, t1h, t1l)
        na_h, na_l = _add64(t1h, t1l, t2h, t2l)
        return (na_h, na_l, a_h, a_l, b_h, b_l, c_h, c_l, ne_h, ne_l, e_h, e_l, f_h, f_l, g_h, g_l)

    regs = tuple(state[i // 2, i % 2] for i in range(16))
    regs = jax.lax.fori_loop(0, 80, rnd, regs)
    out = []
    for i in range(8):
        h, l = _add64(state[i, 0], state[i, 1], regs[2 * i], regs[2 * i + 1])
        out.append(jnp.stack([h, l]))
    return jnp.stack(out)


def sha512_batch(words, nblocks):
    """words (NB, 16, 2, B) uint32, nblocks (B,) int32 -> digest (8, 2, B).

    Runs all NB blocks; block j only updates items with j < nblocks[i].
    """
    k_np, h0_np = _constants()
    k_const = jnp.asarray(k_np)
    bdim = words.shape[-1]
    state = jnp.broadcast_to(jnp.asarray(h0_np)[:, :, None], (8, 2, bdim))
    # tie to the (possibly mesh-sharded) input so loop carries are varying
    # over the shard_map axis — constants alone are "unvarying" and fail
    # the scan carry check inside shard_map
    state = state ^ (words[0, 0, 0] * jnp.uint32(0))
    nb = words.shape[0]
    for j in range(nb):
        new_state = _compress_block(state, words[j], k_const)
        active = (j < nblocks)[None, None, :]
        state = jnp.where(active, new_state, state)
    return state


def digest_to_scalar_limbs(digest):
    """(8, 2, B) uint32 big-endian words -> 40 x 13-bit limbs of the
    little-endian 512-bit integer (RFC 8032 interpretation)."""
    # bytes little-endian: byte index 8*w + (7 - b) for word w, BE byte b.
    # Build the 512-bit little-endian integer's bit stream from the words:
    # word w contributes bits [64w, 64w+64) as the byte-reversed u64.
    bdim = digest.shape[-1]
    # byte k of word w (little-endian within word) = byte (7-k) of BE pair
    # stream byte k of word w (k=0 first) is the BE word's most-significant
    # byte first: k 0..3 from hi (MSB down), k 4..7 from lo
    bytes_per_word = []
    for w in range(8):
        hi = digest[w, 0]
        lo = digest[w, 1]
        for k in range(8):
            src, off = (hi, 3 - k) if k < 4 else (lo, 7 - k)
            bytes_per_word.append((src >> (8 * off)) & 0xFF)
    allbytes = jnp.stack(bytes_per_word).astype(jnp.int32)  # (64, B) LE bytes
    # 64 bytes -> 40 limbs of 13 bits: limb i = bits [13i, 13i+13)
    limbs = []
    for i in range(40):
        bit = 13 * i
        byi, sh = bit // 8, bit % 8
        v = allbytes[byi] >> sh
        if byi + 1 < 64:
            v = v | (allbytes[byi + 1] << (8 - sh))
        if byi + 2 < 64 and 8 - sh + 8 < 13 + 8:
            v = v | (allbytes[byi + 2] << (16 - sh))
        limbs.append(v & 0x1FFF)
    return jnp.stack(limbs)  # (40, B)
