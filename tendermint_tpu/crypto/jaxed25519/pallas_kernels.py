"""Fused Pallas TPU kernels for the Ed25519 batch-verify hot path.

Why: the XLA path in field.py/curve.py materializes the schoolbook-conv
intermediates of every field multiply in HBM (~10-60 MB per mul at
B=10k), which makes the ~2800-mul Straus chain HBM-bound (~21.5 us/mul
measured vs a ~3 us fused roofline — see PROFILE.md). This module runs
the ENTIRE joint scalar-multiplication loop as one Pallas kernel: the
accumulator, the per-item 15-entry table and every conv intermediate
stay in VMEM; HBM traffic collapses to the kernel inputs and outputs.

Semantics mirror field.py/curve.py exactly (same 20x13-bit limb
representation, same LIMB_BOUND invariant, same RFC 8032 complete
addition formulas); the reference behavior being replaced is the serial
verify loop at crypto/ed25519/ed25519.go:151-157 driven by
types/validator_set.go:345-371.

Value-level differences from field.py (pallas-friendly forms only):
- jnp.pad / .at[] are replaced by concatenate + pltpu.roll with static
  shifts (interpret mode substitutes jnp.roll, which pltpu.roll does
  not support off-TPU).
- The fixed-base niels table lookup is a one-hot f32 matmul on the MXU
  (exact: one-hot times 13-bit entries, single-term sums stay far under
  the 24-bit f32 mantissa), which is otherwise idle in this kernel.
- The per-item variable-base window select is a 4-level binary tree of
  lane-broadcast selects on the window bits (half the VPU ops of the
  15-term masked multiply-accumulate it replaces).
- Doublings and the per-window niels add skip the extended T coordinate
  whenever no consumer reads it (T is only needed by the one doubling
  that feeds add_cached, and by the final window when the caller wants
  T back): 4 of the ~45 field muls per window are dead and dropped.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pack import BITS, MASK, NLIMB


# --- field arithmetic on VMEM values (mirrors field.py) --------------------


def _zeros(rows, blk):
    return jnp.zeros((rows, blk), jnp.int32)


def _carry(v):
    """One parallel carry round within 20 limbs (field._carry_round)."""
    blk = v.shape[1]
    r = v >> BITS
    m = v & MASK
    # m[1:] += r[:-1]; m[0] += 608 * r[19]
    shifted = jnp.concatenate([_zeros(1, blk), r[:-1]], axis=0)
    top = jnp.concatenate([608 * r[19:20], _zeros(NLIMB - 1, blk)], axis=0)
    return m + shifted + top


def _reduce39(c):
    """39-coefficient conv output -> 20 bounded limbs (field._reduce_conv)."""
    blk = c.shape[1]
    r = c >> BITS
    m = c & MASK
    full = jnp.concatenate([m, _zeros(1, blk)], axis=0) + jnp.concatenate(
        [_zeros(1, blk), r], axis=0
    )
    v = full[:NLIMB] + 608 * full[NLIMB:]
    for _ in range(3):
        v = _carry(v)
    return v


def _tree_sum(terms):
    while len(terms) > 1:
        terms = [
            terms[j] + terms[j + 1] if j + 1 < len(terms) else terms[j]
            for j in range(0, len(terms), 2)
        ]
    return terms[0]


def _make_ops(interpret: bool):
    """Field + point ops bound to the right roll implementation."""
    roll = jnp.roll if interpret else pltpu.roll

    def neg(a):
        return _carry(-a)

    # Exact carry/borrow resolution: the Kogge-Stone parallel-prefix
    # resolves in field.py (one shared implementation — everything they
    # use lowers in Mosaic: concatenate/full/where/shifts on 2-D shapes).
    # vs the old sequential 20-step chains this is 5 dependent rounds of
    # full-width (20, blk) selects instead of ~60 dependent (1, blk) ops
    # at 1/8 sublane utilization.
    from . import field as _field

    seq_carry = _field._seq_carry
    cond_sub = _field._cond_sub

    def freeze(a, p_mults):
        """Canonical limbs in [0, p); p_mults = (16p, 8p, 4p, 2p, p, p)."""
        v = a
        for _ in range(2):
            limbs, carry = seq_carry(v)
            v = jnp.concatenate([limbs[:1] + 608 * carry, limbs[1:]], axis=0)
        limbs, _ = seq_carry(v)
        v = limbs
        for m in p_mults:
            v = cond_sub(v, m)
        return v

    def mul(a, b):
        blk = a.shape[1]
        z19 = _zeros(NLIMB - 1, blk)
        terms = []
        for i in range(NLIMB):
            prod = a[i : i + 1] * b  # (20, blk)
            padded = jnp.concatenate([prod, z19], axis=0)  # (39, blk)
            terms.append(roll(padded, i, 0) if i else padded)
        return _reduce39(_tree_sum(terms))

    def sq(a):
        blk = a.shape[1]
        a2 = a + a
        terms = []
        for i in range(NLIMB):
            # diagonal term once, cross terms doubled for j > i (20-i rows)
            parts = [a[i : i + 1]]
            if i + 1 < NLIMB:
                parts.append(a2[i + 1 :])
            row = a[i : i + 1] * jnp.concatenate(parts, axis=0)
            padded = jnp.concatenate([row, _zeros(NLIMB - 1 + i, blk)], axis=0)
            terms.append(roll(padded, 2 * i, 0) if i else padded)
        return _reduce39(_tree_sum(terms))

    add = lambda a, b: _carry(a + b)
    sub = lambda a, b: _carry(a - b)

    def _double_efgh(p):
        X1, Y1, Z1 = p[0], p[1], p[2]
        a = sq(X1)
        b = sq(Y1)
        zz = sq(Z1)
        c = add(zz, zz)
        h = add(a, b)
        xy = add(X1, Y1)
        e = sub(h, sq(xy))
        g = sub(a, b)
        f = add(c, g)
        return e, f, g, h

    def double(p):
        e, f, g, h = _double_efgh(p)
        return (mul(e, f), mul(g, h), mul(f, g), mul(e, h))

    def double3(p):
        """Doubling without the extended T output — for chains where the
        next op is another doubling (which never reads T)."""
        e, f, g, h = _double_efgh(p)
        return (mul(e, f), mul(g, h), mul(f, g))

    def to_cached(p, d2):
        X, Y, Z, T = p
        return (add(Y, X), sub(Y, X), Z, mul(T, d2))

    def add_cached(p, q):
        X1, Y1, Z1, T1 = p
        yplusx2, yminusx2, Z2, t2d2 = q
        a = mul(sub(Y1, X1), yminusx2)
        b = mul(add(Y1, X1), yplusx2)
        c = mul(T1, t2d2)
        zz = mul(Z1, Z2)
        d = add(zz, zz)
        e = sub(b, a)
        f = sub(d, c)
        g = add(d, c)
        h = add(b, a)
        return (mul(e, f), mul(g, h), mul(f, g), mul(e, h))

    def _add_niels_efgh(p, n):
        X1, Y1, Z1, T1 = p
        yplusx2, yminusx2, xy2d2 = n
        a = mul(sub(Y1, X1), yminusx2)
        b = mul(add(Y1, X1), yplusx2)
        c = mul(T1, xy2d2)
        d = add(Z1, Z1)
        e = sub(b, a)
        f = sub(d, c)
        g = add(d, c)
        h = add(b, a)
        return e, f, g, h

    def add_niels(p, n):
        e, f, g, h = _add_niels_efgh(p, n)
        return (mul(e, f), mul(g, h), mul(f, g), mul(e, h))

    def add_niels3(p, n):
        """Niels add without the extended T output — for window tails
        where the next consumer is a doubling."""
        e, f, g, h = _add_niels_efgh(p, n)
        return (mul(e, f), mul(g, h), mul(f, g))

    def pow2k(x, k):
        return jax.lax.fori_loop(0, k, lambda _, v: sq(v), x)

    def pow_chain_250(z):
        """z^(2^250 - 1) — shared prefix of invert/pow22523 (field.py)."""
        z2 = sq(z)
        t = sq(sq(z2))
        z9 = mul(t, z)
        z11 = mul(z9, z2)
        t = sq(z11)
        z_5_0 = mul(t, z9)
        t = pow2k(z_5_0, 5)
        z_10_0 = mul(t, z_5_0)
        t = pow2k(z_10_0, 10)
        z_20_0 = mul(t, z_10_0)
        t = pow2k(z_20_0, 20)
        z_40_0 = mul(t, z_20_0)
        t = pow2k(z_40_0, 10)
        z_50_0 = mul(t, z_10_0)
        t = pow2k(z_50_0, 50)
        z_100_0 = mul(t, z_50_0)
        t = pow2k(z_100_0, 100)
        z_200_0 = mul(t, z_100_0)
        t = pow2k(z_200_0, 50)
        z_250_0 = mul(t, z_50_0)
        return z_250_0, z11

    def invert(z):
        z_250_0, z11 = pow_chain_250(z)
        return mul(pow2k(z_250_0, 5), z11)

    def pow22523(z):
        z_250_0, _ = pow_chain_250(z)
        return mul(pow2k(z_250_0, 2), z)

    import types

    return types.SimpleNamespace(
        mul=mul, sq=sq, add=add, sub=sub, neg=neg, double=double,
        double3=double3, to_cached=to_cached, add_cached=add_cached,
        add_niels=add_niels, add_niels3=add_niels3,
        seq_carry=seq_carry, cond_sub=cond_sub, freeze=freeze,
        pow2k=pow2k, invert=invert, pow22523=pow22523,
    )


@lru_cache(maxsize=1)
def _btab_np():
    """(16, 64) int32: niels rows [j]B for j=0..15 in cols 0:60."""
    from .curve import _small_base_table_np

    t = np.zeros((16, 64), dtype=np.int32)
    t[:, :60] = _small_base_table_np().astype(np.int64).astype(np.int32)
    return t


def _tree_select(idx, entries):
    """4-level binary-tree select of one of 16 table entries per lane.

    idx: (1, blk) int32 in [0, 16); entries: length-16 list of tuples of
    (rows, blk) arrays. Costs 15 lane-broadcast selects per component —
    about half the VPU work of a 16-term masked multiply-accumulate."""
    level = entries
    for bit in range(4):
        b = ((idx >> bit) & 1) != 0  # (1, blk)
        level = [
            tuple(jnp.where(b, hi, lo) for lo, hi in zip(level[2 * j], level[2 * j + 1]))
            for j in range(len(level) // 2)
        ]
    return level[0]


def _straus_loop(ops, s_win_ref, k_win_ref, neg_a, d2, btab, blk,
                 want_t: bool = False):
    """The joint [s]B + [k]*neg_a chain on VMEM values (see
    curve.straus_mul_sub for the algorithm). Returns (X, Y, Z) — plus the
    extended T when want_t (callers that only encode never read T, and
    skipping it drops 4 dead muls per window)."""
    # per-item table cached([j]*neg_a), j=1..15 — VMEM-resident
    na_cached = ops.to_cached(neg_a, d2)
    mults = [neg_a]
    for j in range(2, 16):
        if j % 2 == 0:
            mults.append(ops.double(mults[j // 2 - 1]))
        else:
            mults.append(ops.add_cached(mults[j - 2], na_cached))
    table = [ops.to_cached(p, d2) for p in mults]
    # tree-select domain is 16 entries; index 15 is only produced by the
    # kw==0 lanes whose add is discarded by the where below — pad with a
    # duplicate so every index is in range
    table16 = table + [table[14]]

    zero = _zeros(NLIMB, blk)
    one = jnp.concatenate(
        [jnp.ones((1, blk), jnp.int32), _zeros(NLIMB - 1, blk)], axis=0
    )
    btab_f = btab[:, :60].astype(jnp.float32)  # (16, 60), loop-invariant

    def window(w, acc3, tail_t: bool):
        acc3 = ops.double3(ops.double3(ops.double3(acc3)))
        acc = ops.double(acc3)  # full: add_cached consumes T
        # variable-base window: binary-tree select over the cached table
        kw = k_win_ref[pl.ds(w, 1), :]  # (1, blk)
        sel = _tree_select((kw - 1) & 15, table16)
        added = ops.add_cached(acc, sel)
        acc = tuple(jnp.where(kw != 0, x, y) for x, y in zip(added, acc))
        # fixed-base window: one-hot f32 matmul on the (otherwise idle)
        # MXU — exact, one-hot times 13-bit entries
        sw = s_win_ref[pl.ds(w, 1), :]  # (1, blk)
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, (16, blk), 0) == sw
        ).astype(jnp.float32)
        # HIGHEST precision is required: the TPU MXU's default f32 path
        # rounds inputs to bf16 (8 mantissa bits), which corrupts 13-bit
        # table entries; the 3-way bf16 split is exact at these magnitudes
        ent = jax.lax.dot_general(
            btab_f, onehot, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)  # (60, blk)
        n = (ent[:20], ent[20:40], ent[40:60])
        return ops.add_niels(acc, n) if tail_t else ops.add_niels3(acc, n)

    acc3 = jax.lax.fori_loop(
        0, 63, lambda w, a: window(w, a, False), (zero, one, one)
    )
    return window(63, acc3, want_t)


def _make_straus_kernel(interpret: bool):
    ops = _make_ops(interpret)

    def kernel(s_win_ref, k_win_ref, nax_ref, nay_ref, naz_ref, nat_ref,
               btab_ref, ox_ref, oy_ref, oz_ref, ot_ref):
        from . import ref

        na = (nax_ref[:], nay_ref[:], naz_ref[:], nat_ref[:])
        blk = na[0].shape[1]
        d2 = _const_fe_rows(ref.D2, blk)
        btab = btab_ref[:]  # (16, 64)
        X, Y, Z, T = _straus_loop(ops, s_win_ref, k_win_ref, na, d2, btab, blk,
                                  want_t=True)
        ox_ref[:] = X
        oy_ref[:] = Y
        oz_ref[:] = Z
        ot_ref[:] = T

    return kernel


def _pick_block(b: int) -> int:
    # blk=1024 overflows the 16MB VMEM budget (17.9M measured); 512 fits
    for blk in (512, 256, 128):
        if b % blk == 0:
            return blk
    return b


@lru_cache(maxsize=16)
def _straus_call(bdim: int, interpret: bool):
    blk = _pick_block(bdim)
    win_spec = pl.BlockSpec((64, blk), lambda i: (0, i))
    fe_spec = pl.BlockSpec((NLIMB, blk), lambda i: (0, i))
    btab_spec = pl.BlockSpec((16, 64), lambda i: (0, 0))
    out_sh = jax.ShapeDtypeStruct((NLIMB, bdim), jnp.int32)
    return pl.pallas_call(
        _make_straus_kernel(interpret),
        grid=(bdim // blk,),
        in_specs=[win_spec, win_spec, fe_spec, fe_spec, fe_spec, fe_spec,
                  btab_spec],
        out_specs=[fe_spec] * 4,
        out_shape=[out_sh] * 4,
        interpret=interpret,
    )


# --- the fused verify tail: decompress -> straus -> encode -> compare ------


def _const_fe_rows(v: int, blk: int):
    """Python-int field constant -> (20, blk) rows of scalar splats (Mosaic
    rejects (n,1)->(n,blk) lane broadcasts; splat-from-immediate is fine)."""
    rows = [
        jnp.full((1, blk), (v >> (BITS * i)) & MASK, jnp.int32)
        for i in range(NLIMB)
    ]
    return jnp.concatenate(rows, axis=0)


def _make_verify_tail_kernel(interpret: bool):
    ops = _make_ops(interpret)
    from . import ref

    def kernel(ay_ref, asign_ref, ry_ref, rsign_ref, s_win_ref, k_win_ref,
               btab_ref, mask_ref):
        a_y = ay_ref[:]
        blk = a_y.shape[1]
        d = _const_fe_rows(ref.D, blk)
        d2 = _const_fe_rows(ref.D2, blk)
        sqrt_m1 = _const_fe_rows(ref.SQRT_M1, blk)
        p1 = _const_fe_rows(ref.P, blk)
        p_mults = [
            _const_fe_rows(16 * ref.P, blk), _const_fe_rows(8 * ref.P, blk),
            _const_fe_rows(4 * ref.P, blk), _const_fe_rows(2 * ref.P, blk),
            p1, p1,
        ]
        one = jnp.concatenate(
            [jnp.ones((1, blk), jnp.int32), _zeros(NLIMB - 1, blk)], axis=0
        )

        # decompress A (curve.decompress: Go feFromBytes semantics, y mod p)
        a_sign = asign_ref[:]  # (1, blk)
        yy = ops.mul(a_y, a_y)
        u = ops.sub(yy, one)
        v = ops.add(ops.mul(d, yy), one)
        # sqrt_ratio (field.sqrt_ratio, RFC 8032 5.1.3)
        v2 = ops.sq(v)
        v3 = ops.mul(v2, v)
        v7 = ops.mul(ops.sq(v3), v)
        t = ops.pow22523(ops.mul(u, v7))
        x = ops.mul(ops.mul(u, v3), t)
        vxx = ops.mul(v, ops.sq(x))
        is0 = lambda fz: jnp.all(fz == 0, axis=0, keepdims=True)  # (1, blk)
        ok_plus = is0(ops.freeze(ops.sub(vxx, u), p_mults))
        ok_minus = is0(ops.freeze(ops.sub(vxx, ops.neg(u)), p_mults))
        x = jnp.where(ok_minus, ops.mul(x, sqrt_m1), x)
        ok = ok_plus | ok_minus
        xf = ops.freeze(x, p_mults)
        x_is_zero = is0(xf)
        ok = ok & ~(x_is_zero & (a_sign == 1))
        flip = ((xf[:1] & 1) != a_sign) & ~x_is_zero
        x = jnp.where(flip, ops.neg(xf), xf)
        a_pt = (x, a_y, jnp.broadcast_to(one, a_y.shape), ops.mul(x, a_y))
        # failed decompress -> identity (safe downstream), masked by ok
        ident = (_zeros(NLIMB, blk), one, one, _zeros(NLIMB, blk))
        a_pt = tuple(jnp.where(ok, g, i) for g, i in zip(a_pt, ident))
        neg_a = (ops.neg(a_pt[0]), a_pt[1], a_pt[2], ops.neg(a_pt[3]))

        # R' = [S]B + [k](-A), one shared-doubling chain (T never read)
        X, Y, Z = _straus_loop(
            ops, s_win_ref, k_win_ref, neg_a, d2, btab_ref[:], blk
        )

        # encode + compare against the signature's R
        zinv = ops.invert(Z)
        xe = ops.freeze(ops.mul(X, zinv), p_mults)
        ye = ops.freeze(ops.mul(Y, zinv), p_mults)
        eq = jnp.all(ye == ry_ref[:], axis=0, keepdims=True)
        eq = eq & ((xe[:1] & 1) == rsign_ref[:])
        mask_ref[:] = (ok & eq).astype(jnp.int32)

    return kernel


@lru_cache(maxsize=16)
def _verify_tail_call(bdim: int, interpret: bool):
    blk = _pick_block(bdim)
    win_spec = pl.BlockSpec((64, blk), lambda i: (0, i))
    fe_spec = pl.BlockSpec((NLIMB, blk), lambda i: (0, i))
    row_spec = pl.BlockSpec((1, blk), lambda i: (0, i))
    btab_spec = pl.BlockSpec((16, 64), lambda i: (0, 0))
    return pl.pallas_call(
        _make_verify_tail_kernel(interpret),
        grid=(bdim // blk,),
        in_specs=[fe_spec, row_spec, fe_spec, row_spec, win_spec, win_spec,
                  btab_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((1, bdim), jnp.int32),
        interpret=interpret,
    )


def verify_tail(a_y, a_sign, r_y, r_sign, s_limbs, k_limbs, *,
                interpret: bool = False):
    """Fused device tail of _verify_core: decompress(A), R' = [S]B − [k]A,
    encode, compare with R. Returns a (B,) bool mask. Inputs as in
    verify._verify_core (a_sign/r_sign are (B,) int32)."""
    from .curve import _windows_msb_first

    bdim = a_y.shape[-1]
    s_win = _windows_msb_first(s_limbs, bdim)
    k_win = _windows_msb_first(k_limbs, bdim)
    btab = jnp.asarray(_btab_np())
    mask = _verify_tail_call(bdim, bool(interpret))(
        a_y, a_sign.reshape(1, bdim).astype(jnp.int32), r_y,
        r_sign.reshape(1, bdim).astype(jnp.int32), s_win, k_win, btab,
    )
    return mask[0] != 0


def straus_mul_sub(s_limbs, k_limbs, neg_a, *, interpret: bool = False):
    """Drop-in fused replacement for curve.straus_mul_sub: [s]B + [k]*neg_a
    with one shared doubling chain, entirely VMEM-resident per block."""
    from .curve import _windows_msb_first

    bdim = s_limbs.shape[-1]
    s_win = _windows_msb_first(s_limbs, bdim)
    k_win = _windows_msb_first(k_limbs, bdim)
    btab = jnp.asarray(_btab_np())
    X, Y, Z, T = _straus_call(bdim, bool(interpret))(s_win, k_win, *neg_a, btab)
    return (X, Y, Z, T)
