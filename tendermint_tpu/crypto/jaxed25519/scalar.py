"""Scalar (mod L) arithmetic for the verify kernel.

Reduces the 512-bit SHA-512 output k to < 2^253 with k ≡ SHA mod L, via
three fold stages at the 2^252 boundary: k = lo + 2^252*hi ≡ lo - C*hi
(C = L - 2^252). Negative intermediates are avoided by adding a fixed
multiple of L per stage. Only partial reduction is needed — the scalar
mult consumes any 256-bit representative.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from . import ref
from .pack import BITS, MASK

C = ref.L - 2**252  # 125 bits


def _int_to_limbs_n(v: int, n: int) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = v & MASK
        v >>= BITS
    assert v == 0, "constant too large for limb count"
    return out


@lru_cache(maxsize=1)
def _consts():
    c10 = _int_to_limbs_n(C, 10)
    m1 = ((1 << 393) // ref.L + 1) * ref.L
    m2 = ((1 << 276) // ref.L + 1) * ref.L
    m3 = ((1 << 150) // ref.L + 1) * ref.L
    return c10, _int_to_limbs_n(m1, 31), _int_to_limbs_n(m2, 22), _int_to_limbs_n(m3, 20)


def _seq_carry_exact(coeffs, out_limbs: int):
    """Exact sequential carry into out_limbs 13-bit limbs. The final carry
    must be provably zero by construction (value fits)."""
    outs = []
    carry = jnp.zeros(coeffs.shape[1:], dtype=jnp.int32)
    n = coeffs.shape[0]
    for i in range(out_limbs):
        v = (coeffs[i] if i < n else jnp.zeros_like(carry)) + carry
        carry = v >> BITS
        outs.append(v & MASK)
    return jnp.stack(outs)


def _fold_stage(k, in_limbs: int, out_limbs: int, m_limbs: np.ndarray):
    c10, *_ = _consts()
    bdim = k.shape[-1]
    # hi limbs: bits >= 252 (limb 19, offset 5)
    n_hi = in_limbs - 19
    his = []
    for j in range(n_hi):
        v = k[19 + j] >> 5
        if 20 + j < in_limbs:
            v = v | (k[20 + j] << 8)
        his.append(v & MASK)
    hi = jnp.stack(his)  # (n_hi, B)
    lo = k[:20].at[19].set(k[19] & 31)
    # t = hi * C  (conv, coefficients < 10 * 2^26)
    t = jnp.zeros((n_hi + 10 - 1, bdim), dtype=jnp.int32)
    for i in range(10):
        t = t.at[i : i + n_hi].add(jnp.int32(int(c10[i])) * hi)
    # k' = lo + M - t; M (a multiple of L >= max t) keeps the value nonnegative
    width = out_limbs
    assert len(m_limbs) == width and t.shape[0] <= width and width >= 20
    acc = jnp.zeros((width, bdim), dtype=jnp.int32)
    acc = acc.at[:20].add(lo)
    acc = acc.at[: t.shape[0]].add(-t)
    acc = acc + jnp.asarray(m_limbs[:, None])
    return _seq_carry_exact(acc, out_limbs)


def _cond_sub(v, const_limbs: np.ndarray):
    """v - const if nonnegative else v (canonical 20-limb, exact chain)."""
    c = jnp.asarray(const_limbs[:, None])
    t = v - c
    outs = []
    borrow = jnp.zeros(v.shape[1:], dtype=jnp.int32)
    for i in range(v.shape[0]):
        x = t[i] + borrow
        borrow = x >> BITS
        outs.append(x & MASK)
    t_norm = jnp.stack(outs)
    return jnp.where((borrow < 0)[None, :], v, t_norm)


def reduce_512(k40):
    """(40, B) 13-bit limbs of a 512-bit value -> (20, B) canonical mod L.

    Full canonical reduction (not just partial): Go's sc_reduce is
    canonical, and for adversarial pubkeys with small-order components
    [k]A differs between k and k+m*L — consensus-critical to match.
    """
    _, m1, m2, m3 = _consts()
    k = _fold_stage(k40, 40, 31, m1)
    k = _fold_stage(k, 31, 22, m2)
    k = _fold_stage(k, 22, 20, m3)
    # k < 2^254 < 4L: two conditional subtracts make it canonical
    k = _cond_sub(k, _int_to_limbs_n(2 * ref.L, 20))
    k = _cond_sub(k, _int_to_limbs_n(ref.L, 20))
    return k


def mul_mod_l(a, b):
    """(20, B) x (20, B) canonical-ish scalars (< 2^253) -> (20, B)
    canonical product mod L. Schoolbook conv (coefficients < 20*8191^2
    < 2^31), exact carry into 40 limbs, then the reduce_512 fold chain —
    used by the aggregate (random-linear-combination) batch verifier."""
    bdim = a.shape[-1]
    terms = []
    pad = [(0, 0)] * (a.ndim - 1)
    for i in range(20):
        terms.append(jnp.pad(a[i] * b, [(i, 19 - i)] + pad))
    c = terms[0]
    for t in terms[1:]:
        c = c + t  # (39, B)
    c40 = jnp.pad(c, [(0, 1)] + pad)
    return reduce_512(_seq_carry_exact(c40, 40))


def sum_mod_l_groups(v, group: int):
    """(20, B) canonical scalars -> (20, B//group) per-group sums mod L.
    Limb sums stay exact in int32 for group <= 2^17."""
    bdim = v.shape[-1]
    g = v.reshape(20, bdim // group, group).sum(axis=2)  # limbs < 8191*group
    g40 = jnp.pad(_seq_carry_exact(g, 24), [(0, 16), (0, 0)])
    return reduce_512(g40)


def scalar_bits(s20, nbits: int = 256):
    """(20, B) canonical limbs -> (nbits, B) int32 bits, little-endian."""
    shifts = jnp.arange(BITS, dtype=jnp.int32)[None, :, None]
    bits = (s20[:, None, :] >> shifts) & 1  # (20, 13, B)
    return bits.reshape(20 * BITS, -1)[:nbits]
