"""Edwards25519 point operations on device (batched, extended coordinates).

Points are tuples of field elements (each (20, B) int32 limbs):
  P3     = (X, Y, Z, T)           extended homogeneous, T = XY/Z
  niels  = (Y+X, Y-X, 2dXY)       affine precomputed (fixed-base table rows)
  cached = (Y+X, Y-X, Z, 2dT)     projective precomputed (variable base)

Formulas are the RFC 8032 §5.1.4 unified add/double (complete on the
curve, no exceptional cases — crucial: batches mix arbitrary adversarial
points and everything must stay branch-free).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from . import field, ref
from .pack import int_to_limbs
from .scalar import scalar_bits


def identity_p3(bdim):
    zero = jnp.zeros((20, bdim), dtype=jnp.int32)
    one = zero.at[0].set(1)
    return (zero, one, one, zero)


def identity_p3_like(fe):
    """Identity point whose arrays derive from `fe` — keeps loop carries
    varying over a shard_map mesh axis (plain constants are unvarying and
    fail scan's carry-type check)."""
    zero = fe - fe
    one = zero.at[0].set(1)
    return (zero, one, one, zero)


def broadcast_const_p3(pt_ints, bdim):
    """Python-int extended point -> batched device point."""
    X, Y, Z, T = pt_ints
    mk = lambda v: jnp.broadcast_to(field.const_fe(v), (20, bdim)).astype(jnp.int32)
    return (mk(X), mk(Y), mk(Z), mk(T))


def double(p):
    X1, Y1, Z1, _ = p
    a = field.square(X1)
    b = field.square(Y1)
    zz = field.square(Z1)
    c = field.add(zz, zz)
    h = field.add(a, b)
    xy = field.add(X1, Y1)
    e = field.sub(h, field.square(xy))
    g = field.sub(a, b)
    f = field.add(c, g)
    return (field.mul(e, f), field.mul(g, h), field.mul(f, g), field.mul(e, h))


def to_cached(p):
    X, Y, Z, T = p
    d2 = field.const_fe(ref.D2)
    return (field.add(Y, X), field.sub(Y, X), Z, field.mul(T, d2))


def add_cached(p, q):
    X1, Y1, Z1, T1 = p
    yplusx2, yminusx2, Z2, t2d2 = q
    a = field.mul(field.sub(Y1, X1), yminusx2)
    b = field.mul(field.add(Y1, X1), yplusx2)
    c = field.mul(T1, t2d2)
    zz = field.mul(Z1, Z2)
    d = field.add(zz, zz)
    e = field.sub(b, a)
    f = field.sub(d, c)
    g = field.add(d, c)
    h = field.add(b, a)
    return (field.mul(e, f), field.mul(g, h), field.mul(f, g), field.mul(e, h))


def add_niels(p, n):
    """Mixed add: P3 + affine niels (Z2 = 1)."""
    X1, Y1, Z1, T1 = p
    yplusx2, yminusx2, xy2d2 = n
    a = field.mul(field.sub(Y1, X1), yminusx2)
    b = field.mul(field.add(Y1, X1), yplusx2)
    c = field.mul(T1, xy2d2)
    d = field.add(Z1, Z1)
    e = field.sub(b, a)
    f = field.sub(d, c)
    g = field.add(d, c)
    h = field.add(b, a)
    return (field.mul(e, f), field.mul(g, h), field.mul(f, g), field.mul(e, h))


def negate(p):
    X, Y, Z, T = p
    return (field.neg(X), Y, Z, field.neg(T))


def select_point(mask, p, q):
    return tuple(field.select(mask, a, b) for a, b in zip(p, q))


# --- decompression ---------------------------------------------------------


def decompress(y_limbs, sign):
    """y (20, B) raw 255-bit limbs, sign (B,) -> (P3 point, ok (B,) bool).

    Go-compatible (crypto/ed25519 feFromBytes): y is interpreted mod p —
    no canonicity rejection. Fails only when x recovery has no root, or
    x == 0 with sign bit set. Failed items yield the identity (safe for
    downstream arithmetic); callers mask by `ok`.
    """
    y = y_limbs
    one = field.const_fe(1)
    yy = field.mul(y, y)
    u = field.sub(yy, one)
    v = field.add(field.mul(field.const_fe(ref.D), yy), one)
    x, ok = field.sqrt_ratio(u, v)
    xf = field.freeze(x)
    x_is_zero = field.is_zero_frozen(xf)
    ok = ok & ~(x_is_zero & (sign == 1))
    # match parity to the sign bit (on the canonical representative)
    flip = (field.parity_frozen(xf) != sign) & ~x_is_zero
    x = field.select(flip, field.neg(xf), xf)
    pt = (x, y, jnp.broadcast_to(one, y.shape).astype(jnp.int32), field.mul(x, y))
    return select_point(ok, pt, identity_p3(y.shape[-1])), ok


# --- encoding --------------------------------------------------------------


def encode(p):
    """P3 -> (y_frozen (20, B) canonical limbs, x_parity (B,)).

    The canonical 32-byte encoding is y (255 bits) | parity(x) << 255;
    we keep it in limb space for comparison against raw signature bytes.
    """
    X, Y, Z, _ = p
    zinv = field.invert(Z)
    x = field.freeze(field.mul(X, zinv))
    y = field.freeze(field.mul(Y, zinv))
    return y, field.parity_frozen(x)


# --- scalar multiplication -------------------------------------------------


@lru_cache(maxsize=1)
def _base_table_np():
    """(64, 16, 60) float32: niels rows [j * 16^i]B, limbs concatenated.

    f32 is exact here (limb values < 2^13 << 2^24) and enables one-hot
    selection as an MXU matmul instead of a gather.
    """
    table = ref.base_table()
    out = np.zeros((64, 16, 60), dtype=np.float32)
    for i in range(64):
        for j in range(16):
            yplusx, yminusx, xy2d = table[i][j]
            out[i, j, :20] = int_to_limbs(yplusx)
            out[i, j, 20:40] = int_to_limbs(yminusx)
            out[i, j, 40:] = int_to_limbs(xy2d)
    return out


def fixed_base_mul(s_limbs):
    """[s]B via 64 windowed mixed additions, no doublings.

    s_limbs: (20, B) canonical limbs, value < 2^256.
    """
    bdim = s_limbs.shape[-1]
    bits = scalar_bits(s_limbs, 256)  # (256, B)
    weights = jnp.asarray([1, 2, 4, 8], dtype=jnp.int32)[None, :, None]
    windows = jnp.sum(bits.reshape(64, 4, bdim) * weights, axis=1)  # (64, B)
    table = jnp.asarray(_base_table_np())  # (64, 16, 60) f32

    def body(i, acc):
        row = jax.lax.dynamic_slice_in_dim(table, i, 1, axis=0)[0]  # (16, 60)
        onehot = (windows[i][None, :] == jnp.arange(16)[:, None]).astype(jnp.float32)
        # HIGHEST precision: default matmul precision is bf16 (8 mantissa
        # bits), which rounds the 13-bit limb values — must be exact f32
        entry = jnp.matmul(
            row.T,
            onehot,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        entry = entry.astype(jnp.int32)  # (60, B)
        return add_niels(acc, (entry[:20], entry[20:40], entry[40:]))

    return jax.lax.fori_loop(0, 64, body, identity_p3_like(s_limbs))


@lru_cache(maxsize=1)
def _small_base_table_np():
    """(16, 60) float32 niels rows [j]B for j = 0..15 (row 0 is the
    identity in niels form: (1, 1, 0)). Used by the Straus joint loop,
    which shares one doubling chain across both scalars so the base
    table needs no 16^i positioning."""
    out = np.zeros((16, 60), dtype=np.float32)
    out[0, 0] = 1.0
    out[0, 20] = 1.0
    base = ref.base_point()
    for j in range(1, 16):
        x, y = ref.to_affine(ref.scalar_mult(j, base))
        yplusx = (y + x) % ref.P
        yminusx = (y - x) % ref.P
        xy2d = (x * y % ref.P) * ref.D2 % ref.P
        out[j, :20] = int_to_limbs(yplusx)
        out[j, 20:40] = int_to_limbs(yminusx)
        out[j, 40:] = int_to_limbs(xy2d)
    return out


def _windows_msb_first(s_limbs, bdim, nbits: int = 256):
    """(nbits//4, B) int32 4-bit windows, most-significant first."""
    bits = scalar_bits(s_limbs, nbits)  # (nbits, B) LSB-first
    weights = jnp.asarray([1, 2, 4, 8], dtype=jnp.int32)[None, :, None]
    w = jnp.sum(bits.reshape(nbits // 4, 4, bdim) * weights, axis=1)
    return w[::-1]


def straus_mul_sub(s_limbs, k_limbs, neg_a):
    """[s]B + [k]·neg_a with ONE shared doubling chain (Straus/Shamir,
    4-bit windows) — the joint form of the verification equation
    R' = [S]B − [k]A. Replaces fixed_base_mul + var_base_mul + final
    add: 252 doublings + 64 cached adds + 64 niels adds instead of
    256 doublings + 256 conditional adds + 64 niels adds + 1 add.

    s_limbs, k_limbs: (20, B) canonical scalars. neg_a: P3 batch.
    """
    bdim = s_limbs.shape[-1]
    s_win = _windows_msb_first(s_limbs, bdim)
    k_win = _windows_msb_first(k_limbs, bdim)

    # per-item table of cached([j]·neg_a), j = 1..15: odd rows by cached
    # add, even rows by doubling j/2 (14 point ops total)
    neg_a_cached = to_cached(neg_a)
    mults = [neg_a]
    for j in range(2, 16):
        if j % 2 == 0:
            mults.append(double(mults[j // 2 - 1]))
        else:
            mults.append(add_cached(mults[j - 2], neg_a_cached))
    cached = [to_cached(pt) for pt in mults]  # 15 × (4 × (20, B))
    # stack per component: 4 arrays of (15, 20, B)
    a_table = tuple(
        jnp.stack([c[comp] for c in cached], axis=0) for comp in range(4)
    )
    b_table = jnp.asarray(_small_base_table_np())  # (16, 60) f32

    def body(i, acc):
        acc = double(double(double(double(acc))))
        # variable-base window: masked-sum select of cached([j]negA)
        kw = k_win[i]  # (B,)
        mask = (jnp.arange(1, 16, dtype=jnp.int32)[:, None]
                == kw[None, :])  # (15, B)
        sel = tuple(
            jnp.sum(jnp.where(mask[:, None, :], comp, 0), axis=0)
            for comp in a_table
        )
        added = add_cached(acc, sel)
        acc = select_point(kw != 0, added, acc)
        # fixed-base window: one-hot × (16, 60) table on the MXU
        onehot = (s_win[i][None, :]
                  == jnp.arange(16)[:, None]).astype(jnp.float32)
        entry = jnp.matmul(
            b_table.T,
            onehot,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        ).astype(jnp.int32)  # (60, B)
        return add_niels(acc, (entry[:20], entry[20:40], entry[40:]))

    return jax.lax.fori_loop(0, 64, body, identity_p3_like(s_limbs))


# --- grouped multi-scalar multiplication (aggregate/RLC verification) ------


def add_points(p, q):
    """Full P3 + P3 addition (complete)."""
    return add_cached(p, to_cached(q))


def build_p3_table(p):
    """[j]p for j = 1..15 in P3 form (14 point ops) — the per-item window
    table of the grouped MSM."""
    p_cached = to_cached(p)
    mults = [p]
    for j in range(2, 16):
        if j % 2 == 0:
            mults.append(double(mults[j // 2 - 1]))
        else:
            mults.append(add_cached(mults[j - 2], p_cached))
    return mults


def _select_p3(table, win_row):
    """Per-item table row select by 4-bit digit; digit 0 -> identity."""
    sel = [jnp.zeros_like(table[0][0]) for _ in range(4)]
    for j in range(15):
        m = (win_row == j + 1).astype(jnp.int32)[None, :]
        for c in range(4):
            sel[c] = sel[c] + table[j][c] * m
    m0 = (win_row == 0).astype(jnp.int32)
    sel[1] = sel[1].at[0].add(m0)  # identity = (0, 1, 1, 0)
    sel[2] = sel[2].at[0].add(m0)
    return tuple(sel)


def _group_tree_reduce(p, group: int):
    """Sum contiguous groups of `group` lanes (power of two) down to one
    point per group via pairwise adds — (20, B) -> (20, B//group)."""
    while group > 1:
        a = tuple(c[:, 0::2] for c in p)
        b = tuple(c[:, 1::2] for c in p)
        p = add_points(a, b)
        group //= 2
    return p


def msm_groups(r_pts, z_win, a_pts, zk_win, group: int):
    """Per-group Σ_j ([z_j]R_j + [zk_j]A_j) with ONE doubling chain shared
    by the whole group — the core of aggregate (random-linear-combination)
    batch verification. z_win: (nz, B) 4-bit windows MSB-first (the short
    per-item randomizers); zk_win: (64, B) (253-bit). Returns a P3 batch
    of width B//group. The doubling work drops by the group factor vs
    per-item chains; window adds tree-reduce within each contiguous lane
    group."""
    bdim = r_pts[0].shape[-1]
    assert bdim % group == 0 and (group & (group - 1)) == 0
    nz = z_win.shape[0]
    assert nz <= 64
    table_r = build_p3_table(r_pts)
    table_a = build_p3_table(a_pts)
    acc0 = identity_p3(bdim // group)

    def step(acc, w, with_r):
        acc = double(double(double(double(acc))))
        sel_a = _select_p3(table_a, zk_win[w])
        acc = add_points(acc, _group_tree_reduce(sel_a, group))
        if with_r:
            sel_r = _select_p3(table_r, z_win[w - (64 - nz)])
            acc = add_points(acc, _group_tree_reduce(sel_r, group))
        return acc

    # zk has 64 windows; the short z joins for the last nz of them
    acc = jax.lax.fori_loop(0, 64 - nz, lambda w, a: step(a, w, False), acc0)
    return jax.lax.fori_loop(64 - nz, 64, lambda w, a: step(a, w, True), acc)


def is_identity(p):
    """(B,) bool: p == neutral element (X/Z == 0 and Y/Z == 1)."""
    X, Y, Z, _ = p
    return field.is_zero_frozen(field.freeze(X)) & field.eq_mod_p(Y, Z)


def var_base_mul(p, s_limbs):
    """[s]P by double-and-(conditionally-)add over 256 bits, branch-free.

    Simple and robust first cut; windowed/table version is a later-round
    optimization (see SURVEY §7 hard parts — latency discipline).
    """
    bdim = s_limbs.shape[-1]
    bits = scalar_bits(s_limbs, 256)  # (256, B)
    p_cached = to_cached(p)

    def body(i, acc):
        acc = double(acc)
        added = add_cached(acc, p_cached)
        bit = bits[255 - i]
        return select_point(bit == 1, added, acc)

    return jax.lax.fori_loop(0, 256, body, identity_p3_like(s_limbs))
