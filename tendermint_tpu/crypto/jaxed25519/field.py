"""GF(2^255-19) arithmetic on TPU: 20 x 13-bit limbs in int32, batch-last.

Design notes (this is the arithmetic core of the batch-verify north star,
replacing the serial per-signature loop at reference
crypto/ed25519/ed25519.go:151-157):

- A field element is an int32 array of shape (20, B): limb i holds bits
  [13i, 13i+13). Batch B is the LAST axis so it maps onto the TPU's
  128-wide vector lanes; limb position is the sublane axis.
- 13-bit limbs keep schoolbook products in int32: 20 partial products of
  <= (2^13.3)^2 sum to < 2^31 with ~15% headroom. The working invariant
  after every op is |limb| <= LIMB_BOUND (~2^13.3, small negatives allowed
  from subtraction borrows); exact canonical form only exists after
  freeze().
- Carries are PARALLEL rounds (shift/mask/roll over the limb axis), not a
  sequential 20-step chain — 4 rounds bound limbs back under LIMB_BOUND
  from any conv output. 2^260 overflow folds back multiplied by 608
  (2^260 = 32 * (2^255-19) + 608*... precisely: 2^260 mod p = 608), and a
  *negative* top carry folds the same way, which adds a multiple of p —
  value mod p is preserved in both directions.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from . import ref
from .pack import BITS, MASK, NLIMB, int_to_limbs

# bound on |limb| maintained between ops (see module docstring)
LIMB_BOUND = MASK + 1216 + 2  # 8191 + fold residue; conv stays in int32


@lru_cache(maxsize=None)
def _const_np(v: int):
    # cache numpy, not device arrays: device constants created inside a jit
    # trace are tracers and must never leak across traces
    return int_to_limbs(v, NLIMB)[:, None]


def const_fe(v: int) -> jnp.ndarray:
    """Python int -> (20, 1) limb constant (broadcasts over batch)."""
    return jnp.asarray(_const_np(v % ref.P), dtype=jnp.int32)


def _cached_const(v: int):
    return const_fe(v)


def zeros_like(a):
    return jnp.zeros_like(a)


def _carry_round(v):
    """One parallel carry round within 20 limbs; top carry folds via 608."""
    r = v >> BITS
    m = v & MASK
    m = m.at[1:].add(r[:-1])
    m = m.at[0].add(608 * r[19])
    return m


def _reduce_conv(c):
    """39-coefficient conv output -> 20 bounded limbs (fold + carries)."""
    # round 1 over 39 coeffs, then fold positions >= 20 (x608)
    r = c >> BITS
    m = c & MASK
    pad = [(0, 0)] * (c.ndim - 1)
    full = jnp.pad(m, [(0, 1)] + pad) + jnp.pad(r, [(1, 0)] + pad)
    v = full[:NLIMB] + 608 * full[NLIMB:]
    for _ in range(3):
        v = _carry_round(v)
    return v


def mul(a, b):
    """Field multiply. Inputs with |limb| <= LIMB_BOUND; output likewise.

    Schoolbook conv as padded shifts + a balanced tree sum — keeps the
    whole product chain elementwise/fusible (dynamic-update-slice chains
    defeat XLA fusion and were ~10x slower on TPU).
    """
    pad = [(0, 0)] * (max(a.ndim, b.ndim) - 1)
    terms = [
        jnp.pad(a[i] * b, [(i, NLIMB - 1 - i)] + pad) for i in range(NLIMB)
    ]
    while len(terms) > 1:
        terms = [
            terms[j] + terms[j + 1] if j + 1 < len(terms) else terms[j]
            for j in range(0, len(terms), 2)
        ]
    return _reduce_conv(terms[0])


def square(a):
    """a^2 — exploits conv symmetry: c[k] = sum_{i<j, i+j=k} 2 a_i a_j
    + (a_{k/2})^2, roughly halving the multiplies."""
    a2 = a + a
    pad = [(0, 0)] * (a.ndim - 1)
    terms = []
    for i in range(NLIMB):
        # diagonal term once, cross terms with doubled operand for j > i
        row = a[i] * jnp.concatenate(
            [a[i : i + 1], a2[i + 1 :]], axis=0
        )  # (NLIMB - i, B)
        terms.append(jnp.pad(row, [(2 * i, NLIMB - 1 - i)] + pad))
    while len(terms) > 1:
        terms = [
            terms[j] + terms[j + 1] if j + 1 < len(terms) else terms[j]
            for j in range(0, len(terms), 2)
        ]
    return _reduce_conv(terms[0])


def add(a, b):
    return _carry_round(a + b)


def sub(a, b):
    return _carry_round(a - b)


def neg(a):
    return _carry_round(-a)


def mul_small(a, k: int):
    """Multiply by a small positive constant (k < 2^17)."""
    v = a * jnp.int32(k)
    for _ in range(3):
        v = _carry_round(v)
    return v


def select(mask, a, b):
    """Per-batch-item select: mask (B,) bool -> where(mask, a, b)."""
    return jnp.where(mask[None, :], a, b)


def _pow2k(x, k: int):
    return jax.lax.fori_loop(0, k, lambda _, v: square(v), x)


def _pow_chain_250(z):
    """z^(2^250 - 1) — shared prefix of the inversion/sqrt chains."""
    z2 = square(z)  # 2
    t = square(z2)  # 4
    t = square(t)  # 8
    z9 = mul(t, z)  # 9
    z11 = mul(z9, z2)  # 11
    t = square(z11)  # 22
    z_5_0 = mul(t, z9)  # 2^5 - 1
    t = _pow2k(z_5_0, 5)
    z_10_0 = mul(t, z_5_0)  # 2^10 - 1
    t = _pow2k(z_10_0, 10)
    z_20_0 = mul(t, z_10_0)  # 2^20 - 1
    t = _pow2k(z_20_0, 20)
    z_40_0 = mul(t, z_20_0)  # 2^40 - 1
    t = _pow2k(z_40_0, 10)
    z_50_0 = mul(t, z_10_0)  # 2^50 - 1
    t = _pow2k(z_50_0, 50)
    z_100_0 = mul(t, z_50_0)  # 2^100 - 1
    t = _pow2k(z_100_0, 100)
    z_200_0 = mul(t, z_100_0)  # 2^200 - 1
    t = _pow2k(z_200_0, 50)
    z_250_0 = mul(t, z_50_0)  # 2^250 - 1
    return z_250_0, z11


def invert(z):
    """z^(p-2) = z^(2^255 - 21)."""
    z_250_0, z11 = _pow_chain_250(z)
    t = _pow2k(z_250_0, 5)
    return mul(t, z11)


def pow22523(z):
    """z^((p-5)/8) = z^(2^252 - 3)."""
    z_250_0, _ = _pow_chain_250(z)
    t = _pow2k(z_250_0, 2)
    return mul(t, z)


# --- canonical form --------------------------------------------------------


_CIN = (-2, -1, 0, 1)  # carry domain: |limb| <= 2*MASK ⇒ carry-out ∈ [-2, 1]


def _shift_limbs(x, s, fill):
    """out[i] = x[i-s] for i >= s; bottom s rows = identity fill."""
    pad = jnp.full((s,) + x.shape[1:], fill, jnp.int32)
    return jnp.concatenate([pad, x[:-s]], axis=0)


def _sel4(quad, x):
    """Evaluate the carry-function quad at carry values x ∈ {-2,-1,0,1}."""
    return jnp.where(
        x < -1, quad[0],
        jnp.where(x < 0, quad[1], jnp.where(x < 1, quad[2], quad[3])))


def _seq_carry(v):
    """Exact carry resolve; returns (limbs in [0, 2^13), carry_out as a
    keepdims (1, ...) row — 2-D shapes lower in Mosaic too, so the pallas
    kernels reuse this exact implementation).

    Works for signed inputs with |limb| <= 2*MASK (the LIMB_BOUND
    regime). Not a 20-step sequential chain: each limb's carry-out is a
    function of its carry-in, tabulated on the 4-value carry domain and
    composed with a Kogge-Stone parallel-prefix scan — log2(20)=5 rounds
    of full-width selects instead of 20 dependent (1, B) ops.
    """
    quad = [(v + c) >> BITS for c in _CIN]
    s = 1
    while s < NLIMB:
        low = [_shift_limbs(quad[e], s, _CIN[e]) for e in range(4)]
        quad = [_sel4(quad, low[e]) for e in range(4)]
        s <<= 1
    # carry INTO limb i = prefix over limbs [0..i-1] evaluated at 0
    zero = jnp.zeros((1,) + v.shape[1:], jnp.int32)
    cin = jnp.concatenate([zero, quad[2][:-1]], axis=0)
    return (v + cin) & MASK, quad[2][NLIMB - 1:]


def _cond_sub(v, const_limbs):
    """v - const if that's >= 0, else v. Both canonical 20-limb.
    Borrow domain is {-1, 0}, so a function PAIR suffices."""
    t = v - const_limbs
    pair = [(t - 1) >> BITS, t >> BITS]
    s = 1
    while s < NLIMB:
        low0 = _shift_limbs(pair[0], s, -1)
        low1 = _shift_limbs(pair[1], s, 0)
        pair = [jnp.where(low0 < 0, pair[0], pair[1]),
                jnp.where(low1 < 0, pair[0], pair[1])]
        s <<= 1
    zero = jnp.zeros((1,) + t.shape[1:], jnp.int32)
    bin_ = jnp.concatenate([zero, pair[1][:-1]], axis=0)
    t_norm = (t + bin_) & MASK
    return jnp.where(pair[1][NLIMB - 1:] < 0, v, t_norm)


def _p_multiples():
    # trailing extra 1*p covers the value in [32p, 32p+608) edge after folding
    return [const_fe_raw(k * ref.P) for k in (16, 8, 4, 2, 1, 1)]


def const_fe_raw(v: int) -> jnp.ndarray:
    """Like const_fe but without mod-p reduction (for p multiples)."""
    return jnp.asarray(_const_np_raw(v), dtype=jnp.int32)


@lru_cache(maxsize=None)
def _const_np_raw(v: int):
    return int_to_limbs(v, NLIMB)[:, None]


def freeze(a):
    """Fully canonical limbs in [0, p)."""
    v = a
    for _ in range(2):
        limbs, carry = _seq_carry(v)
        v = jnp.concatenate([limbs[:1] + 608 * carry, limbs[1:]], axis=0)
    limbs, _ = _seq_carry(v)  # carry is 0 now; value < 32p
    v = limbs
    for m in _p_multiples():
        v = _cond_sub(v, m)
    return v


def is_zero_frozen(a_frozen):
    return jnp.all(a_frozen == 0, axis=0)


def eq_mod_p(a, b):
    """a == b (mod p), arbitrary representations."""
    return is_zero_frozen(freeze(sub(a, b)))


def parity_frozen(a_frozen):
    return a_frozen[0] & 1


# --- square root (point decompression) ------------------------------------


def sqrt_ratio(u, v):
    """x with v*x^2 == u, per RFC 8032 §5.1.3. Returns (x, ok)."""
    v2 = square(v)
    v3 = mul(v2, v)
    v7 = mul(square(v3), v)
    t = pow22523(mul(u, v7))
    x = mul(mul(u, v3), t)
    vxx = mul(v, square(x))
    ok_plus = eq_mod_p(vxx, u)
    ok_minus = eq_mod_p(vxx, neg(u))
    sqrt_m1 = _cached_const(ref.SQRT_M1)
    x = select(ok_minus, mul(x, sqrt_m1), x)
    return x, ok_plus | ok_minus


# --- host conversion helpers (tests/debug) ---------------------------------


def to_int(a) -> int:
    """Single element (20,) or (20,1) -> python int (value, not mod p)."""
    arr = np.asarray(a).reshape(NLIMB, -1)
    assert arr.shape[1] == 1
    return sum(int(arr[i, 0]) << (BITS * i) for i in range(NLIMB))
