"""The batched Ed25519 verify kernel — the north-star TPU path.

Replaces the reference's serial verify loop (types/validator_set.go:345-371
→ crypto/ed25519/ed25519.go:151-157) with one jitted device program per
(batch-bucket, block-count) shape:

    SHA-512(R||A||M) → reduce mod L → decompress A → [S]B (fixed-base
    windowed) + [k](-A) (double-and-add) → canonical encode → compare R.

Per-item validity masks come back — mixed valid/invalid batches are
first-class (no all-or-nothing batch equation). With more than one device
visible the batch shards across a 1-D "dp" mesh via shard_map; signatures
are the batch dimension, so the commit of a 10k-validator set simply
spreads over the pod with no cross-device traffic except the final
all-gather of masks.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import BatchVerifier
from . import curve, pack, scalar, sha512

# persistent compilation cache: the kernel is expensive to compile (~20-40s
# on TPU) and identical across processes
_cache_dir = os.environ.get("TM_TPU_JAX_CACHE", os.path.expanduser("~/.cache/tm_tpu_jax"))
try:  # pragma: no cover
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


def _verify_core(msg_words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs):
    digest = sha512.sha512_batch(msg_words, nblocks)
    k = scalar.reduce_512(sha512.digest_to_scalar_limbs(digest))
    a_pt, ok_a = curve.decompress(a_y, a_sign)
    # R' = [S]B + [k](−A) in ONE Straus chain (shared doublings)
    r_prime = curve.straus_mul_sub(s_limbs, k, curve.negate(a_pt))
    y, parity = curve.encode(r_prime)
    eq = jnp.all(y == r_y, axis=0) & (parity == r_sign)
    return ok_a & eq


@lru_cache(maxsize=32)
def _jitted(nb: int, bpad: int, ndev: int):
    if ndev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()[:ndev]), ("dp",))
        last = lambda n: NamedSharding(mesh, P(*([None] * (n - 1) + ["dp"])))
        in_sh = (last(4), last(1), last(2), last(1), last(2), last(1), last(2))
        return jax.jit(_verify_core, in_shardings=in_sh, out_shardings=last(1))
    return jax.jit(_verify_core)


def _verify_packed_core(buf, nb: int):
    """Unpack ONE (rows, B) int32 buffer into the 7 _verify_core inputs.
    A single host→device transfer instead of seven — the transfer link
    (PCIe, or the axon tunnel) charges per round trip."""
    w = nb * 32
    # int32 → uint32 is a bitcast; SHA-512 needs logical shifts
    words = buf[:w].astype(jnp.uint32).reshape(nb, 16, 2, -1)
    nblocks = buf[w]
    a_y = buf[w + 1 : w + 21]
    a_sign = buf[w + 21]
    r_y = buf[w + 22 : w + 42]
    r_sign = buf[w + 42]
    s_limbs = buf[w + 43 : w + 63]
    return _verify_core(words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs)


@lru_cache(maxsize=32)
def _jitted_packed(nb: int, bpad: int, ndev: int):
    if ndev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()[:ndev]), ("dp",))
        sh = NamedSharding(mesh, P(None, "dp"))
        out = NamedSharding(mesh, P("dp"))
        return jax.jit(partial(_verify_packed_core, nb=nb),
                       in_shardings=(sh,), out_shardings=out)
    return jax.jit(partial(_verify_packed_core, nb=nb))


def _bucket(n: int) -> int:
    if n <= 8:
        return 8
    if n <= 512:
        return 1 << (n - 1).bit_length()
    return (n + 511) // 512 * 512


def verify_batch(msgs, sigs, pks, devices: int | None = None):
    """Lists of (msg bytes, 64-byte sig, 32-byte pubkey) -> list[bool]."""
    n = len(msgs)
    if n == 0:
        return []
    well_formed = np.array(
        [len(s) == 64 and len(p) == 32 for s, p in zip(sigs, pks)], dtype=bool
    )
    if well_formed.all():
        sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
        pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(n, 32)
    else:
        sig_arr = np.zeros((n, 64), dtype=np.uint8)
        pk_arr = np.zeros((n, 32), dtype=np.uint8)
        for i, (s, p) in enumerate(zip(sigs, pks)):
            if well_formed[i]:
                sig_arr[i] = np.frombuffer(s, dtype=np.uint8)
                pk_arr[i] = np.frombuffer(p, dtype=np.uint8)
    r_y, r_sign, s_limbs, s_ok = pack.split_signatures(sig_arr)
    a_y, a_sign = pack.split_pubkeys(pk_arr)
    prefixes = np.concatenate([sig_arr[:, :32], pk_arr], axis=1)
    words, nblocks = pack.sha512_pad_batch(prefixes, [bytes(m) for m in msgs])

    ndev = devices if devices is not None else len(jax.devices())
    bpad = _bucket(n)
    if ndev > 1:
        bpad = max(bpad, ndev)
        bpad = (bpad + ndev - 1) // ndev * ndev

    # one packed (rows, bpad) int32 buffer = one h2d transfer
    nb = words.shape[0]
    rows = nb * 32 + 63
    buf = np.zeros((rows, bpad), dtype=np.int32)
    w = nb * 32
    buf[:w, :n] = words.astype(np.int32).reshape(w, n)
    buf[w, :n] = nblocks
    buf[w + 1 : w + 21, :n] = a_y
    buf[w + 21, :n] = a_sign
    buf[w + 22 : w + 42, :n] = r_y
    buf[w + 42, :n] = r_sign
    buf[w + 43 : w + 63, :n] = s_limbs

    fn = _jitted_packed(nb, bpad, ndev)
    mask = fn(jnp.asarray(buf))
    out = np.asarray(mask)[:n] & s_ok & well_formed
    return [bool(v) for v in out]


def make_sharded_commit_step(mesh):
    """Sharded verify-commit step over a 1-D 'dp' mesh: per-signature
    validity masks (sharded) plus the 2/3-quorum voting-power tally via a
    psum collective — the device-parallel equivalent of the reference's
    talliedVotingPower loop (types/validator_set.go:358-366).

    The tally is exact int32 arithmetic in 2^16 limbs (powers split into
    lo/hi 16-bit halves, summed separately, recombined on host as Python
    ints by the caller via `lo + (hi << 16)`), so the 2/3-quorum decision
    never rounds: batch ≤ 2^15 items with per-item power < 2^31 stays
    exact. The authoritative quorum decision in verify_commit additionally
    re-tallies host-side from the mask with unbounded Python ints."""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    dp = lambda n: P(*([None] * (n - 1) + ["dp"]))

    def step(words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs, powers, for_block):
        mask = _verify_core(words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs)
        powers = powers.astype(jnp.int32)
        counted = jnp.where(mask & (for_block == 1), powers, 0)
        lo = jnp.sum(counted & 0xFFFF)
        hi = jnp.sum(counted >> 16)
        return mask, jax.lax.psum(lo, "dp"), jax.lax.psum(hi, "dp")

    return jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(dp(4), dp(1), dp(2), dp(1), dp(2), dp(1), dp(2), dp(1), dp(1)),
            out_specs=(dp(1), P(), P()),
        )
    )


def tallied_power(lo, hi) -> int:
    """Recombine the limb sums from make_sharded_commit_step exactly."""
    return int(lo) + (int(hi) << 16)


def warmup(buckets=(8, 16, 64), nb: int = 2, devices: int | None = None) -> None:
    """Compile the hot bucket shapes ahead of time. First-use compile of
    a bucket costs 20-40s on TPU (persistent cache makes later processes
    cheap, but the FIRST node on a machine pays it) — a consensus node
    must not discover that cost inside the live vote path, so node
    startup calls this from a background thread. Vote sign-bytes pad to
    2 SHA-512 blocks (nb=2); bucket sizes cover the adaptive batcher's
    first escalation steps."""
    import numpy as np

    ndev = devices if devices is not None else len(jax.devices())
    for b in buckets:
        bpad = _bucket(b)
        if ndev > 1:
            bpad = max(bpad, ndev)
            bpad = (bpad + ndev - 1) // ndev * ndev
        rows = nb * 32 + 63
        fn = _jitted_packed(nb, bpad, ndev)
        fn(jnp.asarray(np.zeros((rows, bpad), dtype=np.int32)))


class JAXBatchVerifier(BatchVerifier):
    """BatchVerifier backend running the vectorized TPU kernel."""

    def verify(self):
        if not self._items:
            return []
        msgs = [m for m, _, _ in self._items]
        sigs = [s for _, s, _ in self._items]
        pks = [p for _, _, p in self._items]
        return verify_batch(msgs, sigs, pks)
