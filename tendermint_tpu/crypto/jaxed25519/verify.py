"""The batched Ed25519 verify kernel — the north-star TPU path.

Replaces the reference's serial verify loop (types/validator_set.go:345-371
→ crypto/ed25519/ed25519.go:151-157) with one jitted device program per
(batch-bucket, block-count) shape:

    SHA-512(R||A||M) → reduce mod L → decompress A → [S]B (fixed-base
    windowed) + [k](-A) (double-and-add) → canonical encode → compare R.

Per-item validity masks come back — mixed valid/invalid batches are
first-class (no all-or-nothing batch equation). With more than one device
visible the batch shards across a 1-D "dp" mesh via shard_map; signatures
are the batch dimension, so the commit of a 10k-validator set simply
spreads over the pod with no cross-device traffic except the final
all-gather of masks.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..batch import BatchVerifier
from . import curve, pack, pallas_kernels, scalar, sha512

# persistent compilation cache: the kernel is expensive to compile (~20-40s
# on TPU) and identical across processes
_cache_dir = os.environ.get("TM_TPU_JAX_CACHE", os.path.expanduser("~/.cache/tm_tpu_jax"))
try:  # pragma: no cover
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


@lru_cache(maxsize=1)
def on_tpu() -> bool:
    """True when the default backend is TPU hardware (directly, or via the
    axon tunnel) — gates the fused pallas kernels, which only lower via
    Mosaic (a GPU backend must keep the XLA path)."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _verify_core(msg_words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs,
                 use_pallas: bool = False):
    digest = sha512.sha512_batch(msg_words, nblocks)
    k = scalar.reduce_512(sha512.digest_to_scalar_limbs(digest))
    if use_pallas:
        # fused VMEM-resident tail: decompress -> Straus -> encode -> compare
        # (one Mosaic kernel, no HBM intermediates — see PROFILE.md)
        return pallas_kernels.verify_tail(a_y, a_sign, r_y, r_sign, s_limbs, k)
    a_pt, ok_a = curve.decompress(a_y, a_sign)
    # R' = [S]B + [k](−A) in ONE Straus chain (shared doublings)
    r_prime = curve.straus_mul_sub(s_limbs, k, curve.negate(a_pt))
    y, parity = curve.encode(r_prime)
    eq = jnp.all(y == r_y, axis=0) & (parity == r_sign)
    return ok_a & eq


def _bytes_from_rows(rows_i32, nbytes: int):
    """(ceil(nbytes/4), B) int32 of 4 packed LE bytes -> (nbytes, B) int32."""
    parts = [(rows_i32 >> (8 * k)) & 0xFF for k in range(4)]
    stacked = jnp.stack(parts, axis=1)  # (rows, 4, B)
    return stacked.reshape(-1, rows_i32.shape[-1])[:nbytes]


def _limbs_from_bytes(bts):
    """(32, B) int32 LE bytes -> (20, B) 13-bit limbs (device twin of
    pack.bytes_to_limbs_batch)."""
    bdim = bts.shape[-1]
    zero = jnp.zeros((1, bdim), dtype=jnp.int32)
    rows = []
    for i in range(pack.NLIMB):
        bit = pack.BITS * i
        s, o = bit // 8, bit % 8
        v = bts[s] >> o
        if s + 1 < 32:
            v = v | (bts[s + 1] << (8 - o))
        if s + 2 < 32 and 16 - o < pack.BITS:
            v = v | (bts[s + 2] << (16 - o))
        rows.append(v & pack.MASK)
    return jnp.stack(rows, axis=0)


@lru_cache(maxsize=32)
def _jitted(nb: int, bpad: int, ndev: int):
    if ndev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()[:ndev]), ("dp",))
        last = lambda n: NamedSharding(mesh, P(*([None] * (n - 1) + ["dp"])))
        in_sh = (last(4), last(1), last(2), last(1), last(2), last(1), last(2))
        return jax.jit(_verify_core, in_shardings=in_sh, out_shardings=last(1))
    return jax.jit(_verify_core)


ROWS_AUX = 25  # mlen row + 16 sig rows + 8 pk rows


def _verify_packed_core(buf, nb: int, mrows: int, use_pallas: bool = False):
    """Unpack ONE (25 + mrows, B) int32 buffer into the _verify_core
    inputs. One host→device transfer; everything rides byte-dense
    (signature/pubkey/message bytes 4-per-int32) and the SHA-512 block
    construction — R||A prefix placement, 0x80 terminator, big-endian bit
    length — happens ON DEVICE from the raw bytes. vs shipping padded
    blocks + limbs this cuts the 10k-sig transfer 5.2MB → ~2.2MB (the
    axon tunnel charges ~64ms latency per round trip plus ~10-30ms/MB).

    Layout: row 0 = message length (bytes); rows 1:17 = signature;
    rows 17:25 = pubkey; rows 25: = message bytes."""
    bdim = buf.shape[-1]
    mlen = buf[0]
    sig_bytes = _bytes_from_rows(buf[1:17], 64)
    pk_bytes = _bytes_from_rows(buf[17:25], 32)
    msg_bytes = _bytes_from_rows(buf[25:], mrows * 4)

    # SHA-512 message region (after the 64-byte R||A prefix): mask tail
    # garbage, place 0x80 at mlen and the BE bit-length at inb*128-8
    region_len = nb * 128 - 64
    if mrows * 4 < region_len:
        msg_bytes = jnp.concatenate(
            [msg_bytes, jnp.zeros((region_len - mrows * 4, bdim), jnp.int32)],
            axis=0,
        )
    j = jnp.arange(region_len, dtype=jnp.int32)[:, None]
    inb = (mlen + 64 + 17 + 127) // 128  # per-item padded block count
    region = jnp.where(j < mlen[None, :], msg_bytes, 0)
    region = region + jnp.where(j == mlen[None, :], 0x80, 0)
    bitlen = (mlen + 64) * 8
    base = inb * 128 - 72  # region-relative start of the 8-byte BE length
    for t in range(8):
        v = (bitlen >> (8 * (7 - t))) & 0xFF
        region = region + jnp.where(j == (base + t)[None, :], v[None, :], 0)

    full = jnp.concatenate([sig_bytes[:32], pk_bytes, region], axis=0)
    f4 = full.astype(jnp.uint32).reshape(nb * 32, 4, bdim)
    words32 = (f4[:, 0] << 24) | (f4[:, 1] << 16) | (f4[:, 2] << 8) | f4[:, 3]
    words = words32.reshape(nb, 16, 2, bdim)

    r_y = _limbs_from_bytes(sig_bytes[:32])
    r_sign = (r_y[19] >> 8) & 1
    r_y = r_y.at[19].set(r_y[19] & 0xFF)
    s_limbs = _limbs_from_bytes(sig_bytes[32:64])
    a_y = _limbs_from_bytes(pk_bytes)
    a_sign = (a_y[19] >> 8) & 1
    a_y = a_y.at[19].set(a_y[19] & 0xFF)
    return _verify_core(words, inb, a_y, a_sign, r_y, r_sign, s_limbs,
                        use_pallas=use_pallas)


@lru_cache(maxsize=32)
def _jitted_packed(nb: int, mrows: int, bpad: int, ndev: int):
    if ndev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        # GSPMD cannot auto-partition a Mosaic custom call: the sharded
        # path stays on the XLA kernel (shard_map+pallas is future work)
        mesh = Mesh(np.asarray(jax.devices()[:ndev]), ("dp",))
        sh = NamedSharding(mesh, P(None, "dp"))
        out = NamedSharding(mesh, P("dp"))
        return jax.jit(partial(_verify_packed_core, nb=nb, mrows=mrows,
                               use_pallas=False),
                       in_shardings=(sh,), out_shardings=out)
    return jax.jit(partial(_verify_packed_core, nb=nb, mrows=mrows,
                           use_pallas=on_tpu()))


@lru_cache(maxsize=1)
def _ref_L() -> int:
    from . import ref

    return ref.L


def _pack_le_rows(arr: np.ndarray) -> np.ndarray:
    """(B, nbytes) uint8 -> (nbytes//4, B) int32, 4 LE bytes per word."""
    b, nbytes = arr.shape
    w = arr.reshape(b, nbytes // 4, 4).astype(np.uint32)
    packed = w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)
    return np.ascontiguousarray(packed.T).view(np.int32)


def pack_buffer(msgs, sig_arr: np.ndarray, pk_arr: np.ndarray, ndev: int = 1):
    """Build the single packed h2d buffer (see _verify_packed_core layout).
    Returns (buf (ROWS_AUX+mrows, bpad) int32, nb, mrows, bpad). The ONLY
    place the layout is produced — bench/profiling code reuses it."""
    n = len(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
    maxlen = int(lens.max()) if n else 0
    nb = (64 + maxlen + 17 + 127) // 128
    # mrows bucketed to 64-byte granularity: vote sign-bytes from 65 to
    # 128 bytes (any realistic chain id) share the mrows=32 compile that
    # warmup() pre-builds — a fresh mrows key would stall the live path
    mrows = max(16, ((maxlen + 3) // 4 + 15) // 16 * 16)
    msg_mat = np.zeros((n, mrows * 4), dtype=np.uint8)
    pack.fill_msg_bytes(msg_mat, [bytes(m) for m in msgs], lens)

    bpad = _bucket(n)
    if ndev > 1:
        bpad = max(bpad, ndev)
        bpad = (bpad + ndev - 1) // ndev * ndev

    buf = np.zeros((ROWS_AUX + mrows, bpad), dtype=np.int32)
    buf[0, :n] = lens
    buf[1:17, :n] = _pack_le_rows(sig_arr)
    buf[17:25, :n] = _pack_le_rows(pk_arr)
    buf[25:, :n] = _pack_le_rows(msg_mat)
    return buf, nb, mrows, bpad


def _bucket(n: int) -> int:
    if n <= 8:
        return 8
    if n <= 512:
        return 1 << (n - 1).bit_length()
    return (n + 511) // 512 * 512


def verify_batch(msgs, sigs, pks, devices: int | None = None):
    """Lists of (msg bytes, 64-byte sig, 32-byte pubkey) -> list[bool]."""
    n = len(msgs)
    if n == 0:
        return []
    well_formed = np.array(
        [len(s) == 64 and len(p) == 32 for s, p in zip(sigs, pks)], dtype=bool
    )
    if well_formed.all():
        sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
        pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(n, 32)
    else:
        sig_arr = np.zeros((n, 64), dtype=np.uint8)
        pk_arr = np.zeros((n, 32), dtype=np.uint8)
        for i, (s, p) in enumerate(zip(sigs, pks)):
            if well_formed[i]:
                sig_arr[i] = np.frombuffer(s, dtype=np.uint8)
                pk_arr[i] = np.frombuffer(p, dtype=np.uint8)
    # canonicity of S (s < L) is a pure host-side byte check — no transfer
    s_ok = pack.lt_const_le_batch(sig_arr[:, 32:], _ref_L())

    ndev = devices if devices is not None else len(jax.devices())
    buf, nb, mrows, bpad = pack_buffer(msgs, sig_arr, pk_arr, ndev)
    fn = _jitted_packed(nb, mrows, bpad, ndev)
    # device_put submits the transfer asynchronously; the dispatch and the
    # mask fetch then ride the same pipeline (one latency leg, not three)
    mask = fn(jax.device_put(buf))
    out = np.asarray(mask)[:n] & s_ok & well_formed
    return [bool(v) for v in out]


def make_sharded_commit_step(mesh):
    """Sharded verify-commit step over a 1-D 'dp' mesh: per-signature
    validity masks (sharded) plus the 2/3-quorum voting-power tally via a
    psum collective — the device-parallel equivalent of the reference's
    talliedVotingPower loop (types/validator_set.go:358-366).

    The tally is exact int32 arithmetic in 2^16 limbs (powers split into
    lo/hi 16-bit halves, summed separately, recombined on host as Python
    ints by the caller via `lo + (hi << 16)`), so the 2/3-quorum decision
    never rounds: batch ≤ 2^15 items with per-item power < 2^31 stays
    exact. The authoritative quorum decision in verify_commit additionally
    re-tallies host-side from the mask with unbounded Python ints."""
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    dp = lambda n: P(*([None] * (n - 1) + ["dp"]))

    def step(words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs, powers, for_block):
        mask = _verify_core(words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs)
        powers = powers.astype(jnp.int32)
        counted = jnp.where(mask & (for_block == 1), powers, 0)
        lo = jnp.sum(counted & 0xFFFF)
        hi = jnp.sum(counted >> 16)
        return mask, jax.lax.psum(lo, "dp"), jax.lax.psum(hi, "dp")

    return jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(dp(4), dp(1), dp(2), dp(1), dp(2), dp(1), dp(2), dp(1), dp(1)),
            out_specs=(dp(1), P(), P()),
        )
    )


def tallied_power(lo, hi) -> int:
    """Recombine the limb sums from make_sharded_commit_step exactly."""
    return int(lo) + (int(hi) << 16)


def warmup(buckets=(8, 16, 64), nb: int = 2, mrows: int = 32,
           devices: int | None = None) -> None:
    """Compile the hot bucket shapes ahead of time. First-use compile of
    a bucket costs 20-40s on TPU (persistent cache makes later processes
    cheap, but the FIRST node on a machine pays it) — a consensus node
    must not discover that cost inside the live vote path, so node
    startup calls this from a background thread. Vote sign-bytes are
    ~97-128 bytes (nb=2 blocks, mrows=32 message rows); bucket sizes
    cover the adaptive batcher's first escalation steps."""
    import numpy as np

    ndev = devices if devices is not None else len(jax.devices())
    for b in buckets:
        bpad = _bucket(b)
        if ndev > 1:
            bpad = max(bpad, ndev)
            bpad = (bpad + ndev - 1) // ndev * ndev
        fn = _jitted_packed(nb, mrows, bpad, ndev)
        fn(jnp.asarray(np.zeros((ROWS_AUX + mrows, bpad), dtype=np.int32)))


class JAXBatchVerifier(BatchVerifier):
    """BatchVerifier backend running the vectorized TPU kernel."""

    def verify(self):
        if not self._items:
            return []
        msgs = [m for m, _, _ in self._items]
        sigs = [s for _, s, _ in self._items]
        pks = [p for _, _, p in self._items]
        return verify_batch(msgs, sigs, pks)
