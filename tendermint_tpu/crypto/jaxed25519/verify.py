"""The batched Ed25519 verify kernel — the north-star TPU path.

Replaces the reference's serial verify loop (types/validator_set.go:345-371
→ crypto/ed25519/ed25519.go:151-157) with one jitted device program per
(batch-bucket, block-count) shape:

    SHA-512(R||A||M) → reduce mod L → decompress A → [S]B (fixed-base
    windowed) + [k](-A) (double-and-add) → canonical encode → compare R.

Per-item validity masks come back — mixed valid/invalid batches are
first-class (no all-or-nothing batch equation). With more than one device
visible the batch shards across a 1-D "dp" mesh via shard_map; signatures
are the batch dimension, so the commit of a 10k-validator set simply
spreads over the pod with no cross-device traffic except the final
all-gather of masks.
"""

from __future__ import annotations

import os
import threading
import time
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import batch as batch_mod
from .. import kernel_cache
from ..batch import BatchVerifier
from . import curve, pack, pallas_kernels, scalar, sha512

# compile-once layer (crypto/kernel_cache): persistent XLA compilation
# cache + AOT-serialized executables, so kernels compile once per
# machine instead of per process. Honors TM_TPU_COMPILE_CACHE (and the
# legacy TM_TPU_JAX_CACHE spelling) until node config takes over.
kernel_cache.ensure_configured()


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 - backend init failed
        return "cpu"


@lru_cache(maxsize=1)
def on_tpu() -> bool:
    """True when the default backend is TPU hardware (directly, or via the
    axon tunnel) — gates the fused pallas kernels, which only lower via
    Mosaic (a GPU backend must keep the XLA path)."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _verify_core(msg_words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs,
                 use_pallas: bool = False, pallas_interpret: bool = False):
    digest = sha512.sha512_batch(msg_words, nblocks)
    k = scalar.reduce_512(sha512.digest_to_scalar_limbs(digest))
    if use_pallas:
        # fused VMEM-resident tail: decompress -> Straus -> encode -> compare
        # (one Mosaic kernel, no HBM intermediates — see PROFILE.md);
        # interpret=True runs the SAME kernel path on a CPU mesh (dryrun)
        return pallas_kernels.verify_tail(a_y, a_sign, r_y, r_sign, s_limbs, k,
                                          interpret=pallas_interpret)
    a_pt, ok_a = curve.decompress(a_y, a_sign)
    # R' = [S]B + [k](−A) in ONE Straus chain (shared doublings)
    r_prime = curve.straus_mul_sub(s_limbs, k, curve.negate(a_pt))
    y, parity = curve.encode(r_prime)
    eq = jnp.all(y == r_y, axis=0) & (parity == r_sign)
    return ok_a & eq


def _bytes_from_rows(rows_i32, nbytes: int):
    """(ceil(nbytes/4), B) int32 of 4 packed LE bytes -> (nbytes, B) int32."""
    parts = [(rows_i32 >> (8 * k)) & 0xFF for k in range(4)]
    stacked = jnp.stack(parts, axis=1)  # (rows, 4, B)
    return stacked.reshape(-1, rows_i32.shape[-1])[:nbytes]


def _limbs_from_bytes(bts):
    """(32, B) int32 LE bytes -> (20, B) 13-bit limbs (device twin of
    pack.bytes_to_limbs_batch)."""
    bdim = bts.shape[-1]
    zero = jnp.zeros((1, bdim), dtype=jnp.int32)
    rows = []
    for i in range(pack.NLIMB):
        bit = pack.BITS * i
        s, o = bit // 8, bit % 8
        v = bts[s] >> o
        if s + 1 < 32:
            v = v | (bts[s + 1] << (8 - o))
        if s + 2 < 32 and 16 - o < pack.BITS:
            v = v | (bts[s + 2] << (16 - o))
        rows.append(v & pack.MASK)
    return jnp.stack(rows, axis=0)


@lru_cache(maxsize=32)
def _jitted(nb: int, bpad: int, ndev: int):
    if ndev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(jax.devices()[:ndev]), ("dp",))
        last = lambda n: NamedSharding(mesh, P(*([None] * (n - 1) + ["dp"])))
        in_sh = (last(4), last(1), last(2), last(1), last(2), last(1), last(2))
        return jax.jit(_verify_core, in_shardings=in_sh, out_shardings=last(1))
    return jax.jit(_verify_core)


ROWS_AUX = 25  # mlen row + 16 sig rows + 8 pk rows


def _verify_packed_core(buf, nb: int, mrows: int, use_pallas: bool = False,
                        pallas_interpret: bool = False):
    """Unpack ONE (25 + mrows, B) int32 buffer into the _verify_core
    inputs. One host→device transfer; everything rides byte-dense
    (signature/pubkey/message bytes 4-per-int32) and the SHA-512 block
    construction — R||A prefix placement, 0x80 terminator, big-endian bit
    length — happens ON DEVICE from the raw bytes. vs shipping padded
    blocks + limbs this cuts the 10k-sig transfer 5.2MB → ~2.2MB (the
    axon tunnel charges ~64ms latency per round trip plus ~10-30ms/MB).

    Layout: row 0 = message length (bytes); rows 1:17 = signature;
    rows 17:25 = pubkey; rows 25: = message bytes."""
    bdim = buf.shape[-1]
    mlen = buf[0]
    sig_bytes = _bytes_from_rows(buf[1:17], 64)
    pk_bytes = _bytes_from_rows(buf[17:25], 32)
    msg_bytes = _bytes_from_rows(buf[25:], mrows * 4)

    # SHA-512 message region (after the 64-byte R||A prefix): mask tail
    # garbage, place 0x80 at mlen and the BE bit-length at inb*128-8
    region_len = nb * 128 - 64
    if mrows * 4 < region_len:
        msg_bytes = jnp.concatenate(
            [msg_bytes, jnp.zeros((region_len - mrows * 4, bdim), jnp.int32)],
            axis=0,
        )
    j = jnp.arange(region_len, dtype=jnp.int32)[:, None]
    inb = (mlen + 64 + 17 + 127) // 128  # per-item padded block count
    region = jnp.where(j < mlen[None, :], msg_bytes, 0)
    region = region + jnp.where(j == mlen[None, :], 0x80, 0)
    bitlen = (mlen + 64) * 8
    base = inb * 128 - 72  # region-relative start of the 8-byte BE length
    for t in range(8):
        v = (bitlen >> (8 * (7 - t))) & 0xFF
        region = region + jnp.where(j == (base + t)[None, :], v[None, :], 0)

    full = jnp.concatenate([sig_bytes[:32], pk_bytes, region], axis=0)
    f4 = full.astype(jnp.uint32).reshape(nb * 32, 4, bdim)
    words32 = (f4[:, 0] << 24) | (f4[:, 1] << 16) | (f4[:, 2] << 8) | f4[:, 3]
    words = words32.reshape(nb, 16, 2, bdim)

    r_y = _limbs_from_bytes(sig_bytes[:32])
    r_sign = (r_y[19] >> 8) & 1
    r_y = r_y.at[19].set(r_y[19] & 0xFF)
    s_limbs = _limbs_from_bytes(sig_bytes[32:64])
    a_y = _limbs_from_bytes(pk_bytes)
    a_sign = (a_y[19] >> 8) & 1
    a_y = a_y.at[19].set(a_y[19] & 0xFF)
    return _verify_core(words, inb, a_y, a_sign, r_y, r_sign, s_limbs,
                        use_pallas=use_pallas,
                        pallas_interpret=pallas_interpret)


def _pallas_flags(force_pallas=None) -> tuple:
    """(use_pallas, pallas_interpret) for the current backend.

    Default: the fused Mosaic kernel on TPU, the XLA kernel elsewhere.
    force_pallas=True additionally enables INTERPRET mode on non-TPU
    backends so a CPU mesh exercises the exact pallas-in-shard_map code
    path (dryrun_multichip does this); it is far too slow for general
    CPU testing, hence opt-in. TM_TPU_FORCE_PALLAS=0/1 fills in the
    DEFAULT only — an explicit force_pallas argument always wins, so a
    caller that claims to validate the pallas path cannot be silently
    rerouted by the environment."""
    if force_pallas is None:
        env = os.environ.get("TM_TPU_FORCE_PALLAS")
        if env in ("0", "1"):
            force_pallas = env == "1"
    if force_pallas is None:
        return on_tpu(), False
    if not force_pallas:
        return False, False
    return True, not on_tpu()


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    try:
        # pallas_call out_shapes don't declare vma; skip the check so the
        # fused kernel can live inside the shard_map body
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover - older jax without check_vma
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)


def _donate_default() -> bool:
    """Whether verify_batch donates the packed h2d buffer to the kernel
    (steady-state verification then reuses device memory instead of
    allocating per batch). Default: on for accelerators, off for the
    CPU backend (XLA CPU can rarely alias the buffer and warns instead).
    TM_TPU_DONATE=0/1 forces either way. Donated kernels are a separate
    compile key: introspection/profiling callers that re-dispatch on a
    resident device array keep the undonated variant (donate=False, the
    _jitted_packed default)."""
    env = os.environ.get("TM_TPU_DONATE")
    if env in ("0", "1"):
        return env == "1"
    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:  # noqa: BLE001 - no backend: nothing to donate to
        return False


def _jitted_packed(nb: int, mrows: int, bpad: int, ndev: int,
                   force_pallas=None, donate: bool = False):
    # resolve env/backend flags BEFORE the cache so flipping
    # TM_TPU_FORCE_PALLAS between calls can't return a stale kernel path
    use_pallas, interp = _pallas_flags(force_pallas)
    return _jitted_packed_impl(nb, mrows, bpad, ndev, use_pallas, interp,
                               donate)


@lru_cache(maxsize=32)
def _jitted_packed_impl(nb: int, mrows: int, bpad: int, ndev: int,
                        use_pallas: bool, interp: bool,
                        donate: bool = False):
    donate_kw = {"donate_argnums": (0,)} if donate else {}
    if ndev > 1:
        from jax.sharding import Mesh, PartitionSpec as P

        # GSPMD cannot auto-partition a Mosaic custom call, but shard_map
        # hands the body per-device blocks — exactly the shape the pallas
        # kernel wants — so the fused kernel runs per chip with no
        # cross-device traffic except the output concat
        mesh = Mesh(np.asarray(jax.devices()[:ndev]), ("dp",))
        body = partial(_verify_packed_core, nb=nb, mrows=mrows,
                       use_pallas=use_pallas, pallas_interpret=interp)
        fn = jax.jit(_shard_map(body, mesh,
                                in_specs=(P(None, "dp"),),
                                out_specs=P("dp")), **donate_kw)
    else:
        fn = jax.jit(partial(_verify_packed_core, nb=nb, mrows=mrows,
                             use_pallas=use_pallas,
                             pallas_interpret=interp), **donate_kw)
    if interp:
        # pallas interpret mode is a CPU-mesh dryrun path; its artifacts
        # are worthless cross-process and its lowering is the slow part
        return fn
    return kernel_cache.aot_wrap(
        "ed25519_packed",
        (nb, mrows, bpad, ndev, use_pallas, donate), fn)


@lru_cache(maxsize=1)
def _ref_L() -> int:
    from . import ref

    return ref.L


def _pack_le_rows(arr: np.ndarray) -> np.ndarray:
    """(B, nbytes) uint8 -> (nbytes//4, B) int32, 4 LE bytes per word."""
    b, nbytes = arr.shape
    w = arr.reshape(b, nbytes // 4, 4).astype(np.uint32)
    packed = w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24)
    return np.ascontiguousarray(packed.T).view(np.int32)


# per-thread packed-buffer rings for the chunked dispatch: one ring per
# (chunks, shape), so concurrent verify_batch callers (dispatch threads
# + direct callers) never share host memory. Reuse is ACROSS calls only
# — within a call every chunk packs its own slot, because device_put is
# async and the host array must stay unmodified until the copy lands.
_host_bufs = threading.local()


def _host_buf_ring(chunks: int, shape) -> list:
    key = (chunks, shape)
    pool = getattr(_host_bufs, "pool", None)
    if pool is None or pool[0] != key:
        pool = (key, [np.zeros(shape, dtype=np.int32)
                      for _ in range(chunks)])
        _host_bufs.pool = pool
    return pool[1]


def pack_buffer(msgs, sig_arr: np.ndarray, pk_arr: np.ndarray, ndev: int = 1,
                dims=None, out: np.ndarray | None = None):
    """Build the single packed h2d buffer (see _verify_packed_core layout).
    Returns (buf (ROWS_AUX+mrows, bpad) int32, nb, mrows, bpad). The ONLY
    place the layout is produced — bench/profiling code reuses it.
    `dims=(nb, mrows, bpad)` forces the padded shape (chunked dispatch:
    every chunk must share ONE jit key regardless of its own maxima).
    `out` reuses a caller-held buffer of exactly that shape instead of
    allocating (the chunked path ping-pongs two buffers)."""
    n = len(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
    maxlen = int(lens.max()) if n else 0
    nb = (64 + maxlen + 17 + 127) // 128
    # mrows bucketed to 64-byte granularity: vote sign-bytes from 65 to
    # 128 bytes (any realistic chain id) share the mrows=32 compile that
    # warmup() pre-builds — a fresh mrows key would stall the live path
    mrows = max(16, ((maxlen + 3) // 4 + 15) // 16 * 16)

    bpad = _bucket(n)
    if ndev > 1:
        bpad = max(bpad, ndev)
        bpad = (bpad + ndev - 1) // ndev * ndev
    if dims is not None:
        nb, mrows, bpad = dims

    msg_mat = np.zeros((n, mrows * 4), dtype=np.uint8)
    pack.fill_msg_bytes(msg_mat, [bytes(m) for m in msgs], lens)

    if out is not None and out.shape == (ROWS_AUX + mrows, bpad):
        buf = out
        buf.fill(0)
    else:
        buf = np.zeros((ROWS_AUX + mrows, bpad), dtype=np.int32)
    buf[0, :n] = lens
    buf[1:17, :n] = _pack_le_rows(sig_arr)
    buf[17:25, :n] = _pack_le_rows(pk_arr)
    buf[25:, :n] = _pack_le_rows(msg_mat)
    return buf, nb, mrows, bpad


def _bucket(n: int) -> int:
    if n <= 8:
        return 8
    if n <= 512:
        return 1 << (n - 1).bit_length()
    return (n + 511) // 512 * 512


def _pack_well_formed(msgs, sigs, pks):
    """Shared validation+packing front end: -> (sig_arr (n,64), pk_arr
    (n,32), ok_host (n,) bool) where ok_host = well-formed lengths AND
    canonical S (s < L, a pure host-side byte check — no transfer).
    Malformed rows are zeroed so downstream vector code stays shape-stable."""
    n = len(msgs)
    well_formed = np.array(
        [len(s) == 64 and len(p) == 32 for s, p in zip(sigs, pks)], dtype=bool
    )
    if well_formed.all():
        sig_arr = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
        pk_arr = np.frombuffer(b"".join(pks), dtype=np.uint8).reshape(n, 32)
    else:
        sig_arr = np.zeros((n, 64), dtype=np.uint8)
        pk_arr = np.zeros((n, 32), dtype=np.uint8)
        for i, (s, p) in enumerate(zip(sigs, pks)):
            if well_formed[i]:
                sig_arr[i] = np.frombuffer(s, dtype=np.uint8)
                pk_arr[i] = np.frombuffer(p, dtype=np.uint8)
    s_ok = pack.lt_const_le_batch(sig_arr[:, 32:], _ref_L())
    return sig_arr, pk_arr, s_ok & well_formed


def verify_batch(msgs, sigs, pks, devices: int | None = None):
    """Lists of (msg bytes, 64-byte sig, 32-byte pubkey) -> list[bool].

    TM_TPU_VERIFY_CHUNKS=k (default 1) splits large batches into k
    equal chunks dispatched back-to-back: chunk i+1's host->device
    transfer overlaps chunk i's kernel, hiding min(transfer, compute)
    per extra chunk on direct-attached TPU. All chunks share one jit
    key (same padded shape), and chunking composes with multi-device
    meshes (each chunk's bpad stays a multiple of ndev, so every chunk
    shards cleanly). Only batches >= 2048 split — below that the extra
    dispatch overhead outweighs the overlap. On accelerators the host
    side packs into a per-thread RING of `chunks` buffers — distinct
    per chunk within one call (device_put is async and PJRT only
    requires the host buffer stay unmodified until the copy completes,
    so a buffer is never repacked under an in-flight transfer) and
    reused across back-to-back calls (this function returns only after
    every mask materializes, which bounds every transfer) — and the
    device buffer is DONATED to the kernel, so steady-state
    verification reuses both host and device memory instead of
    allocating per batch."""
    n = len(msgs)
    if n == 0:
        return []
    sig_arr, pk_arr, ok_host = _pack_well_formed(msgs, sigs, pks)

    ndev = devices if devices is not None else len(jax.devices())
    try:
        chunks = int(os.environ.get("TM_TPU_VERIFY_CHUNKS", "1"))
        chunk_min = int(os.environ.get("TM_TPU_VERIFY_CHUNK_MIN", "2048"))
    except ValueError:
        # a malformed env var must never take down verification
        chunks, chunk_min = 1, 2048
    if chunks < 2 or n < chunk_min:
        chunks = 1

    # one jit key for every chunk, derived from GLOBAL maxima: a chunk
    # with its own (nb, mrows, bpad) would trigger a fresh multi-second
    # compile inside the live path, which warmup() exists to prevent
    per = (n + chunks - 1) // chunks
    maxlen = max((len(m) for m in msgs), default=0)
    nb = (64 + maxlen + 17 + 127) // 128
    mrows = max(16, ((maxlen + 3) // 4 + 15) // 16 * 16)
    bpad = _bucket(per)
    if ndev > 1:
        bpad = max(bpad, ndev)
        bpad = (bpad + ndev - 1) // ndev * ndev
    fn = _jitted_packed(nb, mrows, bpad, ndev, donate=_donate_default())

    # host-buffer reuse only where device_put copies out of the host
    # array (accelerators); the CPU backend can alias numpy memory, and
    # an aliased buffer must never be repacked under an in-flight kernel
    reuse_host = chunks > 1 and _platform() != "cpu"
    bufs = (_host_buf_ring(chunks, (ROWS_AUX + mrows, bpad))
            if reuse_host else None)

    # transfer-vs-compute attribution for the CryptoMetrics split gauges
    # (PROFILE.md round 4 measured this with one-off scripts; now it is
    # always on). device_put and the dispatch are async, so "transfer"
    # is host pack + h2d submission and "compute" is the blocking wait
    # for result materialization — the same split the profiling scripts
    # reported, measured per live batch.
    t_transfer = 0.0
    t0 = time.perf_counter()
    masks = []
    for idx, lo in enumerate(range(0, n, per)):
        hi = min(lo + per, n)
        buf, _, _, _ = pack_buffer(
            msgs[lo:hi], sig_arr[lo:hi], pk_arr[lo:hi], ndev,
            dims=(nb, mrows, bpad),
            out=bufs[idx] if reuse_host else None)
        # device_put + dispatch are async: the NEXT chunk's pack and
        # h2d transfer overlap this chunk's kernel (with chunks=1 this
        # is the plain single-dispatch pipeline)
        dev = jax.device_put(buf)
        t_transfer += time.perf_counter() - t0
        masks.append((fn(dev), hi - lo))
        t0 = time.perf_counter()
    out = np.concatenate([np.asarray(m)[:cn] for m, cn in masks]) & ok_host
    t_compute = time.perf_counter() - t0
    batch_mod.record_device_split(t_transfer, t_compute)
    return [bool(v) for v in out]


# --- aggregate (random-linear-combination) verification --------------------


def _rlc_core(words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs, z_limbs,
              group: int):
    """Grouped RLC batch check: for each contiguous group g of `group`
    items, verify Σ_i z_i·s_i · B == Σ_i [z_i]R_i + Σ_i [z_i·k_i]A_i
    with host-supplied random z_i = 8·u_i (u_i random odd 128-bit). The
    factor 8 makes the equation COFACTORED: every small-order (torsion)
    component is annihilated by construction, so acceptance is
    deterministic (never a coin-flip on torsion sums) and the prime-order
    part is sound to 2^-128. One doubling chain per GROUP (shared by all
    members) instead of one per signature — the fast path for valid-heavy
    batches (fast-sync block commits, reference call site
    blockchain/reactor.go:310). Returns (ok_pre (B,), ok_g (B//group,)).
    Items with failed A/R decompress are excluded (their z is zeroed) and
    reported in ok_pre."""
    digest = sha512.sha512_batch(words, nblocks)
    k = scalar.reduce_512(sha512.digest_to_scalar_limbs(digest))
    a_pt, ok_a = curve.decompress(a_y, a_sign)
    r_pt, ok_r = curve.decompress(r_y, r_sign)
    ok_pre = ok_a & ok_r
    z = jnp.where(ok_pre[None, :], z_limbs, 0)
    zk = scalar.mul_mod_l(z, k)
    zs = scalar.mul_mod_l(z, s_limbs)
    s_g = scalar.sum_mod_l_groups(zs, group)
    bdim = a_y.shape[-1]
    zk_win = curve._windows_msb_first(zk, bdim)
    z_win = curve._windows_msb_first(z, bdim, nbits=132)  # 8*u: 131 bits
    t_g = curve.msm_groups(r_pt, z_win, a_pt, zk_win, group)
    rhs = curve.fixed_base_mul(s_g)
    diff = curve.add_points(t_g, curve.negate(rhs))
    return ok_pre, curve.is_identity(diff)


@lru_cache(maxsize=16)
def _jitted_rlc(nb: int, bpad: int, group: int):
    return kernel_cache.aot_wrap(
        "ed25519_rlc", (nb, bpad, group),
        jax.jit(partial(_rlc_core, group=group)))


def verify_batch_rlc(msgs, sigs, pks, group: int = 64,
                     devices: int | None = None):
    """Aggregate (random-linear-combination) batch verification with a
    COFACTORED group equation (z_i = 8·u_i; ZIP-215 / ed25519-dalek
    verify_batch style). Groups whose equation holds are accepted;
    failed groups fall back to the per-item kernel, so ordinary forgeries
    (prime-order defects), corrupted signatures, wrong keys, malformed
    inputs, high-S and non-canonical-R encodings all produce exactly the
    per-item masks (non-canonical R is pre-rejected host-side because Go
    compares encode(R') against the RAW R bytes).

    KNOWN, DELIBERATE divergence from the per-item path: a signature
    whose defect is PURE small-order torsion — R' = R + T with T in the
    8-torsion subgroup, s computed against H(R'||A||M) — satisfies the
    cofactored equation but fails Go's cofactorless byte compare. No
    batch equation can match cofactorless single verification on these
    (Chalkias et al., "Taming the many EdDSAs"); making them pass
    deterministically (rather than with probability ~1/8 on torsion-sum
    cancellation) is the safer, standardized choice. Because of this
    divergence the consensus-critical paths (verify_commit and friends)
    use ONLY the per-item kernel; this mode is for throughput-bound,
    non-consensus batch checks."""
    import secrets as _secrets

    n = len(msgs)
    if n == 0:
        return []
    sig_arr, pk_arr, ok_host = _pack_well_formed(msgs, sigs, pks)
    # Go's verify compares encode(R') against the RAW R bytes: a
    # non-canonical R (y >= p) can never match the canonical encode.
    # The RLC equation tests point equality, so weed those out up front.
    r_masked = sig_arr[:, :32].copy()
    r_masked[:, 31] &= 0x7F
    ok_host = ok_host & pack.lt_const_le_batch(r_masked, _ref_P())

    r_y, r_sign, s_limbs, _ = pack.split_signatures(sig_arr)
    a_y, a_sign = pack.split_pubkeys(pk_arr)
    prefixes = np.concatenate([sig_arr[:, :32], pk_arr], axis=1)
    words, nblocks = pack.sha512_pad_batch(prefixes, [bytes(m) for m in msgs])

    bpad = _bucket(n)
    group = min(group, bpad)

    # z_i = 8·u_i with u_i random odd 128-bit: the odd u keeps z nonzero
    # mod L, the 8 makes the group equation cofactored (see docstring)
    u_bytes = np.frombuffer(_secrets.token_bytes(16 * n), np.uint8
                            ).reshape(n, 16).copy()
    u_bytes[:, 0] |= 1
    z_bytes = np.zeros((n, 17), dtype=np.uint8)  # u << 3, little-endian
    z_bytes[:, :16] = u_bytes << 3
    z_bytes[:, 1:] |= u_bytes >> 5
    z_limbs = pack.bytes_to_limbs_batch(z_bytes)
    z_limbs[:, ~ok_host] = 0  # excluded items must not contribute

    def padb(a):
        padw = [(0, 0)] * (a.ndim - 1) + [(0, bpad - n)]
        return np.pad(a, padw)

    fn = _jitted_rlc(words.shape[0], bpad, group)
    ok_pre, ok_g = fn(
        jnp.asarray(padb(words)), jnp.asarray(padb(nblocks)),
        jnp.asarray(padb(a_y)), jnp.asarray(padb(a_sign)),
        jnp.asarray(padb(r_y)), jnp.asarray(padb(r_sign)),
        jnp.asarray(padb(s_limbs)), jnp.asarray(padb(z_limbs)),
    )
    ok_pre = np.asarray(ok_pre)[:n] & ok_host
    ok_g = np.asarray(ok_g)

    out = np.zeros(n, dtype=bool)
    retry = []
    for i in range(n):
        if not ok_pre[i]:
            continue  # definitively invalid (malformed/non-canonical/decompress)
        if ok_g[i // group]:
            out[i] = True
        else:
            retry.append(i)
    if retry:
        sub = verify_batch([msgs[i] for i in retry], [sigs[i] for i in retry],
                           [pks[i] for i in retry], devices=devices)
        for i, ok in zip(retry, sub):
            out[i] = ok
    return [bool(v) for v in out]


@lru_cache(maxsize=1)
def _ref_P() -> int:
    from . import ref

    return ref.P


def make_sharded_commit_step(mesh, force_pallas=None):
    """Sharded verify-commit step over a 1-D 'dp' mesh: per-signature
    validity masks (sharded) plus the 2/3-quorum voting-power tally via a
    psum collective — the device-parallel equivalent of the reference's
    talliedVotingPower loop (types/validator_set.go:358-366). Each device
    runs the fused pallas kernel on its own block when on TPU (shard_map
    hands the body per-device shapes, so the Mosaic call never meets
    GSPMD); force_pallas=True exercises the same path in interpret mode
    on a CPU mesh.

    The tally is exact int32 arithmetic in 2^16 limbs (powers split into
    lo/hi 16-bit halves, summed separately, recombined on host as Python
    ints by the caller via `lo + (hi << 16)`), so the 2/3-quorum decision
    never rounds: batch ≤ 2^15 items with per-item power < 2^31 stays
    exact. The authoritative quorum decision in verify_commit additionally
    re-tallies host-side from the mask with unbounded Python ints."""
    from jax.sharding import PartitionSpec as P

    use_pallas, interp = _pallas_flags(force_pallas)
    dp = lambda n: P(*([None] * (n - 1) + ["dp"]))

    def step(words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs, powers, for_block):
        mask = _verify_core(words, nblocks, a_y, a_sign, r_y, r_sign, s_limbs,
                            use_pallas=use_pallas, pallas_interpret=interp)
        powers = powers.astype(jnp.int32)
        counted = jnp.where(mask & (for_block == 1), powers, 0)
        lo = jnp.sum(counted & 0xFFFF)
        hi = jnp.sum(counted >> 16)
        return mask, jax.lax.psum(lo, "dp"), jax.lax.psum(hi, "dp")

    return jax.jit(
        _shard_map(
            step,
            mesh,
            in_specs=(dp(4), dp(1), dp(2), dp(1), dp(2), dp(1), dp(2), dp(1), dp(1)),
            out_specs=(dp(1), P(), P()),
        )
    )


def tallied_power(lo, hi) -> int:
    """Recombine the limb sums from make_sharded_commit_step exactly."""
    return int(lo) + (int(hi) << 16)


def _sharded_commit_fn(ndev: int, force_pallas=None):
    # resolve flags BEFORE the cache (same staleness fix as
    # _jitted_packed): flipping TM_TPU_FORCE_PALLAS must not return a
    # kernel compiled for the previous setting
    use_pallas, interp = _pallas_flags(force_pallas)
    return _sharded_commit_fn_impl(ndev, use_pallas, interp)


@lru_cache(maxsize=8)
def _sharded_commit_fn_impl(ndev: int, use_pallas: bool, interp: bool):
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:ndev]), ("dp",))
    # interp is True only when use_pallas is, and make_sharded_commit_step
    # re-derives it identically from the boolean
    step = make_sharded_commit_step(mesh, force_pallas=use_pallas)
    if interp:
        return step  # CPU-mesh dryrun: artifacts are worthless cross-run
    return kernel_cache.aot_wrap(
        "ed25519_commit_step", (ndev, use_pallas), step)


def sharded_commit_verify(msgs, sigs, pks, powers, for_block,
                          devices: int | None = None):
    """Device-parallel commit verification over every visible device:
    per-signature validity masks (batch sharded on a 1-D 'dp' mesh) plus
    the 2/3-quorum voting-power tally as an on-device psum — the
    multi-chip equivalent of the reference's talliedVotingPower loop
    (types/validator_set.go:345-371).

    powers must each be < 2^31 (the exact lo/hi 16-bit tally bound);
    callers with larger powers must use the host path. Returns
    (mask list[bool], psum_tally int). Host-side canonicity (s < L) and
    well-formedness zero out both the mask and the item's tally weight.
    """
    n = len(msgs)
    ndev = devices if devices is not None else len(jax.devices())
    if n == 0:
        return [], 0
    sig_arr, pk_arr, ok_host = _pack_well_formed(msgs, sigs, pks)
    r_y, r_sign, s_limbs, _ = pack.split_signatures(sig_arr)
    a_y, a_sign = pack.split_pubkeys(pk_arr)
    prefixes = np.concatenate([sig_arr[:, :32], pk_arr], axis=1)
    words, nblocks = pack.sha512_pad_batch(prefixes, [bytes(m) for m in msgs])

    bpad = max(_bucket(n), ndev)
    bpad = (bpad + ndev - 1) // ndev * ndev

    def padb(a, fill=0):  # pad batch-last axis to bpad
        padw = [(0, 0)] * (a.ndim - 1) + [(0, bpad - n)]
        return np.pad(a, padw, constant_values=fill)

    powers_arr = np.asarray(powers, dtype=np.int64)
    if (powers_arr >= 2**31).any() or (powers_arr < 0).any():
        raise ValueError("sharded tally requires 0 <= power < 2^31")
    counted_powers = np.where(ok_host, powers_arr, 0).astype(np.int32)
    fb = np.asarray(for_block, dtype=np.int32)

    fn = _sharded_commit_fn(ndev)
    mask, lo, hi = fn(
        jnp.asarray(padb(words)), jnp.asarray(padb(nblocks)),
        jnp.asarray(padb(a_y)), jnp.asarray(padb(a_sign)),
        jnp.asarray(padb(r_y)), jnp.asarray(padb(r_sign)),
        jnp.asarray(padb(s_limbs)), jnp.asarray(padb(counted_powers)),
        jnp.asarray(padb(fb)),
    )
    out = np.asarray(mask)[:n] & ok_host
    return [bool(v) for v in out], tallied_power(lo, hi)


def warmup(buckets=(8, 16, 64), nb: int = 2, mrows: int = 32,
           devices: int | None = None, calibrate: bool = True):
    """Compile the hot bucket shapes ahead of time. First-use compile of
    a bucket costs 20-40s on TPU (persistent cache makes later processes
    cheap, but the FIRST node on a machine pays it) — a consensus node
    must not discover that cost inside the live vote path, so node
    startup calls this from a background thread. Vote sign-bytes are
    ~97-128 bytes (nb=2 blocks, mrows=32 message rows); bucket sizes
    cover the adaptive batcher's first escalation steps.

    With calibrate=True (default; TM_TPU_CALIBRATE=0 disables), also
    measures the compiled-dispatch round trip vs the serial per-sig
    host cost and installs the break-even as the adaptive batch cutoff
    (crypto.batch.set_calibrated_batch_min) — the device is then only
    chosen where it wins on the latency of the hardware actually
    attached (a ~64ms-RTT tunnel calibrates to hundreds; direct-attach
    to tens). Returns the calibrated cutoff, or None."""
    import numpy as np

    ndev = devices if devices is not None else len(jax.devices())
    small_fn, small_shape = None, None
    donate = _donate_default()  # warm the variant the live path runs
    for b in buckets:
        bpad = _bucket(b)
        if ndev > 1:
            bpad = max(bpad, ndev)
            bpad = (bpad + ndev - 1) // ndev * ndev
        fn = _jitted_packed(nb, mrows, bpad, ndev, donate=donate)
        fn(jnp.asarray(np.zeros((ROWS_AUX + mrows, bpad), dtype=np.int32)))
        if small_fn is None or bpad < small_shape[1]:
            small_fn, small_shape = fn, (ROWS_AUX + mrows, bpad)
        if ndev > 1:
            # the multi-device commit path routes through the shard_map
            # psum step (sharded_commit_verify) — compile it too, or the
            # first live verify_commit pays the compile
            step = _sharded_commit_fn(ndev)
            z20 = np.zeros((20, bpad), np.int32)
            zrow = np.zeros((bpad,), np.int32)
            step(np.zeros((nb, 16, 2, bpad), np.uint32), zrow + 1, z20, zrow,
                 z20, zrow, z20, zrow, zrow)
    if (calibrate and small_fn is not None
            and os.environ.get("TM_TPU_CALIBRATE", "1") != "0"):
        return _calibrate_batch_min(small_fn, small_shape)
    return None


def _calibrate_batch_min(fn, shape) -> int | None:
    """Measure break-even between one device dispatch (round trip incl.
    transfer + any tunnel latency) and serial host verifies; install it
    via crypto.batch.set_calibrated_batch_min. Median-of-3 on the
    dispatch (tunnel variance is large); small margin toward serial so
    borderline batches stay on the predictable host path."""
    import time

    import numpy as np

    from ..batch import set_calibrated_batch_min
    from ..keys import PrivKeyEd25519

    try:
        ts = []
        for _ in range(3):
            # put INSIDE the timed region (and fresh per rep): the live
            # path pays the transfer every batch, and a donated kernel
            # consumes its input buffer — re-dispatching a resident
            # array is exactly what donation forbids
            t0 = time.perf_counter()
            d = jax.device_put(np.zeros(shape, dtype=np.int32))
            np.asarray(fn(d))
            ts.append(time.perf_counter() - t0)
        dispatch_ms = sorted(ts)[1] * 1e3

        sk = PrivKeyEd25519.gen_from_secret(b"tm-tpu-calibration")
        msg = b"\xa5" * 110
        sig = sk.sign(msg)
        pk = sk.pub_key()
        reps = 32
        t0 = time.perf_counter()
        for _ in range(reps):
            pk.verify_bytes(msg, sig)
        serial_ms = (time.perf_counter() - t0) / reps * 1e3
        if serial_ms <= 0:
            return None
        n_star = int(min(max(round(dispatch_ms / serial_ms * 1.1), 4), 4096))
        set_calibrated_batch_min(n_star)
        return n_star
    except Exception:
        return None  # calibration is best-effort; the static default stands


class JAXBatchVerifier(BatchVerifier):
    """BatchVerifier backend running the vectorized TPU kernel."""

    BACKEND = "jax"

    def _verify(self):
        if not self._items:
            return []
        if any(len(p) != 32 for _, _, p in self._items):
            # non-Ed25519 triples (e.g. 48-byte BLS pubkeys): this
            # kernel is Ed25519-specific — serial host dispatch instead
            from ..batch import CPUBatchVerifier

            inner = CPUBatchVerifier()
            for m, s, p in self._items:
                inner.add(m, s, p)
            return inner._verify()
        msgs = [m for m, _, _ in self._items]
        sigs = [s for _, s, _ in self._items]
        pks = [p for _, _, p in self._items]
        return verify_batch(msgs, sigs, pks)
