"""Crypto layer: keys, hashing, merkle trees, and the batch-verify engine."""

from .keys import (  # noqa: F401
    PrivKey,
    PrivKeyEd25519,
    PubKey,
    PubKeyEd25519,
    pubkey_from_bytes,
    pubkey_to_bytes,
    privkey_from_bytes,
    privkey_to_bytes,
)
from .batch import (  # noqa: F401
    BatchVerifier,
    CPUBatchVerifier,
    backends,
    batch_verify,
    new_batch_verifier,
    set_default_backend,
)
