"""Compile-once kernel layer: persistent XLA cache + AOT artifact store.

XLA compilation is the dominant tax on the verify hot path: the packed
Ed25519 kernel costs multi-second compiles per (shape, device) key, the
BLS jax-MSM kernel minutes — and every PROCESS used to pay it again.
This module makes kernels compile once per MACHINE:

1. The persistent XLA compilation cache (``jax_compilation_cache_dir``)
   is enabled under a configurable directory (``[crypto]``
   ``compile_cache_dir``, default ``~/.cache/tendermint-tpu/xla``), so
   XLA itself reuses compiled modules across processes.
2. An AOT artifact store layers on top: known kernels are
   ``.lower().compile()``d once, serialized with
   ``jax.experimental.serialize_executable``, and written (atomically)
   under ``<cache_dir>/aot/``. A later process deserializes the native
   executable in milliseconds — no tracing, no XLA compile at all.

Artifacts are keyed by (jax version, backend platform, device kind,
device count, kernel name, static key, argument avals); a corrupted,
truncated, or version-mismatched artifact is IGNORED (fresh compile +
miss counter), never a crash. Writes go through a same-directory
tempfile + ``os.replace`` so concurrent processes racing one entry
cannot corrupt it — last writer wins, both end up with a valid file.

Trust model: artifacts deserialize via pickle, the same local-user
trust boundary as XLA's own persistent cache directory — do not point
``compile_cache_dir`` at an untrusted location.

Everything here is best-effort: any failure in the cache layer falls
back to the plain jit path. The module never imports jax at import
time (mirroring crypto/batch's deferred-registration idiom).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import tempfile
import threading
import time
import weakref
from typing import Callable, Optional

LOG = logging.getLogger("crypto.kernel_cache")

DEFAULT_CACHE_DIR = "~/.cache/tendermint-tpu/xla"

# artifact header: magic + one json metadata line, then the pickled
# serialize_executable payload
_MAGIC = b"TMTPU-AOT1 "

_lock = threading.RLock()
_dir: Optional[str] = None  # resolved cache dir; None = not yet configured
_disabled = False  # explicit opt-out (compile_cache_dir = "")
_stats = {"hits": 0, "misses": 0, "compiles": 0, "load_errors": 0}
# in-progress compiles: unique token -> (kernel, perf_counter() start);
# tokens (not kernel names) so two shapes of one kernel compiling
# concurrently both stay visible until each finishes
_compiling: dict = {}
_compile_seq = 0
# weakrefs to every live aot_wrap in-memory cache (clear_memory's only
# purpose); weak so an aot_wrap dropped by its caller (e.g. lru_cache
# eviction of a kernel shape) actually frees its loaded executables
_wrapper_caches: list = []


class _WrapperCache(dict):
    """A dict that supports weak references (plain dicts don't)."""

    __slots__ = ("__weakref__",)


def _metrics():
    """The process-wide CryptoMetrics sink, if one is installed
    (crypto/batch.set_metrics). Imported lazily: batch imports the jax
    verify module which imports us — a top-level import would cycle."""
    from . import batch as _batch

    return _batch.get_metrics()


def configure(cache_dir: Optional[str]) -> Optional[str]:
    """Set the compile-cache root: enables jax's persistent compilation
    cache there and roots the AOT artifact store at ``<dir>/aot``.
    ``""`` (or None) disables both layers. Returns the resolved dir.

    Safe to call before OR after jax backend init, and repeatedly (a
    node reconfiguring to the same dir is a no-op)."""
    global _dir, _disabled
    with _lock:
        if not cache_dir:
            if _dir is not None:
                try:  # pragma: no cover - depends on jax build
                    import jax

                    jax.config.update("jax_compilation_cache_dir", None)
                except Exception as e:  # noqa: BLE001 - best-effort
                    LOG.debug("persistent XLA cache not disabled: %s", e)
            _disabled = True
            _dir = None
            return None
        resolved = os.path.abspath(os.path.expanduser(cache_dir))
        _disabled = False
        if resolved == _dir:
            return _dir
        _dir = resolved
        try:
            os.makedirs(os.path.join(resolved, "aot"), exist_ok=True)
        except OSError as e:
            LOG.warning("compile cache dir %s unusable, caching disabled: %s",
                        resolved, e)
            _dir, _disabled = None, True
            return None
        _prune_stale(resolved)
        try:  # pragma: no cover - depends on jax build
            import jax

            jax.config.update("jax_compilation_cache_dir", resolved)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
        except Exception as e:  # noqa: BLE001 - cache is best-effort
            LOG.debug("persistent XLA cache not enabled: %s", e)
        return _dir


_TMP_MAX_AGE_S = 24 * 3600.0  # crashed writers' tempfiles age out


def _prune_stale(root: str) -> None:
    """Best-effort GC of the aot/ store, run once per configure():
    artifacts written by a DIFFERENT jax version are permanently
    unreachable (the version is part of the key hash in the filename)
    and multi-MB each, so without this they accumulate forever across
    upgrades; unparseable artifacts can never load either. Live
    same-version artifacts are never touched."""
    try:
        import jax

        version = jax.__version__
    except Exception:  # noqa: BLE001 - no jax, nothing to compare to
        return
    aot = os.path.join(root, "aot")
    try:
        names = os.listdir(aot)
    except OSError:
        return
    now = time.time()
    for name in names:
        path = os.path.join(aot, name)
        try:
            if name.startswith(".tmp-aot-"):
                if now - os.path.getmtime(path) > _TMP_MAX_AGE_S:
                    os.unlink(path)
                continue
            if not name.endswith(".aot"):
                continue
            with open(path, "rb") as f:
                head = f.read(65536)  # meta line sits right after magic
            keep = False
            if head.startswith(_MAGIC):
                nl = head.find(b"\n", len(_MAGIC))
                if nl != -1:
                    try:
                        meta = json.loads(head[len(_MAGIC):nl].decode())
                        keep = json.loads(meta["key"])[0] == version
                    except Exception:  # noqa: BLE001 - junk never loads
                        keep = False
            if not keep:
                os.unlink(path)
        except OSError:
            continue  # racing process: it won the unlink, fine


def unconfigure() -> None:
    """Return to the never-configured state (test fixtures): unlike
    configure(""), which pins the layer DISABLED, the next
    ensure_configured() re-reads the environment/default."""
    global _dir, _disabled
    with _lock:
        _dir = None
        _disabled = False


def ensure_configured() -> Optional[str]:
    """Configure with the environment/default dir unless a configure()
    call already happened. TM_TPU_COMPILE_CACHE wins, then the legacy
    TM_TPU_JAX_CACHE spelling, then DEFAULT_CACHE_DIR; an empty
    TM_TPU_COMPILE_CACHE disables caching."""
    with _lock:
        if _dir is not None or _disabled:
            return _dir
    env = os.environ.get("TM_TPU_COMPILE_CACHE")
    if env is None:
        env = os.environ.get("TM_TPU_JAX_CACHE") or DEFAULT_CACHE_DIR
    return configure(env)


def cache_dir() -> Optional[str]:
    return _dir


def stats() -> dict:
    with _lock:
        return dict(_stats)


def status() -> dict:
    """Bundle for /debug/crypto: store state, counters, and any compile
    currently in progress (a node stuck compiling at boot shows up here
    as {"kernel": elapsed_seconds})."""
    now = time.perf_counter()
    with _lock:
        compiling: dict = {}
        for kernel, t in _compiling.values():
            elapsed = round(now - t, 1)
            # several shapes of one kernel: report the longest-running
            compiling[kernel] = max(elapsed, compiling.get(kernel, 0.0))
        return {
            "dir": _dir,
            "enabled": _dir is not None,
            **_stats,
            "compiling": compiling,
        }


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0


def clear_memory() -> None:
    """Drop every aot_wrap in-memory compiled-kernel reference, so the
    next call re-loads from disk — a fresh process, simulated in-process
    (warm-path tests use this to assert load-without-recompile)."""
    with _lock:
        live = []
        for ref in _wrapper_caches:
            c = ref()
            if c is not None:
                c.clear()
                live.append(ref)
        _wrapper_caches[:] = live  # prune dead wrappers while here


def _bump(key: str, n: int = 1) -> None:
    with _lock:
        _stats[key] += n


def _aval_part(a) -> tuple:
    """Stable key component for one argument: (shape, dtype) for
    anything array-like, a type tag for python scalars."""
    import numpy as np

    if hasattr(a, "shape") and hasattr(a, "dtype"):
        return ("arr", tuple(int(s) for s in a.shape), str(a.dtype))
    if isinstance(a, bool):
        return ("pybool",)
    if isinstance(a, int):
        return ("pyint",)
    if isinstance(a, float):
        return ("pyfloat",)
    return ("other", str(np.asarray(a).shape), str(np.asarray(a).dtype))


def _full_key(kernel: str, static_key: tuple, args) -> str:
    import jax

    try:
        dev = jax.devices()[0]
        platform, kind, ndev = dev.platform, dev.device_kind, len(jax.devices())
    except Exception:  # noqa: BLE001 - no backend: key still stable
        platform, kind, ndev = "none", "none", 0
    return json.dumps([jax.__version__, platform, kind, ndev, kernel,
                       list(static_key), [list(_aval_part(a)) for a in args]],
                      sort_keys=True)


def _artifact_path(kernel: str, key: str) -> Optional[str]:
    if _dir is None:
        return None
    h = hashlib.sha256(key.encode()).hexdigest()[:24]
    return os.path.join(_dir, "aot", f"{kernel}-{h}.aot")


def _try_load(kernel: str, key: str, path: str):
    """Deserialize a stored executable; None on ANY mismatch/corruption
    (counted, logged at debug — the fresh-compile path takes over)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None  # plain miss: not on disk yet
    try:
        if not blob.startswith(_MAGIC):
            raise ValueError("bad magic")
        rest = blob[len(_MAGIC):]
        nl = rest.index(b"\n")
        meta = json.loads(rest[:nl].decode())
        if meta.get("key") != key:
            raise ValueError("key mismatch (different jax/backend/shape)")
        payload = pickle.loads(rest[nl + 1:])
        from jax.experimental import serialize_executable as _se

        compiled = _se.deserialize_and_load(*payload)
        return compiled
    except Exception as e:  # noqa: BLE001 - corrupt/foreign artifact
        _bump("load_errors")
        LOG.debug("ignoring unusable AOT artifact %s: %s", path, e)
        return None


def _try_store(kernel: str, key: str, path: str, compiled) -> None:
    """Serialize + atomic write-rename; failures only cost the cache."""
    try:
        from jax.experimental import serialize_executable as _se

        payload = pickle.dumps(_se.serialize(compiled))
        meta = json.dumps({"key": key, "kernel": kernel}).encode()
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".tmp-aot-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(_MAGIC + meta + b"\n" + payload)
            os.replace(tmp, path)  # atomic: racing writers both stay valid
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception as e:  # noqa: BLE001 - store is best-effort
        LOG.debug("could not persist AOT artifact for %s: %s", kernel, e)


def _timed_compile(kernel: str, jitted, args):
    """lower().compile() with the compile-seconds metric and the
    in-progress marker /debug/crypto surfaces."""
    global _compile_seq
    t0 = time.perf_counter()
    with _lock:
        _compile_seq += 1
        token = _compile_seq
        _compiling[token] = (kernel, t0)
    try:
        compiled = jitted.lower(*args).compile()
    finally:
        with _lock:
            _compiling.pop(token, None)
    dt = time.perf_counter() - t0
    _bump("compiles")
    m = _metrics()
    if m is not None:
        m.compile_seconds.with_labels(kernel).observe(dt)
    LOG.info("compiled kernel %s in %.1fs", kernel, dt)
    return compiled


def load_or_compile(kernel: str, static_key: tuple, jitted, args):
    """One kernel instance: AOT-load from disk if a matching artifact
    exists, else lower+compile from `args` (concrete arrays or
    jax.ShapeDtypeStruct) and write the artifact back. Any cache-layer
    failure degrades to the fresh-compile result."""
    ensure_configured()
    m = _metrics()
    try:
        key = _full_key(kernel, static_key, args)
        path = _artifact_path(kernel, key)
    except Exception as e:  # noqa: BLE001 - never block verification
        LOG.debug("AOT key derivation failed for %s: %s", kernel, e)
        key = path = None
    if path is not None:
        compiled = _try_load(kernel, key, path)
        if compiled is not None:
            _bump("hits")
            if m is not None:
                m.compile_cache_hits.inc()
            return compiled
        _bump("misses")
        if m is not None:
            m.compile_cache_misses.inc()
    try:
        compiled = _timed_compile(kernel, jitted, args)
    except Exception as e:  # noqa: BLE001 - AOT lowering unsupported
        # e.g. an arg form .lower() can't take: the plain jit function
        # is always a correct (lazily compiling) stand-in
        LOG.debug("AOT compile path unavailable for %s (%s); "
                  "falling back to plain jit", kernel, e)
        return jitted
    if path is not None:
        _try_store(kernel, key, path, compiled)
    return compiled


def aot_wrap(kernel: str, static_key: tuple, jitted) -> Callable:
    """Wrap a jitted function with the compile-once layer: the first
    call for each argument-shape signature loads the stored executable
    (or compiles and stores it); later calls dispatch the executable
    directly. Drop-in for the jit callable at every existing call site.
    """
    cache = _WrapperCache()
    lock = threading.Lock()
    with _lock:
        _wrapper_caches.append(weakref.ref(cache))

    def call(*args):
        k = tuple(_aval_part(a) for a in args)
        fn = cache.get(k)
        if fn is None:
            with lock:
                fn = cache.get(k)
                if fn is None:
                    fn = load_or_compile(kernel, static_key, jitted, args)
                    cache[k] = fn
        return fn(*args)

    def prepare(*args) -> None:
        """Force the load-or-compile for this signature without
        executing (args may be jax.ShapeDtypeStruct placeholders) —
        bench warmstart measures exactly this readiness step."""
        k = tuple(_aval_part(a) for a in args)
        with lock:
            if k not in cache:
                cache[k] = load_or_compile(kernel, static_key, jitted, args)

    call.prepare = prepare
    call.kernel_name = kernel
    return call
