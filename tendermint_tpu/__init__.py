"""tendermint_tpu — a TPU-native BFT state-machine-replication framework.

A from-scratch re-design of the capability surface of Tendermint Core
(reference: /root/reference, v0.27.0): BFT consensus, ABCI application
interface, mempool, fast sync, evidence, WAL + crash recovery, validator
signing, RPC, light client, and tooling — with the vote/commit Ed25519
verification hot path (reference: types/validator_set.go:345-371,
types/vote_set.go:189) executed as a vectorized JAX/TPU batch kernel
instead of a serial per-signature loop.
"""

__version__ = "0.1.0"
