"""Consensus write-ahead log (reference consensus/wal.go).

Every message the consensus machine receives (and every timeout it acts
on) is logged BEFORE processing, so a crashed node replays to exactly
where it left off. Records are crc32(4) + len(4) + msgpack payload
(reference WALEncoder :218-241 uses crc32c+amino); `#ENDHEIGHT: H`
markers delimit heights for catchup replay (SearchForEndHeight :159).
"""

from __future__ import annotations

import binascii
import logging
import struct
import time
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

from ..libs import tracing
from ..libs.autofile import Group
from ..types import serde

LOG = logging.getLogger("consensus.wal")

MAX_MSG_SIZE = 1048576  # 1MB (reference wal.go:32)


@dataclass
class TimedWALMessage:
    """reference wal.go:37-40"""

    time: float  # unix seconds
    msg: object  # wal message object (see messages.py to_obj shapes)


@dataclass
class EndHeightMessage:
    """Height H is complete (reference wal.go:43-46)."""

    height: int


class WALCorruptionError(Exception):
    pass


def _encode_record(payload: bytes) -> bytes:
    if len(payload) > MAX_MSG_SIZE:
        raise ValueError(f"WAL message too big: {len(payload)}")
    crc = binascii.crc32(payload) & 0xFFFFFFFF
    return struct.pack(">II", crc, len(payload)) + payload


class WAL:
    """File-backed WAL over a rotating Group (reference baseWAL :69).

    `corrupted_counter` is a Counter-like sink (metrics
    wal_corrupted_records_total) bumped when iter_messages drops a
    CORRUPT record — bad CRC, absurd length, undecodable payload —
    as opposed to the expected truncated crash tail."""

    def __init__(self, path: str, corrupted_counter=None):
        from ..metrics import NOP

        self.group = Group(path)
        self._started = False
        self._corrupted_counter = (corrupted_counter
                                   if corrupted_counter is not None else NOP)
        self._corruption_warned = False
        # plain process-local count mirroring the metric — the
        # /debug/recovery provider reads it without a registry scrape
        self.corrupted_records = 0

    def start(self) -> None:
        self._started = True
        # an empty WAL gets an ENDHEIGHT-0 marker so replay for height 1
        # can find its messages after a crash (reference baseWAL.OnStart)
        if not any(True for _ in self.iter_messages()):
            self.write_sync(EndHeightMessage(0))

    def stop(self) -> None:
        if self._started:
            self.group.sync()
            self.group.close()
            self._started = False

    # --- write --------------------------------------------------------------

    def write(self, msg) -> None:
        """Log a message (no fsync; reference Save → Write)."""
        with tracing.span("wal.write", cat="wal"):
            payload = serde.pack(_msg_obj(msg))
            self.group.write(_encode_record(payload))

    def write_sync(self, msg) -> None:
        """Log + fsync — used for self-originated messages and EndHeight
        (reference consensus/state.go:609,1280)."""
        with tracing.span("wal.writeSync", cat="wal"):
            self.write(msg)
            self.group.sync()

    def flush(self) -> None:
        self.group.flush()

    def write_end_height(self, height: int) -> None:
        self.write_sync(EndHeightMessage(height))
        self.group.maybe_rotate()

    # --- read ---------------------------------------------------------------

    def _note_corruption(self, offset: int, why: str) -> None:
        """Count + one-shot warn: the WAL tolerates a bad record (replay
        stops there, the crash-recovery contract), but silently eaten
        records used to be invisible to operators."""
        self._corrupted_counter.inc()
        self.corrupted_records += 1
        if not self._corruption_warned:
            self._corruption_warned = True
            LOG.warning(
                "WAL corruption at byte offset %d: %s; replay stops here "
                "(records beyond this point are lost). Check the disk.",
                offset, why)

    def iter_messages(self) -> Iterator[object]:
        """All decodable messages oldest → newest; stops at the first
        corrupt/truncated record. A short read at the very end is the
        expected crash tail; a CRC/length/decode failure is disk
        corruption and is counted + warned (wal_corrupted_records_total)."""
        r = self.group.reader()
        offset = 0
        try:
            while True:
                hdr = r.read(8)
                if len(hdr) < 8:
                    return  # clean EOF or truncated crash tail
                crc, ln = struct.unpack(">II", hdr)
                if ln > MAX_MSG_SIZE:
                    self._note_corruption(
                        offset, f"record length {ln} exceeds "
                                f"{MAX_MSG_SIZE} (garbage header)")
                    return
                payload = r.read(ln)
                if len(payload) < ln:
                    return  # truncated crash tail
                if binascii.crc32(payload) & 0xFFFFFFFF != crc:
                    self._note_corruption(offset, "CRC mismatch")
                    return
                try:
                    msg = _msg_from(serde.unpack(payload))
                except (ValueError, TypeError, IndexError) as e:
                    self._note_corruption(
                        offset, f"undecodable payload ({e})")
                    return
                offset += 8 + ln
                yield msg
        finally:
            r.close()

    def search_for_end_height(self, height: int) -> Optional[list]:
        """Messages logged AFTER `#ENDHEIGHT height` (i.e. height+1's
        traffic), or None if the marker is absent (reference
        SearchForEndHeight :159-216). Returns a list for replay."""
        found = False
        out: list = []
        for msg in self.iter_messages():
            if isinstance(msg, EndHeightMessage):
                if msg.height == height:
                    found = True
                    out = []
                continue
            if found:
                out.append(msg)
        return out if found else None


class NilWAL:
    """No-op WAL (reference wal.go:322)."""

    def start(self) -> None: ...
    def stop(self) -> None: ...
    def write(self, msg) -> None: ...
    def write_sync(self, msg) -> None: ...
    def flush(self) -> None: ...
    def write_end_height(self, height: int) -> None: ...
    def iter_messages(self):
        return iter(())
    def search_for_end_height(self, height: int):
        return None


# --- message serde -----------------------------------------------------------
# WAL messages: EndHeight, TimeoutInfo, and msg_info (peer_id + consensus
# message). Consensus messages themselves are (kind, obj) pairs from
# messages.py.


def _msg_obj(msg):
    from .messages import message_to_obj
    from .ticker import TimeoutInfo

    if isinstance(msg, EndHeightMessage):
        return ["end_height", msg.height]
    if isinstance(msg, TimedWALMessage):
        return ["timed", msg.time, _msg_obj(msg.msg)]
    if isinstance(msg, TimeoutInfo):
        return ["timeout", msg.duration, msg.height, msg.round, msg.step]
    if isinstance(msg, tuple) and len(msg) == 2:  # (peer_id, ConsensusMessage)
        peer_id, m = msg
        return ["msg_info", peer_id, message_to_obj(m)]
    raise TypeError(f"cannot WAL-encode {type(msg)}")


def _msg_from(o):
    from .messages import message_from_obj
    from .ticker import TimeoutInfo

    kind = o[0]
    if kind == "end_height":
        return EndHeightMessage(o[1])
    if kind == "timed":
        return TimedWALMessage(o[1], _msg_from(o[2]))
    if kind == "timeout":
        return TimeoutInfo(duration=o[1], height=o[2], round=o[3], step=o[4])
    if kind == "msg_info":
        return (o[1], message_from_obj(o[2]))
    raise ValueError(f"unknown WAL message kind {kind!r}")
