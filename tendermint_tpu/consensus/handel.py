"""Handel aggregation overlay: O(log n) in-round vote aggregation
(arXiv:1906.05132; ours — the reference implementation has no
counterpart at any committee size).

PR 7's BLS lane made the commit CERTIFICATE O(1), but in-round work
stayed O(n): every validator verifies every individual precommit and
the flat certificate lane gossips best-effort pairwise. Handel makes
the aggregation itself logarithmic. Validators are arranged by index
into a binomial tree of ceil(log2 n) levels; node i's level-l peer
group is the complementary half-subtree

    group_l(i) = { j : (i ^ j).bit_length() == l }
               = [base, base + 2^(l-1)) ∩ [0, n),
      base = ((i >> (l-1)) ^ 1) << (l-1)

— a contiguous index range, since levels partition by high bits. At
level l a node SENDS its combined aggregate over its own half
(own signature + verified bests of levels < l) to a scored,
periodically-reshuffled window of candidates in group_l(i), and
RECEIVES aggregates covering group_l(i), verified as ONE aggregate
pairing check each (batched through bls.verify_aggregates_many when
several arrive together) rather than per-vote checks. Completed
levels promote upward until the full-committee certificate emerges;
every quorum-crossing improvement is handed to the caller, who feeds
it through VoteSet.absorb_certificate unchanged — tally soundness,
the timestamp-0 sign-bytes rule, and the PoP trust story live there,
not here.

Scoring and liveness: candidates that deliver verified contributions
score up (first verified contribution at a level scores highest —
"fastest-verified" priority on later rounds); candidates that stay
silent across contacts drift down; garbage contributions burn a
per-peer fail budget (the _AGG_CERT_FAIL_BUDGET idiom) and pruned
peers are never contacted again. A level that stays incomplete past
its timeout stops gating the levels above it, and a session whose
frontier is stuck reports it (`stuck_level`) so the reactor can fall
back to flat certificate gossip — byzantine-silent subtrees cost
latency, never liveness.

Determinism: the module never reads a clock (callers pass `now`,
monotonic seconds) and all shuffling comes from a seeded
random.Random derived from (seed, height, round) — two nodes with
the same seed walk identical candidate windows, which is what makes
scoring/pruning unit-testable and scenario replays exact. Scanned by
scripts/check_determinism.py with zero allowlist entries.
"""

from __future__ import annotations

import hashlib
import random
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..libs.bit_array import BitArray

# score deltas (relative weights matter, absolute values don't):
# verified contribution >> everything; first verified contribution at
# a level wins the fastest-responder bonus; each unanswered contact
# drifts the candidate down one notch
SCORE_VERIFIED = 100
SCORE_FIRST_BONUS = 50
SCORE_SILENT = -1

# emitted certificate guard: never hand the caller an aggregate below
# this many signers (mirrors vote_set._AGG_MIN_CERT_SIGNERS — a
# 1-signer "aggregate" is just a vote)
MIN_CERT_SIGNERS = 2


def level_of(i: int, j: int) -> int:
    """The unique level at which validators i and j are in each
    other's complementary group: the position of their highest
    differing index bit."""
    if i == j:
        raise ValueError("a validator has no level to itself")
    return (i ^ j).bit_length()


def level_range(i: int, level: int, n: int) -> Tuple[int, int]:
    """Complementary group of node i at `level`, as the half-open
    index range [lo, hi) clipped to the committee size (levels
    partition by high index bits, so every group is contiguous)."""
    base = ((i >> (level - 1)) ^ 1) << (level - 1)
    return min(base, n), min(base + (1 << (level - 1)), n)


def num_levels(n: int) -> int:
    """ceil(log2 n): levels in the binomial tree for n validators."""
    if n <= 1:
        return 0
    return (n - 1).bit_length()


class _Level:
    """Per-level state: candidate scoring plus the best verified
    incoming aggregate over the complementary group."""

    __slots__ = ("level", "lo", "hi", "candidates", "score", "asked",
                 "fails", "pruned", "best_bits", "best_point",
                 "complete", "activated_at", "sent_version",
                 "last_sent_tick", "got_first", "answered")

    def __init__(self, level: int, lo: int, hi: int):
        self.level = level
        self.lo = lo
        self.hi = hi
        self.candidates = list(range(lo, hi))
        self.score: Dict[int, int] = {j: 0 for j in self.candidates}
        self.asked: Dict[int, int] = {j: 0 for j in self.candidates}
        self.fails: Dict[int, int] = {j: 0 for j in self.candidates}
        self.pruned: set = set()
        self.best_bits: Optional[BitArray] = None
        self.best_point = None  # G2 point paired with best_bits
        self.complete = self.lo >= self.hi  # empty group (n truncation)
        self.activated_at: Optional[float] = None
        # outgoing bookkeeping: which combined-version each window
        # candidate last saw, so improved payloads re-send and
        # unchanged ones only retry on the resend cadence
        self.sent_version: Dict[int, int] = {}
        self.last_sent_tick: Dict[int, int] = {}
        self.got_first = False
        # origins that delivered a verified contribution: an implicit
        # ack — they are alive and hold our address, so cadence
        # re-sends (a lost-message hedge) stop for them and only
        # payload improvements (version bumps) go out
        self.answered: set = set()

    def window_candidates(self, k: int, rng_order: List[int]) -> List[int]:
        """The k candidates to contact this tick: unpruned, ordered by
        descending score, then fewest unanswered contacts, then the
        current reshuffle order (rng_order maps id -> shuffle rank)."""
        live = [j for j in self.candidates if j not in self.pruned]
        live.sort(key=lambda j: (-self.score[j], self.asked[j],
                                 rng_order[j - self.lo]))
        return live[:k]


class HandelSession:
    """One aggregation session: a single (height, round, block_id)
    precommit message aggregated across the committee.

    The session is crypto-light by construction: it stores signatures
    as opaque bytes plus parsed G2 points, combines them with the
    injected `combine` (G2 addition) and validates incoming
    contributions with the injected `verify_fn` — production wires
    bls.verify_aggregates_many through the valset's pubkeys, tests
    and bench inject counting or failing verifiers. It never touches
    VoteSet: completed aggregates surface via `take_certificate()` and
    the caller routes them through absorb_certificate, which re-checks
    everything under its own DoS gates.
    """

    def __init__(self, n: int, my_index: int, powers: List[int],
                 own_signature: Optional[bytes] = None, *,
                 verify_fn: Callable[[List[Tuple[Tuple[int, ...], bytes]]],
                                     List[bool]],
                 parse_fn: Callable[[bytes], object],
                 add_fn: Callable[[object, object], object],
                 compress_fn: Callable[[object], bytes],
                 seed: int = 0, height: int = 0, round_: int = 0,
                 window: int = 4, fail_budget: int = 8,
                 level_timeout_s: float = 1.0, resend_ticks: int = 4,
                 reshuffle_ticks: int = 8):
        if not (0 <= my_index < n):
            raise ValueError(f"validator index {my_index} outside 0..{n-1}")
        self.n = n
        self.my_index = my_index
        self.powers = list(powers)
        self.total_power = sum(powers)
        self.window = max(1, window)
        self.fail_budget = max(1, fail_budget)
        self.level_timeout_s = level_timeout_s
        self.resend_ticks = max(1, resend_ticks)
        self.reshuffle_ticks = max(1, reshuffle_ticks)
        self._verify_fn = verify_fn
        self._parse = parse_fn
        self._add = add_fn
        self._compress = compress_fn
        # deterministic shuffle source: same (seed, height, round) →
        # same candidate walk on every node and every replay
        digest = hashlib.sha256(
            b"handel:%d:%d:%d:%d" % (seed, height, round_, my_index)
        ).digest()
        self._rng = random.Random(int.from_bytes(digest[:8], "big"))
        self.levels: Dict[int, _Level] = {}
        for l in range(1, num_levels(n) + 1):
            lo, hi = level_range(my_index, l, n)
            self.levels[l] = _Level(l, lo, hi)
        self._shuffle_orders: Dict[int, List[int]] = {}
        self._reshuffle()
        # own contribution
        self.own_point = None
        self.own_bits = BitArray(n)
        if own_signature is not None:
            pt = self._parse(own_signature)
            if pt is None:
                raise ValueError("own signature does not parse")
            self.own_point = pt
            self.own_bits.set_index(my_index, True)
        # per-source improvement counters: index 0 is our own signature,
        # index l a level-l best. A level-l payload aggregates sources
        # strictly below l, so its version is sum(_improves[:l]) — an
        # improvement at level k only re-triggers sends at levels ABOVE
        # k, never re-sends of unchanged lower payloads
        self._improves = [0] * (num_levels(n) + 1)
        self._tick_no = 0
        self.started_at: Optional[float] = None
        # counters the caller mirrors into metrics
        self.verified_total = 0
        self.rejected_total = 0
        self.pruned_total = 0
        self.sends_total = 0
        self._emitted_bits = -1  # num_true of the last emitted cert
        self._pending_cert: Optional[Tuple[BitArray, bytes]] = None

    # -- structure helpers --------------------------------------------

    def _reshuffle(self) -> None:
        for l, lv in self.levels.items():
            order = list(range(len(lv.candidates)))
            self._rng.shuffle(order)
            self._shuffle_orders[l] = order

    def _combined_through(self, max_level: int):
        """(bits, point) aggregating our own signature with every
        verified level best strictly below max_level — exactly the
        payload a level-`max_level` contribution may carry."""
        bits = self.own_bits.copy()
        point = self.own_point
        for l in range(1, max_level):
            lv = self.levels[l]
            if lv.best_bits is not None:
                bits.or_update(lv.best_bits)
                point = self._add(point, lv.best_point)
        return bits, point

    def _level_power(self, bits: BitArray) -> int:
        return sum(self.powers[k] for k in bits.true_indices())

    def _frontier(self) -> int:
        """Lowest incomplete level (len+1 when everything completed)."""
        for l in range(1, len(self.levels) + 1):
            if not self.levels[l].complete:
                return l
        return len(self.levels) + 1

    # -- receiving ----------------------------------------------------

    def add_contributions(self, contribs, now: float):
        """Absorb a batch of incoming contributions:
        contribs = [(origin, level, bits: BitArray, agg_sig: bytes)].
        Structural gates run per item; the survivors verify in ONE
        verify_fn call (the multi-pair Miller loop). Returns
        (n_verified, n_rejected). Garbage burns the origin's fail
        budget at its level; pruned origins are dropped unseen."""
        if self.started_at is None:
            self.started_at = now
        pending = []  # (origin, level_obj, bits, sig, indices)
        rejected = 0
        for origin, level, bits, sig in contribs:
            lv = self.levels.get(level)
            if (lv is None or origin == self.my_index
                    or not (0 <= origin < self.n)
                    or level_of(self.my_index, origin) != level):
                rejected += 1
                continue
            if origin in lv.pruned:
                rejected += 1
                continue
            idxs = bits.true_indices()
            if not idxs or bits.size() != self.n:
                rejected += 1
                self._fail(lv, origin)
                continue
            # a level-l contribution may only cover the sender's own
            # half — OUR complementary range at l
            if idxs[0] < lv.lo or idxs[-1] >= lv.hi:
                rejected += 1
                self._fail(lv, origin)
                continue
            if lv.best_bits is not None and \
                    len(idxs) <= lv.best_bits.num_true():
                # no improvement: drop without paying a pairing (an
                # honest re-send, not garbage — no budget burn)
                continue
            pending.append((origin, lv, bits, sig, tuple(idxs)))
        verified = 0
        if pending:
            verdicts = self._verify_fn(
                [(p[4], p[3]) for p in pending])
            for (origin, lv, bits, sig, idxs), ok in zip(pending, verdicts):
                if not ok:
                    rejected += 1
                    self._fail(lv, origin)
                    continue
                pt = self._parse(sig)
                if pt is None:
                    rejected += 1
                    self._fail(lv, origin)
                    continue
                verified += 1
                lv.answered.add(origin)
                if not lv.got_first:
                    lv.got_first = True
                    lv.score[origin] = lv.score.get(origin, 0) + \
                        SCORE_FIRST_BONUS
                lv.score[origin] = lv.score.get(origin, 0) + SCORE_VERIFIED
                if lv.best_bits is None or \
                        len(idxs) > lv.best_bits.num_true():
                    lv.best_bits = bits.copy()
                    lv.best_point = pt
                    self._improves[lv.level] += 1
                    if len(idxs) == lv.hi - lv.lo:
                        lv.complete = True
        self.verified_total += verified
        self.rejected_total += rejected
        if verified:
            self._maybe_emit()
        return verified, rejected

    def _fail(self, lv: _Level, origin: int) -> None:
        lv.fails[origin] = lv.fails.get(origin, 0) + 1
        if lv.fails[origin] >= self.fail_budget and \
                origin not in lv.pruned:
            lv.pruned.add(origin)
            self.pruned_total += 1

    # -- sending ------------------------------------------------------

    def tick(self, now: float) -> List[Tuple[int, int, BitArray, bytes]]:
        """One gossip tick: activate levels whose gate opened (prior
        levels complete, or their timeout lapsed), reshuffle candidate
        windows on cadence, and return the (target, level, bits, sig)
        contributions to send. The caller owns the wire."""
        if self.started_at is None:
            self.started_at = now
        self._tick_no += 1
        if self._tick_no % self.reshuffle_ticks == 0:
            self._reshuffle()
        out: List[Tuple[int, int, BitArray, bytes]] = []
        for l in range(1, len(self.levels) + 1):
            lv = self.levels[l]
            if lv.lo >= lv.hi:
                continue
            if not self._level_active(l, now):
                break
            if lv.activated_at is None:
                lv.activated_at = now
            bits, point = self._combined_through(l)
            if point is None:
                continue  # nothing to offer yet (no own sig, no bests)
            sig = self._compress(point)
            version = sum(self._improves[:l])
            for j in lv.window_candidates(self.window,
                                          self._shuffle_orders[l]):
                seen = lv.sent_version.get(j)
                last = lv.last_sent_tick.get(j, -10**9)
                if seen == version and \
                        (j in lv.answered
                         or self._tick_no - last < self.resend_ticks):
                    continue
                if seen is not None and j not in lv.answered:
                    # re-contact without an answer: drift the score
                    lv.score[j] = lv.score.get(j, 0) + SCORE_SILENT
                lv.asked[j] = lv.asked.get(j, 0) + 1
                lv.sent_version[j] = version
                lv.last_sent_tick[j] = self._tick_no
                out.append((j, l, bits, sig))
        self.sends_total += len(out)
        return out

    def _level_active(self, level: int, now: float) -> bool:
        """Level l activates once every level below it is complete OR
        the session has aged past (l-1) level-timeouts — a silent
        subtree delays the frontier, it does not freeze it."""
        if level == 1:
            return True
        if all(self.levels[k].complete for k in range(1, level)):
            return True
        if self.started_at is None:
            return False
        return now - self.started_at >= (level - 1) * self.level_timeout_s

    # -- certificates -------------------------------------------------

    def _maybe_emit(self) -> None:
        bits, point = self._combined_through(len(self.levels) + 1)
        k = bits.num_true()
        if point is None or k < MIN_CERT_SIGNERS or k <= self._emitted_bits:
            return
        if 3 * self._level_power(bits) <= 2 * self.total_power:
            return
        self._emitted_bits = k
        self._pending_cert = (bits, self._compress(point))

    def take_certificate(self) -> Optional[Tuple[BitArray, bytes]]:
        """The latest quorum-crossing aggregate not yet handed out, or
        None. Each take is a strict improvement (more signers) over the
        previous one, so the caller pays absorb_certificate's pairing
        only for progress."""
        cert, self._pending_cert = self._pending_cert, None
        return cert

    # -- diagnostics --------------------------------------------------

    def stuck_level(self, now: float) -> int:
        """The frontier level if it has been incomplete past its
        timeout, else 0 — the reactor's flat-gossip fallback signal and
        the monitor's [HANDEL STUCK lvl=k] source."""
        f = self._frontier()
        if f > len(self.levels):
            return 0
        lv = self.levels[f]
        anchor = lv.activated_at if lv.activated_at is not None \
            else self.started_at
        if anchor is None:
            return 0
        return f if now - anchor > self.level_timeout_s else 0

    def complete(self) -> bool:
        return self._frontier() > len(self.levels)

    def status(self, now: float) -> dict:
        """Structured view for /debug/handel (read-only; every field is
        plain JSON)."""
        return {
            "n": self.n,
            "my_index": self.my_index,
            "levels": len(self.levels),
            "frontier": self._frontier(),
            "stuck_level": self.stuck_level(now),
            "complete": self.complete(),
            "verified": self.verified_total,
            "rejected": self.rejected_total,
            "pruned": self.pruned_total,
            "sends": self.sends_total,
            "level_fill": [
                (self.levels[l].best_bits.num_true()
                 if self.levels[l].best_bits is not None else 0)
                for l in range(1, len(self.levels) + 1)
            ],
            "level_sizes": [
                self.levels[l].hi - self.levels[l].lo
                for l in range(1, len(self.levels) + 1)
            ],
        }

    def set_own_signature(self, signature: bytes) -> None:
        """Late-bind our own precommit signature (sessions created by an
        incoming contribution before we signed start without one)."""
        if self.own_point is not None:
            return
        pt = self._parse(signature)
        if pt is None:
            raise ValueError("own signature does not parse")
        self.own_point = pt
        self.own_bits.set_index(self.my_index, True)
        self._improves[0] += 1
        self._maybe_emit()


class HandelManager:
    """Session registry between ConsensusState and the reactor.

    Owned by ConsensusState; touched from two threads (the state's
    receive loop absorbs contributions and our own precommit, the
    reactor's handel tick thread drains outgoing sends), so every
    session operation runs under one leaf lock. Sessions are keyed by
    (height, round, block_id) — competing proposals at a round simply
    aggregate in parallel and the first to cross 2/3 wins, exactly as
    the flat lane behaves.

    Soundness note: nothing the manager emits is trusted. Certificates
    assembled here flow through ConsensusState._add_aggregate_certificate
    → VoteSet.absorb_certificate, which re-verifies the aggregate under
    its own fail budget. Handel is purely a cheaper way to FIND the
    certificate."""

    def __init__(self, cfg, chain_id: str, my_address: Optional[bytes]):
        self.cfg = cfg
        self.chain_id = chain_id
        self.my_address = my_address
        self.metrics = None  # HandelMetrics; node wires it post-build
        self._lock = threading.Lock()
        # (height, round, hash, psh_hash, psh_total) -> (session, block_id)
        self._sessions: Dict[tuple, tuple] = {}
        self._height = 0
        self.certs_emitted = 0

    # -- wiring -------------------------------------------------------

    def set_metrics(self, metrics) -> None:
        self.metrics = metrics

    def enabled(self, validators) -> bool:
        """The overlay runs only when configured on, the committee is
        BLS, and this node is IN the committee (replicas and
        non-validators stay on flat certificate gossip)."""
        if not (self.cfg.enable and validators is not None
                and len(validators.validators) > 1 and validators.is_bls()):
            return False
        if self.my_address is None:
            return False
        idx, _ = validators.get_by_address(self.my_address)
        return idx >= 0

    @staticmethod
    def _key(height: int, round_: int, block_id) -> tuple:
        return (height, round_, bytes(block_id.hash),
                bytes(block_id.parts_header.hash),
                block_id.parts_header.total)

    def _session_for_locked(self, validators, height: int, round_: int,
                     block_id, create: bool):
        key = self._key(height, round_, block_id)
        ent = self._sessions.get(key)
        if ent is not None or not create:
            return ent[0] if ent else None
        from ..crypto import bls as _bls
        from ..crypto.bls.curve import g2_add as _g2_add, \
            g2_compress as _g2_compress
        from ..types.basic import VOTE_TYPE_PRECOMMIT, \
            canonical_vote_sign_bytes
        my_index, _ = validators.get_by_address(self.my_address)
        if my_index < 0:
            return None
        vals = validators.validators
        pubkeys = [v.pub_key.bytes() for v in vals]
        powers = [v.voting_power for v in vals]
        msg = canonical_vote_sign_bytes(
            self.chain_id, VOTE_TYPE_PRECOMMIT, height, round_, block_id, 0)
        metrics = self.metrics

        def verify_fn(items):
            import time as _time
            t0 = _time.perf_counter()
            out = _bls.verify_aggregates_many(
                [([pubkeys[k] for k in idxs], msg, sig)
                 for idxs, sig in items])
            if metrics is not None:
                metrics.verify_seconds.observe(_time.perf_counter() - t0)
            return out

        session = HandelSession(
            len(vals), my_index, powers, None,
            verify_fn=verify_fn,
            parse_fn=_bls._parse_signature_point,
            add_fn=_g2_add,
            compress_fn=_g2_compress,
            seed=self.cfg.seed, height=height, round_=round_,
            window=self.cfg.window,
            fail_budget=self.cfg.fail_budget,
            level_timeout_s=self.cfg.level_timeout_ms / 1000.0,
            resend_ticks=self.cfg.resend_ticks,
            reshuffle_ticks=self.cfg.reshuffle_ticks)
        self._sessions[key] = (session, block_id)
        return session

    # -- state-machine hooks (receive-loop thread) --------------------

    def note_own_precommit(self, vote, validators) -> None:
        """Seed/refresh the session for our own non-nil precommit. The
        session then starts offering level-1 contributions on the next
        tick."""
        if vote.block_id.hash == b"" or not self.enabled(validators):
            return
        with self._lock:
            if vote.height < self._height:
                return
            self._height = max(self._height, vote.height)
            s = self._session_for_locked(validators, vote.height, vote.round,
                                  vote.block_id, create=True)
            if s is not None:
                try:
                    s.set_own_signature(vote.signature)
                except ValueError:
                    pass

    def absorb(self, msgs, validators, height: int, now: float):
        """Feed incoming HandelContributionMessages into their sessions.
        Returns (n_verified, n_rejected, certs) where certs are
        quorum-crossing types.block.AggregateCommit candidates the
        caller must route through _add_aggregate_certificate."""
        from ..types.block import AggregateCommit
        verified = rejected = 0
        certs = []
        if not self.enabled(validators):
            return 0, len(msgs), []
        with self._lock:
            self._height = max(self._height, height)
            by_key: Dict[tuple, list] = {}
            for m in msgs:
                if m.height != height:
                    rejected += 1
                    continue
                by_key.setdefault(
                    self._key(m.height, m.round, m.block_id), []).append(m)
            for key, group in by_key.items():
                m0 = group[0]
                s = self._session_for_locked(validators, m0.height, m0.round,
                                      m0.block_id, create=True)
                if s is None:
                    rejected += len(group)
                    continue
                v, r = s.add_contributions(
                    [(m.origin, m.level, m.signers, m.agg_sig)
                     for m in group], now)
                verified += v
                rejected += r
                cert = s.take_certificate()
                if cert is not None:
                    bits, sig = cert
                    certs.append(AggregateCommit(
                        m0.block_id, m0.height, m0.round, bits, sig))
                    self.certs_emitted += 1
        if self.metrics is not None:
            if verified:
                self.metrics.contributions.with_labels("verified") \
                    .inc(verified)
            if rejected:
                self.metrics.contributions.with_labels("rejected") \
                    .inc(rejected)
        return verified, rejected, certs

    # -- reactor hooks (handel tick thread) ---------------------------

    def outgoing(self, validators, height: int, now: float):
        """Drain one gossip tick across current-height sessions:
        [(target_validator_index, HandelContributionMessage)]. The
        reactor resolves indices to peers; unknown targets drop (an
        unreachable candidate scores down and rotates out)."""
        from .messages import HandelContributionMessage
        if not self.enabled(validators):
            return []
        out = []
        pruned = 0
        with self._lock:
            for key in sorted(self._sessions):
                if key[0] != self._height:
                    continue
                session, block_id = self._sessions[key]
                before = session.pruned_total
                for target, level, bits, sig in session.tick(now):
                    out.append((target, HandelContributionMessage(
                        key[0], key[1], level, session.my_index,
                        block_id, bits, sig)))
                pruned += session.pruned_total - before
        if self.metrics is not None and pruned:
            self.metrics.pruned_peers.inc(pruned)
        return out

    def advance_height(self, height: int) -> None:
        """GC sessions for committed heights (called on height advance;
        round churn within a height keeps its sessions — late rounds
        still need early-round certificates for last_commit)."""
        with self._lock:
            self._height = max(self._height, height)
            for key in [k for k in self._sessions if k[0] < height]:
                del self._sessions[key]

    # -- diagnostics --------------------------------------------------

    def stuck(self, now: float) -> int:
        """Max stuck level across current-height sessions (0 = healthy);
        the reactor's signal to re-open flat certificate gossip."""
        with self._lock:
            worst = 0
            for key, (session, _) in self._sessions.items():
                if key[0] == self._height:
                    worst = max(worst, session.stuck_level(now))
            return worst

    def status(self, now: float) -> dict:
        """/debug/handel payload."""
        with self._lock:
            sessions = []
            for key in sorted(self._sessions):
                session, _ = self._sessions[key]
                st = session.status(now)
                st["height"] = key[0]
                st["round"] = key[1]
                sessions.append(st)
                if self.metrics is not None and key[0] == self._height:
                    for i, fill in enumerate(st["level_fill"]):
                        size = st["level_sizes"][i] or 1
                        self.metrics.level.with_labels(str(i + 1)) \
                            .set(fill / size)
            return {
                "enabled": bool(self.cfg.enable),
                "height": self._height,
                "certs_emitted": self.certs_emitted,
                "sessions": sessions,
            }
