"""Consensus round types (reference consensus/types/).

RoundState is the public snapshot of the machine (round_state.go);
HeightVoteSet tracks prevote+precommit VoteSets for every round of one
height (height_vote_set.go), including the one-honest-peer rule for
tracking votes from future rounds.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..types.basic import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    BlockID,
    Proposal,
    Vote,
)
from ..types.block import Block, Commit
from ..types.part_set import PartSet
from ..types.validator_set import ValidatorSet
from ..types.vote_set import VoteSet

# RoundStepType (reference consensus/types/round_state.go:12-24)
STEP_NEW_HEIGHT = 1
STEP_NEW_ROUND = 2
STEP_PROPOSE = 3
STEP_PREVOTE = 4
STEP_PREVOTE_WAIT = 5
STEP_PRECOMMIT = 6
STEP_PRECOMMIT_WAIT = 7
STEP_COMMIT = 8

_STEP_NAMES = {
    STEP_NEW_HEIGHT: "NewHeight",
    STEP_NEW_ROUND: "NewRound",
    STEP_PROPOSE: "Propose",
    STEP_PREVOTE: "Prevote",
    STEP_PREVOTE_WAIT: "PrevoteWait",
    STEP_PRECOMMIT: "Precommit",
    STEP_PRECOMMIT_WAIT: "PrecommitWait",
    STEP_COMMIT: "Commit",
}


class RoundStepType:
    @staticmethod
    def name(step: int) -> str:
        return _STEP_NAMES.get(step, f"Unknown({step})")


@dataclass
class RoundState:
    """Snapshot of the consensus internal state, exposed on the event bus
    and to the reactor (reference round_state.go:29-71)."""

    height: int = 0
    round: int = 0
    step: int = STEP_NEW_HEIGHT
    start_time: float = 0.0
    commit_time: float = 0.0
    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[PartSet] = None
    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[PartSet] = None
    valid_round: int = -1
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[PartSet] = None
    votes: Optional["HeightVoteSet"] = None
    commit_round: int = -1
    last_commit: Optional[VoteSet] = None
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False

    def event_tags(self) -> dict:
        return {
            "height": str(self.height),
            "round": str(self.round),
            "step": RoundStepType.name(self.step),
        }

    def __str__(self):
        return f"RoundState{{{self.height}/{self.round}/{RoundStepType.name(self.step)}}}"


class HeightVoteSet:
    """Prevotes and precommits for every round of one height (reference
    consensus/types/height_vote_set.go).

    Tracks votes for round 0..round+1; votes from higher rounds are kept
    only once a peer claims 2/3 there (set_peer_maj23) — the
    one-honest-peer rule limiting memory from byzantine spam."""

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self._lock = threading.RLock()
        self.round = 0
        self._round_vote_sets: Dict[int, Dict[int, VoteSet]] = {}
        self._peer_catchup_rounds: Dict[str, List[int]] = {}
        self._add_round(0)

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            return
        self._round_vote_sets[round_] = {
            VOTE_TYPE_PREVOTE: VoteSet(self.chain_id, self.height, round_, VOTE_TYPE_PREVOTE, self.val_set),
            VOTE_TYPE_PRECOMMIT: VoteSet(self.chain_id, self.height, round_, VOTE_TYPE_PRECOMMIT, self.val_set),
        }

    def set_round(self, round_: int) -> None:
        """Track round 0..round+1 (reference height_vote_set.go:84-96)."""
        with self._lock:
            if self.round != 0 and round_ < self.round:
                raise ValueError("set_round must increase the round")
            for r in range(self.round, round_ + 2):
                self._add_round(r)
            self.round = round_

    def add_vote(self, vote: Vote, peer_id: str = "", verified: bool = False) -> bool:
        """Verify+add; returns added. Unwanted future-round votes (no peer
        maj23 claim) return False (reference :105-128). verified=True
        passes through to VoteSet.add_vote (batched pre-verification)."""
        with self._lock:
            vs = self._get(vote.round, vote.type)
            if vs is None:
                rounds = self._peer_catchup_rounds.get(peer_id, [])
                if len(rounds) < 2:
                    self._add_round(vote.round)
                    vs = self._get(vote.round, vote.type)
                    rounds.append(vote.round)
                    self._peer_catchup_rounds[peer_id] = rounds
                else:
                    return False  # punish peer? (reference returns ErrGotVoteFromUnwantedRound)
            return vs.add_vote(vote, verified=verified)

    def prevotes(self, round_: int) -> Optional[VoteSet]:
        with self._lock:
            return self._get(round_, VOTE_TYPE_PREVOTE)

    def precommits(self, round_: int) -> Optional[VoteSet]:
        with self._lock:
            return self._get(round_, VOTE_TYPE_PRECOMMIT)

    def _get(self, round_: int, type_: int) -> Optional[VoteSet]:
        rvs = self._round_vote_sets.get(round_)
        return rvs[type_] if rvs else None

    def pol_info(self) -> tuple:
        """(pol_round, pol_block_id) for the highest round with a prevote
        2/3 majority, else (-1, zero) (reference POLInfo :130-141)."""
        with self._lock:
            for r in range(self.round, -1, -1):
                vs = self._get(r, VOTE_TYPE_PREVOTE)
                if vs is not None:
                    bid = vs.two_thirds_majority()
                    if bid is not None:
                        return r, bid
            return -1, BlockID()

    def set_peer_maj23(self, round_: int, type_: int, peer_id: str, block_id: BlockID) -> None:
        with self._lock:
            self._add_round(round_)
            vs = self._get(round_, type_)
            if vs is not None:
                vs.set_peer_maj23(peer_id, block_id)

    def __str__(self):
        with self._lock:
            return f"HeightVoteSet{{h:{self.height} r:{self.round} rounds:{sorted(self._round_vote_sets)}}}"
