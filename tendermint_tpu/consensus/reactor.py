"""ConsensusReactor — vote/proposal/block-part gossip.

Reference parity: consensus/reactor.go.  Four p2p channels
(State 0x20, Data 0x21, Vote 0x22, VoteSetBits 0x23, :23-26,125-157);
per-peer gossip threads (gossipDataRoutine :456, gossipVotesRoutine
:593, queryMaj23Routine :720); PeerState tracks what each peer has
(:895-1334) so gossip sends only what's missing.  Broadcasts of
NewRoundStep/HasVote ride the node event bus (the reference uses an
internal event switch, reactor.go:371-395).

Vote gossip is where the TPU batch-verify engine aggregates work: gossiped
votes are queued to the consensus receive loop, which drains each
contiguous run of queued VoteMessages and pre-verifies it as one
BatchVerifier call (consensus/state.py _handle_vote_msgs) — a catch-up
peer's vote stream therefore lands on the device in batches, not one
serial verify per message.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from typing import Dict, Optional

from ..libs.bit_array import BitArray
from ..p2p.base_reactor import ChannelDescriptor, Reactor
from ..types import serde
from ..types.basic import BlockID, PartSetHeader
from ..types.basic import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE
from ..types.event_bus import (
    EVENT_NEW_ROUND_STEP,
    EVENT_VOTE,
    query_for_event,
)
from .cstypes import STEP_COMMIT, STEP_NEW_HEIGHT, STEP_PREVOTE_WAIT
from .messages import (
    AggregateCommitMessage,
    BlockPartMessage,
    CommitStepMessage,
    HandelContributionMessage,
    HasVoteMessage,
    NewRoundStepMessage,
    ProposalMessage,
    ProposalPOLMessage,
    VoteMessage,
    VoteSetBitsMessage,
    VoteSetMaj23Message,
    message_from_obj,
    message_to_obj,
)

LOG = logging.getLogger("consensus.reactor")

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23
# Handel overlay contributions (consensus/handel.py). Advertised only
# when [handel] enable is set — with it off the channel vector, and
# therefore the p2p handshake, is byte-identical to a build without
# the overlay.
HANDEL_CHANNEL = 0x24

PEER_GOSSIP_SLEEP = 0.1  # reactor.go:36 peerGossipSleepDuration
PEER_QUERY_MAJ23_SLEEP = 2.0  # reactor.go:39

# flat certificate lane re-send gate (PR 19): a merged cert re-sends to
# a peer only after this interval, UNLESS it grew by at least
# _AGG_RESEND_DELTA signers since the last send — steady-state chatter
# collapses to one message per interval while real aggregation progress
# still propagates immediately
_AGG_RESEND_MIN_S = 0.25
_AGG_RESEND_DELTA = 8


def encode_msg(m) -> bytes:
    return serde.pack(message_to_obj(m))


def decode_msg(b: bytes):
    return message_from_obj(serde.unpack(b))


class PeerRoundState:
    """What we know the peer knows (reference cstypes/peer_round_state.go)."""

    def __init__(self):
        self.height = 0
        self.round = -1
        self.step = STEP_NEW_HEIGHT
        self.start_time = 0.0
        self.proposal = False
        self.proposal_block_parts_header: Optional[PartSetHeader] = None
        self.proposal_block_parts: Optional[BitArray] = None
        self.proposal_pol_round = -1
        self.proposal_pol: Optional[BitArray] = None
        self.prevotes: Optional[BitArray] = None
        self.precommits: Optional[BitArray] = None
        self.last_commit_round = -1
        self.last_commit: Optional[BitArray] = None
        self.catchup_commit_round = -1
        self.catchup_commit: Optional[BitArray] = None


class PeerState:
    """Thread-safe peer-knowledge tracker (reactor.go:895-1334)."""

    def __init__(self, peer):
        self.peer = peer
        self._lock = threading.RLock()
        self.prs = PeerRoundState()
        # gossip-mark self-healing bookkeeping: when the peer's HEIGHT
        # last advanced, and when we last expired our sent-marks for it
        # (see expire_gossip_marks_if_stalled)
        self.last_height_advance = time.monotonic()
        self._marks_expired_at = time.monotonic()
        # flat-lane cert re-send gate: (height, round) -> (sent_at,
        # num_signers at send) — see agg_cert_should_send
        self._agg_sent: Dict[tuple, tuple] = {}

    # -- queries -------------------------------------------------------

    def get_round_state(self) -> PeerRoundState:
        # a shallow COPY under the lock (reference GetRoundState,
        # reactor.go:921-927): gossip threads act on a consistent
        # (height, round, step) instead of racing the receive thread's
        # in-place updates field by field
        with self._lock:
            return copy.copy(self.prs)

    def get_height(self) -> int:
        with self._lock:
            return self.prs.height

    # -- updates from messages ----------------------------------------

    def apply_new_round_step(self, msg: NewRoundStepMessage) -> None:
        """reactor.go:1091-1137."""
        with self._lock:
            prs = self.prs
            # ignore duplicates or decreases (reference CompareHRS guard,
            # reactor.go:1096-1099): a stale/replayed round-step must not
            # regress our view of the peer and wipe its vote bit arrays
            if (msg.height, msg.round, msg.step) <= (prs.height, prs.round, prs.step):
                return
            ps_height, ps_round = prs.height, prs.round
            ps_catchup_round = prs.catchup_commit_round
            ps_catchup_commit = prs.catchup_commit
            # snapshot BEFORE the wipe below: v0.27's reactor.go:1131
            # reads Precommits after nil-ing it, losing the peer's
            # last-commit knowledge on every height transition (fixed in
            # later upstream); we keep the fixed semantics — the bits are
            # genuine peer knowledge and skipping them avoids re-sending
            # every precommit the peer already has
            ps_precommits = prs.precommits

            if ps_height != msg.height:
                self.last_height_advance = time.monotonic()
            prs.height = msg.height
            prs.round = msg.round
            prs.step = msg.step
            prs.start_time = time.time() - msg.seconds_since_start_time
            if ps_height != msg.height or ps_round != msg.round:
                prs.proposal = False
                prs.proposal_block_parts_header = None
                prs.proposal_block_parts = None
                prs.proposal_pol_round = -1
                prs.proposal_pol = None
                prs.prevotes = None
                prs.precommits = None
            if ps_height == msg.height and ps_round != msg.round and msg.round == ps_catchup_round:
                prs.precommits = ps_catchup_commit
            if ps_height != msg.height:
                # peer moved a height: shift precommits to last_commit
                if ps_height + 1 == msg.height and ps_round == msg.last_commit_round:
                    prs.last_commit_round = msg.last_commit_round
                    prs.last_commit = ps_precommits
                else:
                    prs.last_commit_round = msg.last_commit_round
                    prs.last_commit = None
                prs.catchup_commit_round = -1
                prs.catchup_commit = None

    def apply_commit_step(self, msg: CommitStepMessage) -> None:
        with self._lock:
            if self.prs.height != msg.height:
                return
            self.prs.proposal_block_parts_header = msg.block_parts_header
            self.prs.proposal_block_parts = msg.block_parts

    def apply_proposal_pol(self, msg: ProposalPOLMessage) -> None:
        with self._lock:
            prs = self.prs
            if prs.height != msg.height or prs.proposal_pol_round != msg.proposal_pol_round:
                return
            prs.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg: HasVoteMessage) -> None:
        with self._lock:
            if self.prs.height != msg.height:
                return
            self._set_has_vote_locked(msg.height, msg.round, msg.type, msg.index)

    def apply_vote_set_bits(self, msg: VoteSetBitsMessage, our_votes: Optional[BitArray]) -> None:
        """reactor.go:1319-1334: if we have our_votes, the peer's claim is
        OR'd with what we already track (union of knowledge)."""
        with self._lock:
            votes = self._get_vote_bit_array_locked(msg.height, msg.round, msg.type)
            if votes is not None and our_votes is not None:
                have = votes.or_(msg.votes)
                self._set_vote_bit_array_locked(msg.height, msg.round, msg.type, have)
            else:
                self._set_vote_bit_array_locked(msg.height, msg.round, msg.type, msg.votes)

    def set_has_proposal(self, proposal) -> None:
        with self._lock:
            prs = self.prs
            if prs.height != proposal.height or prs.round != proposal.round or prs.proposal:
                return
            prs.proposal = True
            prs.proposal_block_parts_header = proposal.block_parts_header
            if prs.proposal_block_parts is None:
                prs.proposal_block_parts = BitArray(proposal.block_parts_header.total)
            prs.proposal_pol_round = proposal.pol_round
            prs.proposal_pol = None

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        with self._lock:
            prs = self.prs
            if prs.height != height or prs.round != round_:
                return
            if prs.proposal_block_parts is not None:
                prs.proposal_block_parts.set_index(index, True)

    def set_has_vote(self, vote) -> None:
        with self._lock:
            self._set_has_vote_locked(
                vote.height, vote.round, vote.type, vote.validator_index
            )

    def apply_agg_commit(self, cert) -> None:
        """Mark every signer bit of an aggregate precommit certificate
        as known to the peer (sent to it, or received from it) — the
        BLS lane's bulk set_has_vote."""
        with self._lock:
            ba = self._get_vote_bit_array_locked(
                cert.agg_height, cert.agg_round, VOTE_TYPE_PRECOMMIT)
            if ba is None:
                return
            # bulk OR: at mega-committee sizes a per-bit set_index loop
            # is size() lock round-trips per gossip send
            ba.or_update(cert.signers)

    def agg_cert_has_news(self, cert) -> bool:
        """Does the certificate cover any signer the peer isn't known to
        have? (Gossip guard: merged certificates re-send only while they
        still grow the peer's view.)"""
        with self._lock:
            ba = self._get_vote_bit_array_locked(
                cert.agg_height, cert.agg_round, VOTE_TYPE_PRECOMMIT)
            if ba is None:
                # no tracking slot for that (height, round) — stay quiet
                # rather than re-sending every gossip tick; the per-vote
                # path covers mismatched-round peers
                return False
            # any signer bit the peer lacks? — one bulk numpy op, not
            # 2×size() per-bit lock acquisitions per gossip tick
            return not cert.signers.sub(ba).is_empty()

    def agg_cert_should_send(self, cert, now: float,
                             min_s: float, delta: int) -> bool:
        """agg_cert_has_news PLUS the per-peer re-send gate: a growing
        certificate re-sends immediately once it gained `delta` signers,
        anything else waits out `min_s`. apply_agg_commit normally stops
        pure duplicates already — this bounds the chatter left when mark
        expiry (expire_gossip_marks_if_stalled) wipes the peer's bitmap
        during a stall and every tick would otherwise re-offer the same
        bytes."""
        if not self.agg_cert_has_news(cert):
            return False
        with self._lock:
            sent_at, sent_n = self._agg_sent.get(
                (cert.agg_height, cert.agg_round), (0.0, 0))
            n = cert.num_signers()
            return now - sent_at >= min_s or n - sent_n >= delta

    def note_agg_cert_sent(self, cert, now: float) -> None:
        with self._lock:
            self._agg_sent[(cert.agg_height, cert.agg_round)] = (
                now, cert.num_signers())
            if len(self._agg_sent) > 8:  # GC: committed heights
                for k in [k for k in self._agg_sent
                          if k[0] < cert.agg_height - 1]:
                    del self._agg_sent[k]

    def ensure_catchup_commit_round(self, height: int, round_: int, num_validators: int) -> None:
        """reactor.go:975-994."""
        with self._lock:
            prs = self.prs
            if prs.height != height:
                return
            if prs.catchup_commit_round == round_:
                return
            prs.catchup_commit_round = round_
            if round_ == prs.round:
                prs.catchup_commit = prs.precommits
            else:
                prs.catchup_commit = BitArray(num_validators)

    def expire_gossip_marks_if_stalled(self, stall_s: float,
                                       our_height: int = None) -> bool:
        """Self-healing under silent message loss (netchaos drops, lossy
        links, asymmetric partitions): gossip marks votes/parts as
        known-to-the-peer ON SEND, but a dropped send means the peer
        never got them — and with the TCP connection surviving the
        fault, nothing ever clears the poisoned marks, so after the
        fault both sides sit forever believing there is nothing left to
        send (the reference never hits this because TCP either delivers
        or kills the conn, which resets PeerState wholesale).

        When the peer's HEIGHT has not advanced for `stall_s`, wipe the
        knowledge marks so the gossip routines re-offer everything the
        peer might have missed; duplicates are cheap (dup-check + sig
        cache) and the wipe re-arms at most once per stall_s. A peer
        AHEAD of us is excluded via our_height: nothing we hold can
        unstick it, so wiping would only generate duplicate traffic."""
        with self._lock:
            now = time.monotonic()
            if our_height is not None and self.prs.height > our_height:
                return False
            if (now - self.last_height_advance < stall_s
                    or now - self._marks_expired_at < stall_s):
                return False
            self._marks_expired_at = now
            prs = self.prs
            prs.proposal = False
            if prs.proposal_block_parts is not None:
                prs.proposal_block_parts = BitArray(
                    prs.proposal_block_parts.bits)
            prs.proposal_pol = None
            prs.prevotes = None
            prs.precommits = None
            prs.last_commit = None
            # reset the catchup round too: ensure_catchup_commit_round
            # early-returns on a matching round and would otherwise
            # leave catchup_commit None forever
            prs.catchup_commit_round = -1
            prs.catchup_commit = None
            return True

    def ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        """reactor.go:996-1018."""
        with self._lock:
            prs = self.prs
            if prs.height == height:
                if prs.prevotes is None:
                    prs.prevotes = BitArray(num_validators)
                if prs.precommits is None:
                    prs.precommits = BitArray(num_validators)
                if prs.catchup_commit is None and prs.catchup_commit_round >= 0:
                    prs.catchup_commit = BitArray(num_validators)
                if prs.proposal_pol is None and prs.proposal_pol_round >= 0:
                    prs.proposal_pol = BitArray(num_validators)
            elif prs.height == height + 1:
                if prs.last_commit is None:
                    prs.last_commit = BitArray(num_validators)

    # -- internals -----------------------------------------------------

    def _set_has_vote_locked(self, height: int, round_: int, type_: int, index: int) -> None:
        ba = self._get_vote_bit_array_locked(height, round_, type_)
        if ba is not None and index is not None and index >= 0:
            ba.set_index(index, True)

    def _get_vote_bit_array_locked(self, height: int, round_: int, type_: int) -> Optional[BitArray]:
        prs = self.prs
        if prs.height == height:
            if round_ == prs.round:
                return prs.prevotes if type_ == VOTE_TYPE_PREVOTE else prs.precommits
            if round_ == prs.catchup_commit_round and type_ == VOTE_TYPE_PRECOMMIT:
                return prs.catchup_commit
            if round_ == prs.proposal_pol_round and type_ == VOTE_TYPE_PREVOTE:
                return prs.proposal_pol
        elif prs.height == height + 1:
            if round_ == prs.last_commit_round and type_ == VOTE_TYPE_PRECOMMIT:
                return prs.last_commit
        return None

    def _set_vote_bit_array_locked(self, height, round_, type_, ba) -> None:
        prs = self.prs
        if prs.height == height:
            if round_ == prs.round:
                if type_ == VOTE_TYPE_PREVOTE:
                    prs.prevotes = ba
                else:
                    prs.precommits = ba
            elif round_ == prs.catchup_commit_round and type_ == VOTE_TYPE_PRECOMMIT:
                prs.catchup_commit = ba
            elif round_ == prs.proposal_pol_round and type_ == VOTE_TYPE_PREVOTE:
                prs.proposal_pol = ba
        elif prs.height == height + 1:
            if round_ == prs.last_commit_round and type_ == VOTE_TYPE_PRECOMMIT:
                prs.last_commit = ba

    def pick_vote_to_send(self, votes) -> Optional[object]:
        """Pick a random vote from `votes` (a VoteSet) that the peer
        lacks; marks it sent (reactor.go:1077-1089)."""
        if votes is None or votes.size() == 0:
            return None
        with self._lock:
            height, round_, type_ = votes.height, votes.round, votes.type
            self.ensure_vote_bit_arrays(height, len(votes.val_set))
            ps_votes = self._get_vote_bit_array_locked(height, round_, type_)
            if ps_votes is None:
                return None
            missing = votes.bit_array().sub(ps_votes)
            idx = missing.pick_random()
            if idx is None:
                return None
            vote = votes.get_by_index(idx)
            if vote is not None:
                self._set_has_vote_locked(height, round_, type_, idx)
            return vote


class ReplicaConsensusAbsorber(Reactor):
    """Owns the four consensus channels on a read replica ([base]
    mode = replica) WITHOUT any consensus machinery behind them.

    Peers running real consensus gossip votes/steps to every connected
    peer; a node that advertised no owner for those channels would
    disconnect each validator on the first inbound frame (the switch
    treats an unowned channel as a protocol error). The absorber keeps
    the wire protocol intact and drops the traffic — validators' gossip
    routines see a peer that never advances past height 0 and mostly
    sleep (reactor.go's prs.height == 0 guards). The replica itself
    never sends a consensus message."""

    def __init__(self, handel: bool = False):
        super().__init__("ReplicaConsensusAbsorber")
        self.absorbed = 0  # frames dropped; /debug visibility only
        self._handel = handel

    def get_channels(self):
        channels = [
            ChannelDescriptor(id=STATE_CHANNEL, priority=1,
                              send_queue_capacity=2),
            ChannelDescriptor(id=DATA_CHANNEL, priority=1,
                              send_queue_capacity=2,
                              recv_message_capacity=1048576),
            ChannelDescriptor(id=VOTE_CHANNEL, priority=1,
                              send_queue_capacity=2,
                              recv_message_capacity=100 * 1024),
            ChannelDescriptor(id=VOTE_SET_BITS_CHANNEL, priority=1,
                              send_queue_capacity=2,
                              recv_message_capacity=1024),
        ]
        if self._handel:
            # a [handel]-enabled fleet advertises 0x24; the replica must
            # own it too or the first inbound contribution disconnects
            # the validator (unowned channel = protocol error)
            channels.append(ChannelDescriptor(
                id=HANDEL_CHANNEL, priority=1, send_queue_capacity=2,
                recv_message_capacity=100 * 1024))
        return channels

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        self.absorbed += 1

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass


class ConsensusReactor(Reactor):
    """reactor.go:37."""

    def __init__(self, consensus_state, fast_sync: bool = False):
        super().__init__("ConsensusReactor")
        self.cs = consensus_state
        self.fast_sync = fast_sync
        self._peer_states: Dict[str, PeerState] = {}
        self._peer_threads: Dict[str, list] = {}
        self._stop = threading.Event()
        self._bcast_thread: Optional[threading.Thread] = None
        self._handel_thread: Optional[threading.Thread] = None
        # validator index -> peer id, learned from the `origin` field of
        # received contributions (GIL-atomic dict ops; no lock needed)
        self._handel_val_peer: Dict[int, str] = {}
        self._subs = []
        # gossip-mark expiry horizon (expire_gossip_marks_if_stalled):
        # roughly one full round at this chain's timeouts — long enough
        # that normal progress never expires, short enough that a
        # silent-loss stall re-offers within a few rounds
        try:
            conf = consensus_state.config
            self._gossip_resend_s = max(
                2.0,
                2 * (conf.propose(1) + conf.prevote(1) + conf.precommit(1)))
        except Exception:  # noqa: BLE001 - absent config in bare tests
            self._gossip_resend_s = 10.0

    def get_channels(self):
        """reactor.go:125-157."""
        channels = [
            ChannelDescriptor(id=STATE_CHANNEL, priority=5, send_queue_capacity=100),
            ChannelDescriptor(
                id=DATA_CHANNEL, priority=10, send_queue_capacity=100,
                recv_message_capacity=1048576,
            ),
            ChannelDescriptor(
                id=VOTE_CHANNEL, priority=5, send_queue_capacity=100,
                recv_message_capacity=100 * 1024,
            ),
            ChannelDescriptor(
                id=VOTE_SET_BITS_CHANNEL, priority=1, send_queue_capacity=2,
                recv_message_capacity=1024,
            ),
        ]
        if getattr(self.cs, "handel", None) is not None:
            channels.append(ChannelDescriptor(
                id=HANDEL_CHANNEL, priority=5, send_queue_capacity=100,
                recv_message_capacity=100 * 1024,
            ))
        return channels

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        if not self.fast_sync:
            self.cs.start()
        self._bcast_thread = threading.Thread(
            target=self._broadcast_routine, name="cons-bcast", daemon=True
        )
        self._bcast_thread.start()
        self._step_refresh_thread = threading.Thread(
            target=self._step_refresh_routine, name="cons-step-refresh",
            daemon=True)
        self._step_refresh_thread.start()
        if getattr(self.cs, "handel", None) is not None:
            self._handel_thread = threading.Thread(
                target=self._handel_tick_routine, name="cons-handel",
                daemon=True)
            self._handel_thread.start()

    def _step_refresh_routine(self) -> None:
        """Periodically re-announce our round step to every peer.

        Step transitions broadcast NewRoundStep once; under silent
        message loss (netchaos drops, asymmetric partitions) that one
        copy can vanish, and several steps (PREVOTE before 2/3-any,
        PRECOMMIT_WAIT) have NO timeout — a wedged node then emits
        nothing, every peer's view of its (height, round) goes stale,
        and vote gossip keeps aiming at the wrong round forever. A
        ~tiny periodic refresh (one <100B message per peer) re-anchors
        peer views so the mark-expiry resend actually lands.

        It re-sends the LAST step broadcast's bytes rather than
        re-reading RoundState: a fresh shallow copy taken from this
        thread can tear mid-transition, and a torn (height, round,
        step) that jumps FORWARD would poison every peer's view (the
        receive guard only rejects regressions). Stale-but-consistent
        bytes are harmless — receivers ignore anything <= their view."""
        interval = max(0.5, self._gossip_resend_s / 2.0)
        while not self._stop.wait(interval):
            if self.fast_sync:
                continue
            step_bytes = getattr(self, "_last_step_bcast", None)
            if step_bytes is None:
                continue
            try:
                self._broadcast(STATE_CHANNEL, step_bytes)
            except Exception:  # noqa: BLE001 - refresh must outlive bugs
                LOG.exception("round-step refresh failed")

    def stop(self) -> None:
        self._stop.set()
        try:
            self.cs.stop()
        except Exception:
            pass

    def switch_to_consensus(self, state, blocks_synced: int = 0) -> None:
        """Fast-sync handoff (reactor.go:101-123).

        Note the reconstruct AFTER update_to_state: the reference (v0.27)
        calls reconstructLastCommit first and updateToState then clobbers
        cs.LastCommit back to nil (state.go:497-501,533) — a proposer
        that fast-synced could then never build a block. Later upstream
        versions fixed the order; we do the fixed order.
        """
        self.cs.update_to_state(state)
        self.cs._reconstruct_last_commit_if_needed(state)
        self.fast_sync = False
        if blocks_synced > 0:
            # don't bother with the WAL if we fast synced (reactor.go:114-117)
            self.cs.do_wal_catchup = False
        self.cs.start()

    # -- peers ---------------------------------------------------------

    def init_peer(self, peer) -> None:
        peer.set("consensus_peer_state", PeerState(peer))

    def add_peer(self, peer) -> None:
        ps: PeerState = peer.get("consensus_peer_state")
        self._peer_states[peer.id] = ps
        # announce our current state so the peer can gossip to us. This
        # runs on the peer's accept/dial thread: only a CONSISTENT
        # stamped snapshot may be turned into wire bytes (CD-5) — a
        # torn forward-jumping round step poisons the peer's view. On a
        # torn read, fall back to the last receive-thread-built
        # broadcast bytes (always safe, may be stale) or stay quiet;
        # the periodic step refresh re-anchors the peer either way.
        rs = self.cs.get_round_state()
        if getattr(rs, "snapshot_consistent", True):
            peer.send(STATE_CHANNEL, encode_msg(_new_round_step_msg(rs)))
            cs_msg = _commit_step_msg(rs)
            if cs_msg is not None:
                peer.send(STATE_CHANNEL, encode_msg(cs_msg))
        else:
            step_bytes = getattr(self, "_last_step_bcast", None)
            if step_bytes is not None:
                peer.send(STATE_CHANNEL, step_bytes)
        threads = []
        for fn, nm in (
            (self._gossip_data_routine, "gossip-data"),
            (self._gossip_votes_routine, "gossip-votes"),
            (self._query_maj23_routine, "query-maj23"),
        ):
            t = threading.Thread(target=fn, args=(peer, ps), name=f"{nm}-{peer.id[:8]}", daemon=True)
            t.start()
            threads.append(t)
        self._peer_threads[peer.id] = threads

    def remove_peer(self, peer, reason) -> None:
        self._peer_states.pop(peer.id, None)
        self._peer_threads.pop(peer.id, None)
        for idx in [i for i, pid in self._handel_val_peer.items()
                    if pid == peer.id]:
            self._handel_val_peer.pop(idx, None)
        # threads exit on peer.is_running() checks

    # -- inbound -------------------------------------------------------

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        """reactor.go:199-320."""
        msg = decode_msg(msg_bytes)
        if self.switch is not None and peer.is_running():
            self.switch.metrics.peer_msg_recv_total.with_labels(
                peer.id, f"{ch_id:#04x}", type(msg).__name__).inc()
        ps: Optional[PeerState] = peer.get("consensus_peer_state")
        if ps is None:
            return
        if ch_id == STATE_CHANNEL:
            if isinstance(msg, NewRoundStepMessage):
                ps.apply_new_round_step(msg)
            elif isinstance(msg, CommitStepMessage):
                ps.apply_commit_step(msg)
            elif isinstance(msg, HasVoteMessage):
                ps.apply_has_vote(msg)
            elif isinstance(msg, VoteSetMaj23Message):
                self._handle_vote_set_maj23(peer, ps, msg)
        elif ch_id == DATA_CHANNEL:
            if self.fast_sync:
                return
            if isinstance(msg, ProposalMessage):
                ps.set_has_proposal(msg.proposal)
                self.cs.add_peer_message(msg, peer.id)
            elif isinstance(msg, ProposalPOLMessage):
                ps.apply_proposal_pol(msg)
            elif isinstance(msg, BlockPartMessage):
                ps.set_has_proposal_block_part(msg.height, msg.round, msg.part.index)
                self.cs.add_peer_message(msg, peer.id)
        elif ch_id == VOTE_CHANNEL:
            if self.fast_sync:
                return
            if isinstance(msg, VoteMessage):
                rs = self.cs.get_round_state()
                n = len(rs.validators) if rs.validators else 0
                ps.ensure_vote_bit_arrays(rs.height, n)
                ps.ensure_vote_bit_arrays(rs.height - 1, n)
                ps.set_has_vote(msg.vote)
                self.cs.add_peer_message(msg, peer.id)
            elif isinstance(msg, AggregateCommitMessage):
                # Handel-lite lane: everything the cert covers, the peer
                # knows; the consensus loop verifies + merges it
                if msg.commit is not None:
                    rs = self.cs.get_round_state()
                    n = len(rs.validators) if rs.validators else 0
                    ps.ensure_vote_bit_arrays(rs.height, n)
                    ps.ensure_vote_bit_arrays(rs.height - 1, n)
                    ps.apply_agg_commit(msg.commit)
                    self.cs.add_peer_message(msg, peer.id)
        elif ch_id == HANDEL_CHANNEL:
            if self.fast_sync:
                return
            if isinstance(msg, HandelContributionMessage):
                # pin down the peer's validator index from the claimed
                # origin — a lie only misroutes that peer's OWN window
                # traffic (contribution verification is unaffected), and
                # the session's scoring prunes senders of garbage
                if 0 <= msg.origin < (1 << 20):
                    self._handel_val_peer[msg.origin] = peer.id
                self.cs.add_peer_message(msg, peer.id)
        elif ch_id == VOTE_SET_BITS_CHANNEL:
            if self.fast_sync:
                return
            if isinstance(msg, VoteSetBitsMessage):
                rs = self.cs.get_round_state()
                if rs.height == msg.height and rs.votes is not None:
                    vs = (
                        rs.votes.prevotes(msg.round)
                        if msg.type == VOTE_TYPE_PREVOTE
                        else rs.votes.precommits(msg.round)
                    )
                    ours = vs.bit_array_by_block_id(msg.block_id) if vs else None
                    ps.apply_vote_set_bits(msg, ours)
                else:
                    ps.apply_vote_set_bits(msg, None)

    def _handle_vote_set_maj23(self, peer, ps: PeerState, msg: VoteSetMaj23Message) -> None:
        """reactor.go:249-304: record the claim, respond with our bits."""
        rs = self.cs.get_round_state()
        # wire reply below: never answer from a torn snapshot (CD-5);
        # the peer's maj23 query repeats every PEER_QUERY_MAJ23_SLEEP
        if not getattr(rs, "snapshot_consistent", True):
            return
        if rs.height != msg.height or rs.votes is None:
            return
        rs.votes.set_peer_maj23(msg.round, msg.type, peer.id, msg.block_id)
        vs = (
            rs.votes.prevotes(msg.round)
            if msg.type == VOTE_TYPE_PREVOTE
            else rs.votes.precommits(msg.round)
        )
        if vs is None:
            return
        our_votes = vs.bit_array_by_block_id(msg.block_id)
        if our_votes is None:
            our_votes = BitArray(len(vs.val_set))
        peer.try_send(
            VOTE_SET_BITS_CHANNEL,
            encode_msg(
                VoteSetBitsMessage(
                    height=msg.height, round=msg.round, type=msg.type,
                    block_id=msg.block_id, votes=our_votes,
                )
            ),
        )

    # -- broadcast routine (event bus -> all peers) --------------------

    def _broadcast_routine(self) -> None:
        """reactor.go:371-395 subscribeToBroadcastEvents."""
        bus = getattr(self.cs, "event_bus", None)
        if bus is None or not hasattr(bus, "subscribe"):
            return
        sub_step = bus.subscribe("cons-reactor-step", query_for_event(EVENT_NEW_ROUND_STEP))
        sub_vote = bus.subscribe("cons-reactor-vote", query_for_event(EVENT_VOTE))
        self._subs = [sub_step, sub_vote]
        while not self._stop.is_set():
            msg = sub_step.get(timeout=0.05)
            if msg is not None:
                rs = msg.data
                step_bytes = encode_msg(_new_round_step_msg(rs))
                # cache for the periodic refresh: these bytes were built
                # from a receive-thread-published snapshot, so re-sending
                # them later can never leak a torn (height, round, step)
                self._last_step_bcast = step_bytes
                self._broadcast(STATE_CHANNEL, step_bytes)
                cs_msg = _commit_step_msg(rs)
                if cs_msg is not None:
                    # reference makeRoundStepMessages (reactor.go:404-412):
                    # entering commit advertises our block-parts header +
                    # bitmap so peers can feed us the parts we're missing —
                    # WITHOUT this a node that enters commit via catch-up
                    # precommits (e.g. right after the fast-sync handoff)
                    # deadlocks: peers never learn which parts to send
                    self._broadcast(STATE_CHANNEL, encode_msg(cs_msg))
            vmsg = sub_vote.get(timeout=0.0)
            if vmsg is not None:
                vote = vmsg.data["vote"]
                self._broadcast(
                    STATE_CHANNEL,
                    encode_msg(
                        HasVoteMessage(
                            height=vote.height, round=vote.round,
                            type=vote.type, index=vote.validator_index,
                        )
                    ),
                )

    def _broadcast(self, ch_id: int, msg_bytes: bytes) -> None:
        if self.switch is not None:
            self.switch.broadcast(ch_id, msg_bytes)

    # -- per-peer gossip -----------------------------------------------

    def _gossip_data_routine(self, peer, ps: PeerState) -> None:
        """reactor.go:456-526."""
        while peer.is_running() and not self._stop.is_set():
            try:
                if self._gossip_data_once(peer, ps):
                    continue
            except Exception:
                LOG.exception("gossip data error for %s", peer.id[:8])
            time.sleep(PEER_GOSSIP_SLEEP)

    def _gossip_data_once(self, peer, ps: PeerState) -> bool:
        """One attempt; True if something was sent (skip the sleep)."""
        rs = self.cs.get_round_state()
        # everything below builds wire messages from rs: skip the tick
        # on a torn snapshot (CD-5) — the next one is 100ms away
        if not getattr(rs, "snapshot_consistent", True):
            return False
        prs = ps.get_round_state()

        # send proposal block parts the peer is missing
        if (
            rs.proposal_block_parts is not None
            and prs.proposal_block_parts_header is not None
            and rs.proposal_block_parts.has_header(prs.proposal_block_parts_header)
            and prs.proposal_block_parts is not None
        ):
            missing = rs.proposal_block_parts.bit_array().sub(prs.proposal_block_parts)
            idx = missing.pick_random()
            if idx is not None:
                part = rs.proposal_block_parts.get_part(idx)
                if part is not None and peer.send(
                    DATA_CHANNEL,
                    encode_msg(BlockPartMessage(height=rs.height, round=rs.round, part=part)),
                ):
                    ps.set_has_proposal_block_part(prs.height, prs.round, idx)
                    return True

        # peer is catching up: send parts of the committed block at their height
        block_store = getattr(self.cs, "block_store", None)
        if prs.height != 0 and prs.height < rs.height and block_store is not None:
            if prs.height < (block_store.height() or 0) + 1:
                return self._gossip_catchup_block_part(peer, ps, prs, block_store)

        if rs.height != prs.height or rs.round != prs.round:
            return False

        # send the proposal (+POL) if the peer lacks it
        if rs.proposal is not None and not prs.proposal:
            if peer.send(DATA_CHANNEL, encode_msg(ProposalMessage(proposal=rs.proposal))):
                ps.set_has_proposal(rs.proposal)
            if 0 <= rs.proposal.pol_round and rs.votes is not None:
                pol = rs.votes.prevotes(rs.proposal.pol_round)
                if pol is not None:
                    peer.send(
                        DATA_CHANNEL,
                        encode_msg(
                            ProposalPOLMessage(
                                height=rs.height,
                                proposal_pol_round=rs.proposal.pol_round,
                                proposal_pol=pol.bit_array(),
                            )
                        ),
                    )
            return True
        return False

    def _gossip_catchup_block_part(self, peer, ps: PeerState, prs, block_store) -> bool:
        """reactor.go:528-591: feed an old block part by part."""
        meta = block_store.load_block_meta(prs.height)
        if meta is None:
            return False
        if prs.proposal_block_parts_header is None or not (
            prs.proposal_block_parts_header.hash == meta.block_id.parts_header.hash
        ):
            # the peer hasn't advertised the matching parts header yet —
            # it will via its CommitStepMessage once catch-up precommits
            # drive it into the commit step (reactor.go:536-544 just
            # sleeps here too)
            return False
        if prs.proposal_block_parts is None:
            return False
        missing = BitArray(prs.proposal_block_parts.bits)
        for i in range(missing.bits):
            missing.set_index(i, True)
        missing = missing.sub(prs.proposal_block_parts)
        idx = missing.pick_random()
        if idx is None:
            return False
        part = block_store.load_block_part(prs.height, idx)
        if part is None:
            return False
        if peer.send(
            DATA_CHANNEL,
            encode_msg(BlockPartMessage(height=prs.height, round=prs.round, part=part)),
        ):
            ps.set_has_proposal_block_part(prs.height, prs.round, idx)
            return True
        return False

    def _gossip_votes_routine(self, peer, ps: PeerState) -> None:
        """reactor.go:593-717."""
        while peer.is_running() and not self._stop.is_set():
            try:
                if self._gossip_votes_once(peer, ps):
                    continue
                # nothing to send: if the peer's height has been stuck
                # for a full round span, our sent-marks may be lies
                # (silently dropped sends) — expire and re-offer
                ps.expire_gossip_marks_if_stalled(
                    self._gossip_resend_s, our_height=self.cs.rs.height)
            except Exception:
                LOG.exception("gossip votes error for %s", peer.id[:8])
            time.sleep(PEER_GOSSIP_SLEEP)

    def _gossip_votes_once(self, peer, ps: PeerState) -> bool:
        rs = self.cs.get_round_state()
        # wire sends built from rs below: torn snapshot -> skip the
        # tick (CD-5)
        if not getattr(rs, "snapshot_consistent", True):
            return False
        prs = ps.get_round_state()

        def send(vote) -> bool:
            if vote is None:
                return False
            return peer.send(VOTE_CHANNEL, encode_msg(VoteMessage(vote=vote)))

        # BLS fast lane: one merged certificate beats N VoteMessages
        if self._gossip_agg_cert_once(peer, ps, rs, prs):
            return True

        # same height: current-round votes, POL prevotes, last commit
        if rs.height == prs.height and rs.votes is not None:
            # last commit to help the peer finish the previous height
            if prs.step == STEP_NEW_HEIGHT and rs.last_commit is not None:
                if send(ps.pick_vote_to_send(rs.last_commit)):
                    return True
            # POL prevotes for the peer's proposal_pol_round
            if 0 <= prs.proposal_pol_round:
                pol = rs.votes.prevotes(prs.proposal_pol_round)
                if pol is not None and send(ps.pick_vote_to_send(pol)):
                    return True
            # current round votes
            if 0 <= prs.round <= rs.round:
                pv = rs.votes.prevotes(prs.round)
                if prs.step <= STEP_PREVOTE_WAIT and pv is not None:
                    if send(ps.pick_vote_to_send(pv)):
                        return True
                pc = rs.votes.precommits(prs.round)
                if pc is not None and send(ps.pick_vote_to_send(pc)):
                    return True
        # peer one height behind: our last commit is their current precommits
        if rs.height == prs.height + 1 and rs.last_commit is not None:
            if send(ps.pick_vote_to_send(rs.last_commit)):
                return True
        # further behind: stored commit for their height
        block_store = getattr(self.cs, "block_store", None)
        if prs.height != 0 and rs.height >= prs.height + 2 and block_store is not None:
            from ..types.block import AggregateCommit

            commit = block_store.load_block_commit(prs.height)
            if isinstance(commit, AggregateCommit):
                # BLS catch-up: the stored certificate IS the commit —
                # one message instead of one per validator
                ps.ensure_catchup_commit_round(prs.height, commit.round(),
                                               commit.size())
                if ps.agg_cert_has_news(commit) and peer.send(
                    VOTE_CHANNEL,
                    encode_msg(AggregateCommitMessage(commit)),
                ):
                    ps.apply_agg_commit(commit)
                    return True
            elif commit is not None:
                ps.ensure_catchup_commit_round(prs.height, commit.round(), len(commit.precommits))
                vote = ps.pick_vote_to_send(_CommitVoteSetView(commit))
                if send(vote):
                    return True
        return False

    def _gossip_agg_cert_once(self, peer, ps: PeerState, rs, prs) -> bool:
        """Handel-lite aggregation-aware precommit gossip (BLS valsets
        only; Ed25519 chains never reach this). Send our current merged
        (bitmap, aggregate) pair whenever it covers signers the peer
        lacks: the peer merges it with its own running aggregate and
        re-gossips, so quorum assembly takes O(log n) messages instead
        of one per validator."""
        if rs.validators is None or not rs.validators.is_bls():
            return False
        try:
            now = time.monotonic()
            # Handel overlay suppression: while the overlay is on and its
            # frontier is healthy, same-height certificates travel as
            # O(log n) level contributions instead — the flat lane stays
            # armed as the fallback and re-opens the moment a session
            # reports a stuck level (byzantine-silent subtree, partition)
            mgr = getattr(self.cs, "handel", None)
            handel_quiet = (mgr is not None and mgr.enabled(rs.validators)
                            and mgr.stuck(now) == 0)
            # same height: the peer's current round precommits
            if (not handel_quiet and prs.height == rs.height
                    and rs.votes is not None
                    and 0 <= prs.round <= rs.round):
                pc = rs.votes.precommits(prs.round)
                cert = pc.aggregate_certificate() if pc is not None else None
                if cert is not None and cert.num_signers() > 1:
                    ps.ensure_vote_bit_arrays(rs.height, cert.size())
                    if ps.agg_cert_should_send(
                        cert, now, _AGG_RESEND_MIN_S, _AGG_RESEND_DELTA
                    ) and peer.send(
                        VOTE_CHANNEL, encode_msg(AggregateCommitMessage(cert))
                    ):
                        ps.apply_agg_commit(cert)
                        ps.note_agg_cert_sent(cert, now)
                        return True
            # peer one height behind: our last commit as one certificate
            # (never suppressed — catch-up is not an aggregation problem)
            if prs.height + 1 == rs.height and rs.last_commit is not None:
                cert = rs.last_commit.aggregate_certificate()
                if cert is not None:
                    ps.ensure_vote_bit_arrays(prs.height, cert.size())
                    if ps.agg_cert_should_send(
                        cert, now, _AGG_RESEND_MIN_S, _AGG_RESEND_DELTA
                    ) and peer.send(
                        VOTE_CHANNEL, encode_msg(AggregateCommitMessage(cert))
                    ):
                        ps.apply_agg_commit(cert)
                        ps.note_agg_cert_sent(cert, now)
                        return True
        except Exception:
            LOG.exception("aggregate cert gossip error for %s", peer.id[:8])
        return False

    def _handel_tick_routine(self) -> None:
        """One thread drives every Handel session's gossip (not
        per-peer: a tick drains ALL sessions and fans the sends out to
        whichever peers currently back the target validator indices).
        Unmapped targets mean we have not yet seen that validator's
        peer; one representative contribution per still-unmapped peer
        per tick bootstraps the index map (receivers learn OUR index
        from `origin` and their replies pin theirs) without flooding."""
        mgr = self.cs.handel
        interval = max(0.01, getattr(mgr.cfg, "tick_ms", 50) / 1000.0)
        while not self._stop.wait(interval):
            if self.fast_sync:
                continue
            try:
                rs = self.cs.get_round_state()
                # contributions are wire messages: only a consistent
                # snapshot may pick the (height, validators) they bind
                # to (CD-5); retry next tick
                if not getattr(rs, "snapshot_consistent", True):
                    continue
                if rs.validators is None:
                    continue
                sends = mgr.outgoing(rs.validators, rs.height,
                                     time.monotonic())
                if not sends:
                    continue
                self._handel_fan_out(sends)
            except Exception:  # noqa: BLE001 - overlay must outlive bugs
                LOG.exception("handel tick failed")

    def _handel_fan_out(self, sends) -> None:
        """Route [(validator_index, HandelContributionMessage)] to peers.
        Only peers ADVERTISING the channel may receive on it: a frame on
        an undeclared channel is a protocol error that tears down the
        connection (connection.py recv loop), so a mixed fleet — handel
        validators peered with [handel]-off nodes or replicas — would
        flap without this gate."""
        peers = {
            pid: ps for pid, ps in self._peer_states.items()
            if HANDEL_CHANNEL in ps.peer.node_info.channels
        }
        val_peer = self._handel_val_peer
        bootstrap_msg = None
        for target, m in sends:
            pid = val_peer.get(target)
            ps = peers.get(pid) if pid is not None else None
            if ps is not None and ps.peer.is_running():
                ps.peer.try_send(HANDEL_CHANNEL, encode_msg(m))
            else:
                bootstrap_msg = m
        if bootstrap_msg is not None:
            data = encode_msg(bootstrap_msg)
            mapped = set(val_peer.values())
            for pid, ps in peers.items():
                if pid not in mapped and ps.peer.is_running():
                    ps.peer.try_send(HANDEL_CHANNEL, data)

    def _query_maj23_routine(self, peer, ps: PeerState) -> None:
        """reactor.go:720-802: periodically ask the peer for vote bits of
        claimed majorities."""
        while peer.is_running() and not self._stop.is_set():
            time.sleep(PEER_QUERY_MAJ23_SLEEP)
            try:
                rs = self.cs.get_round_state()
                # maj23 claims are wire messages: only from a
                # consistent snapshot (CD-5); retry in 2s
                if not getattr(rs, "snapshot_consistent", True):
                    continue
                prs = ps.get_round_state()
                if rs.votes is None:
                    continue
                if rs.height == prs.height:
                    pv = rs.votes.prevotes(prs.round) if prs.round >= 0 else None
                    if pv is not None:
                        maj = pv.two_thirds_majority()
                        if maj is not None:
                            peer.try_send(
                                STATE_CHANNEL,
                                encode_msg(
                                    VoteSetMaj23Message(
                                        height=prs.height, round=prs.round,
                                        type=VOTE_TYPE_PREVOTE, block_id=maj,
                                    )
                                ),
                            )
                    pc = rs.votes.precommits(prs.round) if prs.round >= 0 else None
                    if pc is not None:
                        maj = pc.two_thirds_majority()
                        if maj is not None:
                            peer.try_send(
                                STATE_CHANNEL,
                                encode_msg(
                                    VoteSetMaj23Message(
                                        height=prs.height, round=prs.round,
                                        type=VOTE_TYPE_PRECOMMIT, block_id=maj,
                                    )
                                ),
                            )
            except Exception:
                LOG.exception("query maj23 error for %s", peer.id[:8])


class _CommitVoteSetView:
    """Adapter presenting a stored Commit as a minimal VoteSet for
    pick_vote_to_send (reference uses Commit.BitArray/GetByIndex via the
    VoteSetReader interface, types/block.go:540-620)."""

    def __init__(self, commit):
        self.commit = commit
        votes = [v for v in commit.precommits]
        self.height = commit.height()
        self.round = commit.round()
        self.type = VOTE_TYPE_PRECOMMIT
        self._votes = votes

        class _VS:
            def __init__(self, n):
                self._n = n

            def __len__(self):
                return self._n

        self.val_set = _VS(len(votes))

    def size(self) -> int:
        return len(self._votes)

    def bit_array(self) -> BitArray:
        return BitArray.from_bools([v is not None for v in self._votes])

    def get_by_index(self, idx: int):
        return self._votes[idx]


def _new_round_step_msg(rs) -> NewRoundStepMessage:
    since = int(time.time() - rs.start_time) if rs.start_time else 0
    last_commit_round = rs.last_commit.round if rs.last_commit is not None else -1
    return NewRoundStepMessage(
        height=rs.height,
        round=rs.round,
        step=rs.step,
        seconds_since_start_time=max(since, 0),
        last_commit_round=last_commit_round,
    )


def _commit_step_msg(rs) -> Optional[CommitStepMessage]:
    """reference makeRoundStepMessages (reactor.go:404-412): at commit
    step, advertise the parts header + which parts we already have."""
    if rs.step != STEP_COMMIT or rs.proposal_block_parts is None:
        return None
    return CommitStepMessage(
        height=rs.height,
        block_parts_header=rs.proposal_block_parts.header(),
        block_parts=rs.proposal_block_parts.bit_array(),
    )
