"""ConsensusState — the Tendermint BFT state machine.

Reference parity: consensus/state.go. The single-writer receive loop
(receiveRoutine :561-622) consumes peer messages, internal (self-signed)
messages, and timeouts from one queue; every message is WAL'd before
processing (fsync'd for internal ones). The transition graph —
enterNewRound :730 → enterPropose :800 → enterPrevote :942 →
enterPrevoteWait :997 → enterPrecommit (lock/unlock/POL) :1025 →
enterPrecommitWait :1121 → enterCommit :1149 → finalizeCommit :1225 —
is reproduced exactly, including proposer selection, POL locking rules,
and the commit fsync ordering with fail points.

Vote ingestion (addVote :1495-1639) is north-star call site #2, and the
live path batches ADAPTIVELY: the receive loop drains the contiguous run
of queued VoteMessages and pre-verifies their signatures as ONE
BatchVerifier call (per-item masks) before running the per-vote
transitions (_handle_vote_msgs / _preverify_votes). Light traffic →
batch of 1 → serial CPU verify, zero added latency; heavy traffic
(catch-up streams, big valsets) → device-sized batches. Bulk ingestion
(VoteSet.add_votes for commit reconstruction, ValidatorSet.verify_commit
for fast sync) rides the same engine. With [crypto] async_dispatch on,
the drained run's batch is dispatched (verify_async) BEFORE its WAL
writes, so the fsync overlaps the device round trip.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from contextlib import contextmanager
from typing import Callable, List, Optional

from ..config import ConsensusConfig
from ..libs import fail, timeline as timeline_mod, tracing
from ..libs.lockdep import GenStamp, stamped_read
from ..state import BlockExecutor
from ..state import state as sm_state
from ..types.basic import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    BlockID,
    ErrVoteConflictingVotes,
    Proposal,
    Vote,
    now_ns,
)
from ..types.block import Block, Commit
from ..types.event_bus import EventBus
from ..types.part_set import PartSet
from ..types.vote_set import ErrVoteInvalid, VoteSet
from . import cstypes
from .cstypes import (
    STEP_COMMIT,
    STEP_NEW_HEIGHT,
    STEP_NEW_ROUND,
    STEP_PRECOMMIT,
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE,
    STEP_PREVOTE_WAIT,
    STEP_PROPOSE,
    HeightVoteSet,
    RoundState,
    RoundStepType,
)
from .messages import (
    AggregateCommitMessage,
    BlockPartMessage,
    HandelContributionMessage,
    ProposalMessage,
    VoteMessage,
)
from .ticker import TimeoutInfo, TimeoutTicker
from .wal import NilWAL, WAL, EndHeightMessage, TimedWALMessage

LOG = logging.getLogger("consensus")

# cap on one drained vote batch — bounds the pre-commit-event latency of
# the first vote in the run and the device bucket size
MAX_VOTE_BATCH = 1024


class ConsensusState:
    """The consensus machine for one node (reference ConsensusState
    :63-119). Not a BaseService subclass: lifecycle is start()/stop()
    with a dedicated receive thread."""

    def __init__(
        self,
        config: ConsensusConfig,
        state,  # sm.State
        block_exec: BlockExecutor,
        block_store,
        mempool=None,
        evpool=None,
        event_bus: Optional[EventBus] = None,
        priv_validator=None,
        wal=None,
        metrics=None,
        handel_cfg=None,
    ):
        self.config = config
        self.block_exec = block_exec
        self.block_store = block_store
        from ..metrics import ConsensusMetrics
        from ..types.event_bus import NopEventBus

        self.metrics = metrics if metrics is not None else ConsensusMetrics()

        self.mempool = mempool
        self.evpool = evpool
        self.event_bus = event_bus or NopEventBus()
        self.priv_validator = priv_validator
        self.wal = wal if wal is not None else NilWAL()
        # process-global tracer (libs/tracing.py): disabled → no-op spans
        self.tracer = tracing.get_tracer()
        # per-height lifecycle recorder (libs/timeline.py), disabled until
        # the node enables it. Per-instance (unlike the tracer): each
        # node's marks and peer attribution must stay its own, even with
        # several in-process nodes (tests, sim harnesses)
        self.timeline = timeline_mod.Timeline()
        # incident ledger (libs/incident.py): the node (or scenario
        # runner) wires one in; None = every incident hook is a no-op.
        # The commit path closes healed incidents (the MTTR clock) and
        # the watchdog attaches stall classifications (the MTTD clock)
        self.incidents = None
        # wall clock of the last (height, round) change — the stall
        # watchdog's dwell anchor; written only by the receive thread.
        # _height_entered anchors the HEIGHT-level dwell: a partition
        # churns rounds fast enough that no single round ever crosses
        # the threshold while the height stays stuck for the whole fault
        self._round_entered = time.time()
        self._height_entered = time.time()

        self.rs = RoundState()
        # seqlock generation stamp over self.rs: the receive loop (the
        # single writer) brackets each message/timeout's processing with
        # write_begin/write_end, so get_round_state() can prove a
        # shallow copy did not interleave with a transition — the
        # PR-10 torn-read class (discipline rule CD-5)
        self._rs_stamp = GenStamp()
        # writer-published fallback snapshot: one (gen, snapshot)
        # tuple, swapped atomically (GIL) after every mutation burst,
        # so readers that lose the stamped-read race get a CONSISTENT,
        # at-most-one-burst-stale copy instead of a torn one — with
        # the generation that MATCHES it (a tuple, not two fields: two
        # loads could pair an old snapshot with a newer gen). Without
        # the fallback a busy receive loop (single-validator producer
        # committing back to back) keeps the generation odd most of
        # the time and every gossip tick would skip — catch-up
        # starves.
        self._rs_published = None  # Optional[(gen, RoundState)]
        self.state = None  # set by update_to_state

        # message queues (reference :38 msgQueueSize=1000)
        self._queue: "queue.Queue" = queue.Queue(maxsize=2000)
        self.ticker = TimeoutTicker()
        self._thread: Optional[threading.Thread] = None
        self._tock_thread: Optional[threading.Thread] = None
        self._done = threading.Event()
        self._stopped = threading.Event()
        self._replay_mode = False
        # reactor.go:114-117: a fast-synced node skips WAL catchup
        self.do_wal_catchup = True

        # test/reactor hooks (reference :106-108,150-153)
        self.decide_proposal: Callable = self._default_decide_proposal
        self.do_prevote: Callable = self._default_do_prevote
        self.set_proposal_fn: Callable = self._default_set_proposal
        # called with each new (height, round, step) — reactor broadcast hook
        self.on_new_round_step: Optional[Callable] = None
        # called with each vote we add — reactor HasVote broadcast hook
        self.on_vote_added: Optional[Callable] = None

        self.n_height_committed = 0  # metrics
        # BLS aggregate lane diagnostics (stall_snapshot / monitor)
        self.n_agg_merges = 0
        self.last_agg_cert_bytes = 0

        # Handel aggregation overlay (consensus/handel.py): built only
        # when [handel] enable is set — None keeps every hook below a
        # no-op and the flat certificate lane byte-identical to a build
        # without the overlay
        self.handel = None
        if handel_cfg is not None and getattr(handel_cfg, "enable", False):
            from .handel import HandelManager

            addr = (priv_validator.get_address()
                    if priv_validator is not None else None)
            self.handel = HandelManager(handel_cfg, state.chain_id, addr)

        self.update_to_state(state)
        self._reconstruct_last_commit_if_needed(state)

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.wal.start()
        self.ticker.start()
        if self.do_wal_catchup:
            self._catchup_replay(self.rs.height)
        self._tock_thread = threading.Thread(
            target=self._tock_forwarder, name="cs-tock", daemon=True
        )
        self._tock_thread.start()
        self._thread = threading.Thread(
            target=self._receive_routine, name="cs-receive", daemon=True
        )
        self._thread.start()
        self._schedule_round0(self.rs)

    def stop(self) -> None:
        self._done.set()
        self.ticker.stop()
        self._stopped.wait(timeout=5.0)
        self.wal.stop()
        # settle any in-flight speculative execution so no exec-spec
        # thread (or open overlay session) outlives consensus
        stop_exec = getattr(self.block_exec, "stop", None)
        if stop_exec is not None:
            stop_exec()

    def wait_until_stopped(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)

    # --- external API (reactor / RPC entry points) --------------------------

    def add_peer_message(self, msg, peer_id: str = "") -> None:
        """Queue a message from a peer (reference :356-365 peerMsgQueue)."""
        try:
            self._queue.put(("msg", (peer_id, msg)), timeout=1.0)
        except queue.Full:
            LOG.warning("consensus queue full; dropping peer message")

    def _send_internal(self, msg) -> None:
        # internal messages must not drop (reference sendInternalMessage :332)
        self._queue.put(("msg", ("", msg)))

    def get_round_state(self) -> RoundState:
        """Stamped snapshot (shallow; the receive loop is the only
        writer). The returned RoundState carries `snapshot_gen` (the
        seqlock generation it was taken at) and `snapshot_consistent`
        (False when no provably-untorn copy could be produced).
        Consumers that build WIRE messages must check the flag — a torn
        forward-jumping (height, round, step) poisons every peer's view
        (PR-10's multi-node stall signature); diagnostic readers may
        tolerate tears but should report the flag.

        Reads from the receive thread itself are always consistent and
        skip the retry loop. Readers that lose the stamped-read race
        against a busy receive loop get the writer-published fallback —
        consistent by construction, at most one burst stale — so
        gossip never starves waiting for a quiet window; inconsistent
        snapshots only escape before the machine has processed its
        first message."""
        import copy

        snap, gen, consistent = stamped_read(
            self._rs_stamp, lambda: copy.copy(self.rs), retries=3)
        if not consistent:
            pub = self._rs_published
            if pub is not None:
                gen, published = pub
                snap, consistent = copy.copy(published), True
        snap.snapshot_gen = gen
        snap.snapshot_consistent = consistent
        return snap

    def is_proposer(self, address: Optional[bytes] = None) -> bool:
        if address is None:
            if self.priv_validator is None:
                return False
            address = self.priv_validator.get_address()
        return self.rs.validators.get_proposer().address == address

    # --- state update -------------------------------------------------------

    def update_to_state(self, state) -> None:
        """Reset the RoundState for state.last_block_height+1 (reference
        updateToState :471-557)."""
        with self._mutating():
            self._update_to_state_inner(state)

    def _update_to_state_inner(self, state) -> None:
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height != state.last_block_height:
            raise RuntimeError(
                f"update_to_state expected height {rs.height}, got {state.last_block_height}"
            )

        # last precommits become LastCommit (reference :497-508)
        last_precommits: Optional[VoteSet] = None
        if rs.commit_round > -1 and rs.votes is not None:
            pc = rs.votes.precommits(rs.commit_round)
            if pc is None or not pc.has_two_thirds_majority():
                raise RuntimeError("update_to_state with no +2/3 precommits")
            last_precommits = pc

        height = state.last_block_height + 1
        validators = state.validators.copy()

        rs.height = height
        rs.round = 0
        rs.step = STEP_NEW_HEIGHT
        if rs.commit_time == 0:
            rs.start_time = self.config.commit_time(time.time())
        else:
            rs.start_time = self.config.commit_time(rs.commit_time)
        rs.validators = validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, validators)
        rs.commit_round = -1
        rs.last_commit = last_precommits
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False

        if self.handel is not None:
            self.handel.advance_height(height)

        self._round_entered = time.time()
        self._height_entered = time.time()
        self.timeline.mark(height, "new_height")
        self.state = state
        self._new_step()

    def _reconstruct_last_commit_if_needed(self, state) -> None:
        """Rebuild LastCommit from the block store's seen commit after a
        restart (reference reconstructLastCommit :446-468)."""
        if state.last_block_height == 0 or self.rs.last_commit is not None:
            return
        seen = self.block_store.load_seen_commit(state.last_block_height)
        if seen is None:
            raise RuntimeError(
                f"no seen commit for height {state.last_block_height} to reconstruct LastCommit"
            )
        last_precommits = VoteSet(
            state.chain_id,
            state.last_block_height,
            seen.round(),
            VOTE_TYPE_PRECOMMIT,
            state.last_validators,
        )
        from ..types.block import AggregateCommit

        if isinstance(seen, AggregateCommit):
            # BLS lane: ONE certificate verification (a pairing) instead
            # of re-verifying N stored precommits
            if not last_precommits.absorb_certificate(seen):
                raise RuntimeError(
                    "stored aggregate seen-commit failed verification")
            if not last_precommits.has_two_thirds_majority():
                raise RuntimeError("reconstructed LastCommit lacks +2/3")
            self.rs.last_commit = last_precommits
            return
        votes = [v for v in seen.precommits if v is not None]
        # bulk path: ONE batched (TPU) verification for the whole commit.
        # add_votes applies per-item — a corrupt signature in the stored
        # commit must not discard the valid +2/3 riding with it; the
        # quorum check below is the authoritative gate.
        try:
            last_precommits.add_votes(votes)
        except ErrVoteInvalid as e:
            LOG.warning("reconstructing LastCommit: %s", e)
        if not last_precommits.has_two_thirds_majority():
            raise RuntimeError("reconstructed LastCommit lacks +2/3")
        self.rs.last_commit = last_precommits

    def _new_step(self) -> None:
        rs = self.get_round_state()
        self.event_bus.publish_new_round_step(rs)
        if self.on_new_round_step is not None:
            self.on_new_round_step(rs)

    @contextmanager
    def _step_span(self, span_name: str, step: str, height: int, round_: int):
        """Wraps the effective body of one step transition (after its
        height/round/step gate passed): a tracer span named after the
        reference transition (enterPropose, …) plus one sample in the
        consensus_step_duration_seconds{step=...} histogram. Both are
        no-ops until the node enables instrumentation."""
        t0 = time.perf_counter()
        try:
            with self.tracer.span("consensus." + span_name, cat="consensus",
                                  height=height, round=round_):
                yield
        finally:
            self.metrics.step_duration.with_labels(step).observe(
                time.perf_counter() - t0)

    # --- the receive loop ---------------------------------------------------

    def _tock_forwarder(self) -> None:
        while not self._done.is_set():
            try:
                ti = self.ticker.tock_queue.get(timeout=0.1)
            except queue.Empty:
                continue
            self._queue.put(("timeout", ti))

    def _receive_routine(self) -> None:
        """Single-writer loop (reference receiveRoutine :561-622). All
        state mutation happens on this thread.

        Adaptive vote batching (SURVEY §7 "latency discipline"): when the
        head of the queue is a VoteMessage, the CONTIGUOUS run of queued
        VoteMessages behind it is drained and signature-verified as ONE
        BatchVerifier call before the per-vote state transitions run.
        Batch size is whatever accumulated while this thread was busy —
        zero added latency when idle (batch of 1 → serial CPU verify via
        the adaptive backend), device-sized batches exactly when vote
        traffic is heavy (catch-up peers, large valsets). Queue order is
        preserved: draining stops at the first non-vote message."""
        try:
            while not self._done.is_set():
                try:
                    item = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    if item[0] == "msg" and isinstance(item[1][1], VoteMessage):
                        votes = [item[1]]
                        tail = None
                        while len(votes) < MAX_VOTE_BATCH:
                            try:
                                nxt = self._queue.get_nowait()
                            except queue.Empty:
                                break
                            if nxt[0] == "msg" and isinstance(nxt[1][1], VoteMessage):
                                votes.append(nxt[1])
                            else:
                                tail = nxt
                                break
                        # dispatch the batched signature verification
                        # BEFORE the WAL writes: the (fsync'd) write of
                        # the drained run overlaps the device round trip
                        finish = None
                        if len(votes) > 1:
                            finish = self._preverify_votes_begin(
                                [m.vote for _, m in votes])
                        try:
                            for peer_id, msg in votes:
                                if peer_id == "":
                                    self.wal.write_sync((peer_id, msg))  # :604-609
                                else:
                                    self.wal.write((peer_id, msg))
                            self._handle_vote_msgs(votes, finish)
                        finally:
                            # the tail was already dequeued — it must not
                            # be lost to a WAL or vote-handling exception
                            if tail is not None:
                                self._handle_item(tail)
                    elif item[0] == "msg" and isinstance(
                            item[1][1], HandelContributionMessage):
                        # same drain idiom for Handel contributions: a
                        # contiguous run becomes ONE multi-pair check in
                        # the session (bls.verify_aggregates_many)
                        run = [item[1][1]]
                        tail = None
                        while len(run) < MAX_VOTE_BATCH:
                            try:
                                nxt = self._queue.get_nowait()
                            except queue.Empty:
                                break
                            if nxt[0] == "msg" and isinstance(
                                    nxt[1][1], HandelContributionMessage):
                                run.append(nxt[1][1])
                            else:
                                tail = nxt
                                break
                        try:
                            with self._mutating():
                                self._add_handel_contributions(
                                    run, item[1][0])
                        finally:
                            if tail is not None:
                                self._handle_item(tail)
                    else:
                        self._handle_item(item)
                except Exception:
                    LOG.exception("error in consensus receive loop")
        finally:
            self._stopped.set()

    @contextmanager
    def _mutating(self):
        """Seqlock bracket around one receive-loop processing burst: any
        RoundState mutation inside is invisible to stamped readers
        until write_end. Re-entrant on the writer thread (the vote
        path's tail handling nests). The outermost exit publishes a
        fresh consistent snapshot for readers that lose the race."""
        import copy

        self._rs_stamp.write_begin()
        try:
            yield
        finally:
            self._rs_stamp.write_end()
            if not self._rs_stamp.is_writer():
                self._rs_published = (self._rs_stamp.gen,
                                      copy.copy(self.rs))

    def _handle_item(self, item) -> None:
        # the seqlock bracket covers ONLY the state transition, not the
        # WAL write (an fsync-scale stall inside the bracket would keep
        # the generation odd for milliseconds and starve every stamped
        # reader into torn-skip fallbacks — gossip ticks would mostly
        # no-op under load)
        kind, payload = item
        if kind == "msg":
            peer_id, msg = payload
            if isinstance(msg, HandelContributionMessage):
                # transient overlay traffic is never WAL'd: it is
                # re-derivable, and replaying pairing checks would slow
                # crash recovery for zero safety (the certificates it
                # yields re-enter through absorb_certificate's gates)
                with self._mutating():
                    self._handle_msg(msg, peer_id)
                return
            if peer_id == "":
                self.wal.write_sync((peer_id, msg))  # :604-609
            else:
                self.wal.write((peer_id, msg))
            with self._mutating():
                self._handle_msg(msg, peer_id)
        elif kind == "timeout":
            ti: TimeoutInfo = payload
            self.wal.write(ti)
            with self._mutating():
                self._handle_timeout(ti)

    def _handle_vote_msgs(self, items, finish=None) -> None:
        """Apply a drained run of VoteMessages: one batched signature
        verification (per-item masks), then the normal per-vote
        transition logic with the verify skipped for items that passed.
        `finish` is the callable returned by _preverify_votes_begin when
        the receive loop already dispatched the batch (to overlap the
        WAL write with the device round trip)."""
        if len(items) == 1:
            peer_id, msg = items[0]
            with self._mutating():
                self._try_add_vote(msg.vote, peer_id)
            return
        if finish is None:
            finish = self._preverify_votes_begin(
                [m.vote for _, m in items])
        # wait for the (device) verification OUTSIDE the bracket: the
        # round trip is milliseconds and mutates nothing — only the
        # tally/transition loop below needs tear protection
        mask = finish()
        with self._mutating():
            for (peer_id, msg), ok in zip(items, mask):
                self._try_add_vote(msg.vote, peer_id, verified=ok)

    def _preverify_votes(self, votes) -> List[bool]:
        """Batch-verify vote signatures against the SAME (valset, chain_id)
        the per-vote add path would use: rs.validators for the current
        height, the LastCommit's valset for late precommits. Votes that
        can't be mapped (wrong height/index/address) come back False and
        take the serial path's normal rejection."""
        return self._preverify_votes_begin(votes)()

    def _preverify_votes_begin(self, votes) -> Callable[[], List[bool]]:
        """Start batched signature verification for a drained vote run.
        The triples are collected synchronously — they read RoundState,
        which this (receive) thread owns — and the batch is dispatched
        async when [crypto] async_dispatch is on, so the caller can
        overlap the run's WAL writes with the device round trip. The
        returned callable blocks for and returns the per-vote mask."""
        from ..crypto import batch as crypto_batch

        triples, slots = self._collect_vote_triples(votes)
        n = len(votes)
        if not triples:
            return lambda: [False] * n

        def _map(mask) -> List[bool]:
            return [bool(mask[s]) if s is not None else False for s in slots]

        tracer = self.tracer
        height = self.rs.height
        if crypto_batch.async_enabled():
            bv = crypto_batch.new_batch_verifier()
            for t in triples:
                bv.add(*t)
            fut = bv.verify_async()

            def finish() -> List[bool]:
                with tracer.span("consensus.preverifyVotes", cat="consensus",
                                 n=n, height=height):
                    return _map(fut.result())

            return finish

        def finish_sync() -> List[bool]:
            with tracer.span("consensus.preverifyVotes", cat="consensus",
                             n=n, height=height):
                return _map(crypto_batch.batch_verify(triples))

        return finish_sync

    def _collect_vote_triples(self, votes):
        """Map each vote to its (sign_bytes, sig, pubkey) triple, or to
        no slot when it can't be mapped (wrong height/index/address)."""
        rs = self.rs
        chain_id = self.state.chain_id
        triples = []
        slots: List[Optional[int]] = []
        for vote in votes:
            val_set = None
            if vote.height == rs.height:
                val_set = rs.validators
            elif (
                vote.height + 1 == rs.height
                and rs.last_commit is not None
                and vote.type == VOTE_TYPE_PRECOMMIT
            ):
                val_set = rs.last_commit.val_set
            slot = None
            if (
                val_set is not None
                and 0 <= vote.validator_index < len(val_set)
                and vote.signature is not None
                and len(vote.signature) in (64, 96)  # ed25519 | bls12381
            ):
                addr, val = val_set.get_by_index(vote.validator_index)
                if addr == vote.validator_address:
                    slot = len(triples)
                    triples.append(
                        (vote.sign_bytes(chain_id), vote.signature, val.pub_key.bytes())
                    )
            slots.append(slot)
        return triples, slots

    def _handle_msg(self, msg, peer_id: str) -> None:
        """reference handleMsg :625-674"""
        if isinstance(msg, ProposalMessage):
            self.set_proposal_fn(msg.proposal)
            # mark AFTER set_proposal accepted it (signature verified,
            # height/round matched): a byzantine peer must not steal the
            # first-wins attribution with a garbage proposal, nor churn
            # the bounded timeline window with unvalidated heights.
            # "" peer_id = our own signed proposal.
            if self.rs.proposal is msg.proposal:
                self.timeline.mark(self.rs.height, "proposal_received",
                                   peer_id=peer_id,
                                   round_=msg.proposal.round)
        elif isinstance(msg, BlockPartMessage):
            self._add_proposal_block_part(msg, peer_id)
        elif isinstance(msg, VoteMessage):
            self._try_add_vote(msg.vote, peer_id)
        elif isinstance(msg, AggregateCommitMessage):
            self._add_aggregate_certificate(msg.commit, peer_id)
        elif isinstance(msg, HandelContributionMessage):
            self._add_handel_contributions([msg], peer_id)
        else:
            LOG.warning("unknown message type %s", type(msg))

    def _add_handel_contributions(self, msgs, peer_id: str) -> None:
        """Handel overlay receive lane: feed a drained run of level
        contributions into their sessions (one multi-pair aggregate
        check per run via bls.verify_aggregates_many) and route any
        quorum-crossing aggregate through the SAME
        _add_aggregate_certificate gate the flat gossip lane uses —
        absorb_certificate re-verifies it, so the overlay adds zero
        trust surface."""
        rs = self.rs
        if self.handel is None or rs.validators is None:
            return
        _, _, certs = self.handel.absorb(
            msgs, rs.validators, rs.height, time.monotonic())
        for cert in certs:
            # "" peer attribution: the certificate was assembled locally
            # from verified contributions, not received on the wire
            self._add_aggregate_certificate(cert, peer_id="")

    def _add_aggregate_certificate(self, cert, peer_id: str) -> None:
        """Handel-lite lane: merge a gossiped precommit certificate into
        the matching VoteSet (current height) or LastCommit (previous
        height). Verification and composability live in
        VoteSet.absorb_certificate; a merged certificate drives the
        same step transitions a 2/3-crossing precommit would."""
        rs = self.rs
        if cert is None:
            return
        if cert.agg_height == rs.height and rs.votes is not None:
            vs = rs.votes.precommits(cert.agg_round)
            if vs is None:
                return
            if vs.absorb_certificate(cert, peer_id=peer_id):
                self.metrics.agg_gossip_merges.inc()
                self.n_agg_merges += 1
                LOG.debug("absorbed aggregate certificate %s from %s",
                          cert, peer_id[:8] if peer_id else "self")
                self._on_precommit_progress(cert.agg_round)
        elif (cert.agg_height + 1 == rs.height
              and rs.last_commit is not None
              and cert.agg_round == rs.last_commit.round):
            if rs.last_commit.absorb_certificate(cert, peer_id=peer_id):
                self.metrics.agg_gossip_merges.inc()
                self.n_agg_merges += 1
                if self.config.skip_timeout_commit and rs.last_commit.has_all():
                    self._enter_new_round(rs.height, 0)

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        """reference handleTimeout :677-711"""
        rs = self.rs
        if ti.height != rs.height or ti.round < rs.round or (
            ti.round == rs.round and ti.step < rs.step
        ):
            return
        if ti.step == STEP_NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == STEP_NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif ti.step == STEP_PROPOSE:
            self.event_bus.publish_timeout_propose(self.get_round_state())
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == STEP_PREVOTE_WAIT:
            self.event_bus.publish_timeout_wait(self.get_round_state())
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == STEP_PRECOMMIT_WAIT:
            self.event_bus.publish_timeout_wait(self.get_round_state())
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)
        else:
            raise RuntimeError(f"invalid timeout step {ti.step}")

    def _schedule_timeout(self, duration: float, height: int, round_: int, step: int) -> None:
        self.ticker.schedule_timeout(TimeoutInfo(duration, height, round_, step))

    def _schedule_round0(self, rs: RoundState) -> None:
        """reference scheduleRound0 :324-329"""
        sleep = max(0.0, rs.start_time - time.time())
        self._schedule_timeout(sleep, rs.height, 0, STEP_NEW_HEIGHT)

    # --- transitions --------------------------------------------------------

    def _enter_new_round(self, height: int, round_: int) -> None:
        """reference enterNewRound :730-794"""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != STEP_NEW_HEIGHT
        ):
            return
        LOG.debug("enterNewRound(%d/%d) cur=%s", height, round_, rs)

        with self._step_span("enterNewRound", "new_round", height, round_):
            # round advance: rotate proposer (reference :747-753)
            validators = rs.validators
            if rs.round < round_:
                validators = validators.copy()
                validators.increment_proposer_priority(round_ - rs.round)

            if rs.round != round_:
                self._round_entered = time.time()
            # round-churn accounting: entry counts per (height, round)
            # let stitched fleet traces tell "extra rounds" apart from
            # "slow gossip" (first-wins marks alone cannot)
            self.timeline.mark_round(height, round_)
            rs.round = round_
            rs.step = STEP_NEW_ROUND
            rs.validators = validators
            if round_ != 0:
                # round 0 fields were set in update_to_state (reference :760-768)
                rs.proposal = None
                rs.proposal_block = None
                rs.proposal_block_parts = None
            rs.votes.set_round(round_ + 1)
            rs.triggered_timeout_precommit = False
            self.event_bus.publish_new_round(self.get_round_state())
            self._new_step()

            # WaitForTxs semantics (reference :775-792 + config.WaitForTxs):
            # with create_empty_blocks off (or paced by an interval), an empty
            # mempool waits — except when a proof block is needed (app hash
            # changed; needProofBlock :713-721)
            wait_for_txs = (
                (not self.config.create_empty_blocks or self.config.create_empty_blocks_interval > 0)
                and round_ == 0
                and self.mempool is not None
                and self.mempool.size() == 0
                and not self._need_proof_block(height)
            )
            if wait_for_txs:
                if self.config.create_empty_blocks_interval > 0:
                    self._schedule_timeout(
                        self.config.create_empty_blocks_interval, height, round_, STEP_NEW_ROUND
                    )
                self.mempool.notify_txs_available(
                    lambda: self._queue.put(("timeout", TimeoutInfo(0, height, round_, STEP_NEW_ROUND)))
                )
                return
        self._enter_propose(height, round_)

    def _need_proof_block(self, height: int) -> bool:
        """A block is needed even without txs when the app hash changed,
        to get the new hash signed (reference needProofBlock :713-721)."""
        if height == 1:
            return True
        last_meta = self.block_store.load_block_meta(height - 1)
        return last_meta is None or self.state.app_hash != last_meta.header.app_hash

    def _enter_propose(self, height: int, round_: int) -> None:
        """reference enterPropose :800-847"""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= STEP_PROPOSE
        ):
            return
        LOG.debug("enterPropose(%d/%d)", height, round_)
        # if we already have the complete proposal, go straight to prevote
        # (guarded at the end, reference :812-820); the cascade runs
        # OUTSIDE the step span so 'propose' never includes prevote time
        try:
            with self._step_span("enterPropose", "propose", height, round_):
                rs.round = round_
                rs.step = STEP_PROPOSE
                self._new_step()

                self._schedule_timeout(self.config.propose(round_), height, round_, STEP_PROPOSE)

                if self.priv_validator is None:
                    return
                if not self.is_proposer():
                    return
                self.decide_proposal(height, round_)
        finally:
            if self._is_proposal_complete():
                self._enter_prevote(height, round_)

    def _default_decide_proposal(self, height: int, round_: int) -> None:
        """reference defaultDecideProposal :850-905; skipped during WAL
        replay (the original signed proposal is in the WAL)."""
        if self._replay_mode:
            return
        rs = self.rs
        if rs.valid_block is not None:
            # re-propose the valid block (the most recent polka winner;
            # a locked block is always also the valid block since locking
            # requires the complete proposal) — reference :855-858
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            made = self._create_proposal_block()
            if made is None:
                return
            block, block_parts = made

        # POLRound is OUR valid_round (reference :868 NewProposal(...,
        # cs.ValidRound, ...)), never a live polka query: a nil polka in
        # the CURRENT round would make pol_round == round, which every
        # honest node (including us) rejects as an invalid proposal.
        pol_round = rs.valid_round
        pol_block_id = (
            BlockID(hash=block.hash(), parts_header=block_parts.header())
            if pol_round >= 0 else BlockID()
        )
        proposal = Proposal(
            height=height,
            round=round_,
            block_parts_header=block_parts.header(),
            pol_round=pol_round,
            pol_block_id=pol_block_id,
            timestamp=now_ns(),
        )
        try:
            self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception:
            LOG.exception("propose: failed to sign proposal")
            return
        # proposer-only mark: the signed proposal leaves for gossip HERE
        # — fleettrace's proposal_build/delivery boundary
        self.timeline.mark(height, "proposal_emit", round_=round_)
        self._send_internal(ProposalMessage(proposal))
        for i in range(block_parts.total()):
            self._send_internal(BlockPartMessage(height, round_, block_parts.get_part(i)))
        LOG.info("signed proposal %s", proposal)

    def _create_proposal_block(self):
        """reference createProposalBlock :907-940"""
        rs = self.rs
        if rs.height == 1:
            commit = Commit(block_id=BlockID(), precommits=[])
            commit_ok = True
        elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            commit = rs.last_commit.make_commit()
            commit_ok = True
        else:
            commit_ok = False
        if not commit_ok:
            LOG.error("propose step; cannot propose without LastCommit")
            return None

        max_bytes = self.state.consensus_params.block_size.max_bytes
        max_gas = self.state.consensus_params.block_size.max_gas
        if self.mempool is not None:
            txs = self.mempool.reap_max_bytes_max_gas(max_bytes // 2, max_gas)
        else:
            txs = []
        evidence = self.evpool.pending_evidence() if self.evpool is not None else []
        proposer = self.priv_validator.get_address()
        from ..types.block import AggregateCommit

        if rs.height == 1:
            t = self.state.last_block_time  # genesis time (reference state.go:146)
        elif isinstance(commit, AggregateCommit):
            # BLS lane: no per-vote timestamps to take a median of — the
            # proposer's clock sets block time, clamped strictly past the
            # previous block (validators enforce monotonicity only)
            t = max(now_ns(),
                    self.state.last_block_time + self.config.blocktime_iota)
        else:
            t = sm_state.median_time(commit, self.state.last_validators)
        block = self.state.make_block(rs.height, txs, commit if rs.height > 1 else None, evidence, proposer, time_ns=t)
        if rs.height == 1:
            block.last_commit = None
        from ..types.block import make_part_set

        return block, make_part_set(block)

    def _is_proposal_complete(self) -> bool:
        """reference isProposalComplete :796-809"""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _enter_prevote(self, height: int, round_: int) -> None:
        """reference enterPrevote :942-975"""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= STEP_PREVOTE
        ):
            return
        LOG.debug("enterPrevote(%d/%d)", height, round_)
        with self._step_span("enterPrevote", "prevote", height, round_):
            rs.round = round_
            rs.step = STEP_PREVOTE
            self._new_step()
            self.do_prevote(height, round_)

    def _default_do_prevote(self, height: int, round_: int) -> None:
        """reference defaultDoPrevote :977-995"""
        rs = self.rs
        if rs.locked_block is not None:
            self._speculate(rs.locked_block)
            self._sign_add_vote(VOTE_TYPE_PREVOTE, rs.locked_block.hash(), rs.locked_block_parts.header())
            return
        if rs.proposal_block is None:
            self._sign_add_vote(VOTE_TYPE_PREVOTE, b"", None)
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except Exception as e:
            LOG.warning("prevote: ProposalBlock is invalid: %s", e)
            self._sign_add_vote(VOTE_TYPE_PREVOTE, b"", None)
            return
        # the block we are about to prevote is the likely decision:
        # start executing it NOW on the speculation thread so commit
        # only finalizes already-computed state ([execution]
        # speculative; adopted at finalize only on exact block +
        # base-state match, discarded otherwise)
        self._speculate(rs.proposal_block)
        self._sign_add_vote(
            VOTE_TYPE_PREVOTE, rs.proposal_block.hash(), rs.proposal_block_parts.header()
        )

    def _speculate(self, block) -> None:
        if block is None or not self.block_exec.speculation_enabled:
            return
        try:
            self.block_exec.begin_speculation(self.state, block)
        except Exception:  # noqa: BLE001 - speculation must never stall a vote
            LOG.exception("begin_speculation failed (ignored)")

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        """reference enterPrevoteWait :997-1022"""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= STEP_PREVOTE_WAIT
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise RuntimeError("enter_prevote_wait without +2/3 prevotes (any)")
        LOG.debug("enterPrevoteWait(%d/%d)", height, round_)
        with self._step_span("enterPrevoteWait", "prevote_wait", height, round_):
            rs.round = round_
            rs.step = STEP_PREVOTE_WAIT
            self._new_step()
            self._schedule_timeout(self.config.prevote(round_), height, round_, STEP_PREVOTE_WAIT)

    def _enter_precommit(self, height: int, round_: int) -> None:
        """reference enterPrecommit :1025-1118 — the POL lock/unlock
        logic."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step >= STEP_PRECOMMIT
        ):
            return
        LOG.debug("enterPrecommit(%d/%d)", height, round_)
        with self._step_span("enterPrecommit", "precommit", height, round_):
            rs.round = round_
            rs.step = STEP_PRECOMMIT
            self._new_step()

            prevotes = rs.votes.prevotes(round_)
            block_id = prevotes.two_thirds_majority() if prevotes else None

            # no polka: precommit nil (reference :1044-1052)
            if block_id is None:
                self._sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", None)
                return

            self.event_bus.publish_polka(self.get_round_state())

            # polka for nil: unlock if locked (reference :1061-1075)
            if not block_id.hash:
                if rs.locked_block is not None:
                    rs.locked_round = -1
                    rs.locked_block = None
                    rs.locked_block_parts = None
                    self.event_bus.publish_unlock(self.get_round_state())
                self._sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", None)
                return

            # polka for our locked block: re-lock (reference :1078-1086)
            if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
                rs.locked_round = round_
                self.event_bus.publish_relock(self.get_round_state())
                self._sign_add_vote(VOTE_TYPE_PRECOMMIT, block_id.hash, block_id.parts_header)
                return

            # polka for our proposal block: lock it (reference :1089-1103)
            if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                try:
                    # decided=True: +2/3 already prevoted this block, so
                    # SUBJECTIVE proposal-time checks (the aggregate-lane
                    # clock-drift bound) must not be re-asserted — a
                    # clock-lagging validator that re-judged timeliness
                    # here would abstain from a polka'd block and lose
                    # its precommit every affected round
                    self.block_exec.validate_block(self.state, rs.proposal_block,
                                                   decided=True)
                except Exception as e:
                    raise RuntimeError(f"enter_precommit: +2/3 prevoted an invalid block: {e}")
                rs.locked_round = round_
                rs.locked_block = rs.proposal_block
                rs.locked_block_parts = rs.proposal_block_parts
                self.event_bus.publish_lock(self.get_round_state())
                self._sign_add_vote(VOTE_TYPE_PRECOMMIT, block_id.hash, block_id.parts_header)
                return

            # polka for a block we don't have: unlock, fetch (reference :1106-1116)
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                block_id.parts_header
            ):
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet(block_id.parts_header)
            self.event_bus.publish_unlock(self.get_round_state())
            self._sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", None)

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        """reference enterPrecommitWait :1121-1146"""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise RuntimeError("enter_precommit_wait without +2/3 precommits (any)")
        LOG.debug("enterPrecommitWait(%d/%d)", height, round_)
        with self._step_span("enterPrecommitWait", "precommit_wait", height, round_):
            rs.triggered_timeout_precommit = True
            self._new_step()
            self._schedule_timeout(self.config.precommit(round_), height, round_, STEP_PRECOMMIT_WAIT)

    def _enter_commit(self, height: int, commit_round: int) -> None:
        """reference enterCommit :1149-1198"""
        rs = self.rs
        if rs.height != height or rs.step >= STEP_COMMIT:
            return
        LOG.debug("enterCommit(%d/%d)", height, commit_round)
        self.timeline.mark(height, "commit", round_=commit_round)
        try:
            with self._step_span("enterCommit", "commit", height, commit_round):
                rs.step = STEP_COMMIT
                rs.commit_round = commit_round
                rs.commit_time = time.time()

                block_id = rs.votes.precommits(commit_round).two_thirds_majority()
                if block_id is None:
                    raise RuntimeError("enter_commit without +2/3 precommit majority")
                # our locked block IS the committed block (reference :1168-1174)
                if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
                    rs.proposal_block = rs.locked_block
                    rs.proposal_block_parts = rs.locked_block_parts
                if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
                    if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                        block_id.parts_header
                    ):
                        # need to fetch the committed block (reference :1180-1190)
                        rs.proposal_block = None
                        rs.proposal_block_parts = PartSet(block_id.parts_header)
        finally:
            # the reference runs newStep in a defer (:1152-1160), i.e.
            # AFTER ProposalBlockParts is set — the step event carries the
            # parts header the reactor's CommitStepMessage advertises; an
            # event fired before the parts are set would deadlock catch-up.
            # Both run OUTSIDE the step span so 'commit' never includes
            # finalize_commit time (that has its own histogram label).
            self._new_step()
            self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        """reference tryFinalizeCommit :1201-1222"""
        rs = self.rs
        if rs.height != height:
            raise RuntimeError("try_finalize_commit wrong height")
        block_id = rs.votes.precommits(rs.commit_round).two_thirds_majority()
        if block_id is None or not block_id.hash:
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return  # don't have the block yet
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """reference finalizeCommit :1225-1318 — the fsync-ordered commit
        sequence with fail points."""
        rs = self.rs
        if rs.height != height or rs.step != STEP_COMMIT:
            return
        with self._step_span("finalizeCommit", "finalize_commit", height, rs.commit_round):
            block_id = rs.votes.precommits(rs.commit_round).two_thirds_majority()
            block, block_parts = rs.proposal_block, rs.proposal_block_parts
            if block is None or block.hash() != block_id.hash:
                raise RuntimeError("cannot finalize: no proposal block / hash mismatch")

            # 2/3 already precommitted this block — it is decided, so
            # proposal-time-only checks (agg clock drift) don't apply
            self.block_exec.validate_block(self.state, block, decided=True)  # :1243

            LOG.info(
                "finalizing commit of block h=%d hash=%s txs=%d",
                block.header.height,
                (block.hash() or b"").hex()[:12],
                len(block.data.txs),
            )

            fail.fail_point("FinalizeCommit.BeforeSave")  # :1251
            if self.block_store.height() < block.header.height:
                seen_commit = rs.votes.precommits(rs.commit_round).make_commit()
                from ..types.block import AggregateCommit

                if isinstance(seen_commit, AggregateCommit):
                    self.last_agg_cert_bytes = seen_commit.size_bytes()
                    from ..crypto import batch as crypto_batch

                    cm = crypto_batch.get_metrics()
                    if cm is not None:
                        cm.agg_commit_size_bytes.set(self.last_agg_cert_bytes)
                self.block_store.save_block(block, block_parts, seen_commit)  # :1254-1259
            fail.fail_point("FinalizeCommit.AfterSave")  # :1265

            # WAL EndHeight BEFORE ApplyBlock: on crash we replay from here and
            # the handshake re-applies the block to the app (reference :1271-1285)
            _t_wal = time.perf_counter()
            self.wal.write_end_height(height)
            _sp = getattr(self.block_exec, "stage_profile", None)
            if _sp is not None:  # stub executors in tests have none
                _sp.observe("wal", time.perf_counter() - _t_wal)
            self.timeline.mark(height, "wal_fsync", round_=rs.commit_round)
            fail.fail_point("FinalizeCommit.AfterWAL")  # :1282

            state_copy = self.state.copy()
            try:
                state_copy = self.block_exec.apply_block(
                    state_copy, BlockID(block.hash(), block_parts.header()), block
                )
            except Exception:
                LOG.exception("failed to apply block; exiting consensus")
                raise
            self.timeline.mark(height, "apply_block", round_=rs.commit_round)
            fail.fail_point("FinalizeCommit.AfterApplyBlock")  # :1300

            self.n_height_committed += 1
            if self.incidents is not None:
                self.incidents.note_commit(height)
            self._record_metrics(block, block_parts)
            self.update_to_state(state_copy)  # :1306
            self._schedule_round0(self.rs)  # :1312

    def _record_metrics(self, block, block_parts) -> None:
        """reference consensus/state.go recordMetrics:1320-1350."""
        m = self.metrics
        m.height.set(block.header.height)
        m.committed_height.set(block.header.height)
        m.rounds.set(self.rs.round)
        if self.rs.validators is not None:
            m.validators.set(len(self.rs.validators))
            m.validators_power.set(self.rs.validators.total_voting_power())
        if block.last_commit is not None:
            from ..types.block import AggregateCommit

            if isinstance(block.last_commit, AggregateCommit):
                m.missing_validators.set(block.last_commit.num_absent())
            else:
                m.missing_validators.set(
                    sum(1 for v in block.last_commit.precommits if v is None))
        m.byzantine_validators.set(len(block.evidence.evidence))
        m.num_txs.set(len(block.data.txs))
        m.total_txs.add(len(block.data.txs))
        # the part set already holds the encoded block — no re-encode
        m.block_size_bytes.set(sum(
            len(block_parts.get_part(i).bytes)
            for i in range(block_parts.total())
            if block_parts.get_part(i) is not None))
        prev = self.block_store.load_block_meta(block.header.height - 1)
        if prev is not None:
            m.block_interval_seconds.observe(
                max(block.header.time - prev.header.time, 0) / 1e9)

    # --- proposal handling --------------------------------------------------

    def _default_set_proposal(self, proposal: Proposal) -> None:
        """reference defaultSetProposal :1324-1357"""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            proposal.pol_round >= 0 and proposal.pol_round >= proposal.round
        ):
            raise ErrVoteInvalid("invalid proposal POL round")
        proposer = rs.validators.get_proposer()
        if not proposer.pub_key.verify_bytes(
            proposal.sign_bytes(self.state.chain_id), proposal.signature
        ):
            raise ErrVoteInvalid("invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet(proposal.block_parts_header)
        LOG.info("received proposal %s", proposal)

    def _add_proposal_block_part(self, msg: BlockPartMessage, peer_id: str) -> bool:
        """reference addProposalBlockPart :1361-1462"""
        rs = self.rs
        if msg.height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        try:
            added = rs.proposal_block_parts.add_part(msg.part)
        except ValueError as e:
            # a part whose proof fails against OUR current parts header
            # is usually not malice: gossip for the previous round's
            # proposal racing our round change lands here (the sender's
            # view of our round was a beat stale). Reject the part,
            # keep the peer and the receive loop.
            LOG.debug("rejecting block part h=%d r=%d from %s: %s",
                      msg.height, msg.round, peer_id[:8] or "self", e)
            return False
        if not added:
            return False
        if rs.proposal_block_parts.is_complete():
            from ..types import serde

            rs.proposal_block = serde.decode_block(rs.proposal_block_parts.assemble())
            LOG.info("received complete proposal block %s", rs.proposal_block)
            self.event_bus.publish_complete_proposal(self.get_round_state())

            prevotes = rs.votes.prevotes(rs.round)
            block_id = prevotes.two_thirds_majority() if prevotes else None
            if block_id is not None and block_id.hash and rs.valid_round < rs.round:
                if rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = rs.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts

            if rs.step <= STEP_PROPOSE and self._is_proposal_complete():
                self._enter_prevote(rs.height, rs.round)
            elif rs.step == STEP_COMMIT:
                self._try_finalize_commit(rs.height)
        return True

    # --- vote handling ------------------------------------------------------

    def _try_add_vote(self, vote: Vote, peer_id: str, verified: bool = False) -> bool:
        """reference tryAddVote :1468-1493 — conflicting votes become
        evidence. verified=True: signature already checked by the batched
        pre-verification in _handle_vote_msgs."""
        try:
            return self._add_vote(vote, peer_id, verified=verified)
        except ErrVoteConflictingVotes as e:
            if self.priv_validator is not None and vote.validator_address == self.priv_validator.get_address():
                LOG.error("found conflicting vote from ourselves: %s", vote)
                return False
            if self.evpool is not None:
                from ..types.evidence import DuplicateVoteEvidence

                _, val = self.rs.validators.get_by_address(vote.validator_address)
                if val is not None:
                    self.evpool.add_evidence(
                        DuplicateVoteEvidence(val.pub_key, e.vote_a, e.vote_b)
                    )
            return False
        except ErrVoteInvalid as e:
            LOG.warning("invalid vote from %s: %s", peer_id or "self", e)
            return False

    def _add_vote(self, vote: Vote, peer_id: str, verified: bool = False) -> bool:
        """reference addVote :1495-1639"""
        rs = self.rs

        # late precommit for the previous height (reference :1504-1527)
        if vote.height + 1 == rs.height:
            if not (vote.type == VOTE_TYPE_PRECOMMIT and rs.step == STEP_NEW_HEIGHT and rs.last_commit is not None):
                return False
            added = rs.last_commit.add_vote(vote, verified=verified)
            if added:
                LOG.debug("added late precommit to last commit: %s", rs.last_commit)
                self.timeline.mark_vote(vote.height, "precommit",
                                        vote.validator_index, peer_id,
                                        round_=vote.round)
                self.event_bus.publish_vote(vote)
                if self.on_vote_added is not None:
                    self.on_vote_added(vote)
                if self.config.skip_timeout_commit and rs.last_commit.has_all():
                    self._enter_new_round(rs.height, 0)
            return added

        if vote.height != rs.height:
            LOG.debug("vote ignored: wrong height %d vs %d", vote.height, rs.height)
            return False

        added = rs.votes.add_vote(vote, peer_id, verified=verified)
        if not added:
            return False
        self.timeline.mark_vote(
            vote.height,
            "prevote" if vote.type == VOTE_TYPE_PREVOTE else "precommit",
            vote.validator_index, peer_id, round_=vote.round)
        self.event_bus.publish_vote(vote)
        if self.on_vote_added is not None:
            self.on_vote_added(vote)

        if vote.type == VOTE_TYPE_PREVOTE:
            self._on_prevote_added(vote)
        elif vote.type == VOTE_TYPE_PRECOMMIT:
            self._on_precommit_added(vote)
        return True

    def _on_prevote_added(self, vote: Vote) -> None:
        """reference addVote prevote branch :1539-1601"""
        rs = self.rs
        prevotes = rs.votes.prevotes(vote.round)
        block_id = prevotes.two_thirds_majority()

        if block_id is not None:
            self.timeline.mark(rs.height, "prevote_23", peer_id="",
                               round_=vote.round)
            # unlock on newer polka (reference :1547-1558)
            if (
                rs.locked_block is not None
                and rs.locked_round < vote.round
                and vote.round <= rs.round
                and rs.locked_block.hash() != block_id.hash
            ):
                LOG.info("unlocking because of POL at round %d", vote.round)
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                self.event_bus.publish_unlock(self.get_round_state())
            # valid-block update (reference :1561-1581)
            if block_id.hash and rs.valid_round < vote.round and vote.round == rs.round:
                if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    rs.proposal_block = None
                if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                    block_id.parts_header
                ):
                    rs.proposal_block_parts = PartSet(block_id.parts_header)

        # step transitions (reference :1585-1601)
        if rs.round < vote.round and prevotes.has_two_thirds_any():
            self._enter_new_round(rs.height, vote.round)
        elif rs.round == vote.round and rs.step >= STEP_PREVOTE:
            if block_id is not None and (self._is_proposal_complete() or not block_id.hash):
                self._enter_precommit(rs.height, vote.round)
            elif prevotes.has_two_thirds_any():
                self._enter_prevote_wait(rs.height, vote.round)
        elif rs.proposal is not None and 0 <= rs.proposal.pol_round == vote.round:
            if self._is_proposal_complete():
                self._enter_prevote(rs.height, rs.round)

    def _on_precommit_added(self, vote: Vote) -> None:
        """reference addVote precommit branch :1603-1632"""
        self._on_precommit_progress(vote.round)

    def _on_precommit_progress(self, round_: int) -> None:
        """Shared precommit-quorum transitions: driven by a single added
        vote OR a merged aggregate certificate (the Handel-lite lane) —
        both can cross 2/3 for the round."""
        rs = self.rs
        precommits = rs.votes.precommits(round_)
        block_id = precommits.two_thirds_majority()
        if block_id is not None:
            self.timeline.mark(rs.height, "precommit_23", peer_id="",
                               round_=round_)
            self._enter_new_round(rs.height, round_)
            self._enter_precommit(rs.height, round_)
            if block_id.hash:
                self._enter_commit(rs.height, round_)
                if self.config.skip_timeout_commit and precommits.has_all():
                    self._enter_new_round(rs.height, 0)
            else:
                self._enter_precommit_wait(rs.height, round_)
        elif rs.round <= round_ and precommits.has_two_thirds_any():
            self._enter_new_round(rs.height, round_)
            self._enter_precommit_wait(rs.height, round_)

    # --- vote signing -------------------------------------------------------

    def _sign_vote(self, type_: int, hash_: bytes, header) -> Vote:
        """reference signVote :1641-1668"""
        rs = self.rs
        addr = self.priv_validator.get_address()
        idx, _ = rs.validators.get_by_address(addr)
        from ..types.basic import PartSetHeader

        vote = Vote(
            validator_address=addr,
            validator_index=idx,
            height=rs.height,
            round=rs.round,
            timestamp=self._vote_time(),
            type=type_,
            block_id=BlockID(hash_, header or PartSetHeader()),
        )
        self.priv_validator.sign_vote(self.state.chain_id, vote)
        return vote

    def _vote_time(self) -> int:
        """Vote time must exceed the voted block's time by iota, so the
        next block's median commit time is strictly increasing (reference
        voteTime :1658-1673).

        BLS fast lane: votes carry timestamp 0 — aggregation requires
        every precommit for (height, round, block_id) to sign IDENTICAL
        bytes, and the timestamp is the only per-validator field. Block
        time then comes from the proposer's clock under a strict
        monotonicity rule (PARITY_DEVIATIONS.md)."""
        rs = self.rs
        if rs.validators is not None and rs.validators.is_bls():
            return 0
        now = now_ns()
        min_t = now
        if rs.locked_block is not None:
            min_t = rs.locked_block.header.time + self.config.blocktime_iota
        elif rs.proposal_block is not None:
            min_t = rs.proposal_block.header.time + self.config.blocktime_iota
        return max(now, min_t)

    def _sign_add_vote(self, type_: int, hash_: bytes, header) -> Optional[Vote]:
        """reference signAddVote :1676-1690. Signing happens during WAL
        replay too: the privval double-sign filter makes a re-sign of an
        already-WAL'd vote idempotent (same timestamp restored), and a
        vote that was never signed before the crash — e.g. killed between
        completing the proposal and prevoting — gets signed now, which is
        what un-sticks the height after replay. Sign errors are expected
        in replay (privval may be ahead) and only logged live."""
        rs = self.rs
        if self.priv_validator is None:
            return None
        idx, _ = rs.validators.get_by_address(self.priv_validator.get_address())
        if idx < 0:
            return None  # not a validator
        try:
            vote = self._sign_vote(type_, hash_, header)
        except Exception:
            if not self._replay_mode:
                LOG.exception("failed signing %s vote", "prevote" if type_ == VOTE_TYPE_PREVOTE else "precommit")
            return None
        self._send_internal(VoteMessage(vote))
        if (self.handel is not None and type_ == VOTE_TYPE_PRECOMMIT
                and hash_ != b"" and not self._replay_mode):
            # seed the Handel session with our own precommit — level 1
            # starts offering it on the next reactor tick
            try:
                self.handel.note_own_precommit(vote, rs.validators)
            except Exception:  # noqa: BLE001 - overlay must not kill voting
                LOG.exception("handel: seeding own precommit failed")
        LOG.debug("signed and queued vote %s", vote)
        return vote

    # --- stall diagnostics --------------------------------------------------

    def round_dwell_seconds(self) -> float:
        """Wall seconds since the machine entered the current
        (height, round) — the watchdog's primary signal."""
        return max(0.0, time.time() - self._round_entered)

    def height_dwell_seconds(self) -> float:
        """Wall seconds since the machine entered the current HEIGHT —
        the partition/churn signal: round churn (propose timeout →
        nil prevotes → next round) keeps every per-round dwell short
        while the height itself goes nowhere."""
        return max(0.0, time.time() - self._height_entered)

    def handel_status(self) -> dict:
        """Handel overlay view for /debug/handel and stall_snapshot —
        {"enabled": False} when the overlay is off so the route surface
        is identical either way."""
        if self.handel is None:
            return {"enabled": False}
        try:
            return self.handel.status(time.monotonic())
        except Exception:  # noqa: BLE001 - diagnostics must not raise
            LOG.exception("handel status failed")
            return {"enabled": True, "error": "status failed"}

    def stall_snapshot(self, switch=None, reason: str = "",
                       dwell_s: float = 0.0) -> dict:
        """Structured diagnostic bundle for the current round: RoundState
        summary, vote bit arrays, the validators we're missing votes
        from, per-peer PeerState, and the crypto engine's in-flight
        batch count. Read-only over shallow snapshots, so it is safe to
        call from the watchdog thread while the receive loop runs."""
        from ..crypto import batch as crypto_batch

        rs = self.get_round_state()
        out = {
            "reason": reason,
            "dwell_s": round(dwell_s, 3),
            "time": time.time(),
            "round_state": {
                "height": rs.height,
                "round": rs.round,
                "step": RoundStepType.name(rs.step),
                "start_time": rs.start_time,
                "have_proposal": rs.proposal is not None,
                "have_proposal_block": rs.proposal_block is not None,
                "locked_round": rs.locked_round,
                "valid_round": rs.valid_round,
            },
            "votes": {},
            "n_validators": (len(rs.validators)
                             if rs.validators is not None else 0),
            "missing_validators": [],
            "peers": [],
            "inflight_verify_batches": crypto_batch.inflight_count(),
            # BLS aggregate fast lane: whether this chain runs it, how
            # many gossiped certificates merged, and the last persisted
            # certificate's wire size (monitor surfaces these)
            "agg": {
                "enabled": bool(rs.validators is not None
                                and rs.validators.is_bls()),
                "gossip_merges": self.n_agg_merges,
                "last_cert_bytes": self.last_agg_cert_bytes,
            },
            "handel": self.handel_status(),
        }
        try:
            if rs.votes is not None and rs.validators is not None:
                n_vals = len(rs.validators)
                missing: set = set()
                for name, vs in (("prevotes", rs.votes.prevotes(rs.round)),
                                 ("precommits", rs.votes.precommits(rs.round))):
                    if vs is None:
                        continue
                    ba = vs.bit_array()
                    out["votes"][name] = {
                        "bits": _bits_str(ba),
                        "have": ba.num_true(),
                        "total": n_vals,
                    }
                    missing.update(
                        i for i in range(n_vals) if not ba.get_index(i))
                for i in sorted(missing):
                    addr, _ = rs.validators.get_by_index(i)
                    out["missing_validators"].append(
                        {"index": i, "address": (addr or b"").hex()})
        except Exception:  # noqa: BLE001 - diagnostics must not raise
            LOG.exception("stall snapshot: vote section failed")
        if switch is not None:
            try:
                out["peers"] = _peer_states_json(switch, rs.height)
            except Exception:  # noqa: BLE001
                LOG.exception("stall snapshot: peer section failed")
        return out

    # --- WAL catchup replay -------------------------------------------------

    def _catchup_replay(self, height: int) -> None:
        """Replay WAL messages for `height` after a crash (reference
        catchupReplay :97-155)."""
        msgs = self.wal.search_for_end_height(height - 1)
        if msgs is None:
            if height == 1:
                return
            LOG.info("no WAL data for height %d; relying on handshake", height)
            return
        self._replay_mode = True
        try:
            for m in msgs:
                with self._mutating():
                    self._replay_one(m)
            LOG.info("WAL replay for height %d done: %d messages", height, len(msgs))
        finally:
            self._replay_mode = False

    def _replay_one(self, msg) -> None:
        if isinstance(msg, EndHeightMessage):
            return
        if isinstance(msg, TimedWALMessage):
            msg = msg.msg
        if isinstance(msg, TimeoutInfo):
            self._handle_timeout(msg)
        elif isinstance(msg, tuple):
            peer_id, m = msg
            try:
                self._handle_msg(m, peer_id)
            except Exception:
                LOG.exception("error replaying WAL message")


# --- stall watchdog ---------------------------------------------------------


def _bits_str(ba) -> str:
    """BitArray as a compact '1011…' string for diagnostic bundles."""
    if ba is None:
        return ""
    return "".join("1" if ba.get_index(i) else "0" for i in range(ba.bits))


# a peer that delivered no packet for this long is silent: either gone,
# or the far side of a partition whose writes never reach us. Live
# consensus peers gossip steps/votes many times a second, so anything
# healthy sits far under it; a freshly (re)dialed connection counts as
# silent until its first packet lands — a redial straight into a
# partition (the handshake rides the raw socket, only post-upgrade
# traffic hits the fault rules) must not look reachable. Partition
# classification scales this with the watchdog threshold (a stalled
# production round legitimately goes seconds between messages); this
# default serves the /debug payload's per-peer view.
PEER_SILENT_AFTER_S = 3.0


def _peer_is_silent(peer, after_s: float = PEER_SILENT_AFTER_S) -> bool:
    try:
        last = peer.mconn.last_recv_time
    except Exception:  # noqa: BLE001 - diagnostics never raise
        return True
    return last == 0.0 or time.monotonic() - last >= after_s


def _reachable_peer_count(switch,
                          after_s: float = PEER_SILENT_AFTER_S) -> int:
    """Peers we are actually HEARING from — the quorum-reachability
    input for partition classification."""
    return sum(1 for p in switch.peers.list()
               if not _peer_is_silent(p, after_s))


def _peer_states_json(switch, our_height: int) -> List[dict]:
    """Per-peer consensus PeerState summaries (heights, steps, vote bit
    arrays, lag vs our height) for /debug/consensus and the monitor."""
    peers = []
    for p in switch.peers.list():
        ps = p.get("consensus_peer_state")
        entry = {"peer_id": p.id, "moniker": p.node_info.moniker,
                 "silent": _peer_is_silent(p)}
        if ps is not None:
            prs = ps.get_round_state()
            entry.update({
                "height": prs.height,
                "round": prs.round,
                "step": prs.step,
                "prevotes": _bits_str(prs.prevotes),
                "precommits": _bits_str(prs.precommits),
                "lag_blocks": max(0, our_height - prs.height)
                if prs.height > 0 else 0,
            })
        peers.append(entry)
    return peers


def classify_stall(rs: RoundState, switch=None, state=None,
                   silent_after_s: float = PEER_SILENT_AFTER_S) -> str:
    """Map the stuck round's state to a coarse diagnosis, used as the
    consensus_stalls_total{reason} label (bounded cardinality).

    With network/chain context (the watchdog passes both), two sharper
    diagnoses outrank the generic missing-quorum labels:

    - partition_suspected: quorum is missing AND the peers we can still
      reach cannot possibly carry +2/3 even if every one of them were a
      distinct validator — count-based quorum-reachability, the netchaos
      partition signature.
    - valset_rotation: quorum is missing right after a validator-set
      change took effect (churn epoch) — votes may be aimed at (or
      coming from) a set the sender no longer agrees on.
    """
    if rs.step in (STEP_NEW_HEIGHT, STEP_NEW_ROUND):
        return "slow_round_start"
    if rs.step == STEP_PROPOSE and rs.proposal is None:
        base = "no_proposal"
    elif rs.step == STEP_PROPOSE:
        base = "incomplete_proposal"
    elif rs.step in (STEP_PREVOTE, STEP_PREVOTE_WAIT):
        base = "no_prevote_quorum"
    elif rs.step in (STEP_PRECOMMIT, STEP_PRECOMMIT_WAIT):
        base = "no_precommit_quorum"
    elif rs.step == STEP_COMMIT:
        base = "commit_not_finalized"
    else:
        return "unknown"
    quorum_missing = base in ("no_proposal", "no_prevote_quorum",
                              "no_precommit_quorum")
    if quorum_missing and rs.validators is not None:
        n_vals = len(rs.validators)
        # rotation FIRST: while a validator-set change is still taking
        # effect, missing quorum most likely reflects the churn itself,
        # and the count-based partition heuristic below is unreliable
        # (phantom/offline validators make every peer-count look like a
        # minority). rs.height > 1 guard: genesis state reports
        # last_height_validators_changed == 1, which is bootstrap.
        if (state is not None and rs.height > 1
                and state.last_height_validators_changed >= rs.height):
            return "valset_rotation"
        if switch is not None and n_vals > 1:
            # responsive peers + ourselves: even if every one were a
            # distinct validator, could they carry +2/3?
            reachable = _reachable_peer_count(switch, silent_after_s) + 1
            if 3 * reachable <= 2 * n_vals:
                return "partition_suspected"
    return base


class StallWatchdog:
    """Detects a consensus machine dwelling too long in one
    (height, round) and snapshots why (no reference equivalent; the
    reference leaves operators to diff dump_consensus_state by hand).

    A daemon thread samples ConsensusState.round_dwell_seconds() every
    `interval`, publishes it as consensus_round_dwell_seconds, and —
    once the dwell crosses `threshold_s` — increments
    consensus_stalls_total{reason} and captures a structured diagnostic
    bundle (RoundState, vote BitArrays, missing validators, per-peer
    PeerState, in-flight verify batches). One trip per (height, round):
    a round that stays stuck doesn't spam bundles. Bundles + a live
    snapshot are served at /debug/consensus on the ProfServer. on_tick
    callables run every sample — the node hooks per-peer gauge refresh
    (flow rates, queue depths, p2p_peer_lag_blocks) here so peer
    telemetry shares the watchdog's cadence."""

    def __init__(self, cs: ConsensusState, threshold_s: float = 30.0,
                 switch=None, interval: Optional[float] = None,
                 max_bundles: int = 8,
                 height_threshold_s: Optional[float] = None):
        self.cs = cs
        self.switch = switch
        self.threshold_s = threshold_s
        # height-level stall detection: a partition/churn fault churns
        # ROUNDS (each under threshold_s) while the HEIGHT goes nowhere;
        # default = 3x the round threshold, 0 disables
        if height_threshold_s is None:
            height_threshold_s = 3.0 * threshold_s if threshold_s > 0 else 0.0
        self.height_threshold_s = height_threshold_s
        if interval is None:
            interval = min(1.0, threshold_s / 4.0) if threshold_s > 0 else 1.0
        self.interval = max(0.05, interval)
        self.on_tick: List[Callable[[], None]] = []
        self._bundles: collections.deque = collections.deque(
            maxlen=max_bundles)
        self._stalls_total = 0
        self._flagged: Optional[tuple] = None
        self._flagged_height: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="cs-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - watchdog must outlive bugs
                LOG.exception("stall watchdog tick failed")

    # -- sampling ------------------------------------------------------

    def _tick(self) -> None:
        dwell = self.cs.round_dwell_seconds()
        self.cs.metrics.round_dwell.set(dwell)
        for fn in self.on_tick:
            try:
                fn()
            except Exception:  # noqa: BLE001
                LOG.exception("watchdog on_tick hook failed")
        rs = self.cs.rs
        if self.threshold_s > 0 and dwell >= self.threshold_s:
            # one bundle per (height, round) — unless the DIAGNOSIS
            # shifts while the round stays stuck (e.g. a quorum stall
            # sharpening into partition_suspected once the cut-off
            # peers have been silent long enough): a changed reason
            # records again, a constant one never spams
            reason = self._classify(rs)
            key = (rs.height, rs.round, reason)
            if self._flagged != key:
                self._flagged = key
                self._trip(rs, dwell, "round", reason)
                return
        # height-level detection: rounds may churn under the per-round
        # threshold while the height dwells (partition signature)
        h_dwell = self.cs.height_dwell_seconds()
        if self.height_threshold_s > 0 and h_dwell >= self.height_threshold_s:
            reason = self._classify(rs)
            if self._flagged_height != (rs.height, reason):
                self._flagged_height = (rs.height, reason)
                self._trip(rs, h_dwell, "height", reason)

    def _classify(self, rs: RoundState) -> str:
        # silence cutoff tracks the threshold: a prod deployment's
        # stalled rounds legitimately go seconds between messages, a
        # fast-timeout test net goes milliseconds
        cutoff = max(1.0, min(PEER_SILENT_AFTER_S, self.threshold_s)) \
            if self.threshold_s > 0 else PEER_SILENT_AFTER_S
        return classify_stall(rs, switch=self.switch, state=self.cs.state,
                              silent_after_s=cutoff)

    def _trip(self, rs: RoundState, dwell: float, scope: str,
              reason: str) -> None:
        self.cs.metrics.stalls.with_labels(reason).inc()
        self._stalls_total += 1
        if self.cs.incidents is not None:
            self.cs.incidents.note_detection(
                reason, height=rs.height, round=rs.round,
                scope=scope, dwell_s=round(dwell, 3))
        bundle = self.cs.stall_snapshot(
            switch=self.switch, reason=reason, dwell_s=dwell)
        bundle["scope"] = scope  # which dwell crossed: round | height
        self._bundles.append(bundle)
        LOG.warning(
            "consensus stall (%s): h=%d r=%d dwelt %.1fs reason=%s",
            scope, rs.height, rs.round, dwell, reason)

    # -- export (/debug/consensus) -------------------------------------

    @property
    def stalls_total(self) -> int:
        return self._stalls_total

    def stall_bundles(self) -> List[dict]:
        return list(self._bundles)

    def status(self) -> dict:
        """The /debug/consensus payload: live diagnostics + the bundles
        captured at stall time."""
        dwell = self.cs.round_dwell_seconds()
        rs = self.cs.rs
        return {
            "height": rs.height,
            "round": rs.round,
            "step": RoundStepType.name(rs.step),
            "dwell_s": round(dwell, 3),
            "height_dwell_s": round(self.cs.height_dwell_seconds(), 3),
            "threshold_s": self.threshold_s,
            "height_threshold_s": self.height_threshold_s,
            "stalls_total": self._stalls_total,
            "stalls": list(self._bundles),
            "live": self.cs.stall_snapshot(
                switch=self.switch, reason="live", dwell_s=dwell),
        }


