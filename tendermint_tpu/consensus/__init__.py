"""Consensus: the Tendermint BFT state machine (reference consensus/)."""

from .cstypes import (  # noqa: F401
    HeightVoteSet,
    RoundState,
    RoundStepType,
    STEP_COMMIT,
    STEP_NEW_HEIGHT,
    STEP_NEW_ROUND,
    STEP_PRECOMMIT,
    STEP_PRECOMMIT_WAIT,
    STEP_PREVOTE,
    STEP_PREVOTE_WAIT,
    STEP_PROPOSE,
)
from .state import ConsensusState  # noqa: F401
from .ticker import TimeoutInfo, TimeoutTicker  # noqa: F401
from .wal import WAL, EndHeightMessage, NilWAL, TimedWALMessage  # noqa: F401
