"""Handshake / block replay — reconciling node and app state on boot.

Reference parity: consensus/replay.go. On startup the node asks the app
where it is (ABCI Info) and replays stored blocks the app missed
(ReplayBlocks :267-418 decision table). The WAL catchup replay for the
in-flight height lives in ConsensusState._catchup_replay.

Replayed block commits are verified upstream by the block store's
integrity; the app replay path batches DeliverTxs straight through the
proxy connection.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..abci import types as abci
from ..crypto import pubkey_to_bytes
from ..state import (
    BlockExecutor,
    load_abci_responses,
    save_state,
)
from ..state import store as sm_store
from ..types.basic import BlockID
from ..types.block import make_part_set

LOG = logging.getLogger("consensus.replay")


class HandshakeError(Exception):
    pass


class Handshaker:
    """reference consensus/replay.go:195-260"""

    def __init__(self, state_db, state, block_store, genesis_doc, event_bus=None):
        self.state_db = state_db
        self.initial_state = state
        self.store = block_store
        self.genesis_doc = genesis_doc
        self.event_bus = event_bus
        self.n_blocks = 0
        # inclusive height span of replayed blocks, (0, 0) when none —
        # recovery telemetry (/debug/recovery, tm-monitor [REPLAYED])
        self.replay_from = 0
        self.replay_to = 0

    def handshake(self, proxy_app) -> bytes:
        """Sync app ← chain; returns the app hash after sync (reference
        Handshake :227-260)."""
        res = proxy_app.query.info(abci.RequestInfo(version="tendermint-tpu"))
        app_block_height = res.last_block_height
        app_hash = res.last_block_app_hash
        LOG.info(
            "ABCI handshake: app height=%d hash=%s", app_block_height, app_hash.hex()[:16]
        )
        app_hash = self.replay_blocks(self.initial_state, app_hash, app_block_height, proxy_app)
        LOG.info(
            "completed ABCI handshake: replayed %d blocks, app hash=%s",
            self.n_blocks,
            app_hash.hex()[:16],
        )
        return app_hash

    def replay_blocks(self, state, app_hash: bytes, app_block_height: int, proxy_app) -> bytes:
        """The decision table (reference ReplayBlocks :267-418)."""
        store_block_height = self.store.height()
        state_block_height = state.last_block_height
        LOG.info(
            "ABCI replay: app=%d store=%d state=%d",
            app_block_height,
            store_block_height,
            state_block_height,
        )

        # app is fresh: InitChain (reference :283-320)
        if app_block_height == 0:
            validators = [
                abci.ValidatorUpdate(pub_key=pubkey_to_bytes(v.pub_key), power=v.power)
                for v in self.genesis_doc.validators
            ]
            req = abci.RequestInitChain(
                time=self.genesis_doc.genesis_time,
                chain_id=self.genesis_doc.chain_id,
                validators=validators,
                app_state_bytes=b"",
            )
            res_init = proxy_app.consensus.init_chain(req)
            if state_block_height == 0 and res_init.validators:
                # app dictates the initial validator set (reference :305-315)
                from ..crypto import pubkey_from_bytes
                from ..types.validator_set import Validator, ValidatorSet

                vals = [
                    Validator.new(pubkey_from_bytes(u.pub_key), u.power)
                    for u in res_init.validators
                ]
                state.validators = ValidatorSet(vals)
                state.next_validators = ValidatorSet(vals)
                state.next_validators.increment_proposer_priority(1)
                save_state(self.state_db, state)

        if store_block_height == 0:
            return app_hash

        if store_block_height < app_block_height:
            raise HandshakeError(
                f"app block height {app_block_height} ahead of store {store_block_height}"
            )
        if store_block_height < state_block_height:
            raise HandshakeError(
                f"state height {state_block_height} ahead of store {store_block_height}"
            )
        if store_block_height > state_block_height + 1:
            raise HandshakeError(
                f"store height {store_block_height} > state height {state_block_height}+1"
            )

        if store_block_height == state_block_height:
            # chain state is in sync; catch the app up if needed (:354-365)
            if app_block_height < store_block_height:
                return self._replay_range(state, proxy_app, app_block_height, store_block_height, False)
            return app_hash

        # store == state + 1: block saved but not applied (crash between
        # SaveBlock and ApplyBlock; reference :367-414)
        if app_block_height < state_block_height:
            # app even further behind: replay to store-1 then apply last
            return self._replay_range(state, proxy_app, app_block_height, store_block_height, True)
        if app_block_height == state_block_height:
            # apply the saved block with the real app (:377-388)
            return self._apply_block(state, proxy_app.consensus, store_block_height)
        if app_block_height == store_block_height:
            # app already executed it: replay state-mutation only with a
            # mock app serving stored ABCI responses (:390-404)
            responses = load_abci_responses(self.state_db, store_block_height)
            if responses is None:
                raise HandshakeError(
                    f"no ABCI responses stored for height {store_block_height}"
                )
            mock = _MockProxyApp(app_hash, responses)
            return self._apply_block(state, mock, store_block_height)

        raise HandshakeError(
            f"unhandled replay case app={app_block_height} store={store_block_height} state={state_block_height}"
        )

    def _replay_range(
        self, state, proxy_app, app_block_height: int, store_block_height: int, mutate_state: bool
    ) -> bytes:
        """Replay blocks through the app only (no state mutation), except
        optionally the last one (reference replayBlocks :420-460)."""
        app_hash = b""
        final = store_block_height
        first = app_block_height + 1
        if mutate_state:
            final -= 1
        for height in range(first, final + 1):
            LOG.info("applying block %d (app-only replay)", height)
            block = self.store.load_block(height)
            app_hash = _exec_block_on_app(proxy_app.consensus, block, self.state_db)
            self.n_blocks += 1
            self._note_replayed(height)
        if mutate_state:
            return self._apply_block(state, proxy_app.consensus, store_block_height)
        return app_hash

    def _note_replayed(self, height: int) -> None:
        if self.replay_from == 0:
            self.replay_from = height
        self.replay_to = max(self.replay_to, height)

    def _apply_block(self, state, app_conn, height: int):
        """Full ApplyBlock for the stored block at `height` (reference
        replayBlock :462-480)."""
        block = self.store.load_block(height)
        part_set = make_part_set(block)
        block_exec = BlockExecutor(self.state_db, app_conn, event_bus=self.event_bus)
        new_state = block_exec.apply_block(
            state, BlockID(block.hash(), part_set.header()), block
        )
        self.n_blocks += 1
        self._note_replayed(height)
        self.initial_state = new_state
        return new_state.app_hash


def _exec_block_on_app(app_conn, block, state_db) -> bytes:
    """BeginBlock→DeliverTx→EndBlock→Commit against the app only
    (reference ExecCommitBlock, state/execution.go:509-525; no chain-state
    mutation, returns the app hash). BeginBlock carries the same
    last-commit vote info as original execution, loaded from the
    historical validator store (reference getBeginBlockValidatorInfo)."""
    from ..state.execution import make_last_commit_info

    last_validators = None
    if block.header.height > 1:
        try:
            last_validators = sm_store.load_validators(state_db, block.header.height - 1)
        except sm_store.NoValSetForHeightError:
            LOG.warning(
                "no historical valset for height %d; replaying BeginBlock without vote info",
                block.header.height - 1,
            )
    app_conn.begin_block(
        abci.RequestBeginBlock(
            hash=block.hash() or b"",
            header=block.header,
            last_commit_info=make_last_commit_info(last_validators, block),
            byzantine_validators=[
                abci.Evidence(
                    type="duplicate/vote",
                    validator_address=ev.address(),
                    height=ev.height(),
                    time=block.header.time,
                )
                for ev in block.evidence.evidence
            ],
        )
    )
    for tx in block.data.txs:
        app_conn.deliver_tx(tx)
    app_conn.end_block(abci.RequestEndBlock(height=block.header.height))
    res = app_conn.commit()
    return res.data


def resync_app(app_conn, state, block_store, state_db, genesis_doc) -> bytes:
    """Re-sync a RESTARTED app to the already-committed chain state,
    app-only — the mid-flight counterpart of Handshaker.replay_blocks,
    run by the resilient consensus conn's on_failure = "handshake"
    policy after a reconnect (proxy/resilient.py).

    Unlike the boot handshake this NEVER mutates chain state: the
    in-flight block application re-drives itself from scratch once this
    returns (BlockExecutor.apply_block retries on ABCIAppRestartedError),
    so mutating here would race it. A fresh app (height 0) is InitChained
    from genesis, then replayed up to `state.last_block_height` through
    BeginBlock→DeliverTx→EndBlock→Commit only. An app AHEAD of chain
    state (it committed the in-flight block before dying) cannot be
    reconciled without mutating state — that is the boot handshake's
    app==store case — so we refuse and let the supervisor halt; a node
    restart recovers it."""
    res = app_conn.info(abci.RequestInfo(version="tendermint-tpu"))
    app_height = res.last_block_height
    target = state.last_block_height
    LOG.warning("re-syncing restarted app: app=%d chain=%d",
                app_height, target)
    if app_height > target:
        raise HandshakeError(
            f"restarted app at height {app_height} is ahead of chain "
            f"state {target}; restart the node to reconcile via the "
            f"boot handshake")
    if app_height == 0:
        validators = [
            abci.ValidatorUpdate(pub_key=pubkey_to_bytes(v.pub_key),
                                 power=v.power)
            for v in genesis_doc.validators
        ]
        app_conn.init_chain(abci.RequestInitChain(
            time=genesis_doc.genesis_time,
            chain_id=genesis_doc.chain_id,
            validators=validators,
            app_state_bytes=b"",
        ))
    app_hash = res.last_block_app_hash
    for height in range(app_height + 1, target + 1):
        LOG.info("re-applying block %d to restarted app (app-only)", height)
        block = block_store.load_block(height)
        app_hash = _exec_block_on_app(app_conn, block, state_db)
    if target > 0 and app_hash != state.app_hash:
        raise HandshakeError(
            f"restarted app re-synced to height {target} but hashes "
            f"diverge: app {app_hash.hex()[:16]} != state "
            f"{state.app_hash.hex()[:16]}")
    return app_hash


class _MockProxyApp:
    """Serves stored ABCI responses instead of re-executing (reference
    newMockProxyApp :446-481)."""

    def __init__(self, app_hash: bytes, abci_responses):
        self._app_hash = app_hash
        self._responses = abci_responses
        self._tx_count = 0

    def begin_block(self, req):
        self._tx_count = 0
        return abci.ResponseBeginBlock()

    def deliver_tx(self, tx):
        r = self._responses.deliver_tx[self._tx_count]
        self._tx_count += 1
        return r

    def end_block(self, req):
        return self._responses.end_block or abci.ResponseEndBlock()

    def commit(self):
        return abci.ResponseCommit(data=self._app_hash)

    def flush(self):
        pass
